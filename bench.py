"""Benchmark harness — prints one JSON line per BASELINE.md config.

The FIRST line is the driver's headline metric (BASELINE.json): k-select
throughput in elems/sec/chip with exact-match verification against the
sequential oracle. The baseline is the reference's own algorithm —
sort-then-index (``kth-problem-seq.c:32-33``) — measured on this host via
NumPy over the identical seeded input, so ``vs_baseline`` is the speedup of
the TPU radix path over the reference approach at the reference's operating
point (N=1e8-class int32, k=N/2 median; ``kth-problem-seq.c~:24``).

Subsequent lines cover the remaining BASELINE.md configs: single-chip top-k
(N=64M float32, k=128), batched top-k (4096 x 32768 float32, k=8), the
CGM/MPI parity backend at 4 ranks, and the seq-oracle config.

Timing method: the TPU is reached through a tunnel with ~100 ms round-trip
latency, and identical repeated calls can be served from a result cache, so
single-call wall times measure the tunnel, not the chip. Instead we time two
jitted chains of R1 and R2 *data-dependent* iterations (iteration i depends
on iteration i-1, so no iteration can be elided) and report the differential
(t2 - t1) / (R2 - R1): pure device-side solve time.
"""
# ksel: noqa-file[KSL004] -- the differential perturb-chain methodology reads clocks inline around chained device calls; utils/timing.time_fn's block-per-call semantics would break the chain (its own docstring points here)

from __future__ import annotations

import json
import sys
import time


def _emit(rec):
    print(json.dumps(rec), flush=True)


def _jit_cache_size(run):
    """The jitted callable's compile-cache entry count (None when the
    probe is unavailable on this jax) — the MEASURED ground truth behind
    the steady-state recompile gate: a timed invocation that grows it
    recompiled."""
    probe = getattr(run, "_cache_size", None)
    try:
        return None if probe is None else int(probe())
    except Exception:  # pragma: no cover - jax-internal API drift
        return None


def _timed_chain(build_chain, xd, seed0, reps, site=None):
    """Best-of-3 differential timing of build_chain(reps) jitted chains.

    With ``site``, every invocation reports into the process
    ProgramLedger (obs/ledger.py): the first call per chain is the
    compile (its wall clocked by the ledger), the timed calls are cache
    hits — and the jit cache's own size is probed around the timed
    window, so ``recompiles_after_warmup`` is MEASURED off the compiled
    function, not asserted. Returns ``(per_rep_seconds, stats)`` then;
    bare ``per_rep_seconds`` otherwise."""
    import numpy as np

    from mpi_k_selection_tpu.obs.ledger import LEDGER

    r1, r2 = reps
    stats = {"recompiles_after_warmup": 0, "warmup_unmeasured": False}

    def t(run, r):
        key = ("chain", int(r))
        if site is None:
            _ = np.asarray(run(xd, seed0(0)))  # compile
        else:
            with LEDGER.compile_span(site, key):
                _ = np.asarray(run(xd, seed0(0)))  # compile (clocked)
        warm = _jit_cache_size(run)
        best = float("inf")
        for i in range(1, 4):
            t0 = time.perf_counter()
            _ = np.asarray(run(xd, seed0(i)))
            best = min(best, time.perf_counter() - t0)
            if site is not None:
                LEDGER.note_hit(site, key)
        after = _jit_cache_size(run)
        if warm is None or after is None:
            stats["warmup_unmeasured"] = True
        else:
            grew = after - warm
            if grew > 0:
                stats["recompiles_after_warmup"] += grew
                if site is not None:
                    # fold the measured recompiles into the ledger book
                    with LEDGER.compile_span(site, key + ("recompiled",)):
                        pass
        return best

    t1, t2 = t(build_chain(r1), r1), t(build_chain(r2), r2)
    per = max((t2 - t1) / (r2 - r1), 1e-9)
    if site is None:
        return per
    return per, stats


def bench_kselect_headline(on_tpu: bool):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.backends import seq
    from mpi_k_selection_tpu.ops.radix import radix_select
    from mpi_k_selection_tpu.utils import datagen

    # TPU: reference-class N (2^27 = 134M ≈ the reference's 1e8). CPU CI: small.
    n = 1 << 27 if on_tpu else 1 << 22
    k = n // 2
    x = datagen.generate(n, pattern="uniform", seed=0, dtype=np.int32)

    # baseline: the reference algorithm (sort-then-index) on the host, via
    # the same oracle implementation the test suite verifies against
    t0 = time.perf_counter()
    want = int(seq.kselect_sort(x, k))
    baseline_s = time.perf_counter() - t0

    xd = jax.device_put(jnp.asarray(x))
    kd = jnp.asarray(k, jnp.int32)
    got = int(np.asarray(radix_select(xd, kd)))  # compile + correctness check
    exact = got == want

    def chain(reps):
        @jax.jit
        def run(xs, k0):
            def body(_, kk):
                ans = radix_select(xs, kk)
                # serialize: next k depends on this answer (defeats caching)
                return k0 + jnp.abs(ans).astype(jnp.int32) % 7

            return jax.lax.fori_loop(0, reps, body, k0)

        return run

    per = _timed_chain(
        chain,
        xd,
        lambda i: jnp.asarray(k - i, jnp.int32),
        (5, 45) if on_tpu else (1, 3),
    )
    throughput = n / per if exact else 0.0
    _emit(
        {
            "metric": "kselect_throughput_1chip",
            "value": round(throughput, 1),
            "unit": "elems/sec/chip",
            "vs_baseline": round(baseline_s / per, 3) if exact else 0.0,
            "n": n,
            "k": k,
            "seconds": round(per, 6),
            "baseline_seconds": round(baseline_s, 6),
            "exact_match": exact,
            "backend": "tpu" if on_tpu else "cpu",
        }
    )
    return exact


def bench_kselect_1b(on_tpu: bool):
    """BASELINE north-star N: 1B int32 median on one chip (VERDICT r4
    item 2 — previously an r2 one-off, now a per-round driver artifact).

    Gated to TPU: the 4 GB input neither fits nor means anything on the
    CPU CI host. Data is generated ON DEVICE (jax PRNG) and exactness is
    checked against an on-device full sort — shipping a host-generated
    4 GB array through the tunnel plus an np.partition oracle made this
    one line cost ~12 min/run (measured; the host-data variant gave the
    same 53 ms select time). ``vs_baseline`` is the on-chip sort-then-
    index time over the select time: the reference's own algorithm on
    the same hardware, a far STRONGER baseline than its host sort."""
    if not on_tpu:
        return True
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.radix import radix_select

    n = 1_000_000_000
    k = n // 2
    xd = jax.jit(
        lambda: jax.random.randint(
            jax.random.PRNGKey(0), (n,), -(2**31), 2**31 - 1, jnp.int32
        )
    )()
    xd.block_until_ready()
    sort_index = jax.jit(lambda v: jnp.sort(v)[k - 1])
    want = int(sort_index(xd))  # on-device sort-then-index oracle (+ compile)
    # steady-state baseline (ADVICE r5 #3): time a SECOND invocation, compile
    # excluded — jit caches compilations, not results, so the same buffer
    # re-runs the full sort (no extra 4 GB copy resident during it)
    base_cache0 = _jit_cache_size(sort_index)
    t0 = time.perf_counter()
    _ = int(sort_index(xd))
    baseline_s = time.perf_counter() - t0
    # the baseline's steady-state claim, measured: its timed (second)
    # invocation must not have grown the sort's jit cache
    base_cache1 = _jit_cache_size(sort_index)
    baseline_recompiled = (
        None
        if base_cache0 is None or base_cache1 is None
        else base_cache1 - base_cache0
    )

    kd = jnp.asarray(k, jnp.int32)
    got = int(np.asarray(radix_select(xd, kd)))  # compile + correctness
    # data-sanity guard: generation and oracle both live on the device
    # under test, so degenerate PRNG output (constant / low-entropy data)
    # would pass exact_match while inflating throughput (the select would
    # terminate in fewer effective passes). Cheap device reductions prove
    # the draw actually spans the int32 range.
    spread_ok = (int(xd.max()) - int(xd.min())) > 2**31
    exact = got == want and spread_ok

    def chain(reps):
        @jax.jit
        def run(xs, k0):
            def body(_, kk):
                ans = radix_select(xs, kk)
                return k0 + jnp.abs(ans).astype(jnp.int32) % 7

            return jax.lax.fori_loop(0, reps, body, k0)

        return run

    from mpi_k_selection_tpu.obs.ledger import LEDGER, snapshot_delta

    # the MEASURED steady-state contract (ISSUE 14): the ledger delta
    # carries the chains' compile count + walls, and the jit cache is
    # probed around the timed window — a recompile during it fails the
    # bench instead of silently riding `baseline_includes_compile: false`
    led0 = LEDGER.snapshot()
    per, chain_stats = _timed_chain(
        chain, xd, lambda i: jnp.asarray(k - i, jnp.int32), (3, 13),
        site="bench.kselect_1b",
    )
    led = snapshot_delta(led0, LEDGER.snapshot())
    unmeasured = (
        chain_stats["warmup_unmeasured"] or baseline_recompiled is None
    )
    recompiles = chain_stats["recompiles_after_warmup"] + (
        baseline_recompiled or 0
    )
    # gate only what was measured: a jax without the cache-size probe is
    # REPORTED (recompile_gate_measured: false, recompiles null) rather
    # than failed — a measured recompile still fails the bench
    steady = unmeasured or recompiles == 0
    _emit(
        {
            "metric": "kselect_1b_int32",
            "value": round(n / per, 1) if exact else 0.0,
            "unit": "elems/sec/chip",
            "vs_baseline": round(baseline_s / per, 3) if exact else 0.0,
            "n": n,
            "k": k,
            "seconds": round(per, 6),
            "baseline_seconds": round(baseline_s, 6),
            "baseline": "on-chip jnp.sort-then-index (steady-state, 2nd call)",
            "baseline_includes_compile": False,
            "compile_count": led["compiles"],
            "compile_seconds": led["compile_seconds"],
            "recompiles_after_warmup": None if unmeasured else recompiles,
            "recompile_gate_measured": not unmeasured,
            "ledger": led,
            "exact_match": exact,
        }
    )
    del xd
    return exact and steady


def bench_topk_single(on_tpu: bool):
    """BASELINE config: single-chip top-k, N=64M float32, k=128 (MoE logits)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.topk import topk

    n = 1 << 26 if on_tpu else 1 << 21
    k = 128
    x = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    want = np.sort(x)[::-1][:k]

    xd = jax.device_put(jnp.asarray(x))
    vals, idx = topk(xd, k)
    got = np.asarray(vals)
    exact = bool(np.array_equal(got, want)) and bool(
        np.array_equal(np.sort(np.asarray(x)[np.asarray(idx)])[::-1], want)
    )

    # lax.top_k reference on the same chip, for the speedup column.
    # Rep differences are sized so (diff * per-iter) >> the ~50 ms tunnel
    # noise floor; small diffs made this metric swing by 3x run-to-run.
    t_ref = _timed_chain(
        lambda reps: _perturb_chain(lambda xs: jax.lax.top_k(xs, k)[0], reps),
        xd,
        lambda i: jnp.uint32(i + 1),
        (2, 8) if on_tpu else (1, 3),
    )
    per = _timed_chain(
        lambda reps: _perturb_chain(lambda xs: topk(xs, k)[0], reps),
        xd,
        lambda i: jnp.uint32(i + 1),
        (3, 63) if on_tpu else (1, 3),
    )
    _emit(
        {
            "metric": "topk_64m_f32_k128",
            "value": round(n / per, 1) if exact else 0.0,
            "unit": "elems/sec/chip",
            "vs_baseline": round(t_ref / per, 3) if exact else 0.0,
            "n": n,
            "k": k,
            "seconds": round(per, 6),
            "lax_topk_seconds": round(t_ref, 6),
            "exact_match": exact,
        }
    )
    return exact


def _perturb_chain(fn, reps):
    """Chain fn(xs) with a data-dependent single-element perturbation per
    iteration (in-place on the loop carry — O(1) per step, so the measured
    time is fn's own). The write is real (value depends on the previous
    iteration's output), so neither XLA nor a result cache can elide any
    iteration; exact-match is verified separately on the pristine input."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(xs, s0):
        def body(_, carry):
            xs, s = carry
            shape = xs.shape
            i = (s % jnp.uint32(shape[-1])).astype(jnp.int32)
            x2 = xs.reshape(-1, shape[-1])
            delta = ((s & jnp.uint32(1)).astype(xs.dtype) - xs.dtype.type(0.5)) * xs.dtype.type(1e-7)
            x2 = x2.at[0, i].set(x2[0, i] + delta)
            xs = x2.reshape(shape)
            out = fn(xs)
            bump = jax.lax.bitcast_convert_type(
                out.ravel()[0].astype(jnp.float32), jnp.uint32
            )
            return xs, s + (bump & jnp.uint32(3)) + jnp.uint32(1)

        _, s = jax.lax.fori_loop(0, reps, body, (xs, s0))
        return s

    return run


def bench_topk_batched(on_tpu: bool):
    """BASELINE config: batched top-k, B=4096 x D=32768 float32, k=8."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.topk import batched_topk

    b, d = (4096, 32768) if on_tpu else (64, 4096)
    k = 8
    x = np.random.default_rng(2).standard_normal((b, d)).astype(np.float32)
    want = -np.sort(-x, axis=-1)[:, :k]

    xd = jax.device_put(jnp.asarray(x))
    vals, idx = batched_topk(xd, k)
    exact = bool(np.array_equal(np.asarray(vals), want)) and bool(
        np.array_equal(
            -np.sort(-np.take_along_axis(x, np.asarray(idx), axis=-1), axis=-1),
            want,
        )
    )

    def tuple_consumer(fn):
        # consume BOTH outputs (r5: the metric covers values + indices).
        # The 1e-20-scaled index term is a real data dependency (so XLA
        # cannot DCE the index recovery) that never perturbs the chain.
        def run(xs):
            v, i = fn(xs)
            return v[0, 0] + i.sum(dtype=jnp.int32).astype(
                jnp.float32
            ) * jnp.float32(1e-20)

        return run

    # values-only reference/paths (the r1-r4 metric, kept for history):
    t_ref = _timed_chain(
        lambda reps: _perturb_chain(lambda xs: jax.lax.top_k(xs, k)[0], reps),
        xd,
        lambda i: jnp.uint32(i + 1),
        (3, 43) if on_tpu else (1, 3),
    )
    per = _timed_chain(
        lambda reps: _perturb_chain(lambda xs: batched_topk(xs, k)[0], reps),
        xd,
        lambda i: jnp.uint32(i + 1),
        (5, 85) if on_tpu else (1, 3),
    )
    # full-tuple (values + indices) timing — the beam-search consumer shape
    # the config is named for. The XLA reference is NOT re-measured here:
    # lax.top_k with indices consumed lowers to a variadic-sort program
    # (~135-142 ms measured at this shape on v5e, any dtype) and one
    # 40-rep chain of it would add ~20 min of tunnel time per bench run;
    # vs_baseline_tuple uses the values-only t_ref as a CONSERVATIVE
    # stand-in (the true tuple speedup is ~25x larger).
    per_tuple = _timed_chain(
        lambda reps: _perturb_chain(tuple_consumer(lambda xs: batched_topk(xs, k)), reps),
        xd,
        lambda i: jnp.uint32(i + 1),
        (4, 44) if on_tpu else (1, 3),
    )
    _emit(
        {
            "metric": "batched_topk_4096x32768_k8",
            "value": round(b * d / per, 1) if exact else 0.0,
            "unit": "elems/sec/chip",
            "vs_baseline": round(t_ref / per, 3) if exact else 0.0,
            "batch": b,
            "d": d,
            "k": k,
            "seconds": round(per, 6),
            "tuple_seconds": round(per_tuple, 6),
            "lax_topk_seconds": round(t_ref, 6),
            "exact_match": exact,
        }
    )
    _emit(
        {
            "metric": "batched_topk_tuple_4096x32768_k8",
            "value": round(b * d / per_tuple, 1) if exact else 0.0,
            "unit": "elems/sec/chip",
            "vs_baseline": round(t_ref / per_tuple, 3) if exact else 0.0,
            "batch": b,
            "d": d,
            "k": k,
            "seconds": round(per_tuple, 6),
            "lax_topk_values_only_seconds": round(t_ref, 6),
            "exact_match": exact,
        }
    )
    return exact


def bench_multirank(
    on_tpu: bool,
    qs=(0.5, 0.9, 0.99),
    metric="multirank_p50_p90_p99",
    reps=None,
):
    """Multi-rank selection: K quantile ranks of one large int32 array in
    one call (the telemetry shape). All K queries ride one shared data
    sweep per pass (the multi-prefix kernels) plus one batched collect;
    baseline is the reference approach — one host sort + K indexes
    (``kth-problem-seq.c:32-33`` amortized across the queries).

    Run twice by main(): K=3 (p50/p90/p99) and K=9 (deciles — the shape the
    round-2 claims used). Per-query pass cost is linear in K (the masked
    SWAR accumulate per query, ~5.3 ms/pass at K=9 vs ~0.7 ms shared pass,
    measured r4), so the two lines track the scaling; one lax.sort (409 ms
    at 134M) only overtakes the walk near K~110."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.ops.radix import radix_select_many
    from mpi_k_selection_tpu.utils import datagen

    n = 1 << 27 if on_tpu else 1 << 22
    ks = np.array([max(1, int(np.ceil(q * n))) for q in qs])
    x = datagen.generate(n, pattern="uniform", seed=5, dtype=np.int32)

    t0 = time.perf_counter()
    s = np.sort(x, kind="stable")
    want = s[ks - 1]
    baseline_s = time.perf_counter() - t0

    xd = jax.device_put(jnp.asarray(x))
    got = np.asarray(radix_select_many(xd, jnp.asarray(ks, jnp.int32)))
    exact = bool(np.array_equal(got, want))

    def chain(reps):
        @jax.jit
        def run(xs, k0):
            def body(_, kks):
                ans = radix_select_many(xs, kks)
                return k0 + jnp.abs(ans).astype(jnp.int32) % 7

            return jax.lax.fori_loop(0, reps, body, k0)

        return run

    per = _timed_chain(
        chain,
        xd,
        lambda i: jnp.asarray(ks - i, jnp.int32),
        (reps or ((3, 23) if on_tpu else (1, 3))),
    )
    _emit(
        {
            "metric": metric,
            "value": round(len(ks) * n / per, 1) if exact else 0.0,
            "unit": "query-elems/sec/chip",
            "vs_baseline": round(baseline_s / per, 3) if exact else 0.0,
            "n": n,
            "ks": [int(v) for v in ks],
            "seconds": round(per, 6),
            "baseline_seconds": round(baseline_s, 6),
            "exact_match": exact,
        }
    )
    return exact


def _bucket_read_totals(o):
    """Staged-bucket read accounting off one run's metrics registry (the
    fused-ingest evidence, ISSUE 11): per-phase ``ingest.bucket_reads`` /
    ``ingest.bucket_read_bytes`` counters plus their total, next to
    ``ingest.staged_bytes`` — both in PADDED bucket bytes, so
    ``bytes_read / bytes_staged`` is the per-pass read amplification the
    fused program collapses to ~1.0."""
    by_phase = {}
    bytes_read = 0
    bytes_staged = 0
    for m in o.metrics.metrics():
        if m.name == "ingest.bucket_reads" and m.labels:
            ph = dict(m.labels).get("phase", "?")
            by_phase.setdefault(ph, {})["programs"] = m.value
        elif m.name == "ingest.bucket_read_bytes" and m.labels:
            ph = dict(m.labels).get("phase", "?")
            by_phase.setdefault(ph, {})["bytes"] = m.value
            bytes_read += m.value
        elif m.name == "ingest.staged_bytes":
            bytes_staged += m.value
    return {
        "by_phase": by_phase,
        "bytes_read": bytes_read,
        "bytes_staged": bytes_staged,
    }


def bench_streaming_oc(on_tpu: bool):
    """Out-of-core exact k-select (the streaming subsystem): N=2^33 int32
    median on TPU — the 32 GB input is ~2x a 16 GB HBM, so the on-device
    baseline (resident sort OR resident radix select) cannot exist at this
    n; `vs_baseline` is therefore reported as 0.0 with the reason in the
    record. Chunks are generated HOST-side per index (numpy PRNG keyed by
    chunk number — replay-stable across passes): the honest out-of-core
    ingest shape, where every chunk pays host key-encode + a host->device
    transfer per pass — exactly the costs the pipelined ingest
    (streaming/pipeline.py) exists to hide. The solve runs TWICE on the
    same source — synchronous (`pipeline_depth=0`, the oracle) and
    double-buffered (depth 2) — and the record carries the comparison:
    `speedup` (sync/pipelined wall), `ingest_hidden_frac` (fraction of
    producer-side produce+encode+stage time the descent never waited for),
    and `exact_match` REQUIRES the two answers be bit-identical. Exactness
    is proven by a streamed O(n) rank certificate (less < k <= leq); CPU
    CI runs a small config with a real host oracle on top (expect ~1x
    speedup there — a CPU "device" shares the host the producer runs on).

    When more than one local device exists, a SECOND record runs the same
    stream with `devices=<all>` — chunks staged round-robin, one histogram
    in flight per chip — reporting per-device throughput,
    `ingest_hidden_frac`, and `device_scaling` (devices=1 wall /
    multi-device wall), with `exact_match` requiring bit-equality against
    both the sync oracle and the devices=1 answer. On the CPU CI mesh the
    virtual devices all share one core, so scaling measures pure dispatch
    overhead and lands WELL below 1x there (r6: ~0.2x) — the CI record
    exists for the bit-equality contract; the real factor needs TPU
    validation."""
    import numpy as np

    from mpi_k_selection_tpu.obs import MetricsRegistry, Observability
    from mpi_k_selection_tpu.streaming.chunked import (
        streaming_kselect,
        streaming_rank_certificate,
    )
    from mpi_k_selection_tpu.streaming.executor import collect_hidden_frac
    from mpi_k_selection_tpu.streaming.pipeline import ingest_hidden_frac
    from mpi_k_selection_tpu.utils.profiling import PhaseTimer

    from mpi_k_selection_tpu.streaming.pipeline import STAGING_POOL

    def _obs_snapshot(o, pool_before, ledger_before=None):
        """Compact embed of the run's metrics registry: occupancy (total
        AND per executor phase — the descent/collect split is the deferred
        executor's before/after evidence), StagingPool hit rate, stall
        seconds, chunks/bytes per device — the numbers the TPU validation
        sweep needs alongside wall time. The registry mirrors the MODULE
        pool's process-lifetime counters; ``pool_before`` (hits, misses)
        rebases them to THIS run's deltas so the record is per-run, not
        cumulative across warmups/records — and ``ledger_before`` (a
        ProgramLedger.snapshot) does the same for the compile/byte book,
        embedding the per-run ledger delta (compiles, compile walls,
        device_bytes peaks; ISSUE 14)."""
        snap = o.metrics.as_dict()
        occ = snap.get("inflight.occupancy", {})
        hits = snap.get("staging_pool.hits", {}).get("value", 0)
        misses = snap.get("staging_pool.misses", {}).get("value", 0)
        by_phase = {}
        for m in o.metrics.metrics():
            if m.name == "inflight.occupancy" and m.labels:
                ph = dict(m.labels).get("phase", "?")
                by_phase[ph] = {
                    "count": m.count,
                    "mean": round(m.mean, 4) if m.count else None,
                    "max": m.max,
                }
        reads = _bucket_read_totals(o)
        ledger_delta = None
        if ledger_before is not None:
            from mpi_k_selection_tpu.obs.ledger import LEDGER, snapshot_delta

            ledger_delta = snapshot_delta(ledger_before, LEDGER.snapshot())
        return {
            **({"ledger": ledger_delta} if ledger_delta is not None else {}),
            "inflight_occupancy": {
                k: occ.get(k) for k in ("count", "mean", "max")
            },
            "occupancy_by_phase": by_phase,
            "staging_pool_hits": hits - pool_before[0],
            "staging_pool_misses": misses - pool_before[1],
            "pipeline_stall_seconds": snap.get(
                'phase.seconds{phase="pipeline.stall"}', {}
            ).get("value"),
            "chunks_per_device": {
                dict(m.labels).get("device", "?"): m.value
                for m in o.metrics.metrics()
                if m.name == "ingest.chunks"
            },
            # the fused-ingest read-amplification evidence (ISSUE 11):
            # bucket_read_bytes / staged_bytes ~ 1.0 means every staged
            # key is read once per pass
            "bucket_reads_by_phase": reads["by_phase"],
            "bytes_read": reads["bytes_read"],
            "bytes_staged": reads["bytes_staged"],
        }

    def _collect_frac(o, window):
        """collect_hidden_frac off one run's labeled collect histogram."""
        occ = o.metrics.histogram(
            "inflight.occupancy", labels={"phase": "collect"}
        )
        frac = collect_hidden_frac(occ, window)
        return round(frac, 4) if frac is not None else None

    n, chunk = (1 << 33, 1 << 27) if on_tpu else (1 << 22, 1 << 19)
    nchunks = n // chunk
    k = n // 2

    def gen(i):
        return np.random.default_rng(9 + i).integers(
            -(2**31), 2**31 - 1, size=chunk, dtype=np.int32
        )

    source = lambda: (gen(i) for i in range(nchunks))

    # untimed warmup over a 2-chunk prefix: chunk sizes are uniform, so
    # this compiles every histogram program BOTH timed runs will hit —
    # otherwise the first-run (sync) wall time carries the XLA compiles
    # the second (pipelined) run gets from cache, inflating the speedup.
    # The tiny collect_budget forces the warmup through the deep
    # prefix-filtered passes (a different program from pass 0's
    # prefix=None sweep), which the default budget could cut short at
    # exactly the TPU config's pass-0 bucket population
    warm = lambda: (gen(i) for i in range(2))
    streaming_kselect(warm, chunk, pipeline_depth=0, collect_budget=64)
    streaming_kselect(warm, chunk, pipeline_depth=2, collect_budget=64)

    t0 = time.perf_counter()
    ans_sync = streaming_kselect(source, k, pipeline_depth=0)
    sync_s = time.perf_counter() - t0

    from mpi_k_selection_tpu.obs.ledger import LEDGER as _LEDGER

    timer = PhaseTimer()
    obs = Observability(metrics=MetricsRegistry())
    pool0 = (STAGING_POOL.hits, STAGING_POOL.misses)
    ledger0 = _LEDGER.snapshot()
    t0 = time.perf_counter()
    ans = streaming_kselect(source, k, pipeline_depth=2, timer=timer, obs=obs)
    dt = time.perf_counter() - t0
    hidden = ingest_hidden_frac(timer)

    less, leq = streaming_rank_certificate(source, ans)
    exact = (less < k <= leq) and int(ans) == int(ans_sync)
    rec = {
        "metric": "kselect_streaming_oc_8b_int32" if on_tpu else "kselect_streaming_oc",
        # v2: chunks are HOST-generated, so `value` now includes per-pass
        # host produce+encode+transfer (prior rounds generated on device
        # and excluded them) — not comparable with v1 rounds of this metric
        "methodology": "hostgen-v2",
        "value": round(n / dt, 1) if exact else 0.0,
        "unit": "elems/sec/chip",
        "n": n,
        "k": k,
        "chunks": nchunks,
        "chunk_elems": chunk,
        "seconds": round(dt, 6),
        "pipeline_depth": 2,
        "sync_seconds": round(sync_s, 6),
        "speedup": round(sync_s / dt, 3) if exact else 0.0,
        "ingest_hidden_frac": round(hidden, 4) if hidden is not None else 0.0,
        "rank_certificate": [less, leq],
        "obs": _obs_snapshot(obs, pool0, ledger0),
        "exact_match": bool(exact),
    }
    if on_tpu:
        rec["vs_baseline"] = 0.0
        rec["baseline"] = (
            "infeasible on-device: 2^33 int32 (32 GB) exceeds HBM; "
            "certificate-verified instead"
        )
    else:
        x = np.concatenate([gen(i) for i in range(nchunks)])
        t0 = time.perf_counter()
        want = int(np.sort(x, kind="stable")[k - 1])
        baseline_s = time.perf_counter() - t0
        exact = exact and int(ans) == want
        rec["exact_match"] = bool(exact)
        rec["value"] = round(n / dt, 1) if exact else 0.0
        rec["vs_baseline"] = round(baseline_s / dt, 3) if exact else 0.0
        rec["baseline_seconds"] = round(baseline_s, 6)
    _emit(rec)
    ok = bool(exact)

    # --- spill config: the survivor spill store (ISSUE 5) on a deeper
    # descent — radix_bits=4 and a tiny collect budget force several
    # prefix-filtered passes, so the record can PROVE the geometric
    # shrink: pass 0 reads the source (and tees gen 0), pass 1 reads gen 0
    # whole, every later pass reads ~1/2^radix_bits of its predecessor.
    # `pass_shrink_ratio` is the worst (largest) bytes_read ratio between
    # consecutive spill-read histogram passes after pass 1 — the issue's
    # acceptance bound is <= ~1/2^(radix_bits-1); `exact_match` REQUIRES
    # bit-equality against the spill=off answer on the same source. Run at
    # a reduced n on TPU (the shrink contract is scale-free and gen 0
    # costs n key bytes of disk).
    from mpi_k_selection_tpu.streaming.spill import SpillStore

    sp_n, sp_chunk = (1 << 27, 1 << 24) if on_tpu else (1 << 22, 1 << 19)
    sp_nchunks, sp_k = sp_n // sp_chunk, sp_n // 2

    def sp_gen(i):
        return np.random.default_rng(23 + i).integers(
            -(2**31), 2**31 - 1, size=sp_chunk, dtype=np.int32
        )

    sp_source = lambda: (sp_gen(i) for i in range(sp_nchunks))
    sp_rb, sp_budget = 4, 512
    ans_off = streaming_kselect(
        sp_source, sp_k, radix_bits=sp_rb, collect_budget=sp_budget,
        spill="off",
    )
    # the deferred executor's before/after on THIS record: the primary
    # timed run uses the deferred default; a second spill run with
    # deferred="off" (the pre-executor eager tee/collect) supplies
    # `eager_seconds`, and the obs registries supply the per-phase window
    # occupancy + collect_hidden_frac. Run across every local device when
    # there is more than one — the serialization only shows p-wide
    import jax as _jax

    sp_ndev = len(_jax.devices())
    sp_devices = sp_ndev if sp_ndev > 1 else None
    obs_sp = Observability(metrics=MetricsRegistry())
    with SpillStore() as sp_store:
        t0 = time.perf_counter()
        ans_spill = streaming_kselect(
            sp_source, sp_k, radix_bits=sp_rb, collect_budget=sp_budget,
            spill=sp_store, devices=sp_devices, obs=obs_sp,
        )
        sp_s = time.perf_counter() - t0
        sp_passes = list(sp_store.pass_log)
    obs_sp_eager = Observability(metrics=MetricsRegistry())
    with SpillStore() as sp_store_eager:
        t0 = time.perf_counter()
        ans_spill_eager = streaming_kselect(
            sp_source, sp_k, radix_bits=sp_rb, collect_budget=sp_budget,
            spill=sp_store_eager, devices=sp_devices, deferred="off",
            obs=obs_sp_eager,
        )
        sp_eager_s = time.perf_counter() - t0
    # one-shot leg: the same stream as a consumed generator, spill=auto —
    # the lifted replayable-source requirement must yield the SAME bits
    ans_oneshot = streaming_kselect(
        (sp_gen(i) for i in range(sp_nchunks)), sp_k,
        radix_bits=sp_rb, collect_budget=sp_budget,
    )
    spill_reads = [
        p["bytes_read"] for p in sp_passes
        if isinstance(p["pass"], int) and p["pass"] >= 1
    ]
    shrink = (
        max(
            b / a for a, b in zip(spill_reads, spill_reads[1:])
        )
        if len(spill_reads) >= 2
        else 0.0
    )
    exact_sp = (
        int(ans_spill) == int(ans_off) == int(ans_oneshot)
        == int(ans_spill_eager)
    )
    _emit(
        {
            "metric": "kselect_streaming_oc_spill",
            "value": round(sp_n / sp_s, 1) if exact_sp else 0.0,
            "unit": "elems/sec/chip",
            "n": sp_n,
            "k": sp_k,
            "chunks": sp_nchunks,
            "chunk_elems": sp_chunk,
            "radix_bits": sp_rb,
            "collect_budget": sp_budget,
            "devices": sp_ndev,
            "seconds": round(sp_s, 6),
            # deferred-executor before/after (ISSUE 8): eager is the
            # pre-executor consumption discipline on the SAME config; on
            # the CPU CI mesh all virtual devices share one core, so the
            # wall-clock ratio needs TPU validation — the occupancy
            # split is the CI-provable half of the contract
            "deferred": "on",
            "eager_seconds": round(sp_eager_s, 6),
            "deferred_speedup": round(sp_eager_s / sp_s, 3) if exact_sp else 0.0,
            "collect_hidden_frac": _collect_frac(obs_sp, sp_ndev),
            "occupancy_by_phase": _obs_snapshot(obs_sp, (0, 0))[
                "occupancy_by_phase"
            ],
            "occupancy_by_phase_eager": _obs_snapshot(obs_sp_eager, (0, 0))[
                "occupancy_by_phase"
            ],
            "_spill": {
                "passes": sp_passes,
                "bytes_streamed_per_pass": [p["bytes_read"] for p in sp_passes],
                "pass_shrink_ratio": round(shrink, 6),
                "shrink_bound": 1.0 / (1 << (sp_rb - 1)),
                "one_shot_ok": int(ans_oneshot) == int(ans_off),
            },
            "exact_match": bool(exact_sp),
        }
    )
    ok = ok and exact_sp and (0.0 < shrink <= 1.0 / (1 << (sp_rb - 1)))

    # --- width-schedule + packed-spill config (ISSUE 19): the SAME spill
    # stream with width_schedule="auto" (one wide pass-0 digit) and
    # pack_spill="auto" (digit-segmented gen-0 tee + prefix-packed
    # survivor generations). The acceptance gates: total LOGICAL bytes
    # streamed <= 1.2 * n * key_bytes (the legacy spill path pays ~2x —
    # pass 0 reads the source, pass 1 re-reads ALL of gen 0; the
    # segment-pruned replay deletes that second full-n read), packed
    # PHYSICAL writes strictly below the unpacked run's at every
    # generation past gen 0, and `exact_match` REQUIRES bit-equality
    # against BOTH oracles (spill="off" and the unpacked spill run).
    from mpi_k_selection_tpu.streaming.chunked import resolve_width_schedule

    wp_sched = resolve_width_schedule("auto", 32, sp_rb)
    with SpillStore() as wp_off_store:
        ans_wp_off = streaming_kselect(
            sp_source, sp_k, radix_bits=sp_rb, collect_budget=sp_budget,
            spill=wp_off_store, devices=sp_devices,
            width_schedule="auto", pack_spill="off",
        )
        wp_off_passes = list(wp_off_store.pass_log)
    obs_wp = Observability(metrics=MetricsRegistry())
    with SpillStore() as wp_store:
        t0 = time.perf_counter()
        ans_wp = streaming_kselect(
            sp_source, sp_k, radix_bits=sp_rb, collect_budget=sp_budget,
            spill=wp_store, devices=sp_devices,
            width_schedule="auto", pack_spill="auto", obs=obs_wp,
        )
        wp_s = time.perf_counter() - t0
        wp_passes = list(wp_store.pass_log)
    key_bytes = 4  # int32 stream
    wp_streamed = sum(p["bytes_read"] for p in wp_passes)
    wp_disk_w = sum(p.get("disk_bytes_written") or 0 for p in wp_passes)
    wp_logical_w = sum(p.get("bytes_written") or 0 for p in wp_passes)
    # per-generation packed-vs-unpacked physical writes: the two runs
    # share the schedule, so pass labels line up; every survivor
    # generation past gen 0 must be STRICTLY smaller packed
    unpacked_w = {
        p["pass"]: p.get("disk_bytes_written") or 0 for p in wp_off_passes
    }
    packed_under = all(
        (p.get("disk_bytes_written") or 0) < unpacked_w[p["pass"]]
        for p in wp_passes
        if isinstance(p["pass"], int) and p["pass"] >= 1
        and unpacked_w.get(p["pass"], 0) > 0
    )
    exact_wp = int(ans_wp) == int(ans_off) == int(ans_wp_off)
    wp_ratio = wp_streamed / (sp_n * key_bytes)
    _emit(
        {
            "metric": "kselect_streaming_oc_width_pack",
            "value": round(sp_n / wp_s, 1) if exact_wp else 0.0,
            "unit": "elems/sec/chip",
            "n": sp_n,
            "k": sp_k,
            "radix_bits": sp_rb,
            "collect_budget": sp_budget,
            "devices": sp_ndev,
            "seconds": round(wp_s, 6),
            "width_schedule": "auto",
            "pack_spill": "auto",
            "pass_schedule": list(wp_sched),
            "bytes_streamed_total": wp_streamed,
            "bytes_streamed_over_n_key_bytes": round(wp_ratio, 4),
            "bytes_streamed_bound": 1.2,
            "unpacked_bytes_streamed_total": sum(
                p["bytes_read"] for p in sp_passes
            ),
            "disk_bytes_ratio": (
                round(wp_disk_w / wp_logical_w, 4) if wp_logical_w else None
            ),
            "packed_below_unpacked_past_gen0": bool(packed_under),
            "passes": wp_passes,
            "exact_match": bool(exact_wp),
        }
    )
    ok = ok and exact_wp and wp_ratio <= 1.2 and packed_under

    # --- parallel host data plane (ISSUE 20): the SAME encode-bound
    # packed-spill config, ingest_workers=1 (legacy single producer) vs
    # "auto" (the pooled plane), interleaved A/B across rounds so host
    # drift lands on both legs equally; best-of per leg. The gate is
    # EITHER-OR by design: on a many-core host the pool must win wall
    # time outright (`workers_speedup` > 1) or prove the encode wall is
    # already hidden behind the consumer (`encode_hidden_frac` >= 0.9);
    # on a 1-core CI host auto resolves to 1, BOTH legs are byte-for-
    # byte the same code path, and any measured "speedup" is pure noise
    # — there is no perf claim to test, so only the correctness clauses
    # gate. `exact_match` REQUIRES bit-equality of BOTH legs against
    # the spill-off oracle, and the workers=1 leg must never touch the
    # sequencer (`seq_wait` == 0 — byte-for-byte legacy means no
    # coordination phase at all).
    from mpi_k_selection_tpu.streaming.pipeline import (
        SEQ_WAIT_PHASE,
        encode_hidden_frac,
        resolve_ingest_workers,
    )

    pw_auto = resolve_ingest_workers("auto")
    pw_times: dict = {1: [], "auto": []}
    pw_ans: dict = {}
    pw_timers = {1: PhaseTimer(), "auto": PhaseTimer()}
    for _pw_round in range(2):
        for pw_wk in (1, "auto"):
            t0 = time.perf_counter()
            pw_ans[pw_wk] = streaming_kselect(
                sp_source, sp_k, radix_bits=sp_rb,
                collect_budget=sp_budget, spill="force",
                devices=sp_devices, width_schedule="auto",
                pack_spill="auto", ingest_workers=pw_wk,
                timer=pw_timers[pw_wk],
            )
            pw_times[pw_wk].append(time.perf_counter() - t0)
    pw_s1, pw_sp = min(pw_times[1]), min(pw_times["auto"])
    pw_speedup = pw_s1 / pw_sp if pw_sp > 0 else 0.0
    pw_hidden = encode_hidden_frac(pw_timers["auto"])
    pw_seq_wait_w1 = pw_timers[1].phases.get(SEQ_WAIT_PHASE, 0.0)
    exact_pw = int(pw_ans[1]) == int(pw_ans["auto"]) == int(ans_off)
    pw_gate = (
        exact_pw
        and (
            pw_auto == 1
            or pw_speedup > 1.0
            or (pw_hidden or 0.0) >= 0.9
        )
        and pw_seq_wait_w1 < 1e-9
    )
    _emit(
        {
            "metric": "kselect_streaming_oc_workers",
            "value": round(sp_n / pw_sp, 1) if exact_pw else 0.0,
            "unit": "elems/sec/chip",
            "n": sp_n,
            "k": sp_k,
            "radix_bits": sp_rb,
            "collect_budget": sp_budget,
            "devices": sp_ndev,
            "ingest_workers": pw_auto,
            "seconds_workers_1": round(pw_s1, 6),
            "seconds_workers_auto": round(pw_sp, 6),
            "workers_speedup": round(pw_speedup, 4),
            "encode_hidden_frac": (
                round(pw_hidden, 4) if pw_hidden is not None else None
            ),
            "seq_wait_workers_1": round(pw_seq_wait_w1, 6),
            "exact_match": bool(exact_pw),
        }
    )
    ok = ok and pw_gate

    # --- multi-device config: the same stream, staged round-robin across
    # every local device (devices=p, ISSUE 4) vs the devices=1 run above.
    # `device_scaling` is pipelined-devices=1 wall / multi-device wall;
    # `value` is PER-DEVICE throughput so rounds at different p stay
    # comparable; exact_match REQUIRES the answer be bit-identical to both
    # the sync oracle and the devices=1 pipelined run
    import jax

    ndev = len(jax.devices())
    if ndev > 1:
        # warm the per-device compile caches: executables are per committed
        # device, so the warmup stream must carry >= ndev chunks for the
        # round robin to touch EVERY slot (2 chunks would leave p-2 chips
        # compiling inside the timed run)
        warm_md = lambda: (gen(i) for i in range(ndev))
        streaming_kselect(warm_md, chunk, pipeline_depth=2, devices=ndev,
                          collect_budget=64)
        timer_md = PhaseTimer()
        obs_md = Observability(metrics=MetricsRegistry())
        pool0_md = (STAGING_POOL.hits, STAGING_POOL.misses)
        ledger0_md = _LEDGER.snapshot()
        t0 = time.perf_counter()
        ans_md = streaming_kselect(
            source, k, pipeline_depth=2, devices=ndev, timer=timer_md,
            obs=obs_md,
        )
        md_s = time.perf_counter() - t0
        hidden_md = ingest_hidden_frac(timer_md)
        # eager (deferred="off") leg on the same stream: the pre-executor
        # consumption discipline, the denominator of `deferred_speedup`
        obs_md_eager = Observability(metrics=MetricsRegistry())
        t0 = time.perf_counter()
        ans_md_eager = streaming_kselect(
            source, k, pipeline_depth=2, devices=ndev, deferred="off",
            obs=obs_md_eager,
        )
        md_eager_s = time.perf_counter() - t0
        exact_md = (
            int(ans_md) == int(ans_sync) == int(ans) == int(ans_md_eager)
        )
        _emit(
            {
                "metric": (
                    "kselect_streaming_oc_8b_int32_multidev"
                    if on_tpu
                    else "kselect_streaming_oc_multidev"
                ),
                "methodology": "hostgen-v2",
                "value": round(n / md_s / ndev, 1) if exact_md else 0.0,
                "unit": "elems/sec/chip",
                "n": n,
                "k": k,
                "chunks": nchunks,
                "chunk_elems": chunk,
                "devices": ndev,
                "pipeline_depth": 2,
                "seconds": round(md_s, 6),
                "singledev_seconds": round(dt, 6),
                "device_scaling": round(dt / md_s, 3) if exact_md else 0.0,
                "deferred": "on",
                "eager_seconds": round(md_eager_s, 6),
                "deferred_speedup": (
                    round(md_eager_s / md_s, 3) if exact_md else 0.0
                ),
                "collect_hidden_frac": _collect_frac(obs_md, ndev),
                "occupancy_by_phase_eager": _obs_snapshot(
                    obs_md_eager, (0, 0)
                )["occupancy_by_phase"],
                "ingest_hidden_frac": (
                    round(hidden_md, 4) if hidden_md is not None else 0.0
                ),
                "obs": _obs_snapshot(obs_md, pool0_md, ledger0_md),
                "exact_match": bool(exact_md),
            }
        )
        ok = ok and exact_md
    return ok


def bench_ingest_fusion(on_tpu: bool):
    """Single-read ingest tiers (ISSUEs 11 + 13): the spill config —
    radix_bits=4 and a tiny collect budget force several prefix-filtered
    passes whose staged buckets the UNFUSED bundle reads 2-3x each
    (histogram + spill tee per descent pass, one compaction per spec in
    the collect) — run all three tiers interleaved on the same
    multi-rank stream: fused="kernel" (the single-sweep pallas program,
    ONE guaranteed HBM read per bucket; interpret-mode off TPU),
    fused="xla" (the one-XLA-program fusion) and fused="off" (the
    unfused oracle). The record carries interleaved best-of-3 walls
    (`fused_speedup` = off/kernel, `kernel_vs_xla` = xla/kernel), the
    read-amplification evidence (`bytes_read_per_pass` vs
    `bytes_staged_per_pass`, both in padded bucket bytes;
    `read_amplification` gated <= 1.0 for the kernel leg — every staged
    key dispatched to exactly one program per pass), and `exact_match`
    REQUIRES bit-equality of all three legs against the spill="off"
    replay answer. Chunks are small (many dispatches) because the
    CPU-CI-visible win is dispatch/read count, not bandwidth — the
    kernel tier's bandwidth factor is what the TPU run records."""
    import numpy as np

    from mpi_k_selection_tpu.obs import MetricsRegistry, Observability
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect_many
    from mpi_k_selection_tpu.streaming.spill import SpillStore

    import jax as _jax

    n, chunk = (1 << 27, 1 << 22) if on_tpu else (1 << 22, 1 << 16)
    nchunks = n // chunk
    ks = [1, n // 4, n // 2, (3 * n) // 4, n]  # multi-rank: a real collect
    rb, budget = 4, 512
    ndev = len(_jax.devices())
    devices = ndev if ndev > 1 else None
    modes = ("kernel", "xla", "off")

    def gen(i):
        return np.random.default_rng(41 + i).integers(
            -(2**31), 2**31 - 1, size=chunk, dtype=np.int32
        )

    source = lambda: (gen(i) for i in range(nchunks))
    want = streaming_kselect_many(
        source, ks, radix_bits=rb, collect_budget=budget, spill="off"
    )

    # untimed warmup over a short prefix compiles every program ALL legs
    # hit (the sweep kernel, the XLA fusion, the unfused bundle's), so no
    # timed run carries another's compiles
    warm = lambda: (gen(i) for i in range(max(2, ndev)))
    for mode in modes:
        with SpillStore() as ws:
            streaming_kselect_many(
                warm, [chunk, 2 * chunk], radix_bits=rb, collect_budget=64,
                spill=ws, devices=devices, fused=mode,
            )

    best = {m: float("inf") for m in modes}
    answers = {}
    obs_by = {}
    passes_by = {}
    for _rep in range(3):  # interleaved best-of-3: shared-host noise hedge
        for mode in modes:
            o = Observability(metrics=MetricsRegistry())
            with SpillStore() as store:
                t0 = time.perf_counter()
                ans = streaming_kselect_many(
                    source, ks, radix_bits=rb, collect_budget=budget,
                    spill=store, devices=devices, fused=mode, obs=o,
                )
                dt = time.perf_counter() - t0
                passes_by[mode] = len(store.pass_log)
            answers[mode] = [int(a) for a in ans]
            if dt < best[mode]:
                best[mode] = dt
                obs_by[mode] = o

    reads = {m: _bucket_read_totals(obs_by[m]) for m in modes}
    amp = {
        m: (
            round(reads[m]["bytes_read"] / reads[m]["bytes_staged"], 4)
            if reads[m]["bytes_staged"]
            else None
        )
        for m in modes
    }
    exact = (
        answers["kernel"] == answers["xla"] == answers["off"]
        == [int(w) for w in want]
    )
    rec = {
        "metric": "kselect_ingest_fusion",
        "value": round(n / best["kernel"], 1) if exact else 0.0,
        "unit": "elems/sec/chip",
        "n": n,
        "ks": ks,
        "chunks": nchunks,
        "chunk_elems": chunk,
        "radix_bits": rb,
        "collect_budget": budget,
        "devices": ndev,
        "seconds": round(best["kernel"], 6),
        "xla_seconds": round(best["xla"], 6),
        "unfused_seconds": round(best["off"], 6),
        "fused_speedup": (
            round(best["off"] / best["kernel"], 3) if exact else 0.0
        ),
        "kernel_vs_xla": (
            round(best["xla"] / best["kernel"], 3) if exact else 0.0
        ),
        # the issue's acceptance evidence: under the kernel tier every
        # staged key is dispatched to exactly ONE program per pass
        # (ratio <= 1.0 — and on silicon, one guaranteed HBM sweep); the
        # unfused leg shows the amplification the fusion removed
        "bytes_read_per_pass": (
            round(reads["kernel"]["bytes_read"] / passes_by["kernel"], 1)
            if passes_by.get("kernel")
            else None
        ),
        "bytes_staged_per_pass": (
            round(reads["kernel"]["bytes_staged"] / passes_by["kernel"], 1)
            if passes_by.get("kernel")
            else None
        ),
        "read_amplification": amp["kernel"],
        "read_amplification_xla": amp["xla"],
        "read_amplification_unfused": amp["off"],
        "bucket_reads_by_phase": reads["kernel"]["by_phase"],
        "bucket_reads_by_phase_unfused": reads["off"]["by_phase"],
        "exact_match": bool(exact),
    }
    # the width-schedule + packed-spill knobs on the kernel tier (ISSUE
    # 19): wide passes route per-bucket counting to the scatter path (the
    # rb <= 8 kernel support rule), so this leg proves the schedule
    # composes with the fused dispatch — and records the byte columns
    from mpi_k_selection_tpu.streaming.chunked import resolve_width_schedule

    with SpillStore() as wp_store:
        ans_wp = streaming_kselect_many(
            source, ks, radix_bits=rb, collect_budget=budget,
            spill=wp_store, devices=devices, fused="kernel",
            width_schedule="auto", pack_spill="auto",
        )
        wp_log = list(wp_store.pass_log)
    wp_streamed = sum(p["bytes_read"] for p in wp_log)
    wp_disk_w = sum(p.get("disk_bytes_written") or 0 for p in wp_log)
    wp_logical_w = sum(p.get("bytes_written") or 0 for p in wp_log)
    exact_wp = [int(a) for a in ans_wp] == [int(w) for w in want]
    rec["pass_schedule"] = list(resolve_width_schedule("auto", 32, rb))
    rec["bytes_streamed_total"] = wp_streamed
    rec["bytes_streamed_over_n_key_bytes"] = round(wp_streamed / (n * 4), 4)
    rec["disk_bytes_ratio"] = (
        round(wp_disk_w / wp_logical_w, 4) if wp_logical_w else None
    )
    rec["width_pack_exact_match"] = bool(exact_wp)
    _emit(rec)
    return (
        bool(exact)
        and amp["kernel"] is not None
        and amp["kernel"] <= 1.0
        and amp["xla"] is not None
        and amp["xla"] <= 1.1
        and amp["off"] is not None
        and amp["off"] > amp["kernel"]
        and bool(exact_wp)
        and wp_streamed <= 1.2 * n * 4
    )


def bench_serve(on_tpu: bool):
    """Resident-dataset query server (serve/): queries/sec and p50/p99
    request latency per tier at client concurrency {1, 8, 64}, the
    batch-width histogram snapshot, plus the ISSUE 18 hot-path records:
    the cold-vs-warm first-query latency split (``warmup`` on/off, the
    compile wall attributed via the ledger's ``serve.programs`` site
    book) and the sketch-tier fast-path on/off qps comparison.
    ``exact_match`` REQUIRES bit-equality between the server's answers
    (exact and auto tiers, every concurrency level, both first-query
    legs) and one-at-a-time ``api.kselect`` over the same resident bits;
    sketch-tier answers must bracket the true value with their exact
    bounds. Latency here includes the coalescing window (2 ms) — that is
    the serving trade the batcher makes: a bounded latency add buys one
    shared-pass walk per concurrent burst. Acceptance gates: fast-path
    sketch qps >= 2x the queued path at concurrency 64, and the warmed
    dataset's first exact query runs with ZERO on-path compiles."""
    import threading

    import numpy as np

    from mpi_k_selection_tpu import api
    from mpi_k_selection_tpu.obs import MetricsRegistry, Observability
    from mpi_k_selection_tpu.obs.ledger import LEDGER, snapshot_delta
    from mpi_k_selection_tpu.serve import KSelectServer
    from mpi_k_selection_tpu.utils import datagen

    n = 1 << 24 if on_tpu else 1 << 20
    x = datagen.generate(n, pattern="uniform", seed=11, dtype=np.int32)
    queries_per_cell = 192 if on_tpu else 48
    ks_pool = [1 + (i * 104729) % n for i in range(queries_per_cell)]
    ref = {k: np.asarray(api.kselect(x, k)).item() for k in sorted(set(ks_pool))}
    s_host = np.sort(x, kind="stable")
    exact = True

    def storm(srv, dataset, tier, conc, pool):
        """One concurrency cell: ``conc`` client threads splitting
        ``pool``, per-query wall latencies + bit/bounds checks."""
        nonlocal exact
        lat: list[float] = []
        mismatches = []
        lock = threading.Lock()
        shards = [pool[i::conc] for i in range(conc)]

        def worker(shard):
            mine, bad = [], 0
            for k in shard:
                t0 = time.perf_counter()
                a = srv.kselect(dataset, k, tier=tier)
                mine.append(time.perf_counter() - t0)
                if a.tier == "sketch":
                    v_lo, v_hi = a.value_bounds
                    if not v_lo <= s_host[k - 1] <= v_hi:
                        bad += 1
                elif int(a.value) != ref[k]:
                    bad += 1
            with lock:
                lat.extend(mine)
                if bad:
                    mismatches.append(bad)

        threads = [
            threading.Thread(target=worker, args=(sh,)) for sh in shards if sh
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if mismatches:
            exact = False
        lat.sort()
        return {
            "qps": round(len(lat) / max(wall, 1e-9), 1),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "p99_ms": round(
                lat[min(len(lat) - 1, (99 * len(lat)) // 100)] * 1e3, 3
            ),
        }

    # -- cold vs warm first-query (ISSUE 18): distinct n per leg so the
    # process-wide jit cache cannot lend either leg the other's compile
    first_query = {}
    compile_books = {}
    for leg, warm, extra in (("cold", False, 4099), ("warm", True, 8209)):
        n_leg = n + extra
        x_leg = datagen.generate(n_leg, pattern="uniform", seed=13, dtype=np.int32)
        k_probe = 1 + (n_leg // 3)
        v_ref = np.asarray(api.kselect(x_leg, k_probe)).item()
        with KSelectServer() as srv:
            reg0 = LEDGER.snapshot()
            srv.add_dataset("fq", x_leg, warmup=warm)
            snap0 = LEDGER.snapshot()
            t0 = time.perf_counter()
            a = srv.kselect("fq", k_probe, tier="exact")
            first_query[leg] = round(time.perf_counter() - t0, 6)
            if int(a.value) != v_ref:
                exact = False
            on_path = snapshot_delta(snap0, LEDGER.snapshot())["sites"].get(
                "serve.programs", {}
            )
            reg_book = snapshot_delta(reg0, snap0)["sites"].get(
                "serve.programs", {}
            )
            compile_books[leg] = {
                "registration_compiles": reg_book.get("compiles", 0),
                "registration_compile_seconds": round(
                    reg_book.get("compile_seconds", 0.0), 6
                ),
                "on_path_compiles": on_path.get("compiles", 0),
                "on_path_compile_seconds": round(
                    on_path.get("compile_seconds", 0.0), 6
                ),
            }
    warm_excludes_compile_wall = compile_books["warm"]["on_path_compiles"] == 0

    # -- sketch-tier fast path on/off (ISSUE 18): the same query storm
    # against the same bits, answered inline vs through the lane
    fast_pool = [1 + (i * 104729) % n for i in range(4 * queries_per_cell)]
    fastpath_out = {}
    for label, enabled in (("on", True), ("off", False)):
        with KSelectServer(window=0.002, fast_path=enabled) as srv:
            srv.add_dataset("bench", x)
            srv.kselect("bench", 1, tier="sketch")  # open the path once
            fastpath_out[label] = {
                str(conc): storm(srv, "bench", "sketch", conc, fast_pool)
                for conc in (1, 8, 64)
            }
    fastpath_speedup_64 = round(
        fastpath_out["on"]["64"]["qps"]
        / max(fastpath_out["off"]["64"]["qps"], 1e-9),
        2,
    )

    obs = Observability(metrics=MetricsRegistry())
    tiers_out = {}
    with KSelectServer(window=0.002, obs=obs) as srv:
        srv.add_dataset("bench", x)
        srv.kselect("bench", 1, tier="exact")  # warm compile + cache
        for tier in ("sketch", "exact", "auto"):
            tiers_out[tier] = {
                str(conc): storm(srv, "bench", tier, conc, ks_pool)
                for conc in (1, 8, 64)
            }
        width = obs.metrics.histogram("serve.batch_width").as_dict()
        cache = srv.collect_metrics().as_dict()
        lanes = srv.batcher.lane_summary()
    _emit(
        {
            "metric": "serve_kselect_qps",
            # headline: exact-tier throughput under the widest burst
            "value": tiers_out["exact"]["64"]["qps"] if exact else 0.0,
            "unit": "queries/sec",
            "n": n,
            "window_s": 0.002,
            "queries_per_cell": queries_per_cell,
            "tiers": tiers_out,
            "first_query_seconds": first_query,
            "first_query_compile_books": compile_books,
            "fastpath_qps": fastpath_out,
            "fastpath_speedup_64": fastpath_speedup_64,
            "lanes": lanes,
            "batch_width": {
                key: width.get(key) for key in ("count", "mean", "max")
            },
            "program_cache": {
                "hits": cache["serve.program_cache.hits"]["value"],
                "misses": cache["serve.program_cache.misses"]["value"],
            },
            "exact_match": bool(exact),
        }
    )
    return bool(
        exact and warm_excludes_compile_wall and fastpath_speedup_64 >= 2.0
    )


def bench_chaos(on_tpu: bool):
    """Resilience cost + seeded recovery on the spill config (ISSUE 9).

    Two legs over the SAME stream (radix_bits=4 + tiny budget — the deep
    spill descent, the config every recovery hook sits on):

    - **fault-free overhead**: wall time with ``retry="off"`` (the
      pre-resilience PR 8 path) vs ``retry`` at its default (policies
      armed, no faults injected) — best-of-5 each, interleaved so host
      drift hits both legs alike. The acceptance gate is
      ``overhead_frac <= 0.02``: the policies are O(1) checks per
      chunk/pass, so arming them must be ~free.
    - **seeded chaos recovery**: the same descent under
      ``FaultPlan.seeded`` (transient source/stage raises, spill-record
      corruption, stalls through a VirtualSleeper so backoff costs no
      wall time), REQUIRING the recovered answer be bit-identical to
      the fault-free one, and reporting what fired and which recovery
      actions ran.
    """
    import numpy as np

    from mpi_k_selection_tpu import faults
    from mpi_k_selection_tpu.obs import ListSink, Observability
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect

    n, chunk = (1 << 24, 1 << 21) if on_tpu else (1 << 21, 1 << 18)
    nchunks, k = n // chunk, n // 2
    rb, budget = 4, 512

    def gen(i):
        return np.random.default_rng(77 + i).integers(
            -(2**31), 2**31 - 1, size=chunk, dtype=np.int32
        )

    source = lambda: (gen(i) for i in range(nchunks))
    kw = dict(radix_bits=rb, collect_budget=budget, spill="force")

    # warmup compiles every program both timed legs hit
    streaming_kselect(source, k, **kw)

    best_off = best_on = float("inf")
    ans_off = ans_on = None
    for _ in range(5):
        t0 = time.perf_counter()
        ans_off = streaming_kselect(source, k, retry="off", **kw)
        best_off = min(best_off, time.perf_counter() - t0)
        t0 = time.perf_counter()
        ans_on = streaming_kselect(source, k, **kw)  # default policy
        best_on = min(best_on, time.perf_counter() - t0)
    overhead = best_on / best_off - 1.0

    vs = faults.VirtualSleeper()
    obs = Observability(events=ListSink())
    plan = faults.FaultPlan.seeded(9, n_chunks=nchunks, faults=4)
    with faults.inject(plan, sleeper=vs, obs=obs) as inj:
        ans_chaos = streaming_kselect(
            inj.wrap_chunk_source(source), k,
            retry=faults.RetryPolicy(sleeper=vs), obs=obs, **kw,
        )
    exact = int(ans_off) == int(ans_on) == int(ans_chaos)
    gate = 0.02
    ok = exact and overhead <= gate
    _emit(
        {
            "metric": "kselect_chaos_resilience",
            # headline: fault-free throughput WITH the policies armed —
            # the number that must not regress vs the PR 8 spill record
            "value": round(n / best_on, 1) if exact else 0.0,
            "unit": "elems/sec/chip",
            "n": n,
            "k": k,
            "chunks": nchunks,
            "radix_bits": rb,
            "collect_budget": budget,
            "seconds_retry_off": round(best_off, 6),
            "seconds_retry_default": round(best_on, 6),
            "overhead_frac": round(overhead, 4),
            "overhead_gate": gate,
            "chaos": {
                "seed": 9,
                "fired": list(inj.fired),
                "recovery_actions": sorted(
                    {
                        e.action
                        for e in obs.events.of_kind("fault")
                        if e.action != "inject"
                    }
                ),
                "virtual_backoff_seconds": round(vs.total, 4),
                "recovered_exact": int(ans_chaos) == int(ans_off),
            },
            "exact_match": bool(exact),
        }
    )
    return ok


def bench_monitor(on_tpu: bool):
    """Continuous windowed quantiles (ISSUE 10, monitor/): the two
    claims the subsystem makes, measured.

    - **O(1) amortized window advance**: per-epoch cost of
      (fold one bucket of data, advance, full-window ``query()``) must
      be FLAT in window length — the two-stack suffix aggregation does
      ~2 sketch merges per epoch whether the ring holds 8 buckets or
      256. The gate is ``advance_flat_ratio <= 1.5`` between window=8
      and window=256 (a from-scratch re-merge would be ~32x).
    - **Bit-identity of ring re-aggregation**: at several epochs (ring
      not yet full, just full, wrapped several times) ``query()`` must
      equal a from-scratch RadixSketch fold of the same live buckets —
      and the decayed variant's fold must be grouping-invariant.
      ``exact_match`` requires all of it.
    """
    import numpy as np

    from mpi_k_selection_tpu.monitor import (
        DecayedWindowedSketch,
        WindowedSketch,
    )
    from mpi_k_selection_tpu.streaming.sketch import RadixSketch

    windows = (8, 64, 256)
    bucket_elems = 1 << 15 if on_tpu else 1 << 13
    epochs = 640  # >= 2.5 full wraps of the largest ring
    # 4 bits x 3 levels (~34 KB/bucket): the ring's merge count is the
    # quantity under test, and the default 4x4 sketch's 0.56 MB buckets
    # would let LLC pressure (256 live buckets = 143 MB) masquerade as
    # a merge-count slope
    skw = dict(radix_bits=4, levels=3)
    rng = np.random.default_rng(55)
    data = [
        rng.integers(-(2**31), 2**31 - 1, size=bucket_elems, dtype=np.int32)
        for _ in range(8)
    ]  # 8 distinct buckets cycled — contents must not matter to the cost

    exact = True
    per_window = {}
    for w in windows:
        ws = WindowedSketch(np.int32, window=w, **skw)
        # warm allocations / first-touch
        for e in range(4):
            ws.update(data[e % len(data)])
            ws.query()
            ws.advance()
        ws = WindowedSketch(np.int32, window=w, **skw)
        check_epochs = {0, w - 1, w, 2 * w + 3, epochs - 1}
        t0 = time.perf_counter()
        for e in range(epochs):
            c = data[e % len(data)]
            ws.update(c)
            m = ws.query()
            if e in check_epochs:
                # from-scratch merge of the same live buckets — any
                # grouping must be bitwise identical (pause the clock:
                # the oracle fold is O(window), the thing under test is
                # not allowed to be)
                t_pause = time.perf_counter()
                scratch = RadixSketch(np.int32, **skw)
                for b in ws.live_buckets():
                    scratch.fold_scaled(b, 1)
                exact = exact and (m == scratch)
                t0 += time.perf_counter() - t_pause
            ws.advance()
        per_window[w] = (time.perf_counter() - t0) / epochs
    flat_ratio = per_window[windows[-1]] / per_window[windows[0]]

    # decayed leg: fold-order invariance + the degenerate identity
    dws = DecayedWindowedSketch(np.int32, window=8, decay=0.5)
    base = WindowedSketch(np.int32, window=8)
    for e in range(12):
        dws.update(data[e % len(data)])
        base.update(data[e % len(data)])
        if e < 11:
            dws.advance()
            base.advance()
    md = dws.query()
    fwd = dws.query()  # two independent folds, same buckets/ages
    exact = exact and (md == fwd)
    d1 = DecayedWindowedSketch(np.int32, window=8, decay=1.0)
    for e in range(12):
        d1.update(data[e % len(data)])
        if e < 11:
            d1.advance()
    m1, mb = d1.query(), base.query()
    exact = exact and m1.quantiles([0.5, 0.9, 0.99]) == mb.quantiles(
        [0.5, 0.9, 0.99]
    )

    gate = 1.5
    ok = exact and flat_ratio <= gate
    _emit(
        {
            "metric": "monitor_window_advance",
            # headline: monitored elements per second at the largest ring
            "value": (
                round(bucket_elems / per_window[windows[-1]], 1) if exact else 0.0
            ),
            "unit": "elems/sec",
            "bucket_elems": bucket_elems,
            "epochs": epochs,
            "seconds_per_advance": {
                str(w): round(s, 7) for w, s in per_window.items()
            },
            "advance_flat_ratio": round(flat_ratio, 4),
            "advance_flat_gate": gate,
            "decayed_fold_invariant": bool(md == fwd),
            "exact_match": bool(exact),
        }
    )
    return ok


def bench_cgm_native():
    """BASELINE config: CGM/MPI parity backend, 4 ranks, N=16M, k=N/2.

    Single-shot wall time (includes fork + shm setup — the analogue of one
    `mpirun -np 4` launch of the reference, `TODO-kth-problem-cgm.c`)."""
    import numpy as np

    from mpi_k_selection_tpu.errors import NativeUnavailableError
    from mpi_k_selection_tpu.utils import datagen

    try:
        from mpi_k_selection_tpu.backends import mpi as mpi_backend

        n = 1 << 24
        k = n // 2
        x = datagen.generate(n, pattern="uniform", seed=3, dtype=np.int32)
        want = int(np.sort(x, kind="stable")[k - 1])
        t0 = time.perf_counter()
        got = int(mpi_backend.kselect(x, k, num_procs=4))
        dt = time.perf_counter() - t0
        exact = got == want
        _emit(
            {
                "metric": "cgm_mpi_16m_4ranks",
                "value": round(n / dt, 1) if exact else 0.0,
                "unit": "elems/sec",
                "vs_baseline": 1.0 if exact else 0.0,
                "n": n,
                "k": k,
                "seconds": round(dt, 6),
                "exact_match": exact,
            }
        )
        return exact
    except Exception as e:
        _emit({"metric": "cgm_mpi_16m_4ranks", "value": 0.0, "unit": "elems/sec",
               "vs_baseline": 0.0, "error": str(e)[:200]})
        # only a missing native toolchain is tolerable (typed, so a reworded
        # message can't change the outcome); a crash in the backend itself
        # must fail the bench exit code
        return isinstance(e, NativeUnavailableError)


def bench_seq_oracle():
    """BASELINE config: the seq program's own workload (N=1M int32, k=N/2)."""
    import numpy as np

    from mpi_k_selection_tpu.backends import seq
    from mpi_k_selection_tpu.utils import datagen

    n = 1 << 20
    k = n // 2
    x = datagen.generate(n, pattern="uniform", seed=4, dtype=np.int32)
    t0 = time.perf_counter()
    _ = int(seq.kselect_sort(x, k))
    dt = time.perf_counter() - t0
    _emit(
        {
            "metric": "seq_oracle_1m",
            "value": round(n / dt, 1),
            "unit": "elems/sec",
            "vs_baseline": 1.0,  # this IS the reference algorithm
            "n": n,
            "k": k,
            "seconds": round(dt, 6),
            "exact_match": True,
        }
    )
    return True


def main() -> int:
    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    ok = bench_kselect_headline(on_tpu)
    ok &= bench_kselect_1b(on_tpu)
    ok &= bench_topk_single(on_tpu)
    ok &= bench_topk_batched(on_tpu)
    ok &= bench_multirank(on_tpu)
    ok &= bench_multirank(
        on_tpu,
        qs=tuple(i / 10 for i in range(1, 10)),
        metric="multirank_deciles_k9",
        reps=(2, 8) if on_tpu else (1, 3),
    )
    ok &= bench_streaming_oc(on_tpu)
    ok &= bench_ingest_fusion(on_tpu)
    ok &= bench_serve(on_tpu)
    ok &= bench_chaos(on_tpu)
    ok &= bench_monitor(on_tpu)
    ok &= bench_cgm_native()
    ok &= bench_seq_oracle()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
