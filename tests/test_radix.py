"""radix_select vs the sequential oracle, across dtypes/patterns/k/methods."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.ops.radix import radix_select
from mpi_k_selection_tpu.utils import datagen, x64

N = 5000
KS = [1, 2, N // 2, N - 1, N]


@pytest.mark.parametrize("pattern", ["uniform", "seqlike", "descending", "equal"])
@pytest.mark.parametrize("k", KS)
def test_int32_matches_oracle(pattern, k):
    x = datagen.generate(N, pattern=pattern, seed=9, dtype=np.int32)
    want = seq.kselect(x, k)
    got = radix_select(jnp.asarray(x), k)
    assert int(got) == int(want)


@pytest.mark.parametrize("dtype", [np.uint32, np.int16, np.uint8])
def test_other_int_dtypes(dtype):
    rng = np.random.default_rng(11)
    info = np.iinfo(dtype)
    x = rng.integers(info.min, info.max, size=3001, endpoint=True, dtype=dtype)
    for k in (1, 1500, 3001):
        assert int(radix_select(jnp.asarray(x), k)) == int(seq.kselect(x, k))


def test_float32():
    x = datagen.generate(4096, pattern="normal", seed=2, dtype=np.float32)
    x[17] = 0.0
    x[18] = -0.0
    for k in (1, 5, 2048, 4096):
        got = float(radix_select(jnp.asarray(x), k))
        want = float(seq.kselect(x, k))
        assert got == want


def test_duplicates_heavy():
    # the E > 1 equal-count path of the reference's exact-hit test (TODO-…:194)
    rng = np.random.default_rng(3)
    x = rng.integers(0, 7, size=4001, dtype=np.int32)
    for k in (1, 1000, 2000, 4001):
        assert int(radix_select(jnp.asarray(x), k)) == int(seq.kselect(x, k))


@pytest.mark.parametrize("method", ["scatter", "onehot"])
def test_hist_methods_agree(method):
    x = datagen.generate(3333, pattern="uniform", seed=4, dtype=np.int32)
    k = 1234
    got = radix_select(jnp.asarray(x), k, hist_method=method, chunk=512)
    assert int(got) == int(seq.kselect(x, k))


@pytest.mark.parametrize("radix_bits", [4, 8, 16])
def test_radix_bits(radix_bits):
    x = datagen.generate(2048, pattern="uniform", seed=5, dtype=np.int32)
    k = 777
    got = radix_select(jnp.asarray(x), k, radix_bits=radix_bits)
    assert int(got) == int(seq.kselect(x, k))


def test_traced_k():
    x = jnp.asarray(datagen.generate(1024, pattern="uniform", seed=6, dtype=np.int32))

    @jax.jit
    def f(x, k):
        return radix_select(x, k)

    xs = np.asarray(x)
    for k in (1, 512, 1024):
        assert int(f(x, jnp.asarray(k))) == int(seq.kselect(xs, k))


def test_negative_values():
    rng = np.random.default_rng(8)
    x = rng.integers(-(2**31), 2**31 - 1, size=3000, dtype=np.int64).astype(np.int32)
    for k in (1, 1500, 3000):
        assert int(radix_select(jnp.asarray(x), k)) == int(seq.kselect(x, k))


def test_int64_under_x64():
    with x64.enable_x64():
        rng = np.random.default_rng(13)
        x = rng.integers(-(2**62), 2**62, size=2049, dtype=np.int64)
        for k in (1, 1025, 2049):
            got = radix_select(jnp.asarray(x), k)
            assert got.dtype == jnp.int64
            assert int(got) == int(seq.kselect(x, k))


def test_extremes_fixture():
    for name, x in datagen.adversarial_fixtures(1024, dtype=np.int32, seed=1):
        k = 100
        assert int(radix_select(jnp.asarray(x), k)) == int(seq.kselect(x, k)), name


@pytest.mark.parametrize("pattern", ["uniform", "descending", "equal", "seqlike"])
def test_early_exit_budget_matches_oracle(pattern):
    # opt-in cutover path (lax.cond pass skipping + survivor collection)
    n = 200_001
    x = datagen.generate(n, pattern=pattern, seed=13, dtype=np.int32)
    want = np.sort(x)
    for k in (1, n // 2, n):
        got = radix_select(jnp.asarray(x), k, early_exit_budget=4096)
        assert int(got) == int(want[k - 1]), (pattern, k)


def test_early_exit_duplicates_straddling_budget():
    rng = np.random.default_rng(17)
    x = np.repeat(rng.integers(0, 50, size=100, dtype=np.int32), 5000)
    rng.shuffle(x)
    want = np.sort(x)
    for k in (1, x.size // 2, x.size):
        got = radix_select(jnp.asarray(x), k, early_exit_budget=4096)
        assert int(got) == int(want[k - 1]), k


def test_early_exit_float32():
    rng = np.random.default_rng(19)
    x = rng.standard_normal(100_001).astype(np.float32)
    k = 31_337
    got = radix_select(jnp.asarray(x), k, early_exit_budget=4096)
    assert float(got) == float(np.sort(x)[k - 1])


@pytest.mark.parametrize("dtype", [np.int16, np.float16])
def test_radix_select_sub32_dtypes_with_pallas_cutover(rng, dtype):
    # sub-32-bit keys use widened uint32 tiles for the histogram passes but
    # must keep the native-width sortable keys for the cutover collect
    # (regression: uint16 vs uint32 cond-branch dtype mismatch, and a
    # wrong-width mshift had the dtypes been coerced)
    if dtype == np.int16:
        x = rng.integers(-30000, 30000, size=120001, dtype=np.int16)
    else:
        x = (rng.standard_normal(120001) * 100).astype(np.float16)
    k = 60000
    got = radix_select(
        jnp.asarray(x), k, hist_method="pallas", cutover=1, cutover_budget=65536,
        block_rows=256,
    )
    want = np.sort(x, kind="stable")[k - 1]
    assert np.asarray(got)[()] == want


def test_disable_jit_python_paths(rng):
    # SURVEY.md §4: run the python-level branches un-jitted (asserts,
    # validation, dispatch) — shapes stay tiny, semantics must not change
    import jax

    x = jnp.asarray(rng.integers(-1000, 1000, size=2049, dtype=np.int32))
    with jax.disable_jit():
        got = int(radix_select(x, 1025))
    assert got == int(np.sort(np.asarray(x))[1024])


def test_property_fuzz_random_configs(rng):
    # randomized sweep over (n, k, dtype, duplicates) vs the oracle —
    # SURVEY.md §4 "property tests (random N, k, dtypes, duplicates-heavy)".
    # n is drawn from a fixed odd-size grid: k is a TRACED operand, so
    # repeats of an (n, dtype) pair hit the jit cache — 25 fully-random n
    # meant 25 fresh compiles (~15 s of this test's runtime for no extra
    # path coverage; data and k stay random per trial)
    dtypes = [np.int32, np.uint32, np.int16, np.float32]
    sizes = [1, 977, 12_347, 69_999]
    for trial in range(24):
        n = sizes[(trial // 4) % len(sizes)]
        k = int(rng.integers(1, n + 1))
        dt = dtypes[trial % len(dtypes)]
        if rng.integers(0, 2):  # duplicates-heavy half the time
            base = rng.integers(0, max(2, n // 100) + 1, size=n)
        else:
            base = rng.integers(-(2**15), 2**15, size=n)
        x = base.astype(dt)
        got = np.asarray(radix_select(jnp.asarray(x), k))[()]
        want = np.sort(x, kind="stable")[k - 1]
        assert got == want, (trial, n, k, dt, got, want)
