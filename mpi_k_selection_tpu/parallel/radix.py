"""Distributed radix k-selection over a device mesh — the flagship path.

The TPU-native replacement for the reference's entire CGM protocol
(``TODO-kth-problem-cgm.c:103-293``). Where the reference scatters data,
iterates gather-medians -> bcast-pivot -> count -> allreduce -> physically
discard, and finally gathers survivors to rank 0, this path:

- keeps every shard resident in HBM and never moves an element
  (the reference's only bulk transfers — initial Scatterv ``:103`` and final
  Gatherv ``:270`` — become a one-time sharding annotation and nothing);
- runs a fixed number of histogram passes (key_bits / radix_bits); each pass
  is one local Pallas/XLA histogram + one ``lax.psum`` of the bucket counts
  over the ICI mesh — the direct analogue of the single
  ``MPI_Allreduce(leg, 3, SUM)`` at ``TODO-…:190``, except 4 rounds total
  instead of O(log N) rounds;
- computes the bucket walk replicated on every device (the reference computes
  the weighted median only on rank 0 and broadcasts, ``:139-168``; SPMD
  replication makes the Bcast implicit).

Per-pass communication is one small vector of counts, independent of N —
the same "O(p) scalars per round" property SURVEY.md §3.2 identifies as the
reference's key design feature, mapped onto ICI collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
from mpi_k_selection_tpu.ops.radix import (
    bucket_walk_step,
    default_radix_bits,
    select_count_dtype,
)
from mpi_k_selection_tpu.parallel import mesh as mesh_lib
from mpi_k_selection_tpu.utils import debug as _debug, dtypes as _dt


def _prep_shard(hist_method, xs):
    """Per-shard kernel-view prep: raw tiles + in-kernel key fold when
    available (saves the per-shard to_sortable pass — see
    ops/histogram.py:prepare_raw), key-space tiles otherwise. Returns
    ``(u, tiles, tiles_n, key_op, key_xor)`` with ``u`` None on the raw
    path."""
    from mpi_k_selection_tpu.ops.histogram import prepare_keys, prepare_raw

    raw = prepare_raw(hist_method, xs)
    if raw is not None:
        tiles, tiles_n, key_op, key_xor = raw
        return None, tiles, tiles_n, key_op, key_xor
    u = _dt.to_sortable_bits(xs)
    tiles, tiles_n = prepare_keys(hist_method, u)
    return u, tiles, tiles_n, "none", 0


@functools.lru_cache(maxsize=64)
def _jitted_select(mesh, n, total_bits, cdt, radix_bits, hist_method, chunk):
    """Build-and-cache the jitted sharded program for one (mesh, config).

    Rebuilding shard_map + jit per call would force a retrace/recompile on
    every invocation (jit caches are per jit *object*); caching here makes
    repeat calls hit the XLA executable cache like any other jitted fn.
    """
    axis = mesh.axis_names[0]

    def shard_fn(xs, kk):
        u, tiles, tiles_n, key_op, key_xor = _prep_shard(hist_method, xs.ravel())
        kdt = jnp.dtype(_dt.key_dtype(xs.dtype))
        kk = jnp.clip(kk.astype(cdt), 1, n)
        prefix = None
        for p in range(total_bits // radix_bits):
            shift = total_bits - (p + 1) * radix_bits
            local = masked_radix_histogram(
                u,
                shift=shift,
                radix_bits=radix_bits,
                prefix=prefix,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=tiles,
                orig_n=tiles_n,
                key_op=key_op,
                key_xor=key_xor,
            )
            hist = jax.lax.psum(local, axis)  # the MPI_Allreduce analogue (TODO-…:190)
            prefix, kk, _ = bucket_walk_step(hist, kk, prefix, kdt, radix_bits)
        return _dt.from_sortable_bits(prefix, xs.dtype)

    fn = jax.shard_map(shard_fn, mesh=mesh, in_specs=(P(axis), P()), out_specs=P())
    return jax.jit(fn)


def distributed_radix_select(
    x: jax.Array,
    k,
    *,
    mesh=None,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
):
    """Exact k-th smallest (1-indexed) of sharded ``x``; replicated scalar out."""
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)

    x = jnp.ravel(jnp.asarray(x))
    _debug.check_concrete_k(k, x.shape[0])
    if radix_bits is None:
        radix_bits = default_radix_bits(x.dtype, hist_method)
    x, n = mesh_lib.pad_to_multiple(x, mesh.size)
    # counts are sized for the padded total: sentinels are counted too, and
    # padding can push the histogram total past the unpadded dtype boundary
    cdt = select_count_dtype(x.shape[0])
    total_bits = _dt.key_bits(x.dtype)
    if total_bits % radix_bits:
        raise ValueError(f"radix_bits={radix_bits} must divide {total_bits}")

    fn = _jitted_select(mesh, n, total_bits, cdt, radix_bits, hist_method, chunk)
    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    kk = jnp.asarray(k, cdt)
    return fn(xs, kk)


@functools.lru_cache(maxsize=64)
def _jitted_select_many(mesh, n, total_bits, cdt, radix_bits, hist_method, chunk):
    """Sharded multi-rank selection: the shard's tiled view and the
    prefix-free first pass (one local histogram + one ``psum``) are shared
    by every query, and each later pass runs ALL K queries through one
    shared sweep of the shard (the multi-prefix kernels) followed by one
    ``psum`` of the (K, nbuckets) counts — the shard is read ``npasses``
    times total instead of ``1 + K * (npasses - 1)``, and communication
    stays one small psum per pass for the whole batch."""
    axis = mesh.axis_names[0]
    npasses = total_bits // radix_bits

    def shard_fn(xs, ks):
        from mpi_k_selection_tpu.ops.histogram import multi_masked_radix_histogram
        from mpi_k_selection_tpu.ops.radix import bucket_walk_step_multi

        u, tiles, tiles_n, key_op, key_xor = _prep_shard(hist_method, xs.ravel())
        kdt = jnp.dtype(_dt.key_dtype(xs.dtype))

        hist0 = jax.lax.psum(
            masked_radix_histogram(
                u,
                shift=total_bits - radix_bits,
                radix_bits=radix_bits,
                prefix=None,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=tiles,
                orig_n=tiles_n,
                key_op=key_op,
                key_xor=key_xor,
            ),
            axis,
        )
        kk = jnp.clip(ks.astype(cdt), 1, n)
        prefixes, kk, _ = bucket_walk_step_multi(hist0, kk, None, kdt, radix_bits)
        for p in range(1, npasses):
            shift = total_bits - (p + 1) * radix_bits
            local = multi_masked_radix_histogram(
                u,
                shift=shift,
                radix_bits=radix_bits,
                prefixes=prefixes,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=tiles,
                orig_n=tiles_n,
                key_op=key_op,
                key_xor=key_xor,
            )
            hist = jax.lax.psum(local, axis)  # (K, nbuckets), one collective
            prefixes, kk, _ = bucket_walk_step_multi(
                hist, kk, prefixes, kdt, radix_bits
            )
        return _dt.from_sortable_bits(prefixes, xs.dtype)

    fn = jax.shard_map(shard_fn, mesh=mesh, in_specs=(P(axis), P()), out_specs=P())
    return jax.jit(fn)


def distributed_radix_select_many(
    x: jax.Array,
    ks,
    *,
    mesh=None,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
):
    """Exact k-th smallest of sharded ``x`` for every (1-indexed) k in
    ``ks``; replicated vector out, in ``ks`` order."""
    if mesh is None:
        mesh = mesh_lib.make_mesh()
    mesh_lib.require_distributed(mesh)

    x = jnp.ravel(jnp.asarray(x))
    ks_arr = jnp.atleast_1d(jnp.asarray(ks))
    _debug.check_concrete_ks(ks_arr, x.shape[0])
    if radix_bits is None:
        radix_bits = default_radix_bits(x.dtype, hist_method)
    x, n = mesh_lib.pad_to_multiple(x, mesh.size)
    cdt = select_count_dtype(x.shape[0])
    total_bits = _dt.key_bits(x.dtype)
    if total_bits % radix_bits:
        raise ValueError(f"radix_bits={radix_bits} must divide {total_bits}")

    fn = _jitted_select_many(mesh, n, total_bits, cdt, radix_bits, hist_method, chunk)
    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    return fn(xs, ks_arr.astype(cdt).ravel()).reshape(ks_arr.shape)
