"""Out-of-core exact k-selection over chunked streams.

Every resident selection path (ops/radix.py, parallel/radix.py) requires the
whole array on device, bounding serviceable ``n`` by HBM. This module removes
that bound: the input is a *chunk source* — host arrays, device arrays, or a
replayable generator — and each radix pass streams the chunks through the
device one at a time, accumulating ONE digit histogram for the whole stream.
The cross-pass state is the same two scalars as the resident descent
(prefix, k), so chunks are free to be discarded (and regenerated, or re-read
from disk) between passes. This is the reference CGM's own discipline — scan
local data, exchange a small summary, discard, repeat
(``TODO-kth-problem-cgm.c:103-293``) — applied across *time* instead of
across ranks.

Exactness: histogram counts are integers accumulated host-side in int64, so
the walk is exact for ``n`` up to 2^63 regardless of jax's x64 mode (the
per-chunk device counts stay int32 — a chunk never exceeds 2^31 elements).
Keys are produced by the host transform (utils/dtypes.py:np_to_sortable_bits)
for host chunks — which makes streaming float64 selection bit-exact even on
TPU, where resident f64 device storage truncates to ~49 bits — and by the
device transform for device chunks.

Termination mirrors ops/radix.py's cutover: as soon as the surviving
population fits ``collect_budget``, one extra streaming pass collects the
survivors host-side and a tiny partition finishes — so uniform-ish data pays
~2 passes + collect instead of the full ``key_bits / radix_bits`` schedule.

Ingest is pipelined by default (``pipeline_depth=2``): a background
producer thread runs chunk *i+1*'s production, host key-encode and
host->device staging while chunk *i* histograms on device — see
streaming/pipeline.py. ``pipeline_depth=0`` is the fully synchronous
path, kept as the correctness oracle; both return bit-identical answers.

With ``devices`` > 1 the pipelined passes also spread across chips: the
producer stages chunk *j* onto ``devices[j % p]`` (round-robin) and the
consumer keeps one histogram dispatch in flight per device
(streaming/executor.py:StreamExecutor), merging the per-device int32 partials into
the host int64 accumulator strictly in chunk order — the pipelined twin
of ``parallel/sketch.py:distributed_sketch``'s psum merge, and because
the merge order is fixed (and int64 addition is exact), answers stay
bit-identical for every device count. ``devices=1`` (or ``None``) is the
single-device PR 3 path.

The ``spill`` knob adds the reference CGM's OTHER perf idea — the discard
step — to this axis (streaming/spill.py): pass 0 tees each chunk's
encoded keys to an on-disk survivor store, and every later pass reads the
previous generation, filters it to the surviving prefixes on the owning
device, and writes only the compacted ~1/2^radix_bits as the next
generation, so the replay above becomes a geometrically shrinking
generation read (~N·(2 + 1/2^b + ...) total bytes instead of ~passes·N)
and one-shot sources become first-class. ``spill="off"`` is the pure
replay path, bit-identical to the spill path at every devices x depth
combination.

Per-chunk consumption — the histogram merge, the survivor collect, the
rank-certificate count folds, and the spill tee — runs under ONE
event-driven scheduler (streaming/executor.py:StreamExecutor) with
**deferred host transfers** (the ``deferred`` knob): each staged chunk's
work dispatches as a device-side handle (for the collect and the tee, a
jit-compiled mask -> count -> fixed-shape compaction per staging bucket)
and materializes host-side only when the in-flight FIFO window pops —
so on a multi-device pass the consumer no longer blocks per chunk on an
eager boolean gather, and the staged buffer is released exactly when its
last in-flight result lands. ``deferred="off"`` is the pre-executor
eager path; answers are bit-identical across the whole devices x depth x
spill x deferred grid. With deferral on, spill generation reads also use
mmap-backed record payloads (no per-record heap copy of the bytes the
device filter is about to discard).
"""

from __future__ import annotations

import contextlib
import errno as _errno
import warnings

import numpy as np

from mpi_k_selection_tpu import errors as _err
from mpi_k_selection_tpu.faults import policy as _fp
from mpi_k_selection_tpu.obs import events as _ev
from mpi_k_selection_tpu.obs import ledger as _ldg
from mpi_k_selection_tpu.obs import metrics as _om
from mpi_k_selection_tpu.obs import wiring as _wr
from mpi_k_selection_tpu.streaming import executor as _ex
from mpi_k_selection_tpu.streaming import pipeline as _pl
from mpi_k_selection_tpu.streaming import spill as _sp
from mpi_k_selection_tpu.streaming.executor import DEFAULT_DEFERRED, DEFAULT_FUSED
from mpi_k_selection_tpu.streaming.pipeline import DEFAULT_PIPELINE_DEPTH, StagedKeys
from mpi_k_selection_tpu.utils import dtypes as _dt

DEFAULT_COLLECT_BUDGET = 1 << 20

#: Default for the ``spill`` knob: spill only when the source cannot be
#: replayed (a one-shot iterator/generator) — replayable sources keep the
#: bit-identical replay path unless ``"force"`` asks for the spill descent.
DEFAULT_SPILL = "auto"

#: Widest digit one streamed pass may histogram. Bounded by the KSC102
#: counter discipline — per-chunk device counts are int32 partials over
#: ``2**width`` buckets (a chunk never exceeds 2^31 elements, so any
#: single bucket's partial is int32-exact at ANY width; the cap is the
#: device histogram MEMORY: 2^20 int32 bins = 4 MiB per in-flight
#: (prefix, chunk) dispatch, the same bound as
#: streaming/sketch.py:_MAX_RESOLUTION_BITS). Wider would trade the
#: saved ingest bytes for multi-MiB scatter targets per window slot.
MAX_PASS_BITS = 20

#: Default for ``width_schedule``: ``"off"`` keeps the fixed
#: one-radix-digit-per-pass schedule (byte-for-byte the historical
#: descent). ``"auto"`` is opt-in until validated on silicon — flip after
#: a tpu_smoke run confirms the wide pass-0 win end to end (ROADMAP).
DEFAULT_WIDTH_SCHEDULE = "off"

#: Default for ``pack_spill`` (streaming/spill.py:PACK_SPILL_MODES):
#: ``"off"`` writes the historical full-width v1 records; ``"auto"``
#: prefix-packs survivor generations (format v2) wherever packing wins.
DEFAULT_PACK_SPILL = "off"

WIDTH_SCHEDULE_MODES = ("auto", "off")


def validate_width_schedule(width_schedule):
    """Normalize the ``width_schedule`` knob: ``"auto"``, ``"off"``
    (``None`` = off), or an explicit per-pass digit-width tuple. Widths
    outside ``[1, MAX_PASS_BITS]`` are refused LOUDLY here — a wider
    digit would blow the device histogram budget the int32-partial
    counter discipline (KSC102) is sized for — before any stream is
    touched."""
    if width_schedule is None:
        return "off"
    if width_schedule in WIDTH_SCHEDULE_MODES:
        return width_schedule
    if isinstance(width_schedule, str):
        raise ValueError(
            f"width_schedule must be one of {WIDTH_SCHEDULE_MODES} or a "
            f"tuple of per-pass digit widths, got {width_schedule!r}"
        )
    try:
        widths = tuple(int(w) for w in width_schedule)
    except TypeError:
        raise ValueError(
            f"width_schedule must be one of {WIDTH_SCHEDULE_MODES} or a "
            f"tuple of per-pass digit widths, got {width_schedule!r}"
        ) from None
    if not widths:
        raise ValueError("width_schedule tuple must name at least one pass")
    for w in widths:
        if not 1 <= w <= MAX_PASS_BITS:
            raise ValueError(
                f"width_schedule pass width {w} outside [1, {MAX_PASS_BITS}]"
                ": a streamed pass histograms 2**width int32 device "
                "partials per in-flight (prefix, chunk) dispatch (KSC102's "
                "counter discipline), so wider digits would overflow the "
                f"device histogram budget (2**{MAX_PASS_BITS} bins = "
                "4 MiB); split the schedule into more passes instead"
            )
    return widths


def resolve_width_schedule(
    width_schedule, total_bits: int, radix_bits: int, start_bits: int = 0
) -> tuple:
    """Resolve a validated ``width_schedule`` against the stream's key
    geometry (known only at dtype-probe time): the returned tuple's
    widths sum to ``total_bits - start_bits`` (``start_bits`` = a seeding
    sketch's resolved depth). ``"off"`` reproduces the fixed
    ``radix_bits`` schedule exactly (including its divisibility error);
    ``"auto"`` front-loads wide passes — the largest width <= 16 that
    leaves the remainder on radix_bits boundaries — so generation 0
    shrinks by ~2^w0 and the second full-N read disappears, while later
    passes keep the narrow kernel-friendly digits. 64-bit keys (> 32
    remaining bits) get a SECOND wide pass by the same rule: with ~48
    bits still unresolved after pass 0, generation 1 is otherwise still
    descended by narrow digits for 5+ more full-generation reads — a
    second 2^w1 shrink retires most of them (each pass stays within the
    KSC102 2**MAX_PASS_BITS int32-partial budget independently)."""
    remaining = total_bits - start_bits
    if width_schedule == "off":
        if remaining % radix_bits:
            if start_bits:
                raise ValueError(
                    f"radix_bits={radix_bits} must divide the {remaining} "
                    f"key bits left below the resolved {start_bits} bits"
                )
            raise ValueError(
                f"radix_bits={radix_bits} must divide key bits {total_bits}"
            )
        return (radix_bits,) * (remaining // radix_bits)
    if width_schedule == "auto":
        for w in range(min(16, remaining), 0, -1):
            if (remaining - w) % radix_bits == 0:
                rem = remaining - w
                head = (w,)
                if rem > 16 and w > radix_bits and remaining > 32:
                    # 64-bit keys: a second STRICTLY-wide pass (> the
                    # narrow digit, same <= 16 budget, remainder still on
                    # radix_bits boundaries) — 32-bit schedules are
                    # untouched (remaining <= 32 never enters here)
                    for w2 in range(min(16, rem), radix_bits, -1):
                        if (rem - w2) % radix_bits == 0:
                            head += (w2,)
                            rem -= w2
                            break
                return head + (radix_bits,) * (rem // radix_bits)
        # radix_bits > 16 with remaining on its boundaries: no wide first
        # pass fits under the budget — keep the fixed schedule
        return (radix_bits,) * (remaining // radix_bits)
    widths = tuple(width_schedule)
    if sum(widths) != remaining:
        raise ValueError(
            f"width_schedule {widths} resolves {sum(widths)} bits but the "
            f"descent must resolve {remaining}"
            + (
                f" ({total_bits} key bits minus the sketch's {start_bits} "
                "resolved)"
                if start_bits
                else f" ({total_bits} key bits)"
            )
        )
    return widths


def _pass_method(method, width: int):
    """Per-pass histogram method: digits wider than 8 bits exceed the
    SWAR/pallas kernels' radix support (ops/pallas/histogram.py,
    PR 13's rb <= 8 rule), so wide passes route device counting through
    the scatter path — the same method the sketch's deep
    ``resolution_bits``-wide fold already uses on device — while the
    host-exact ``"numpy"`` route is width-agnostic and stays put."""
    if width <= 8 or method == "numpy":
        return method
    return "scatter"


def _is_device_array(chunk) -> bool:
    import jax

    return isinstance(chunk, jax.Array)


def _tpu_backend() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def _is_one_shot_source(source) -> bool:
    """True for a bare iterator/generator — consumable exactly once."""
    if callable(source) or isinstance(source, (list, tuple, np.ndarray)):
        return False
    if isinstance(source, _sp.SpillStore) or _is_device_array(source):
        return False
    return hasattr(source, "__iter__") or hasattr(source, "__next__")


class _OneShotSource:
    """The spill path's wrapper for a bare iterator: pass 0 consumes it
    once (teeing every chunk to the spill store); any second invocation is
    a bug in the spill descent — passes >= 1 must read spill generations —
    and raises instead of silently yielding an empty (or drifted) stream."""

    def __init__(self, it):
        self._it = iter(it)
        self._used = False

    def __call__(self):
        if self._used:
            raise RuntimeError(
                "one-shot chunk source invoked a second time: the spill "
                "descent must serve every pass after pass 0 from the spill "
                "store. This is a bug in streaming/chunked.py, not in the "
                "caller's stream."
            )
        self._used = True
        return self._it


def as_chunk_source(
    source, *, one_shot_ok: bool = False, mmap: bool = False, workers: int = 1,
):
    """Normalize ``source`` to a zero-arg callable returning a fresh chunk
    iterator — the replayable form every streaming pass needs.

    Accepted: a list/tuple of arrays, a single array (one chunk), a
    zero-arg callable returning an iterable of arrays, or a
    :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore` with a
    committed generation (replayed from disk; ``mmap`` selects mmap-backed
    record payload reads — the deferred executor's replay mode, and
    ``workers`` > 1 decodes records on a ``ksel-ingest-decode-*`` pool,
    in-order — spill.py:SpillGeneration.iter_chunks). A bare
    one-shot iterator/generator is accepted only under ``one_shot_ok``
    (the spill descent: pass 0 tees it to disk and never reads it again);
    otherwise it is rejected with instructions — exact selection re-reads
    the stream once per radix pass, which a consumed generator cannot
    serve.
    """
    if isinstance(source, _sp.SpillStore):
        return source.latest_generation().as_source(mmap=mmap, workers=workers)
    if callable(source):
        return source
    if isinstance(source, (list, tuple)):
        return lambda: iter(source)
    if isinstance(source, np.ndarray) or _is_device_array(source):
        return lambda: iter((source,))
    if hasattr(source, "__iter__") or hasattr(source, "__next__"):
        if one_shot_ok:
            return _OneShotSource(source)
        raise TypeError(
            "streaming selection re-reads the data once per radix pass; a "
            "one-shot iterator/generator cannot be replayed. Pass a "
            "list/tuple of chunks or a zero-arg callable returning a fresh "
            "iterator (e.g. lambda: (load(i) for i in range(nchunks))) — or "
            "keep the one-shot stream and let the spill store serve the "
            "later passes: spill='auto'|'force' on the streaming entry "
            "points tees pass 0's encoded keys to disk (streaming/spill.py),"
            " and RadixSketch.update_stream(..., spill=store) does the same "
            "for the sketch-then-refine flow. For single-pass approximate "
            "answers, RadixSketch alone suffices."
        )
    raise TypeError(f"unsupported chunk source type {type(source).__name__!r}")


def _normalize_chunk(chunk, dtype):
    """The ORDER-SENSITIVE half of chunk encoding: ravel, the empty-skip,
    the 2^31 per-chunk counter guard, and the one-dtype-per-stream drift
    check — everything whose errors (and dtype adoption) must fire in
    source order. Returns the raveled chunk (host numpy or device array;
    a :class:`~mpi_k_selection_tpu.streaming.spill.SpillChunk` passes
    through whole), or ``None`` for an empty chunk. ``dtype`` is the
    stream dtype to validate against (``None`` = first chunk: the caller
    adopts the returned chunk's dtype). The pooled ingest plane
    (streaming/pipeline.py) runs THIS on its sequential puller and hands
    the result to a worker for :func:`_encode_normalized`; depth-0 and
    single-producer paths compose both via :func:`_encode_chunk`."""
    if isinstance(chunk, _sp.SpillChunk):
        # replayed spill record: keys are ALREADY the host key-space view
        # (encoded once, at pass-0 tee time) — validate the recorded
        # stream dtype and hand the chunk through whole
        if chunk.keys.size == 0:
            return None
        odt = np.dtype(chunk.orig_dtype)
        if dtype is not None and odt != np.dtype(dtype):
            raise TypeError(
                f"spill chunk dtype {odt} != stream dtype {np.dtype(dtype)}; "
                "streaming selection requires one dtype per stream"
            )
        return chunk
    if _is_device_array(chunk):
        c = chunk.ravel()
    else:
        c = np.ravel(np.asarray(chunk))
    if c.size == 0:
        return None
    if c.size >= 1 << 31:
        raise ValueError(
            f"chunk of {c.size} elements: per-chunk device histogram "
            "counts are int32-exact only below 2^31 elements — split "
            "the stream into smaller chunks (n is unbounded, chunks "
            "are not)"
        )
    if dtype is not None and np.dtype(c.dtype) != np.dtype(dtype):
        raise TypeError(
            f"chunk dtype {np.dtype(c.dtype)} != stream dtype "
            f"{np.dtype(dtype)}; streaming selection requires one dtype "
            "per stream"
        )
    return c


def _encodes_to_host(c) -> bool:
    """True when :func:`_encode_normalized` will produce HOST keys for
    normalized chunk ``c``: replayed spill records (already host
    key-space), host arrays, and the exact f64-on-TPU route (device f64
    keys are the ~49-bit approximation; the chunk decodes to host). The
    pooled puller uses this to pre-assign round-robin staging slots
    without encoding anything."""
    if isinstance(c, _sp.SpillChunk) or not _is_device_array(c):
        return True
    return np.dtype(c.dtype) == np.float64 and _tpu_backend()


def _encode_normalized(c):
    """The ORDER-FREE half of chunk encoding: the key-encode proper of an
    already-:func:`_normalize_chunk`-ed chunk. Returns ``(keys, comp)``
    with ``keys`` the order-preserving unsigned view (host numpy for host
    chunks, device array for device chunks — each stays where it lives)
    and ``comp`` a zero-length dtype carrier for first-chunk probes
    (consumers read only ``.dtype`` off it). Pure per-chunk compute —
    the pooled plane runs it concurrently across ingest workers."""
    if isinstance(c, _sp.SpillChunk):
        return c.keys, np.empty((0,), np.dtype(c.orig_dtype))
    if not _is_device_array(c):
        return _dt.np_to_sortable_bits(c), c
    if np.dtype(c.dtype) == np.float64 and _tpu_backend():
        # device f64 keys on TPU are the ~49-bit approximation
        # (utils/dtypes.py:f64_raw_bits) — decode the chunk's (already
        # storage-truncated) values to host and key them EXACTLY, so
        # every chunk of a stream lives in ONE key space regardless of
        # residency and the answer is exact w.r.t. the chunk contents
        hc = np.asarray(c)
        return _dt.np_to_sortable_bits(hc), hc
    return _dt.to_sortable_bits(c), c


def _encode_chunk(chunk, dtype):
    """Validate + key-encode ONE chunk: returns ``(keys, c)`` with ``keys``
    the order-preserving unsigned view (host numpy for host chunks, device
    array for device chunks — each stays where it lives) and ``c`` the
    raveled original (a zero-length dtype carrier for spill replays), or
    ``None`` for an empty chunk. ``dtype`` is the stream dtype to validate
    against (``None`` = first chunk, adopt its dtype — the caller reads it
    off ``c.dtype``). Shared verbatim by the synchronous iterator below
    and the pipelined producer thread (streaming/pipeline.py), so both
    paths enforce identical contracts; the pooled plane runs the same two
    halves (:func:`_normalize_chunk` on the puller,
    :func:`_encode_normalized` on a worker) split across threads."""
    c = _normalize_chunk(chunk, dtype)
    if c is None:
        return None
    return _encode_normalized(c)


def _iter_key_chunks(src, dtype=None, spill=None):
    """Yield ``(keys, chunk)`` pairs for every non-empty chunk (see
    :func:`_encode_chunk`) — the synchronous path, and the correctness
    oracle for the pipelined one. ``spill`` is an optional
    :class:`~mpi_k_selection_tpu.streaming.spill.SpillWriter` teeing every
    chunk's host encoded keys (the synchronous twin of the pipelined
    producer's tee; the caller commits/aborts it)."""
    for chunk in src():
        pair = _encode_chunk(chunk, dtype)
        if pair is None:
            continue
        keys, c = pair
        if dtype is None:
            dtype = np.dtype(c.dtype)
        if spill is not None:
            hk = keys if isinstance(keys, np.ndarray) else np.asarray(keys)
            slot = (
                chunk.device_slot if isinstance(chunk, _sp.SpillChunk) else None
            )
            spill.append(hk, dtype, device_slot=slot)
        yield keys, c


@contextlib.contextmanager
def _key_chunk_stream(
    src, dtype=None, *, pipeline_depth=0, hist_method=None, timer=None,
    devices=None, spill=None, retry=None, obs=None, workers=1,
):
    """Context-managed ``(keys, chunk)`` iterator: the synchronous
    generator at depth 0, a :class:`~mpi_k_selection_tpu.streaming.
    pipeline.ChunkPipeline` (background produce/encode/stage overlapped
    with the consuming pass, staged round-robin over ``devices``) at
    depth >= 1. The context manager guarantees the producer thread is
    joined on EVERY exit path — normal exhaustion, early exit, and
    consumer-side raises like the replay-stability check. ``spill`` tees
    every chunk's encoded keys to a SpillWriter (on the producer thread
    when pipelined); the caller owns commit/abort. ``retry`` (a
    faults/policy.py RetryPolicy, or None) governs in-place retries of
    the producer's staging transfers; ``obs`` receives their retry
    events. ``workers`` (resolved, >= 1) selects the pooled host data
    plane at depth >= 1; depth 0 ignores it (the synchronous oracle has
    no threads to pool)."""
    depth = _pl.validate_pipeline_depth(pipeline_depth)
    if depth == 0:
        yield _iter_key_chunks(src, dtype, spill=spill)
        return
    pipe = _pl.ChunkPipeline(
        src, dtype, depth=depth, hist_method=hist_method, timer=timer,
        devices=devices, spill=spill, retry=retry, obs=obs, workers=workers,
    )
    try:
        yield iter(pipe)
    finally:
        pipe.close()


def resolve_stream_hist(hist_method: str, dtype) -> str:
    """``"numpy"`` (host bincount) or an ops/histogram.py method name.

    ``"auto"`` keeps the device path (ops/histogram.py resolves it to the
    Pallas kernels on TPU, scatter elsewhere) EXCEPT where the device would
    not be exact: 64-bit keys without x64 (jnp would silently truncate
    them) and float64 on TPU (device keys are the ~49-bit ``f64_raw_bits``
    approximation; the host path keys the exact bits) — host counting
    needs no mode flip and stays exact for both.
    """
    if hist_method == "numpy":
        return "numpy"
    dtype = np.dtype(dtype)
    if dtype.itemsize == 8:
        import jax

        if not jax.config.jax_enable_x64:
            return "numpy"
        if dtype.kind == "f" and jax.default_backend() == "tpu":
            return "numpy"
    return hist_method


# the per-chunk device dispatch/finish pair and the FIFO scheduler live in
# streaming/executor.py (ONE consumption discipline for histogram merge,
# survivor collect, certificate folds, and the spill tee); these aliases
# keep the historical import surface (contract checks, tests) working
_chunk_histograms = _ex.chunk_histograms
_prefix_mask = _ex.prefix_mask


def _np_walk(hist, kk, prefix, radix_bits):
    """Host bucket-walk step (the numpy twin of ops/radix.py:
    bucket_walk_step): pick the bucket containing the kk-th survivor,
    rebase kk, extend the prefix. Returns (prefix, kk, bucket_count)."""
    cum = np.cumsum(hist)
    b = int(np.searchsorted(cum, kk, side="left"))
    kk = int(kk - (cum[b - 1] if b else 0))
    prefix = ((int(prefix) << radix_bits) | b) if prefix is not None else b
    return prefix, kk, int(hist[b])


def _hist_summary(hists) -> tuple[int, int, int]:
    """(total population, heaviest bucket, nonzero buckets) across one
    pass's ``{prefix: int64 histogram}`` dict (or a single histogram)."""
    if not isinstance(hists, dict):
        hists = {None: hists}
    total = bucket_max = nonzero = 0
    for h in hists.values():
        total += int(h.sum())
        bucket_max = max(bucket_max, int(h.max()))
        nonzero += int(np.count_nonzero(h))
    return total, bucket_max, nonzero


def _emit_fault(obs, site, action, exc=None) -> None:
    """One recovery observation: a typed FaultEvent plus the
    ``faults.recovered{site,action}`` counter. Pure host observation."""
    _wr.fault_event(
        obs, site, action, exc=exc,
        counter="faults.recovered", labels={"site": site, "action": action},
    )


def _recover_pass(
    run, *, policy, reading_spill, fallback, on_enospc, obs, site,
):
    """Run ONE streamed pass under the resilience ladder. ``run(src, tee)``
    is a re-invocable pass body: ``src=None`` means "the pass's default
    read source", ``tee=False`` suppresses the spill generation write; it
    must unwind completely on raise (executor aborted, writer aborted,
    staged chunk released — the existing except paths do exactly that),
    so every retry starts from clean state.

    The ladder, in order of specificity:

    - ``SpillRecordError`` while reading a generation: re-read ONCE (a
      transient bad read heals), then re-run the pass from ``fallback``
      (the replayable source, or a one-shot run's protected gen-0 tee) —
      the pass's own prefix filters make the superset read bit-identical
      by construction. No fallback (or the fallback itself failing) ->
      the typed error propagates.
    - ``OSError(ENOSPC)`` while teeing the next generation:
      ``on_enospc`` decides — ``spill="auto"`` descents disable the tee
      and re-run the pass reading the last good generation (a warning
      FaultEvent marks the downgrade); explicit spill modes raise
      :class:`~mpi_k_selection_tpu.errors.SpillCapacityError`.
    - transient errors (``policy.retryable``): re-run the whole pass from
      the same read source, bounded by ``policy.max_attempts`` with the
      policy's backoff — "failed passes re-run from the previous spill
      generation". Exhaustion raises the typed
      :class:`~mpi_k_selection_tpu.errors.RetryExhaustedError`.

    Everything else propagates untouched: retrying a logic error repeats
    it."""
    from mpi_k_selection_tpu.obs import flight as _fl

    transient = 0
    reread = False
    src = None
    tee = True
    while True:
        try:
            return run(src, tee)
        except _err.SpillRecordError as e:
            if not reading_spill or src is not None:
                # unrecoverable spill damage (no ladder rung left): the
                # postmortem hook fires ONCE per flight recorder before
                # the typed error propagates (a no-op without one)
                _fl.auto_dump(obs, "spill-unrecoverable", exc=e)
                raise
            if not reread:
                reread = True
                _emit_fault(obs, "spill.read", "reread", e)
                continue
            if fallback is None:
                _fl.auto_dump(obs, "spill-unrecoverable", exc=e)
                raise
            _emit_fault(obs, "spill.read", "rebuild", e)
            src = fallback
            continue
        except BaseException as e:
            # ENOSPC first — it is an OSError, but so are the RETRYABLE
            # ConnectionError/TimeoutError: dispatch on errno, not on the
            # class, so a transient network/timeout failure falls through
            # to the pass-level retry below instead of being re-raised
            if (
                isinstance(e, OSError)
                and e.errno == _errno.ENOSPC
                and tee
                and on_enospc is not None
            ):
                on_enospc(e)  # raises SpillCapacityError unless degrade is legal
                tee = False
                continue
            if policy is None or not policy.is_retryable(e):
                raise
            transient += 1
            if transient >= policy.max_attempts:
                exhausted = _err.RetryExhaustedError(
                    f"{site}: still failing after {policy.max_attempts} "
                    f"attempts ({type(e).__name__}: {e})",
                    site=site,
                    attempts=policy.max_attempts,
                )
                # the fault-triggered debug bundle (obs/flight.py): at
                # most one per flight recorder, never raises, and the
                # events tail it freezes still holds the retry/inject
                # FaultEvents that led here
                _fl.auto_dump(obs, "retry-exhausted", exc=exhausted)
                raise exhausted from e
            _emit_fault(obs, site, "retry", e)
            policy.sleep(transient)
            continue


def _collect_survivors(
    src, dtype, specs, *, pipeline_depth=0, timer=None, devices=None,
    hist_method=None, obs=None, read_from="source", disk_bytes_read=None,
    deferred=True, fused=False, retry=None, ingest_workers=1,
):
    """One streamed pass collecting survivors for EVERY ``(resolved_bits,
    prefix) -> expected population`` spec at once — the shared finish of
    the multi-rank descent (a single-rank descent passes one spec). Keys
    whose top ``resolved_bits`` equal ``prefix`` survive; device chunks
    are filtered ON device so only survivors cross back to the host.
    Returns ``{spec: host uint key array}``.

    The single-device pipelined path overlaps produce/encode with the
    filtering but never stages (``hist_method`` stays ``None``). With > 1
    ingest device (and a device ``hist_method`` — the host-exact routes
    keep filtering on host), chunks ARE staged round-robin so each device
    filters its own resident chunks. Under ``deferred`` (the default)
    each staged chunk's filter dispatches as a fixed-shape compaction on
    its own device and the survivors cross back only when the p-wide
    FIFO window pops (streaming/executor.py) — the consumer never blocks
    per chunk, which is what lets the collect pass scale with devices
    like the histogram passes. ``deferred=False`` keeps the historical
    eager boolean gather. ``fused`` (the caller's RESOLVED tier —
    ``"kernel"``/``"xla"``/False — and implies deferral) collapses the
    per-spec compaction dispatches into ONE fused program per staged
    bucket (streaming/executor.py:FusedIngestConsumer; the kernel tier
    guarantees one read of each staged chunk, the xla tier one
    dispatch). Survivor multisets are identical in every mode (and the
    final ``np.partition`` is order-invariant regardless)."""
    kdt = np.dtype(_dt.key_dtype(dtype))
    total_bits = _dt.key_bits(dtype)
    devs = _pl.resolve_stream_devices(devices)
    depth = _pl.validate_pipeline_depth(pipeline_depth)
    multi = len(devs) > 1 and depth > 0
    # staging is gated on the RAW knobs (depth, the devices argument) —
    # never on the resolved tuple, so an explicitly requested single
    # device stages committed instead of silently host-folding (KSL022)
    staged = depth > 0 and devices is not None
    sorted_specs = sorted(specs)
    collector = _ex.CollectConsumer(
        sorted_specs, kdt, total_bits, deferred=deferred, obs=obs
    )
    consumer = (
        _ex.FusedIngestConsumer(
            collect=collector, kdt=kdt, total_bits=total_bits, tier=fused,
            obs=obs,
        )
        if fused
        else collector
    )
    ex = _ex.StreamExecutor(
        [consumer], window=len(devs) if multi else 1,
        occupancy=_wr.window_occupancy(obs, phase="collect"),
    )
    chunk_i = keys_read = 0
    keys = None
    try:
        with _pl._phase(timer, "descent.collect"), _key_chunk_stream(
            src, dtype, pipeline_depth=pipeline_depth, timer=timer,
            hist_method=hist_method if staged else None,
            devices=devs if staged else None, retry=retry, obs=obs,
            workers=ingest_workers,
        ) as kc:
            for keys, _ in kc:
                if obs is not None:
                    _wr.chunk_event(obs, "collect", chunk_i, keys, kdt, devs)
                chunk_i += 1
                keys_read += int(keys.size)
                ex.push(keys)
            ex.drain()
    except BaseException:
        ex.abort()
        _ex.release_staged(keys)  # the chunk in hand (idempotent)
        raise
    collected = collector.collected(kdt)
    for spec in sorted_specs:
        c = collected[spec]
        if c.size != specs[spec]:  # pragma: no cover - source changed between passes
            raise RuntimeError(
                f"chunk source is not replay-stable: collected {c.size} "
                f"survivors, histogram pass counted {specs[spec]}. The source "
                "callable must yield identical data on every invocation."
            )
    if obs is not None:
        # honest terminal accounting: the executor knows every spec's
        # survivor count at drain time — bucket_total/max/nonzero describe
        # the collected populations and `survivors` aligns with `prefixes`
        # (both in sorted-spec order), so check_stream_invariants can hold
        # the collect event to the same books as the histogram passes
        sizes = [int(collected[s].size) for s in sorted_specs]
        obs.emit(
            _ev.StreamPassEvent(
                pass_index="collect",
                resolved_bits=0,
                prefixes=tuple(int(p) for _, p in sorted_specs),
                chunks=chunk_i,
                keys_read=keys_read,
                bytes_read=keys_read * kdt.itemsize,
                disk_bytes_read=(
                    keys_read * kdt.itemsize
                    if disk_bytes_read is None
                    else int(disk_bytes_read)
                ),
                read_from=read_from,
                bucket_total=sum(sizes),
                bucket_max=max(sizes, default=0),
                bucket_nonzero=sum(1 for s in sizes if s),
                survivors=tuple(sizes),
            )
        )
    return collected


def _validate_ks(ks, n):
    for k in ks:
        if not 1 <= k <= n:
            raise ValueError(f"k={k} out of range [1, {n}]")


def _resolve_spill(source, spill, spill_dir):
    """Resolve the ``spill`` knob against the source's replayability.

    Returns ``(store, own_store, read_gen)``:

    - ``store`` — the :class:`~mpi_k_selection_tpu.streaming.spill.
      SpillStore` the descent tees into and reads back from (``None`` =
      the pure replay path);
    - ``own_store`` — True when this call created the store and must
      close (delete) it on every exit path;
    - ``read_gen`` — a pre-existing generation to serve pass 0 from
      (the source IS a store: the sketch-then-refine flow).
    """
    spill = _sp.validate_spill_mode(spill)
    in_store = source if isinstance(source, _sp.SpillStore) else None
    read_gen = in_store.latest_generation() if in_store is not None else None
    if isinstance(spill, _sp.SpillStore):
        return spill, False, read_gen
    if spill == "force":
        return _sp.SpillStore(spill_dir), True, read_gen
    if spill == "auto":
        if in_store is not None:
            # the source's own store serves the descent's generations too
            return in_store, False, read_gen
        if _is_one_shot_source(source):
            return _sp.SpillStore(spill_dir), True, None
    # "off", or "auto" with a replayable source: today's replay path,
    # bit-identical (a store source still replays its gen 0 every pass)
    return None, False, read_gen


def streaming_kselect(
    source,
    k,
    *,
    radix_bits: int = 8,
    hist_method: str = "auto",
    collect_budget: int = DEFAULT_COLLECT_BUDGET,
    sketch=None,
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
    timer=None,
    devices=None,
    spill=DEFAULT_SPILL,
    spill_dir=None,
    deferred=DEFAULT_DEFERRED,
    fused=DEFAULT_FUSED,
    width_schedule=DEFAULT_WIDTH_SCHEDULE,
    pack_spill=DEFAULT_PACK_SPILL,
    ingest_workers=None,
    retry=None,
    obs=None,
):
    """Exact k-th smallest (1-indexed) over a chunked stream.

    ``source`` per :func:`as_chunk_source`. ``k`` must be concrete (the
    loop is host-driven — there is nothing to trace). ``sketch`` is an
    optional :class:`~mpi_k_selection_tpu.streaming.sketch.RadixSketch`
    built over the SAME stream: its deepest exact level seeds the descent,
    skipping the first ``sketch.resolution_bits`` worth of passes (the
    ``refine`` fast path). Returns a host scalar of the stream's dtype —
    bit-exact, including float64 on TPU for host chunks (host key space
    end-to-end; see module docstring).

    ``collect_budget`` bounds host memory for the survivor collect (keys of
    at most that many elements are materialized at once); the streamed
    chunks themselves are never concatenated.

    ``pipeline_depth`` >= 1 overlaps chunk *i+1*'s production, host
    key-encode and host->device staging with chunk *i*'s histogram
    (streaming/pipeline.py; 2 = double buffering, the default). Depth 0 is
    the fully synchronous path — the correctness oracle the pipelined one
    is bit-identical to. ``timer`` (a utils/profiling.PhaseTimer) collects
    the pipeline's produce/encode/stage/stall phases for
    :func:`~mpi_k_selection_tpu.streaming.pipeline.ingest_hidden_frac`.

    ``devices`` spreads the pipelined ingest across chips (None/1 = the
    single-device path; an int takes the first p of ``jax.devices()``, a
    device sequence is used as given): staged chunks land round-robin and
    up to p histograms run concurrently, with the host int64 merge drained
    in chunk order — answers are bit-identical for EVERY device count and
    depth. Multi-device staging engages only with ``pipeline_depth >= 1``
    and a device histogram method (the host-exact 64-bit-no-x64 and
    f64-on-TPU routes stay host-side and ignore extra devices).

    ``spill`` engages the survivor spill store (streaming/spill.py):
    pass 0 tees each chunk's encoded keys to disk and every later pass
    reads the previous generation, filters to the surviving prefixes on
    the owning device, and writes only the compacted survivors — total
    bytes streamed drop from ~passes·N to ~N·(2 + 1/2^radix_bits + ...),
    and one-shot iterators/generators become first-class sources (passes
    >= 1 never touch the source). ``"auto"`` (default) spills only for
    one-shot sources, keeping replayable sources on the bit-identical
    replay path; ``"force"`` always spills; ``"off"`` never does (one-shot
    sources are then rejected); a
    :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore` tees into a
    caller-owned store whose pass-0 generation survives the call (and a
    store with a committed generation is itself a valid ``source``).
    ``spill_dir`` roots internally-created stores (default: the system
    temp dir). Answers are bit-identical to ``spill="off"`` in every mode,
    for every devices x pipeline_depth combination.

    ``deferred`` governs the per-chunk consumption discipline
    (streaming/executor.py): ``"auto"``/``"on"`` (default) dispatch each
    staged chunk's survivor filter — the collect's and the spill tee's —
    as a device-side fixed-shape compaction whose host materialization
    happens when the p-wide FIFO window pops, so the consumer never
    blocks per chunk and the collect/spill passes scale with ``devices``
    like the histogram passes; spill replays also read record payloads
    via mmap. ``"off"`` keeps the historical eager gather at
    chunk-arrival time. Answers are bit-identical across the whole
    devices x pipeline_depth x spill x deferred grid; host chunks and
    the host-exact routes (64-bit-no-x64, f64-on-TPU) never stage and so
    bypass deferral by construction. Device-resident source chunks ARE
    staged (pow2-padded on their own device, no transfer) whenever a
    device method consumes them, so they ride the same deferred
    discipline instead of the retired eager gather.

    ``fused`` (default ``"auto"``) collapses the per-chunk device
    programs of each deferred pass — the digit histogram, the survivor
    compactions, the spill-tee payload — into ONE fused program per
    staged bucket, at one of two tiers: ``"kernel"`` dispatches the
    hand-written single-sweep pallas kernel
    (ops/pallas/sweep_ingest.py), which GUARANTEES one HBM read of the
    bucket (each tile is VMEM-resident once and every consumer
    accumulates from it; buckets outside the kernel's support matrix
    fall back to the XLA tier per bucket); ``"xla"`` dispatches the
    one-XLA-program fusion (ops/pallas/fused_ingest.py) — one dispatch,
    shared subexpressions, read count up to XLA. ``"auto"`` resolves to
    ``"kernel"`` on TPU backends and ``"xla"`` elsewhere (off-TPU the
    kernel only interprets — exact but slow — the same resolution rule
    as ``hist_method="auto"``). ``"off"`` keeps the unfused consumer
    bundle as the bit-for-bit oracle; with ``deferred="off"`` the
    bundle is unfused regardless (fusion is a deferral discipline).
    Answers are bit-identical at every tier;
    ``ingest.bucket_reads{phase}`` (docs/OBSERVABILITY.md) makes the
    reads-per-pass collapse measurable.

    ``width_schedule`` (default ``"off"``) makes the per-pass digit
    width adaptive: ``"auto"`` front-loads ONE wide pass — up to 16 bits,
    chosen so the remainder stays on ``radix_bits`` boundaries — so the
    first spill generation shrinks by ~2^w0 instead of ~2^radix_bits and
    the second full-N read disappears; an explicit tuple names every
    pass's width (summing to the unresolved key bits, each within
    ``[1, MAX_PASS_BITS]`` — wider is refused loudly: the device
    histogram's int32 partials budget, KSC102). Wide digits exceed the
    pallas kernels' radix support, so those passes count through the
    scatter path on device (and per staged bucket the ``fused="kernel"``
    tier falls back to the xla tier exactly like any other unsupported
    bucket). ``"off"`` is byte-for-byte the fixed one-digit-per-pass
    descent, and answers are bit-identical under EVERY schedule.

    ``pack_spill`` (default ``"off"``) prefix-packs survivor spill
    generations (streaming/spill.py format v2): generation g's records
    store only each survivor's unresolved low ``total_bits - resolved``
    bits, bit-packed per ``(resolved, prefix)`` segment and CRC'd over
    the packed payload, reconstructed exactly at replay — disk bytes
    shrink multiplicatively with population and resolved depth, and
    replay re-stages onto the recorded device slots unchanged. ``"auto"``
    packs wherever it wins (per record; physical bytes never exceed
    logical); generation 0 always stays full-width v1. Answers are
    bit-identical with packing on or off.

    ``ingest_workers`` (default ``1``) widens the HOST side of the
    pipelined ingest into the parallel data plane
    (streaming/pipeline.py): one sequential puller preserves source
    order (one-shot consumption, drift detection, round-robin slot and
    fault-index assignment), a pool of ``ksel-ingest-*`` workers runs
    each chunk's key-encode, spill-tee pack/CRC and staging
    ``device_put`` concurrently, and a reorder sequencer releases
    chunks to the descent strictly in chunk order — so answers, pass
    events, spill records and chunk->device assignment are
    bit-identical at EVERY worker count. ``"auto"`` resolves to
    ``min(4, cores)``; ``1`` is byte-for-byte the legacy
    single-producer path. Spill replays decode records on the same
    width of pool (read + CRC + v2 unpack off the consumer thread).
    Engages only with ``pipeline_depth >= 1`` (the depth-0 oracle is
    synchronous); it pays off when host encode/pack dominates —
    64-bit keys, ``pack_spill`` on, f64-on-TPU — and is wasted width
    when the device histogram is already the wall.

    ``retry`` configures the resilience policies (see
    :func:`streaming_kselect_many` and docs/ROBUSTNESS.md): ``None`` =
    the bounded-retry default, ``"off"`` = fail on the first transient,
    a :class:`~mpi_k_selection_tpu.faults.RetryPolicy` customizes
    attempts/backoff. Recovered runs are bit-identical to fault-free
    runs.

    ``obs`` (an :class:`~mpi_k_selection_tpu.obs.Observability`) turns on
    the descent telemetry: one typed event per streamed pass and per
    consumed chunk, metrics (StagingPool hits/misses, stall seconds,
    in-flight window occupancy — also per executor phase, spilled
    bytes), and producer/consumer trace spans. Off by default; enabling
    it never changes an answer bit (see docs/OBSERVABILITY.md).
    """
    return streaming_kselect_many(
        source,
        [k],
        radix_bits=radix_bits,
        hist_method=hist_method,
        collect_budget=collect_budget,
        sketch=sketch,
        pipeline_depth=pipeline_depth,
        timer=timer,
        devices=devices,
        spill=spill,
        spill_dir=spill_dir,
        deferred=deferred,
        fused=fused,
        width_schedule=width_schedule,
        pack_spill=pack_spill,
        ingest_workers=ingest_workers,
        retry=retry,
        obs=obs,
    )[0]


def streaming_kselect_many(
    source,
    ks,
    *,
    radix_bits: int = 8,
    hist_method: str = "auto",
    collect_budget: int = DEFAULT_COLLECT_BUDGET,
    sketch=None,
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
    timer=None,
    devices=None,
    spill=DEFAULT_SPILL,
    spill_dir=None,
    deferred=DEFAULT_DEFERRED,
    fused=DEFAULT_FUSED,
    width_schedule=DEFAULT_WIDTH_SCHEDULE,
    pack_spill=DEFAULT_PACK_SPILL,
    ingest_workers=None,
    retry=None,
    obs=None,
):
    """Exact k-th smallest for EVERY (1-indexed) rank in ``ks``, sharing
    each streamed pass across ranks: the stream is replayed once per radix
    level plus one collect — NOT once per rank — with one histogram per
    DISTINCT surviving prefix at each level (ranks whose descents land in
    the same bucket share it). For out-of-core sources the replay is the
    dominant cost, so m quantiles over one stream cost roughly the passes
    of one. Per-rank semantics are exactly :func:`streaming_kselect`'s
    (including its ``pipeline_depth``/``timer``/``devices``,
    ``spill``/``spill_dir``, ``deferred`` and ``obs`` knobs); returns a
    list in input order.

    With spill engaged the "replay" above is a generation read: pass 0
    tees the encoded keys to the spill store, every later pass filters the
    previous generation to the union of unfinished prefixes (the active
    set of that pass plus parked ranks awaiting the collect) and writes
    only the compacted survivors — so the bytes streamed per pass shrink
    by ~2^radix_bits while the multiset of keys each histogram counts is
    unchanged, keeping answers bit-identical to the replay path. Under
    ``deferred`` the tee's filter rides the same executor window as the
    histogram dispatches (one device-side compaction per staged chunk,
    record written at FIFO-finish time), so the spill pass no longer
    serializes on per-chunk gathers — and under ``fused`` (default
    ``"auto"``; see :func:`streaming_kselect`) the tee compaction and the
    histogram are ONE program per staged bucket, so each spilled key is
    read once per pass.

    ``retry`` governs the resilience policies (faults/policy.py;
    docs/ROBUSTNESS.md): ``None`` = the package default
    (:data:`~mpi_k_selection_tpu.faults.DEFAULT_RETRY`: 3 total attempts,
    bounded exponential backoff through the injectable sleeper), a
    :class:`~mpi_k_selection_tpu.faults.RetryPolicy` customizes it,
    ``"off"`` restores the fail-on-first-transient behavior. With a
    policy active: transient chunk-source errors re-pull mid-pass
    (replayable sources), transient staging failures retry in place,
    failed passes re-run from the previous spill generation, corrupt or
    truncated spill records re-read once and then rebuild from the
    source (one-shot sources fall back to the protected gen-0 tee), and
    ENOSPC under ``spill="auto"`` degrades to the replay of the last
    good generation with a warning instead of raising — except while
    teeing generation 0 itself, where no prior generation exists to
    degrade to and the typed ``SpillCapacityError`` is raised. Recovered
    runs are bit-identical to fault-free runs; exhausted policies raise
    typed errors (``RetryExhaustedError``, ``SpillCapacityError``,
    ``SpillRecordError``).

    ``width_schedule`` and ``pack_spill`` (see :func:`streaming_kselect`)
    shrink the descent's byte volume on both axes: a wide pass 0 makes
    generation 0 ~N/2^w0 (total streamed bytes ≈ one read of N plus a
    geometric tail instead of ~2N+), and packed generations store only
    each survivor's unresolved low bits on disk. Both default off;
    answers are bit-identical at every knob setting, and
    ``width_schedule="off"`` + ``pack_spill="off"`` is byte-for-byte the
    historical path.
    """
    width_schedule = validate_width_schedule(width_schedule)
    pack_spill = _sp.validate_pack_spill(pack_spill)
    pipeline_depth = _pl.validate_pipeline_depth(pipeline_depth)
    pool_n = _pl.resolve_ingest_workers(ingest_workers)
    devs = _pl.resolve_stream_devices(devices)
    defer = _ex.resolve_deferred(deferred)
    # fusion is a deferral discipline: the fused handle materializes at
    # window-pop time, so deferred="off" implies the unfused eager bundle
    # (fuse is the resolved TIER otherwise: "kernel" | "xla" | False);
    # the knob still validates on the eager route — a typo must raise,
    # not silently ride the oracle
    fused = _ex.validate_fused(fused)
    fuse = _ex.resolve_fused(fused) if defer else False
    policy = _fp.resolve_retry(retry)
    timer, _restore_recorder = _wr.attach_timer(obs, timer)
    occupancy = _wr.window_occupancy(obs, phase="descent")
    # one in-flight bundle slot per ingest device; the synchronous
    # (depth-0) oracle stays strictly serial regardless of the knob
    window = len(devs) if pipeline_depth > 0 else 1
    # None keeps the PR 3 uncommitted default-device staging; an explicit
    # knob (even a single device) commits staged chunks to its slots
    stream_kw = dict(
        pipeline_depth=pipeline_depth, timer=timer,
        devices=None if devices is None else devs,
        retry=policy, obs=obs, workers=pool_n,
    )
    _wr.ingest_workers_gauge(obs, pool_n)
    ks = [int(k) for k in ks]
    if not ks:
        return []

    store, own_store, read_gen = _resolve_spill(source, spill, spill_dir)
    one_shot = _is_one_shot_source(source)
    src = as_chunk_source(
        source, one_shot_ok=store is not None, mmap=defer, workers=pool_n,
    )
    if policy is not None and not one_shot:
        # mid-pass re-pull for transient source errors (replayable
        # sources only — a consumed generator cannot be re-invoked; its
        # recovery path is the spill store instead)
        src = _fp.resilient_source(src, policy, obs=obs)
    # ENOSPC can downgrade to the replay of the last good generation only
    # when the caller did not ask for spilling explicitly
    degrade_ok = spill == "auto"
    spill_disabled = False
    created = []  # generations this call wrote — its cleanup set
    # a generation never dropped mid-descent: a caller-owned store's
    # pass-0 tee (kept for later calls), or a one-shot run's gen-0
    # recovery anchor (the only rebuild source a consumed stream has —
    # raises the one-shot disk bound to ~3·N·key_bytes worst case)
    protected = None

    def _gen_src(filter_specs=None):
        # filter_specs prune the replay of a v2 (segment-directoried)
        # generation to the surviving buckets — a superset of the pass's
        # own exact filters, so consumers see every key they would have
        # selected from the full read (spill.py:iter_chunks)
        if read_gen is not None:
            return read_gen.as_source(
                mmap=defer, filter_specs=filter_specs, workers=pool_n
            )
        return src

    def _fallback_src():
        """The rebuild source when the generation being read is corrupt:
        the replayable original, or a one-shot run's protected gen-0 tee
        (None = unrecoverable; the typed SpillRecordError propagates)."""
        if not one_shot:
            return src
        if protected is not None and not protected.dropped:
            return protected.as_source(mmap=defer, workers=pool_n)
        return None  # pragma: no cover - one-shot descents always anchor gen 0

    def _log_pass(label, wrote=None, *, keys_read=None, read=None,
                  disk_read=None):
        if store is None:
            return
        if read is None:
            read = "spill" if read_gen is not None else "source"
        if keys_read is None:
            keys_read = int(read_gen.keys) if read_gen is not None else int(n)
        # LOGICAL bytes (full-width keys streamed into consumers) vs the
        # PHYSICAL disk bytes actually read/written — these diverge only
        # for packed (format-v2) generations, and physical <= logical
        # always (spill.py's per-record pack-only-when-it-wins rule)
        entry = {
            "pass": label, "read": read,
            "keys_read": int(keys_read),
            "bytes_read": int(keys_read) * kdt.itemsize,
            "disk_bytes_read": (
                int(keys_read) * kdt.itemsize
                if disk_read is None
                else int(disk_read)
            ),
        }
        if wrote is not None:
            entry["keys_written"] = int(wrote.keys)
            entry["bytes_written"] = int(wrote.logical_nbytes)
            entry["disk_bytes_written"] = int(wrote.nbytes)
        store.pass_log.append(entry)

    def _rotate(gen):
        """Make the just-committed survivor generation the next read
        source and drop the one it replaces — at most two generations
        (plus the protected anchor) ever coexist on disk."""
        nonlocal read_gen
        created.append(gen)
        prev = read_gen
        read_gen = gen
        if prev is not None and prev in created and prev is not protected:
            store.drop_generation(prev)
            created.remove(prev)

    def _on_enospc(e):
        """The ENOSPC rung of the pass-recovery ladder: degrade
        ``spill="auto"`` (disable the tee, keep replaying the last good
        generation), raise typed for explicit spill modes."""
        nonlocal spill_disabled
        if not degrade_ok:
            raise _err.SpillCapacityError(
                "spill store out of disk while writing the next survivor "
                "generation; spilling was requested explicitly "
                f"(spill={spill!r}), so there is no silent fallback — "
                "free disk space, point spill_dir elsewhere, or run "
                "spill='auto'/'off'"
            ) from e
        spill_disabled = True
        _emit_fault(obs, "spill.write", "degrade", e)
        warnings.warn(
            "spill store out of disk (ENOSPC); degrading spill='auto' to "
            "the replay of the last good generation — spilling is "
            "disabled for the rest of this descent and later passes "
            "re-read that generation whole",
            RuntimeWarning,
            stacklevel=2,
        )

    try:
        # per-rank descent state: [prefix, rebased_k, resolved_bits, population]
        if sketch is not None:
            # the sketch names the stream dtype (later passes validate every
            # chunk against it); check_stream validates divisibility of the
            # bits BELOW its resolved prefix — what the remaining passes walk
            dtype = sketch.dtype
            kdt = np.dtype(_dt.key_dtype(dtype))
            total_bits = _dt.key_bits(dtype)
            method = resolve_stream_hist(hist_method, dtype)
            sketch.check_stream(dtype, radix_bits, width_schedule=width_schedule)
            # the remaining passes walk the bits BELOW the sketch's
            # resolved prefix — the schedule covers exactly those
            schedule = resolve_width_schedule(
                width_schedule, total_bits, radix_bits,
                start_bits=sketch.resolution_bits,
            )
            start_bits = sketch.resolution_bits
            n = sketch.n
            _validate_ks(ks, n)
            states = [list(sketch.walk(k)) for k in ks]
        else:
            # pass 0 triples as the length scan and the dtype probe: ONE
            # streamed histogram of the top digit (rank-independent — no
            # prefix filter yet), with dtype (hence key geometry and method)
            # captured from the first chunk — nothing is produced just to be
            # discarded. With spill engaged it ALSO tees every chunk's
            # encoded keys to generation 0 (on the producer thread when
            # pipelined), so no later pass touches the source again.
            dtype = None
            n = 0
            kdt = total_bits = method = schedule = None
            pass0_gen = read_gen  # what pass 0 actually read from

            def _pass0(src_override, tee):
                nonlocal dtype, n, kdt, total_bits, method, schedule
                dtype = None  # fresh per attempt: the probe re-runs whole
                n = 0
                chunk_i0 = 0
                writer = (
                    # pack_spill="auto": tee generation 0 segmented by
                    # each key's top digit, so pass 1's filtered replay
                    # prunes to the surviving buckets instead of
                    # re-reading all N keys (spill.py format v2)
                    store.new_generation(
                        pack_digit_bits=(
                            _sp.GEN0_SEGMENT_BITS
                            if pack_spill == "auto" else None
                        )
                    )
                    if tee and store is not None and read_gen is None
                    else None
                )
                hist_c = ex = keys = None
                try:
                    with _pl._phase(timer, "descent.pass"), _key_chunk_stream(
                        src_override if src_override is not None else _gen_src(),
                        hist_method=hist_method, spill=writer,
                        **stream_kw,
                    ) as kc:
                        for keys, chunk in kc:
                            if dtype is None:
                                dtype = np.dtype(chunk.dtype)
                                kdt = np.dtype(_dt.key_dtype(dtype))
                                total_bits = _dt.key_bits(dtype)
                                # the schedule resolves at dtype-probe time
                                # (key geometry is only now known); "off"
                                # reproduces the fixed radix_bits schedule
                                # INCLUDING its divisibility refusal
                                schedule = resolve_width_schedule(
                                    width_schedule, total_bits, radix_bits
                                )
                                method = resolve_stream_hist(hist_method, dtype)
                                w0 = schedule[0]
                                shift0 = total_bits - w0
                                hist_c = _ex.HistogramConsumer(
                                    shift0, w0, [None],
                                    _pass_method(method, w0), kdt,
                                    obs=obs,
                                )
                                ex = _ex.StreamExecutor(
                                    [hist_c], window=window, occupancy=occupancy
                                )
                            if obs is not None:
                                _wr.chunk_event(obs, 0, chunk_i0, keys, kdt, devs)
                            chunk_i0 += 1
                            n += int(keys.size)
                            ex.push(keys)
                        if ex is not None:
                            ex.drain()
                    if n == 0:
                        raise ValueError(
                            "streaming selection requires a non-empty stream"
                        )
                    hist0 = hist_c.hists[None]
                except BaseException:
                    # the writer's abort rides a finally: an executor
                    # abort (or the staged-chunk release) raising must
                    # not strand the generation's ksel-spill records
                    try:
                        if ex is not None:
                            ex.abort()
                        _ex.release_staged(keys)  # chunk in hand (idempotent)
                    finally:
                        if writer is not None:
                            writer.abort()
                    raise
                gen = writer.commit() if writer is not None else None
                return hist0, gen, chunk_i0

            def _enospc_pass0(e):
                raise _err.SpillCapacityError(
                    "spill store out of disk while teeing generation 0 — "
                    "no prior generation exists to degrade to; free disk "
                    "space, point spill_dir elsewhere, or use spill='off' "
                    "with a replayable source"
                ) from e

            # pass 0 of a ONE-SHOT source consumes the stream as it tees:
            # no re-run is possible mid-stream, so its ladder is disabled
            # (failures propagate typed, writer aborted, threads joined);
            # replayable sources get the full transient-retry ladder
            hist, gen0, chunk_i0 = _recover_pass(
                _pass0,
                policy=None if one_shot else policy,
                reading_spill=read_gen is not None,
                fallback=None,
                on_enospc=_enospc_pass0,
                obs=obs,
                site="pass 0",
            )
            if gen0 is not None:
                created.append(gen0)
                if not own_store or one_shot:
                    protected = gen0
                _log_pass(
                    0, gen0,
                    disk_read=(
                        None if pass0_gen is None else int(pass0_gen.nbytes)
                    ),
                )
                read_gen = gen0
            else:
                _log_pass(
                    0,
                    disk_read=(
                        None if pass0_gen is None else int(pass0_gen.nbytes)
                    ),
                )
            _validate_ks(ks, n)
            start_bits = 0
            states = []
            for k in ks:
                prefix, kk, pop = _np_walk(hist, k, None, schedule[0])
                states.append([prefix, kk, schedule[0], pop])
            if obs is not None:
                if gen0 is not None:
                    obs.emit(
                        _ev.SpillGenerationEvent(
                            generation=gen0.index,
                            records=len(gen0.records),
                            keys=gen0.keys,
                            nbytes=gen0.nbytes,
                            logical_nbytes=gen0.logical_nbytes,
                            packed=gen0.packed,
                        )
                    )
                total0, max0, nz0 = _hist_summary(hist)
                keys_read0 = (
                    int(pass0_gen.keys) if pass0_gen is not None else n
                )
                obs.emit(
                    _ev.StreamPassEvent(
                        pass_index=0,
                        resolved_bits=0,
                        prefixes=(),
                        chunks=chunk_i0,
                        keys_read=keys_read0,
                        bytes_read=keys_read0 * kdt.itemsize,
                        read_from="spill" if pass0_gen is not None else "source",
                        bucket_total=total0,
                        bucket_max=max0,
                        bucket_nonzero=nz0,
                        survivors=tuple(int(st[3]) for st in states),
                        keys_written=None if gen0 is None else int(gen0.keys),
                        bytes_written=(
                            None if gen0 is None else int(gen0.logical_nbytes)
                        ),
                        disk_bytes_read=(
                            int(pass0_gen.nbytes)
                            if pass0_gen is not None
                            else n * kdt.itemsize
                        ),
                        disk_bytes_written=(
                            None if gen0 is None else int(gen0.nbytes)
                        ),
                    )
                )
                _wr.resolved_bits_gauge(obs, 0, schedule[0])

        # per-step schedule bookkeeping: active ranks advance in lockstep,
        # so every pass sits on a schedule-step boundary — map each
        # boundary to (digit width, pass label). base_label reproduces the
        # historical ``resolved // radix_bits`` labels exactly under
        # ``width_schedule="off"`` (floor((start + i*rb)/rb) ==
        # floor(start/rb) + i), and labels stay strictly-increasing ints
        # under every schedule (check_stream_invariants' contract).
        base_label = start_bits // radix_bits
        steps = {}
        acc = start_bits
        for i, w in enumerate(schedule):
            steps[acc] = (w, base_label + i)
            acc += w

        def _active(st):
            return st[2] < total_bits and st[3] > collect_budget

        while any(_active(st) for st in states):
            # active ranks advance in lockstep (a rank only ever EXITS the
            # active set), so they all sit at one resolved depth: one
            # streamed pass serves every distinct surviving prefix
            resolved = next(st[2] for st in states if _active(st))
            width, pass_label = steps[resolved]
            shift = total_bits - resolved - width
            prefixes = sorted({st[0] for st in states if _active(st)})
            expected = {st[0]: st[3] for st in states if _active(st)}
            filter_specs = None
            if store is not None and not spill_disabled:
                # survivors this pass must carry forward: the active
                # prefixes at this depth, plus parked ranks (population
                # already <= collect_budget) still awaiting the collect —
                # so the final generation serves every collect spec
                filter_specs = sorted(
                    {(resolved, int(st[0])) for st in states if _active(st)}
                    | {
                        (st[2], int(st[0]))
                        for st in states
                        if not _active(st) and st[2] < total_bits
                    }
                )
            pass_read_gen = read_gen  # what this pass reads from

            def _run_pass(
                src_override, tee,
                shift=shift, width=width, prefixes=prefixes,
                expected=expected, filter_specs=filter_specs,
                pass_label=pass_label, pass_read_gen=pass_read_gen,
            ):
                writer = (
                    store.new_generation(
                        # pack_spill="auto": the tee's own filter union IS
                        # the segment directory — every surviving key's
                        # resolved prefix is known, so only its unresolved
                        # low bits hit disk (spill.py format v2)
                        pack_specs=(
                            filter_specs if pack_spill == "auto" else None
                        ),
                        total_bits=total_bits,
                    )
                    if tee and filter_specs is not None
                    else None
                )
                chunk_i = 0
                pass_keys = 0
                # what THIS attempt actually reads: the pass's default
                # (the previous generation, or the source), or the
                # recovery ladder's fallback (the source; gen 0 for
                # one-shot runs) — the obs/pass_log accounting must
                # describe the attempt that succeeded, not the schedule
                read_from = (
                    "spill"
                    if (src_override is None and pass_read_gen is not None)
                    or (src_override is not None and one_shot)
                    else "source"
                )
                # the generation whose PHYSICAL bytes this attempt reads
                # (None = a source read, where disk == logical): the
                # scheduled generation, or a one-shot rebuild's gen-0
                # anchor — honest disk accounting per attempt
                disk_gen = (
                    pass_read_gen if src_override is None
                    else (protected if one_shot else None)
                )
                ex = keys = None
                try:
                    # ONE executor bundle per chunk: the spill tee (first,
                    # so its eager form writes before the histogram handle
                    # can finish) and the histogram dispatch share the
                    # FIFO window, and the staged buffer is released when
                    # the LAST of the two results materializes — not
                    # before. Under ``fused`` the tee + histogram collapse
                    # further into ONE device program per staged bucket
                    # (the single-read ingest, ops/pallas/fused_ingest.py)
                    # — the unfused bundle stays the bit-for-bit oracle
                    # (fused="off"). Built INSIDE the try: a consumer/
                    # executor constructor raising must still abort the
                    # generation, or its records strand on disk (KSL020)
                    hist_c = _ex.HistogramConsumer(
                        shift, width, prefixes, _pass_method(method, width),
                        kdt, obs=obs,
                    )
                    tee_c = (
                        _ex.SpillTeeConsumer(
                            writer, filter_specs, dtype, kdt, total_bits,
                            devs, deferred=defer, obs=obs,
                        )
                        if writer is not None
                        else None
                    )
                    if tee_c is not None and fuse:
                        consumers = [
                            _ex.FusedIngestConsumer(
                                hist=hist_c, tee=tee_c, kdt=kdt,
                                total_bits=total_bits, tier=fuse, obs=obs,
                            )
                        ]
                    elif tee_c is not None:
                        consumers = [tee_c, hist_c]
                    else:
                        consumers = [hist_c]
                    ex = _ex.StreamExecutor(
                        consumers, window=window, occupancy=occupancy
                    )
                    with _pl._phase(timer, "descent.pass"), _key_chunk_stream(
                        src_override if src_override is not None
                        else _gen_src(filter_specs),
                        dtype, hist_method=method, **stream_kw
                    ) as kc:
                        for keys, _ in kc:
                            if obs is not None:
                                _wr.chunk_event(
                                    obs, pass_label, chunk_i, keys, kdt, devs
                                )
                            chunk_i += 1
                            pass_keys += int(keys.size)
                            ex.push(keys)
                        ex.drain()
                    hists = hist_c.hists
                    for p in prefixes:
                        # replay-stability check, mirroring
                        # _collect_survivors': this pass's population under
                        # each surviving prefix must equal the bucket count
                        # the PREVIOUS pass (or the seeding sketch)
                        # established — a drifting source fails loudly here
                        # instead of walking a corrupt histogram to a wrong
                        # answer. On the spill path the read is a
                        # checksummed generation, so this is unreachable
                        # short of a store bug; it stays as the belt to the
                        # spill records' braces (and holds the recovery
                        # ladder's REBUILT reads to the same books). Inside
                        # the try: this raise used to strand the writer's
                        # uncommitted generation (KSL020's first run)
                        if int(hists[p].sum()) != expected[p]:
                            raise RuntimeError(
                                f"chunk source is not replay-stable: prefix "
                                f"{p:#x} holds {int(hists[p].sum())} elements "
                                f"this pass, previous pass counted "
                                f"{expected[p]}. The source callable must "
                                "yield identical data on every invocation."
                            )
                except BaseException:
                    # writer.abort() rides a finally: the executor abort
                    # (or staged-chunk release) raising must not strand
                    # the generation's ksel-spill records
                    try:
                        if ex is not None:
                            ex.abort()
                        _ex.release_staged(keys)  # chunk in hand (idempotent)
                    finally:
                        if writer is not None:
                            writer.abort()
                    raise
                gen = writer.commit() if writer is not None else None
                if disk_gen is None:
                    disk_read = pass_keys * kdt.itemsize
                elif src_override is None:
                    # the scheduled (pruned) read: price the directory +
                    # matching segments, not the whole generation
                    disk_read = int(disk_gen.read_nbytes(filter_specs))
                else:
                    disk_read = int(disk_gen.nbytes)
                return hists, gen, chunk_i, pass_keys, read_from, disk_read

            (
                hists, gen, chunk_i, pass_keys, pass_read_from, pass_disk_read,
            ) = _recover_pass(
                _run_pass,
                policy=policy,
                reading_spill=read_gen is not None,
                fallback=_fallback_src(),
                on_enospc=_on_enospc,
                obs=obs,
                site=f"pass {pass_label}",
            )
            if gen is not None:
                _log_pass(
                    pass_label, gen, keys_read=pass_keys, read=pass_read_from,
                    disk_read=pass_disk_read,
                )
                _rotate(gen)
            elif store is not None:
                # degraded (writer-less) passes still log their read, so
                # the pass_log keeps its one-entry-per-pass accounting —
                # and stays consistent with the StreamPassEvents — after
                # an ENOSPC downgrade
                _log_pass(
                    pass_label, keys_read=pass_keys, read=pass_read_from,
                    disk_read=pass_disk_read,
                )
            for st in states:
                if _active(st):
                    st[0], st[1], st[3] = _np_walk(
                        hists[st[0]], st[1], st[0], width
                    )
                    st[2] = resolved + width
            if obs is not None:
                if gen is not None:
                    obs.emit(
                        _ev.SpillGenerationEvent(
                            generation=gen.index,
                            records=len(gen.records),
                            keys=gen.keys,
                            nbytes=gen.nbytes,
                            logical_nbytes=gen.logical_nbytes,
                            packed=gen.packed,
                        )
                    )
                totalp, maxp, nzp = _hist_summary(hists)
                obs.emit(
                    _ev.StreamPassEvent(
                        pass_index=pass_label,
                        resolved_bits=resolved,
                        prefixes=tuple(int(p) for p in prefixes),
                        chunks=chunk_i,
                        # the SUCCESSFUL attempt's actual read (a recovered
                        # pass may have rebuilt from the ladder's fallback)
                        keys_read=pass_keys,
                        bytes_read=pass_keys * kdt.itemsize,
                        read_from=pass_read_from,
                        bucket_total=totalp,
                        bucket_max=maxp,
                        bucket_nonzero=nzp,
                        survivors=tuple(int(st[3]) for st in states),
                        keys_written=None if gen is None else int(gen.keys),
                        bytes_written=(
                            None if gen is None else int(gen.logical_nbytes)
                        ),
                        disk_bytes_read=pass_disk_read,
                        disk_bytes_written=(
                            None if gen is None else int(gen.nbytes)
                        ),
                    )
                )
                _wr.resolved_bits_gauge(obs, pass_label, resolved + width)

        specs = {}
        for prefix, _kk, resolved, pop in states:
            if resolved < total_bits:
                specs[(resolved, int(prefix))] = pop
        collected = {}
        if specs:

            def _run_collect(src_override, tee):
                # the SUCCESSFUL attempt's actual read, for the event AND
                # the pass_log (a rebuilt collect reads the source — or a
                # one-shot run's gen-0 anchor — not the scheduled gen);
                # the scheduled read prunes the generation to the collect
                # specs' segments, and the accounting prices that
                cspecs = tuple(specs)
                if src_override is None:
                    read_from = "spill" if read_gen is not None else "source"
                    kr = read_gen.read_keys(cspecs) if read_gen is not None else n
                    dg = read_gen
                    disk = (
                        int(dg.read_nbytes(cspecs)) if dg is not None
                        else int(kr) * kdt.itemsize
                    )
                elif one_shot:
                    read_from, kr, dg = "spill", protected.keys, protected
                    disk = int(dg.nbytes)
                else:
                    read_from, kr, dg = "source", n, None
                    disk = int(kr) * kdt.itemsize
                return (
                    _collect_survivors(
                        src_override if src_override is not None
                        else _gen_src(cspecs),
                        dtype, specs, pipeline_depth=pipeline_depth,
                        timer=timer, devices=None if devices is None else devs,
                        hist_method=method, obs=obs,
                        read_from=read_from, disk_bytes_read=disk,
                        deferred=defer, fused=fuse, retry=policy,
                        ingest_workers=pool_n,
                    ),
                    read_from,
                    int(kr),
                    disk,
                )

            collected, coll_read, coll_keys, coll_disk = _recover_pass(
                _run_collect,
                policy=policy,
                reading_spill=read_gen is not None,
                fallback=_fallback_src(),
                on_enospc=None,
                obs=obs,
                site="collect",
            )
            _log_pass(
                "collect", keys_read=coll_keys, read=coll_read,
                disk_read=coll_disk,
            )

        if obs is not None and obs.metrics is not None:
            # snapshot the run's counters while the store is still open
            # (the finally below may remove an internal one); the ledger
            # fold carries the PROCESS-lifetime compile/byte book
            # (per-run readings delta two ledger snapshots)
            _om.collect_runtime(
                obs.metrics, staging_pool=_pl.STAGING_POOL,
                spill_store=store, timer=timer,
            )
            _ldg.collect_ledger(obs.metrics)
        answers = []
        for prefix, kk, resolved, _pop in states:
            if resolved == total_bits:
                # every key bit determined (either the schedule ran out or
                # the survivors are duplicates of one key): the prefix IS
                # the answer
                ans_key = kdt.type(prefix)
            else:
                surv = collected[(resolved, int(prefix))]
                ans_key = np.partition(surv, kk - 1)[kk - 1]
            answers.append(
                _dt.np_from_sortable_bits(np.asarray([ans_key], kdt), dtype)[0]
            )
        return answers
    finally:
        _restore_recorder()
        if own_store:
            store.close()
        elif store is not None:
            # caller-owned store: drop descent-internal generations, keep
            # the pass-0 tee (it can serve refine/certificate/next calls)
            for g in created:
                if g is not protected and not g.dropped:
                    store.drop_generation(g)


def streaming_rank_certificate(
    source, value, *, pipeline_depth: int = DEFAULT_PIPELINE_DEPTH, timer=None,
    devices=None, deferred=DEFAULT_DEFERRED, fused=DEFAULT_FUSED,
    width_schedule=DEFAULT_WIDTH_SCHEDULE, pack_spill=DEFAULT_PACK_SPILL,
    ingest_workers=None, retry=None, obs=None,
):
    """``(#elements < value, #elements <= value)`` streamed — the O(n)
    exactness proof of utils/debug.py:rank_certificate without residency:
    an answer for rank k is exact iff ``less < k <= leq``. Comparisons run
    in key space (total order: ties, -0.0/+0.0 and NaN behave exactly like
    the selection itself). ``pipeline_depth`` >= 1 overlaps chunk
    production/encode with the counting (single-device: no staging — the
    counts consume keys wherever they already live). ``devices`` > 1
    stages chunks round-robin so each device counts its own resident
    chunks, with the per-chunk int counts folded into the host int
    accumulators in FIFO chunk order (integer addition — order-exact
    either way); the host-exact 64-bit/f64-on-TPU routes keep counting on
    host. ``deferred`` (default on) traces the staged counts over the
    whole padded bucket with an exact pad correction — one compile per
    staging bucket instead of one per ragged chunk length — and reads
    spill records via mmap; ``"off"`` keeps the historical valid-slice
    sums (bit-identical counts either way). ``fused`` (default
    ``"auto"``; see :func:`streaming_kselect`) engages the single-sweep
    kernel at the ``"kernel"`` tier: a supported staged bucket's
    ``(<, <=)`` pair rides ONE device program (one guaranteed read,
    ``ingest.bucket_reads{phase="certificate"}`` = 1 per bucket) instead
    of the deferred pair of count programs; the ``"xla"`` and ``"off"``
    tiers keep the pair — there was never a separate XLA fusion for it —
    and counts are bit-identical at every tier. ``source`` may be a
    :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore` with a
    committed generation: the single counting pass then replays the
    spilled keys instead of the original stream (certifying a one-shot
    source's answer without re-reading it). ``retry`` (see
    :func:`streaming_kselect_many`; None = the bounded default) gives
    the counting pass mid-pass re-pull on transient source errors and
    in-place staging retries — counts are bit-identical on recovery.
    ``width_schedule``/``pack_spill`` are accepted (and validated — a
    typo must raise here like on every other entry point, so one knob
    dict can serve a whole workload) but are no-ops: the certificate is
    a single comparison pass with no digit histogram to widen and no
    survivor generation to pack. Reading a PACKED store-as-source works
    regardless — record format is a property of the store, not the
    reader. ``ingest_workers`` (see :func:`streaming_kselect`) widens
    the host plane of the counting pass the same way — encode and
    staging on the pool, counts folded in sequencer-preserved chunk
    order, bit-identical at every width."""
    validate_width_schedule(width_schedule)
    _sp.validate_pack_spill(pack_spill)
    defer = _ex.resolve_deferred(deferred)
    # fusion is a deferral discipline (streaming_kselect_many's rule);
    # the knob validates on the eager route too
    fused = _ex.validate_fused(fused)
    fuse = _ex.resolve_fused(fused) if defer else False
    pool_n = _pl.resolve_ingest_workers(ingest_workers)
    policy = _fp.resolve_retry(retry)
    src = as_chunk_source(source, mmap=defer, workers=pool_n)
    if policy is not None:
        src = _fp.resilient_source(src, policy, obs=obs)
    devs = _pl.resolve_stream_devices(devices)
    timer, _restore_recorder = _wr.attach_timer(obs, timer)
    _wr.ingest_workers_gauge(obs, pool_n)
    depth = _pl.validate_pipeline_depth(pipeline_depth)
    # gate staging on the raw knobs, not the resolved tuple (KSL022): an
    # explicit single device must stage committed, not host-fold
    staged = depth > 0 and devices is not None
    vkey = None
    kdt = None
    counter = ex = keys = None
    chunk_i = keys_read = 0
    try:
        with _pl._phase(timer, "certificate.pass"), _key_chunk_stream(
            src, pipeline_depth=pipeline_depth, timer=timer,
            hist_method="auto" if staged else None,
            devices=devs if staged else None, retry=policy, obs=obs,
            workers=pool_n,
        ) as kc:
            for keys, chunk in kc:
                if vkey is None:
                    # key the probe value from the first chunk's dtype — no
                    # chunk is produced just to learn it
                    vkey = _dt.np_to_sortable_bits(
                        np.asarray([value], np.dtype(chunk.dtype))
                    )[0]
                    kdt = np.dtype(_dt.key_dtype(np.dtype(chunk.dtype)))
                    counter = _ex.CountLessLeqConsumer(
                        vkey, kdt, deferred=defer, fused=fuse, obs=obs
                    )
                    # both counts dispatch async on the chunk's own device;
                    # the FIFO materializes the oldest once one bundle per
                    # device is in flight (deferred: over the whole padded
                    # bucket with the exact pad correction — one compile
                    # per bucket instead of one per ragged chunk length)
                    ex = _ex.StreamExecutor(
                        [counter], window=len(devs),
                        occupancy=_wr.window_occupancy(obs, phase="certificate"),
                    )
                if obs is not None:
                    _wr.chunk_event(obs, "certificate", chunk_i, keys, kdt, devs)
                chunk_i += 1
                keys_read += int(keys.size)
                ex.push(keys)
            if ex is not None:
                ex.drain()
    except BaseException:
        if ex is not None:
            ex.abort()
        _ex.release_staged(keys)  # the chunk in hand (idempotent)
        raise
    finally:
        _restore_recorder()
    if vkey is None:
        raise ValueError("streaming_rank_certificate requires a non-empty stream")
    less, leq = counter.less, counter.leq
    if obs is not None:
        obs.emit(
            _ev.CertificateEvent(
                chunks=chunk_i, keys_read=keys_read, less=less, leq=leq
            )
        )
        if obs.metrics is not None:
            _om.collect_runtime(
                obs.metrics, staging_pool=_pl.STAGING_POOL, timer=timer
            )
    return less, leq
