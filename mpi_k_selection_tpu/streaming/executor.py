"""Async streaming executor — ONE event-driven scheduler for every
per-chunk device consumer, with deferred host transfers.

Before this module, the streaming descent had FOUR per-chunk consumers,
each with its own consumption discipline:

- the histogram dispatch/merge rode an in-flight FIFO window (dispatch
  async on the chunk's device, materialize the oldest once one dispatch
  per ingest device is pending) — already overlap-friendly;
- the survivor collect and the spill tee did an EAGER boolean gather
  (``np.asarray(kv[m])``) at chunk-arrival time — the consumer blocked on
  a device->host sync per chunk, so on a multi-device pass the p-wide
  in-flight window degraded toward serial on exactly the biggest
  (pass-1 spill / collect) reads — review r6, the ROADMAP's
  "async streaming executor" item;
- the rank-certificate count folds rode the window but traced their sums
  over the per-chunk ``StagedKeys.valid()`` slice — one XLA compile per
  distinct chunk length instead of one per staging bucket.

This module unifies all four under the existing
:class:`~mpi_k_selection_tpu.streaming.pipeline.InflightWindow` FIFO
discipline with **deferred device-side compaction**: instead of gathering
survivors eagerly, each chunk's work becomes a device-side dispatch
handle — a jit-compiled mask -> count -> fixed-shape compaction program
per (bucket, dtype, device), with the spec ``(shift, prefix)`` pairs as
traced scalars so the program compiles ONCE per staging bucket (the
KSC103 trail-stability contract) — whose host materialization
(``np.asarray`` of only the compacted survivor prefix, plus the count)
happens when the FIFO window pops, not when the chunk arrives.
``StagedKeys.release()`` moves to handle-finish time, so staged buffers
live exactly as long as their in-flight work.

Determinism contract (the grid tests/test_executor.py enforces):

- ``deferred="off"`` reproduces the pre-executor eager behavior exactly
  (eager gathers at chunk-arrival time, certificate sums over the valid
  slice); ``"auto"``/``"on"`` defer — and answers are bit-identical
  across the whole devices x depth x spill x deferred grid, because
  every downstream fold is order-invariant (int64 histogram sums, the
  survivor multiset, integer certificate counts) and the FIFO fixes the
  fold order anyway.
- Deferral engages exactly for :class:`~mpi_k_selection_tpu.streaming.
  pipeline.StagedKeys` chunks (device-resident, pow2-padded). Host
  chunks — including the host-exact 64-bit-no-x64 and f64-on-TPU routes,
  which never stage — always take the host path, and
  ``pipeline_depth=0`` / unstaged device chunks keep the eager path, so
  the synchronous oracle and the single-device defaults are unchanged.
- Chunks with NO in-flight device work (all consumers folded at dispatch
  time) skip the window entirely: no occupancy sample, immediate
  release — exactly the pre-executor serial discipline, which is what
  makes ``deferred="off"`` a bit-for-bit oracle rather than a near
  re-implementation.
- The executor consumes chunks from ONE thread in stream order, and the
  parallel host data plane (``ingest_workers`` > 1,
  streaming/pipeline.py) preserves that: its reorder sequencer releases
  chunks to this consumer strictly in chunk-index order, so the FIFO
  window's push/pop sequence — and therefore every dispatch, fold and
  release order above — is identical at every pool width. Nothing in
  this module is pool-aware; the contract is upheld upstream.

On top of the deferral, the ``fused`` knob (default ``"auto"``)
collapses a pass's per-chunk device programs — the histogram, the
per-spec survivor compactions, the spill-tee payload — into ONE fused
program per staged bucket (:class:`FusedIngestConsumer`), at one of two
tiers: ``"kernel"`` (the hand-written single-sweep pallas program,
ops/pallas/sweep_ingest.py — one GUARANTEED HBM read of the bucket; the
``"auto"`` default on TPU backends) or ``"xla"`` (the one-XLA-program
fusion, ops/pallas/fused_ingest.py — one dispatch, read count up to
XLA; the ``"auto"`` default elsewhere). ``fused="off"`` keeps the
unfused bundle as the bit-for-bit oracle, and lint rule KSL014 flags a
second ingest program against one staged bucket anywhere else in the
streaming layer.

This file is the ONE sanctioned home for the eager
``np.asarray(<indexed device array>)`` gather under ``streaming/`` —
lint rule KSL011 flags it anywhere else in the streaming layer, because
an eager gather on a chunk-consume path is exactly the serialization
this module retires.
"""

from __future__ import annotations

import numpy as np

from mpi_k_selection_tpu.obs import wiring as _wr
from mpi_k_selection_tpu.obs.ledger import ledger_dispatch as _ledger_dispatch
from mpi_k_selection_tpu.ops.pallas import fused_ingest as _fi
from mpi_k_selection_tpu.ops.pallas import sweep_ingest as _si
from mpi_k_selection_tpu.ops.pallas.fused_ingest import (
    compact_core as _compact_core,
)
from mpi_k_selection_tpu.streaming import pipeline as _pl
from mpi_k_selection_tpu.streaming.pipeline import StagedKeys, _bucket_elems

#: Default for the ``deferred`` knob: defer wherever a staged device
#: chunk makes it possible (bit-identical, strictly less consumer
#: blocking — there is no configuration where eager wins, so auto == on;
#: the mode exists so a future heuristic can narrow it without an API
#: change).
DEFAULT_DEFERRED = "auto"

#: The ``deferred`` knob's string modes (bools are also accepted).
DEFERRED_MODES = ("auto", "on", "off")

#: Default for the ``fused`` knob: fuse the per-chunk device programs —
#: histogram, survivor compaction(s), spill-tee payload — into ONE
#: program per staged bucket wherever deferral is engaged (bit-identical,
#: strictly fewer reads of the same buffer). ``"auto"`` resolves to the
#: hand-written sweep kernel tier on TPU backends (one GUARANTEED HBM
#: read — ops/pallas/sweep_ingest.py) and the XLA fusion tier elsewhere,
#: mirroring how ``hist_method="auto"`` resolves to the pallas histogram
#: kernels on TPU. ``"off"`` keeps the unfused consumer bundle as the
#: bit-for-bit oracle.
DEFAULT_FUSED = "auto"

#: The ``fused`` knob's string modes (bools are also accepted):
#: ``kernel`` = the single-sweep pallas program (interpret-mode off-TPU),
#: ``xla`` = the one-XLA-program fusion (PR 11's behavior), ``off`` = the
#: unfused per-consumer bundle, ``auto`` = kernel on TPU, xla elsewhere.
FUSED_MODES = ("auto", "kernel", "xla", "off")

#: The resolved fusion tiers ``resolve_fused`` can return (besides
#: ``False`` for the unfused bundle).
FUSED_TIERS = ("kernel", "xla")


def kernel_tier_available() -> bool:
    """Whether ``fused="auto"`` resolves to the sweep-kernel tier: a jax
    build carrying pallas, on a TPU backend — the same resolution rule as
    ``hist_method="auto"`` (ops/histogram.py routes to the pallas kernels
    on TPU, scatter elsewhere). Off-TPU the kernel only interprets
    (exact but slow), so ``"auto"`` keeps the XLA tier there; pass
    ``fused="kernel"`` to force the interpret-mode kernel."""
    if not _si._pallas_available():
        return False
    import jax

    return jax.default_backend() == "tpu"


def validate_fused(fused) -> str:
    """Check the ``fused`` knob and return its normalized mode string
    WITHOUT resolving ``"auto"`` to a tier — unlike :func:`resolve_fused`
    this never probes the jax backend, so validation-only paths (the
    eager ``StreamingQuantiles.__init__`` check, the ``deferred="off"``
    route that forces the unfused bundle anyway) reject a typo'd knob
    without triggering platform/device initialization."""
    if isinstance(fused, (bool, np.bool_)):
        return "auto" if fused else "off"
    if fused in FUSED_MODES:
        return fused
    raise ValueError(
        f"fused must be one of {FUSED_MODES} or a bool, got {fused!r}"
    )


def resolve_fused(fused):
    """Normalize the ``fused`` knob to a resolved tier: ``"kernel"`` (the
    single-sweep pallas program), ``"xla"`` (the one-XLA-program fusion),
    or ``False`` (the unfused per-consumer bundle, the bit-for-bit
    oracle). Accepts the :data:`FUSED_MODES` strings or a plain bool
    (True = ``"auto"``); ``"auto"`` resolves via
    :func:`kernel_tier_available`. Fusion IS a deferral discipline, so
    ``deferred="off"`` implies the unfused bundle regardless (the
    resolution in streaming/chunked.py applies that)."""
    fused = validate_fused(fused)
    if fused == "auto":
        return "kernel" if kernel_tier_available() else "xla"
    if fused == "off":
        return False
    return fused


def resolve_deferred(deferred) -> bool:
    """Normalize the ``deferred`` knob to a bool (True = deferred
    device-side compaction engages for staged chunks). Accepts
    ``"auto"``/``"on"``/``"off"`` or a plain bool; ``"auto"`` (the
    default) currently equals ``"on"`` — see :data:`DEFAULT_DEFERRED`."""
    if isinstance(deferred, (bool, np.bool_)):
        return bool(deferred)
    if deferred in ("auto", "on"):
        return True
    if deferred == "off":
        return False
    raise ValueError(
        f"deferred must be one of {DEFERRED_MODES} or a bool, got {deferred!r}"
    )


def prefix_mask(kv, resolved, prefix, kdt, total_bits):
    """The survivor filter predicate — keys whose top ``resolved`` bits
    equal ``prefix`` — on ``kv``'s own residency (host numpy, or a device
    shift-compare tracing to a bool mask). The ONE predicate shared by the
    survivor collect, the spill tee, and the deferred compaction program,
    so the KSC102/KSC103 contract coverage of its traced form transfers to
    every caller by construction."""
    shift = total_bits - resolved
    if isinstance(kv, np.ndarray):
        return (kv >> kdt.type(shift)) == kdt.type(prefix)
    import jax

    return jax.lax.shift_right_logical(
        kv, kv.dtype.type(shift)
    ) == kv.dtype.type(prefix)


# ---------------------------------------------------------------------------
# the deferred compaction program — the core lives with the fused-ingest
# kernel (ops/pallas/fused_ingest.py:compact_core, aliased above), which
# unions it with the histogram into ONE program per staged bucket; the
# alias keeps the executor the import surface the contract checks and
# tests address

_COMPACT_FN = None


def _compact_fn():
    global _COMPACT_FN
    if _COMPACT_FN is None:
        import jax

        _COMPACT_FN = jax.jit(_compact_core)
    return _COMPACT_FN


def dispatch_compaction(staged: StagedKeys, specs, kdt, total_bits):
    """Launch the compaction program for the union of ``(resolved_bits,
    prefix)`` ``specs`` on the staged chunk's OWN device (async dispatch —
    ``staged.data`` is committed, so the program runs where the chunk
    lives). Returns the in-flight ``(compacted, count)`` handle for
    :func:`materialize_compacted`."""
    shifts = np.asarray([total_bits - r for r, _ in specs], kdt)
    prefixes = np.asarray([p for _, p in specs], kdt)
    return _compact_fn()(staged.data, np.int32(staged.n_valid), shifts, prefixes)


def materialize_compacted(handle, kdt) -> np.ndarray:
    """Block on one :func:`dispatch_compaction` handle and bring ONLY the
    compacted survivors host-side: the count scalar first (by finish time
    the program has typically long completed — that is the whole point of
    the FIFO deferral), then the survivor prefix rounded up to its pow2
    bucket (device slices compile per shape; the rounding bounds the
    slice-shape set to log2(bucket) per staging bucket, the same
    discipline as the staging pads)."""
    compacted, count = handle
    cnt = int(count)
    if cnt == 0:
        return np.empty((0,), kdt)
    b = _bucket_elems(cnt)
    if b >= compacted.shape[0]:
        return np.asarray(compacted)[:cnt]
    return np.asarray(compacted[:b])[:cnt]


# ---------------------------------------------------------------------------
# per-chunk histogram dispatch/finish (moved from streaming/chunked.py —
# the executor owns every per-chunk device consumer)


def dispatch_chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt):
    """DISPATCH one chunk's digit histogram(s) at ``shift`` for every
    prefix in ``prefixes`` (``None`` = no filter) and return an in-flight
    handle for :func:`finish_chunk_histograms` — the chunk-side work is
    paid ONCE and shared across prefixes: host chunks compute the
    digit/prefix arrays once, device chunks cross the tunnel once and stay
    on device for the counts (the whole point on TPU); only the
    (2**radix_bits,) counts per prefix come back at finish time.

    Device work is dispatched asynchronously on the chunk's OWN device
    (jax async dispatch; :class:`~mpi_k_selection_tpu.streaming.pipeline.
    StagedKeys` are committed to their round-robin slot, so up to one
    dispatch per ingest device runs concurrently under the executor's
    window). The ``"numpy"`` method computes host-side immediately —
    there is nothing to overlap.

    Pipelined passes hand in :class:`StagedKeys` — a pow2-padded,
    already-device-resident buffer. The histogram runs over the WHOLE
    padded buffer (fixed shape, one compile per bucket size) and the pad
    contribution is subtracted host-side at finish: pad keys are key-space
    0, so they land in digit bucket 0 and only under the all-zero prefix —
    an exact integer correction."""
    staged = isinstance(keys, StagedKeys)
    if method == "numpy":
        if staged:  # pragma: no cover - staging only feeds device methods
            keys = np.asarray(keys.valid())
        k = keys if isinstance(keys, np.ndarray) else np.asarray(keys)
        dig = ((k >> kdt.type(shift)) & kdt.type((1 << radix_bits) - 1)).astype(
            np.int64
        )
        nb = 1 << radix_bits
        if len(prefixes) == 1 and prefixes[0] is None:
            return (None, {None: np.bincount(dig, minlength=nb).astype(np.int64)})
        up = k >> kdt.type(shift + radix_bits)
        return (
            None,
            {
                p: np.bincount(dig[up == kdt.type(p)], minlength=nb).astype(np.int64)
                for p in prefixes
            },
        )
    import jax.numpy as jnp

    from mpi_k_selection_tpu.ops.histogram import (
        masked_radix_histogram,
        multi_masked_radix_histogram,
    )

    dk = keys.data if staged else jnp.asarray(keys)  # ksel: noqa[KSL002] -- 64-bit keys only reach this device branch with x64 on: resolve_stream_hist routes them to the host 'numpy' method otherwise
    if len(prefixes) == 1 and prefixes[0] is None:
        h = masked_radix_histogram(
            dk,
            shift=shift,
            radix_bits=radix_bits,
            prefix=None,
            method=method,
            count_dtype=jnp.int32,  # exact per chunk (chunk size < 2^31)
        )
    else:
        # the shared-sweep primitive of the resident multi-rank descent: on
        # the pallas methods all K prefix queries ride ONE read of the chunk
        # (other methods fall back to K single-prefix sweeps — correct,
        # just K reads)
        h = multi_masked_radix_histogram(
            dk,
            shift=shift,
            radix_bits=radix_bits,
            prefixes=np.asarray(prefixes, kdt),
            method=method,
            count_dtype=jnp.int32,
        )
    return ((keys if staged else None, list(prefixes), h), None)


def finish_chunk_histograms(handle, release: bool = True):
    """Materialize one :func:`dispatch_chunk_histograms` handle into the
    ``{prefix: int64 histogram}`` dict: block on the device counts, widen
    to the host int64 accumulator dtype, and apply the exact pad
    correction. ``release`` donates the staged ring slot here — the
    serial (:func:`chunk_histograms`) form; the executor passes False and
    releases once EVERY consumer of the chunk has finished."""
    inflight, done = handle
    if done is not None:
        return done
    staged, prefixes, h = inflight
    if len(prefixes) == 1 and prefixes[0] is None:
        out = {None: np.asarray(h).astype(np.int64)}
    else:
        hk = np.asarray(h).astype(np.int64)
        out = {p: hk[i] for i, p in enumerate(prefixes)}
    if staged is not None:
        if staged.pad:
            # pad keys are key-space 0: digit (0 >> shift) & mask == 0, and
            # they pass a prefix filter only when every upper bit is 0
            for p, hist in out.items():
                if p is None or int(p) == 0:
                    hist[0] -= staged.pad
        if release:
            # the counts above are host-materialized (np.asarray blocked
            # on them), so the ring slot can be donated back eagerly
            staged.release()
    return out


def chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt):
    """Dispatch + finish in one step — the serial form the contract checks
    and unit tests use."""
    return finish_chunk_histograms(
        dispatch_chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt)
    )


# ---------------------------------------------------------------------------
# consumers


def eager_valid(kv):
    """The valid (unpadded) view an EAGER consumer reads off its ``kv``:
    a staged chunk's device slice, derived ON DEMAND — deferred/fused
    paths consume ``keys.data`` whole and never touch it, so the slice
    (a real device program over the padded bucket) is dispatched only
    when an eager path will actually read it."""
    return kv.valid() if isinstance(kv, StagedKeys) else kv


class Consumer:
    """One per-chunk consumer under the executor: ``dispatch`` launches
    (or, for host/eager work, completes) a chunk's work and returns an
    in-flight handle — or ``None`` when everything already folded;
    ``finish`` materializes a pending handle host-side, strictly in chunk
    FIFO order. Implementations fold into their own accumulators; the
    executor owns buffer lifetime (``StagedKeys.release()``).

    ``dispatch(keys, kv)``: ``kv`` is the chunk's keys on their own
    residency (host numpy, or a device array) — EXCEPT for staged chunks,
    where it is the :class:`StagedKeys` itself and an eager path derives
    the valid slice via :func:`eager_valid` (deferred paths read the
    whole padded ``keys.data`` and apply the exact pad correction)."""

    def dispatch(self, keys, kv):  # pragma: no cover - protocol
        raise NotImplementedError

    def finish(self, handle) -> None:  # pragma: no cover - protocol
        raise NotImplementedError


class HistogramConsumer(Consumer):
    """The descent's histogram merge: per-chunk dispatch via
    :func:`dispatch_chunk_histograms`, per-prefix int64 accumulation at
    finish (int64 addition is exact and order-invariant; the FIFO order is
    belt and braces, and keeps the replay-stability diagnostics
    reproducible)."""

    def __init__(self, shift, radix_bits, prefixes, method, kdt, obs=None):
        self.hists = {
            p: np.zeros((1 << radix_bits,), np.int64) for p in prefixes
        }
        self._args = (shift, radix_bits, list(prefixes), method, kdt)
        self._obs = obs

    def dispatch(self, keys, kv):
        shift, radix_bits, prefixes, method, kdt = self._args
        staged = isinstance(keys, StagedKeys)
        if staged and method != "numpy":
            _wr.bucket_read(self._obs, "histogram", keys)
        if method == "numpy":
            handle = dispatch_chunk_histograms(
                keys, shift, radix_bits, prefixes, method, kdt
            )
        else:
            # compile identity of the device histogram program: buffer
            # length (the pow2 bucket for staged chunks, the ragged
            # length otherwise — each distinct length IS a compile),
            # dtype, prefix COUNT (values are traced), method and the
            # static shift/radix geometry
            buf = keys.data if staged else keys
            key = (
                int(buf.shape[0]), kdt.str,
                0 if prefixes[0] is None else len(prefixes),
                method, shift, radix_bits,
            )
            # the per-level shift multiplies compiles in ONE healthy
            # descent (levels x buckets) — strip it from the storm
            # detector's churn identity so only genuine shape/width
            # churn counts toward the threshold
            with _ledger_dispatch(
                "ingest.histogram", key, self._obs,
                storm_key=key[:4] + key[5:],
            ):
                handle = dispatch_chunk_histograms(
                    keys, shift, radix_bits, prefixes, method, kdt
                )
        if handle[1] is not None:  # host-computed: fold now, nothing in flight
            self._fold(handle[1])
            return None
        return handle

    def finish(self, handle) -> None:
        self._fold(finish_chunk_histograms(handle, release=False))

    def _fold(self, hd) -> None:
        for p, h in hd.items():
            self.hists[p] += h


class CollectConsumer(Consumer):
    """The survivor collect: one filter per ``(resolved_bits, prefix)``
    spec per chunk, survivors accumulated per spec in chunk order.
    Deferred: one compaction dispatch per spec on the staged chunk's own
    device, survivors crossing back only at FIFO-finish time. Eager
    (``deferred="off"``, host chunks, unstaged device chunks): the
    historical gather at dispatch time."""

    def __init__(self, specs, kdt, total_bits, *, deferred: bool, obs=None):
        self.specs = list(specs)
        self.out = {s: [] for s in self.specs}
        self._kdt = kdt
        self._bits = total_bits
        self._deferred = bool(deferred)
        self._obs = obs

    def dispatch(self, keys, kv):
        if isinstance(keys, StagedKeys):
            # one program per spec, deferred or eager — the read count the
            # fused consumer collapses to 1
            _wr.bucket_read(self._obs, "collect", keys, len(self.specs))
        if self._deferred and isinstance(keys, StagedKeys):
            # ONE compiled compaction serves every single-spec dispatch of
            # a bucket (shift/prefix are traced scalars): one ledger key
            # per (bucket, dtype), hits for every later spec and chunk
            key = (int(keys.data.shape[0]), self._kdt.str, 1)
            with _ledger_dispatch("ingest.collect", key, self._obs):
                return [
                    dispatch_compaction(keys, [spec], self._kdt, self._bits)
                    for spec in self.specs
                ]
        kv = eager_valid(kv)
        host = isinstance(kv, np.ndarray)
        for spec in self.specs:
            m = prefix_mask(kv, spec[0], spec[1], self._kdt, self._bits)
            # host indexing, or the eager boolean gather device-side —
            # the pre-executor path, kept as the deferred=off oracle
            surv = kv[m] if host else np.asarray(kv[m])
            if surv.size:
                self.out[spec].append(np.asarray(surv, self._kdt))
        return None

    def finish(self, handles) -> None:
        for spec, h in zip(self.specs, handles):
            surv = materialize_compacted(h, self._kdt)
            if surv.size:
                self.out[spec].append(surv)

    def collected(self, kdt) -> dict:
        """``{spec: concatenated host key array}`` after the drain."""
        return {
            spec: (np.concatenate(parts) if parts else np.empty((0,), kdt))
            for spec, parts in self.out.items()
        }


class SpillTeeConsumer(Consumer):
    """The spill tee: filter ONE chunk to the union of surviving specs
    (the collect predicate OR-ed over specs) and append the compacted
    survivors to the next spill generation. Deferred: one union-mask
    compaction on the chunk's own device, the record written at
    FIFO-finish time — so the generation's record order follows the
    executor's deterministic finish order (downstream consumers fold
    order-invariantly; the staged slot each record carries preserves the
    chunk->device replay contract regardless)."""

    def __init__(
        self, writer, specs, dtype, kdt, total_bits, devs, *, deferred,
        obs=None,
    ):
        self._writer = writer
        self._specs = list(specs)
        self._dtype = dtype
        self._kdt = kdt
        self._bits = total_bits
        self._devs = devs
        self._deferred = bool(deferred)
        self._obs = obs

    def _append(self, surv, slot) -> None:
        if surv.size:
            self._writer.append(
                np.asarray(surv, self._kdt), self._dtype, device_slot=slot
            )

    def dispatch(self, keys, kv):
        slot = _wr.staged_slot(keys, self._devs)
        if isinstance(keys, StagedKeys):
            _wr.bucket_read(self._obs, "tee", keys)
        if self._deferred and isinstance(keys, StagedKeys):
            key = (int(keys.data.shape[0]), self._kdt.str, len(self._specs))
            with _ledger_dispatch("ingest.tee", key, self._obs):
                return (
                    slot,
                    dispatch_compaction(
                        keys, self._specs, self._kdt, self._bits
                    ),
                )
        kv = eager_valid(kv)
        m = None
        for resolved, prefix in self._specs:
            mi = prefix_mask(kv, resolved, prefix, self._kdt, self._bits)
            m = mi if m is None else (m | mi)
        if m is None:  # pragma: no cover - a pass always has >= 1 spec
            return None
        # host indexing, or the eager gather on the owning device — the
        # pre-executor path, kept as the deferred=off oracle
        surv = kv[m] if isinstance(kv, np.ndarray) else np.asarray(kv[m])
        self._append(surv, slot)
        return None

    def finish(self, handle) -> None:
        slot, h = handle
        self._append(materialize_compacted(h, self._kdt), slot)


class CountLessLeqConsumer(Consumer):
    """The rank certificate's ``(#keys < v, #keys <= v)`` folds. Deferred:
    the sums run over the WHOLE padded bucket (one compile per staging
    bucket, like the histograms) with the exact pad correction applied at
    finish — pad keys are key-space 0, so each pad lane counts into
    ``< v`` iff ``v != 0`` and into ``<= v`` always (unsigned key space).
    Under the ``"kernel"`` fusion tier a supported staged bucket
    dispatches the single-sweep program instead (ONE device program — and
    one guaranteed read — per bucket, vs the deferred pair; the kernel
    masks pads exactly, so its handle needs no correction). Eager: the
    historical sums over the ragged valid slice."""

    def __init__(self, vkey, kdt, *, deferred: bool, fused=False, obs=None):
        if fused and fused not in FUSED_TIERS:
            raise ValueError(
                f"fused tier must be one of {FUSED_TIERS} or False, "
                f"got {fused!r}"
            )
        self.less = 0
        self.leq = 0
        self._vkey = vkey
        self._kdt = kdt
        self._deferred = bool(deferred)
        # fusion is a deferral discipline (the handle materializes at
        # window-pop time), and only the kernel tier changes anything
        # here — the certificate pair was never a separate XLA program
        # to fuse, so the xla tier keeps the deferred pair
        self._kernel = bool(deferred) and fused == "kernel"
        self._obs = obs

    def dispatch(self, keys, kv):
        if isinstance(kv, np.ndarray):
            self.less += int(np.count_nonzero(kv < self._vkey))
            self.leq += int(np.count_nonzero(kv <= self._vkey))
            return None
        import jax.numpy as jnp

        if (
            self._kernel
            and isinstance(keys, StagedKeys)
            and _si.sweep_supported(keys, self._kdt)
        ):
            # ONE sweep program per staged bucket (pad-exact in kernel)
            _wr.bucket_read(self._obs, "certificate", keys, 1)
            key = (int(keys.data.shape[0]), self._kdt.str, "sweep")
            with _ledger_dispatch("ingest.certificate", key, self._obs):
                _, _, _, (lt, le), _ = _si.dispatch_sweep_ingest(
                    keys, kdt=self._kdt, vkey=self._vkey
                )
            return (lt, le, 0)
        if isinstance(keys, StagedKeys):
            # two count programs (< and <=) per staged bucket
            _wr.bucket_read(self._obs, "certificate", keys, 2)
        if self._deferred and isinstance(keys, StagedKeys):
            v = keys.data.dtype.type(self._vkey)
            key = (int(keys.data.shape[0]), self._kdt.str, "pair")
            with _ledger_dispatch("ingest.certificate", key, self._obs):
                return (
                    jnp.sum(keys.data < v), jnp.sum(keys.data <= v), keys.pad
                )
        kv = eager_valid(kv)
        v = kv.dtype.type(self._vkey)
        key = (int(kv.shape[0]), self._kdt.str, "eager")
        with _ledger_dispatch("ingest.certificate", key, self._obs):
            return (jnp.sum(kv < v), jnp.sum(kv <= v), 0)

    def finish(self, handle) -> None:
        lt, le, pad = handle
        lt, le = int(lt), int(le)
        if pad:
            if int(self._vkey) != 0:
                lt -= pad
            le -= pad
        self.less += lt
        self.leq += le


class FusedIngestConsumer(Consumer):
    """ONE device program per staged bucket per pass — the fused
    replacement for the Histogram/Collect/SpillTee consumer bundle
    (the ``fused`` knob, default ``"auto"``), at either fusion tier:
    ``"kernel"`` dispatches the single-sweep pallas program
    (ops/pallas/sweep_ingest.py — one GUARANTEED HBM read of the
    bucket), ``"xla"`` the one-XLA-program fusion
    (ops/pallas/fused_ingest.py — one dispatch, read count up to XLA).
    Both tiers return the same ``(hist, collect, tee)`` handle
    structure, so one finish path serves both; a kernel-tier bucket the
    sweep kernel does not cover (:func:`~mpi_k_selection_tpu.ops.pallas.
    sweep_ingest.sweep_supported` — small buckets, non-4-byte key
    spaces) falls back to the XLA tier for that bucket, never to a
    wrong answer.

    Wraps the very sub-consumers it replaces: a staged chunk dispatches
    the single fused program (histogram + per-spec compactions + tee
    payload) and the FIFO-finish materializes each part INTO the wrapped
    consumers' own accumulators — the pad correction, survivor ordering,
    and writer append run through the exact unfused finish code, so
    ``fused="off"`` (the unwrapped bundle) is a bit-for-bit oracle by
    construction. Chunks that never staged (host chunks, the host-exact
    routes, depth-0 device chunks) fall back to the sub-consumers' own
    dispatch/finish — the fused path is a read-count optimization for
    staged buckets only.

    Construction invariant: callers build this only when deferral is
    resolved on (fusion IS a deferral discipline — the fused handle
    materializes at window-pop time like any deferred handle)."""

    def __init__(self, *, hist=None, collect=None, tee=None, kdt,
                 total_bits, tier="xla", obs=None):
        if hist is None and collect is None and tee is None:
            raise ValueError("FusedIngestConsumer needs at least one part")
        if tier not in FUSED_TIERS:
            raise ValueError(
                f"fused tier must be one of {FUSED_TIERS}, got {tier!r}"
            )
        self._hist = hist
        self._collect = collect
        self._tee = tee
        self._tier = tier
        # unfused fallback order mirrors the historical bundle: tee first
        # (its eager form writes before the histogram handle can finish)
        self._subs = [c for c in (tee, hist, collect) if c is not None]
        self._kdt = kdt
        self._bits = total_bits
        self._obs = obs

    def dispatch(self, keys, kv):
        if not isinstance(keys, StagedKeys):
            handles = [c.dispatch(keys, kv) for c in self._subs]
            if all(h is None for h in handles):
                return None
            return ("parts", handles)
        _wr.bucket_read(self._obs, "fused", keys)
        if self._hist is not None:
            shift, radix_bits, prefixes, method, _kdt = self._hist._args
            hist_prefixes = prefixes
        else:
            shift = radix_bits = method = hist_prefixes = None
        slot = (
            _wr.staged_slot(keys, self._tee._devs)
            if self._tee is not None
            else None
        )
        collect_specs = self._collect.specs if self._collect else ()
        tee_specs = self._tee._specs if self._tee else ()
        use_kernel = self._tier == "kernel" and _si.sweep_supported(
            keys, self._kdt, radix_bits=radix_bits
        )
        # compile identity of the fused program: the bucket, dtype, the
        # tier that actually runs (kernel support is per bucket), the
        # static shift/radix geometry, and every part's spec COUNT
        # (prefix/spec values are traced)
        key = (
            int(keys.data.shape[0]), self._kdt.str,
            "kernel" if use_kernel else "xla", shift, radix_bits,
            0 if hist_prefixes in (None, [None]) else len(hist_prefixes),
            len(collect_specs), len(tee_specs),
        )
        # shift stripped from the churn identity: per-level compiles in
        # one healthy descent are not shape churn (see HistogramConsumer)
        with _ledger_dispatch(
            "ingest.fused", key, self._obs, storm_key=key[:3] + key[4:]
        ):
            if use_kernel:
                hist_h, collect_h, tee_h, _, _ = _si.dispatch_sweep_ingest(
                    keys,
                    kdt=self._kdt,
                    total_bits=self._bits,
                    shift=shift,
                    radix_bits=radix_bits,
                    hist_prefixes=hist_prefixes,
                    collect_specs=collect_specs,
                    tee_specs=tee_specs,
                )
                handle = (hist_h, collect_h, tee_h)
            else:
                handle = _fi.dispatch_fused_ingest(
                    keys,
                    kdt=self._kdt,
                    total_bits=self._bits,
                    shift=shift,
                    radix_bits=radix_bits,
                    hist_prefixes=hist_prefixes,
                    method=method,
                    collect_specs=collect_specs,
                    tee_specs=tee_specs,
                )
        return ("fused", (keys, slot, handle))

    def finish(self, handle) -> None:
        tag, payload = handle
        if tag == "parts":
            for c, h in zip(self._subs, payload):
                if h is not None:
                    c.finish(h)
            return
        keys, slot, (hist, collect, tee) = payload
        # finish order mirrors the unfused bundle: the tee record lands
        # before the histogram fold, per-chunk
        if tee is not None:
            self._tee._append(materialize_compacted(tee, self._kdt), slot)
        if hist is not None:
            _, _, prefixes, _, _ = self._hist._args
            self._hist._fold(
                finish_chunk_histograms(
                    ((keys, prefixes, hist), None), release=False
                )
            )
        for spec, part in zip(
            self._collect.specs if self._collect else (), collect
        ):
            surv = materialize_compacted(part, self._kdt)
            if surv.size:
                self._collect.out[spec].append(surv)


# ---------------------------------------------------------------------------
# the scheduler


class StreamExecutor:
    """The one per-chunk consumption scheduler: every registered consumer
    dispatches its device work for a chunk at ``push`` time, the bundle
    rides the :class:`~mpi_k_selection_tpu.streaming.pipeline.
    InflightWindow` FIFO (one slot per ingest device), and the chunk's
    staged buffer is released when its bundle finishes — i.e. exactly
    when the LAST result depending on it has materialized host-side.

    A chunk whose every consumer folded at dispatch time (host chunks,
    eager mode) carries no in-flight device work: it skips the window —
    no occupancy sample, immediate release — reproducing the
    pre-executor serial discipline bit for bit.

    ``occupancy`` (an obs/metrics.py Histogram, or the phase-labeled
    fan-out from obs/wiring.py:window_occupancy) samples the in-flight
    bundle count at every windowed push — the r6 consumer-serialization
    made measurable: a p-wide window sampling ~1 under multi-device load
    is the serial regime, ~p the fully deferred one
    (:func:`collect_hidden_frac`)."""

    def __init__(self, consumers, *, window: int, occupancy=None):
        self.consumers = list(consumers)
        self.window = max(1, int(window))
        self._win = _pl.InflightWindow(
            self.window, self._finish_bundle, occupancy=occupancy
        )

    def push(self, keys) -> None:
        """Consume one chunk: dispatch every consumer, enqueue the
        in-flight bundle (finishing the oldest when the window is full),
        or — with nothing in flight — release immediately.

        ``kv`` handed to consumers is the chunk's keys on their own
        residency; a STAGED chunk hands the :class:`StagedKeys` itself
        and an eager consumer derives the valid slice on demand
        (:func:`eager_valid`) — the slice is a real device program over
        the padded bucket, so a fully deferred/fused bundle (which reads
        ``keys.data`` whole) must never dispatch it just to discard it."""
        staged = isinstance(keys, StagedKeys)
        handles = [c.dispatch(keys, keys) for c in self.consumers]
        if all(h is None for h in handles):
            if staged:
                keys.release()
            return
        self._win.push((keys if staged else None, handles))

    def _finish_bundle(self, bundle) -> None:
        keys, handles = bundle
        for c, h in zip(self.consumers, handles):
            if h is not None:
                c.finish(h)
        if keys is not None:
            keys.release()

    def drain(self) -> None:
        """Finish every pending bundle, oldest first (end of stream)."""
        for _ in self._win.drain():
            pass

    def abort(self) -> None:
        """Unwind: drop every pending bundle WITHOUT finishing it,
        releasing the staged buffers (a raise mid-pass must not leak ring
        slots — tests/conftest.py asserts the live-staged count returns
        to baseline after every test)."""
        for keys, _ in self._win.clear_pending():
            if keys is not None:
                keys.release()


def release_staged(keys) -> None:
    """Idempotently release a possibly-staged chunk — the unwind helper
    for the chunk IN HAND when a consumer raises: at that instant it sits
    in neither the pipeline queue (already popped) nor the executor
    window (not yet pushed, or already finished — release is idempotent
    either way), so the pass's except block must free it explicitly."""
    if isinstance(keys, StagedKeys):
        keys.release()


def collect_hidden_frac(occupancy, window: int):
    """How much of the window's extra capacity a deferred pass actually
    used: ``(mean occupancy - 1) / (window - 1)``, clamped to [0, 1].
    ~0.0 is the serial regime the eager gathers forced (every chunk
    materialized before the next arrived); ~1.0 means the full p-wide
    window stayed occupied — the per-chunk host transfers fully hidden
    behind the other devices' in-flight work. ``None`` for a serial
    window (<= 1) or when no sample was recorded (e.g. an eager pass,
    which never enters the window)."""
    if occupancy is None or window <= 1:
        return None
    if not getattr(occupancy, "count", 0):
        return None
    return max(0.0, min(1.0, (occupancy.mean - 1.0) / (window - 1.0)))
