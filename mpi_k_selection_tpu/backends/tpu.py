"""TPU backend (``--backend=tpu``) — JAX/XLA execution.

Single-chip selection dispatches to the radix/sort ops (ops/); when more than
one device is visible and the input is large, selection runs sharded over a
1-D device mesh via the distributed radix path (parallel/), which replaces
the reference's MPI scatter/iterate/gather protocol
(``TODO-kth-problem-cgm.c:103-293``) with XLA collectives over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu import api

NAME = "tpu"


def plan(n: int, algorithm: str = "auto", distribute: str = "auto"):
    """Resolve (effective_algorithm, distributed) for a selection of size n.

    Only the radix algorithm has a distributed path; an explicit
    ``algorithm='sort'`` therefore always runs single-chip, and asking for
    ``distribute='always'`` with it is an error rather than a silent switch.
    """
    n_dev = len(jax.devices())
    distributable = algorithm in ("auto", "radix")
    if distribute == "always" and not distributable:
        # validated independently of the host's device count, so the error
        # surfaces in single-device CI too
        raise ValueError(
            f"algorithm={algorithm!r} has no distributed path; "
            "use algorithm='radix' (or 'auto') with distribute='always'"
        )
    use_mesh = {
        "auto": distributable and n_dev > 1 and n >= 1 << 20 and n % n_dev == 0,
        "never": False,
        "always": n_dev > 1,
    }[distribute]
    if use_mesh:
        return "radix", True
    if algorithm == "auto":
        algorithm = "sort" if n <= 1 << 14 else "radix"
    return algorithm, False


def kselect(x, k: int, *, algorithm: str = "auto", distribute: str = "auto", **kwargs):
    """Exact k-th smallest (1-indexed). ``distribute`` in {auto, never, always}."""
    n = np.asarray(x).size if not hasattr(x, "size") else x.size
    algorithm, use_mesh = plan(n, algorithm, distribute)
    if use_mesh:
        from mpi_k_selection_tpu.parallel import radix as pradix

        return pradix.distributed_radix_select(jnp.asarray(x), k, **kwargs)
    return api.kselect(jnp.asarray(x), k, algorithm=algorithm, **kwargs)


def topk(x, k: int, *, largest: bool = True, **kwargs):
    from mpi_k_selection_tpu.ops.topk import topk as _topk

    return _topk(jnp.asarray(x), k, largest=largest, **kwargs)


def median(x, **kwargs):
    x = jnp.asarray(x)
    return kselect(x, max(1, x.size // 2), **kwargs)
