"""Out-of-core exact k-selection over chunked streams.

Every resident selection path (ops/radix.py, parallel/radix.py) requires the
whole array on device, bounding serviceable ``n`` by HBM. This module removes
that bound: the input is a *chunk source* — host arrays, device arrays, or a
replayable generator — and each radix pass streams the chunks through the
device one at a time, accumulating ONE digit histogram for the whole stream.
The cross-pass state is the same two scalars as the resident descent
(prefix, k), so chunks are free to be discarded (and regenerated, or re-read
from disk) between passes. This is the reference CGM's own discipline — scan
local data, exchange a small summary, discard, repeat
(``TODO-kth-problem-cgm.c:103-293``) — applied across *time* instead of
across ranks.

Exactness: histogram counts are integers accumulated host-side in int64, so
the walk is exact for ``n`` up to 2^63 regardless of jax's x64 mode (the
per-chunk device counts stay int32 — a chunk never exceeds 2^31 elements).
Keys are produced by the host transform (utils/dtypes.py:np_to_sortable_bits)
for host chunks — which makes streaming float64 selection bit-exact even on
TPU, where resident f64 device storage truncates to ~49 bits — and by the
device transform for device chunks.

Termination mirrors ops/radix.py's cutover: as soon as the surviving
population fits ``collect_budget``, one extra streaming pass collects the
survivors host-side and a tiny partition finishes — so uniform-ish data pays
~2 passes + collect instead of the full ``key_bits / radix_bits`` schedule.

Ingest is pipelined by default (``pipeline_depth=2``): a background
producer thread runs chunk *i+1*'s production, host key-encode and
host->device staging while chunk *i* histograms on device — see
streaming/pipeline.py. ``pipeline_depth=0`` is the fully synchronous
path, kept as the correctness oracle; both return bit-identical answers.

With ``devices`` > 1 the pipelined passes also spread across chips: the
producer stages chunk *j* onto ``devices[j % p]`` (round-robin) and the
consumer keeps one histogram dispatch in flight per device
(:class:`_HistogramWindow`), merging the per-device int32 partials into
the host int64 accumulator strictly in chunk order — the pipelined twin
of ``parallel/sketch.py:distributed_sketch``'s psum merge, and because
the merge order is fixed (and int64 addition is exact), answers stay
bit-identical for every device count. ``devices=1`` (or ``None``) is the
single-device PR 3 path.
"""

from __future__ import annotations

import contextlib

import numpy as np

from mpi_k_selection_tpu.streaming import pipeline as _pl
from mpi_k_selection_tpu.streaming.pipeline import DEFAULT_PIPELINE_DEPTH, StagedKeys
from mpi_k_selection_tpu.utils import dtypes as _dt

DEFAULT_COLLECT_BUDGET = 1 << 20


def _is_device_array(chunk) -> bool:
    import jax

    return isinstance(chunk, jax.Array)


def _tpu_backend() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def as_chunk_source(source):
    """Normalize ``source`` to a zero-arg callable returning a fresh chunk
    iterator — the replayable form every streaming pass needs.

    Accepted: a list/tuple of arrays, a single array (one chunk), or a
    zero-arg callable returning an iterable of arrays. A bare one-shot
    iterator/generator is rejected with instructions: exact selection
    re-reads the stream once per radix pass, which a consumed generator
    cannot serve (use :class:`~mpi_k_selection_tpu.streaming.sketch.
    RadixSketch` for single-pass approximate answers).
    """
    if callable(source):
        return source
    if isinstance(source, (list, tuple)):
        return lambda: iter(source)
    if isinstance(source, np.ndarray) or _is_device_array(source):
        return lambda: iter((source,))
    if hasattr(source, "__iter__") or hasattr(source, "__next__"):
        raise TypeError(
            "streaming selection re-reads the data once per radix pass; a "
            "one-shot iterator/generator cannot be replayed. Pass a "
            "list/tuple of chunks or a zero-arg callable returning a fresh "
            "iterator (e.g. lambda: (load(i) for i in range(nchunks))). "
            "For single-pass streams, use RadixSketch (approximate) instead."
        )
    raise TypeError(f"unsupported chunk source type {type(source).__name__!r}")


def _encode_chunk(chunk, dtype):
    """Validate + key-encode ONE chunk: returns ``(keys, c)`` with ``keys``
    the order-preserving unsigned view (host numpy for host chunks, device
    array for device chunks — each stays where it lives) and ``c`` the
    raveled original, or ``None`` for an empty chunk. ``dtype`` is the
    stream dtype to validate against (``None`` = first chunk, adopt its
    dtype — the caller reads it off ``c.dtype``). Shared verbatim by the
    synchronous iterator below and the pipelined producer thread
    (streaming/pipeline.py), so both paths enforce identical contracts."""
    if _is_device_array(chunk):
        c = chunk.ravel()
    else:
        c = np.ravel(np.asarray(chunk))
    if c.size == 0:
        return None
    if c.size >= 1 << 31:
        raise ValueError(
            f"chunk of {c.size} elements: per-chunk device histogram "
            "counts are int32-exact only below 2^31 elements — split "
            "the stream into smaller chunks (n is unbounded, chunks "
            "are not)"
        )
    if dtype is not None and np.dtype(c.dtype) != np.dtype(dtype):
        raise TypeError(
            f"chunk dtype {np.dtype(c.dtype)} != stream dtype "
            f"{np.dtype(dtype)}; streaming selection requires one dtype "
            "per stream"
        )
    if not _is_device_array(c):
        return _dt.np_to_sortable_bits(c), c
    if np.dtype(c.dtype) == np.float64 and _tpu_backend():
        # device f64 keys on TPU are the ~49-bit approximation
        # (utils/dtypes.py:f64_raw_bits) — decode the chunk's (already
        # storage-truncated) values to host and key them EXACTLY, so
        # every chunk of a stream lives in ONE key space regardless of
        # residency and the answer is exact w.r.t. the chunk contents
        hc = np.asarray(c)
        return _dt.np_to_sortable_bits(hc), hc
    return _dt.to_sortable_bits(c), c


def _iter_key_chunks(src, dtype=None):
    """Yield ``(keys, chunk)`` pairs for every non-empty chunk (see
    :func:`_encode_chunk`) — the synchronous path, and the correctness
    oracle for the pipelined one."""
    for chunk in src():
        pair = _encode_chunk(chunk, dtype)
        if pair is None:
            continue
        keys, c = pair
        if dtype is None:
            dtype = np.dtype(c.dtype)
        yield keys, c


@contextlib.contextmanager
def _key_chunk_stream(
    src, dtype=None, *, pipeline_depth=0, hist_method=None, timer=None,
    devices=None,
):
    """Context-managed ``(keys, chunk)`` iterator: the synchronous
    generator at depth 0, a :class:`~mpi_k_selection_tpu.streaming.
    pipeline.ChunkPipeline` (background produce/encode/stage overlapped
    with the consuming pass, staged round-robin over ``devices``) at
    depth >= 1. The context manager guarantees the producer thread is
    joined on EVERY exit path — normal exhaustion, early exit, and
    consumer-side raises like the replay-stability check."""
    depth = _pl.validate_pipeline_depth(pipeline_depth)
    if depth == 0:
        yield _iter_key_chunks(src, dtype)
        return
    pipe = _pl.ChunkPipeline(
        src, dtype, depth=depth, hist_method=hist_method, timer=timer,
        devices=devices,
    )
    try:
        yield iter(pipe)
    finally:
        pipe.close()


def resolve_stream_hist(hist_method: str, dtype) -> str:
    """``"numpy"`` (host bincount) or an ops/histogram.py method name.

    ``"auto"`` keeps the device path (ops/histogram.py resolves it to the
    Pallas kernels on TPU, scatter elsewhere) EXCEPT where the device would
    not be exact: 64-bit keys without x64 (jnp would silently truncate
    them) and float64 on TPU (device keys are the ~49-bit ``f64_raw_bits``
    approximation; the host path keys the exact bits) — host counting
    needs no mode flip and stays exact for both.
    """
    if hist_method == "numpy":
        return "numpy"
    dtype = np.dtype(dtype)
    if dtype.itemsize == 8:
        import jax

        if not jax.config.jax_enable_x64:
            return "numpy"
        if dtype.kind == "f" and jax.default_backend() == "tpu":
            return "numpy"
    return hist_method


def _dispatch_chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt):
    """DISPATCH one chunk's digit histogram(s) at ``shift`` for every
    prefix in ``prefixes`` (``None`` = no filter) and return an in-flight
    handle for :func:`_finish_chunk_histograms` — the chunk-side work is
    paid ONCE and shared across prefixes: host chunks compute the
    digit/prefix arrays once, device chunks cross the tunnel once and stay
    on device for the counts (the whole point on TPU); only the
    (2**radix_bits,) counts per prefix come back at finish time.

    Device work is dispatched asynchronously on the chunk's OWN device
    (jax async dispatch; :class:`~mpi_k_selection_tpu.streaming.pipeline.
    StagedKeys` are committed to their round-robin slot, so up to one
    dispatch per ingest device runs concurrently under
    :class:`_HistogramWindow`). The ``"numpy"`` method computes host-side
    immediately — there is nothing to overlap.

    Pipelined passes hand in :class:`StagedKeys` — a pow2-padded,
    already-device-resident buffer. The histogram runs over the WHOLE
    padded buffer (fixed shape, one compile per bucket size) and the pad
    contribution is subtracted host-side at finish: pad keys are key-space
    0, so they land in digit bucket 0 and only under the all-zero prefix —
    an exact integer correction."""
    staged = isinstance(keys, StagedKeys)
    if method == "numpy":
        if staged:  # pragma: no cover - staging only feeds device methods
            keys = np.asarray(keys.valid())
        k = keys if isinstance(keys, np.ndarray) else np.asarray(keys)
        dig = ((k >> kdt.type(shift)) & kdt.type((1 << radix_bits) - 1)).astype(
            np.int64
        )
        nb = 1 << radix_bits
        if len(prefixes) == 1 and prefixes[0] is None:
            return (None, {None: np.bincount(dig, minlength=nb).astype(np.int64)})
        up = k >> kdt.type(shift + radix_bits)
        return (
            None,
            {
                p: np.bincount(dig[up == kdt.type(p)], minlength=nb).astype(np.int64)
                for p in prefixes
            },
        )
    import jax.numpy as jnp

    from mpi_k_selection_tpu.ops.histogram import (
        masked_radix_histogram,
        multi_masked_radix_histogram,
    )

    dk = keys.data if staged else jnp.asarray(keys)  # ksel: noqa[KSL002] -- 64-bit keys only reach this device branch with x64 on: resolve_stream_hist routes them to the host 'numpy' method otherwise
    if len(prefixes) == 1 and prefixes[0] is None:
        h = masked_radix_histogram(
            dk,
            shift=shift,
            radix_bits=radix_bits,
            prefix=None,
            method=method,
            count_dtype=jnp.int32,  # exact per chunk (chunk size < 2^31)
        )
    else:
        # the shared-sweep primitive of the resident multi-rank descent: on
        # the pallas methods all K prefix queries ride ONE read of the chunk
        # (other methods fall back to K single-prefix sweeps — correct,
        # just K reads)
        h = multi_masked_radix_histogram(
            dk,
            shift=shift,
            radix_bits=radix_bits,
            prefixes=np.asarray(prefixes, kdt),
            method=method,
            count_dtype=jnp.int32,
        )
    return ((keys if staged else None, list(prefixes), h), None)


def _finish_chunk_histograms(handle):
    """Materialize one :func:`_dispatch_chunk_histograms` handle into the
    ``{prefix: int64 histogram}`` dict: block on the device counts, widen
    to the host int64 accumulator dtype, apply the exact pad correction,
    and release (donate) the staged ring slot."""
    inflight, done = handle
    if done is not None:
        return done
    staged, prefixes, h = inflight
    if len(prefixes) == 1 and prefixes[0] is None:
        out = {None: np.asarray(h).astype(np.int64)}
    else:
        hk = np.asarray(h).astype(np.int64)
        out = {p: hk[i] for i, p in enumerate(prefixes)}
    if staged is not None:
        if staged.pad:
            # pad keys are key-space 0: digit (0 >> shift) & mask == 0, and
            # they pass a prefix filter only when every upper bit is 0
            for p, hist in out.items():
                if p is None or int(p) == 0:
                    hist[0] -= staged.pad
        # the counts above are host-materialized (np.asarray blocked on
        # them), so the ring slot can be donated back eagerly instead of
        # waiting out the queue's references
        staged.release()
    return out


def _chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt):
    """Dispatch + finish in one step — the serial form the synchronous
    (depth-0 / single-device) paths and the contract checks use."""
    return _finish_chunk_histograms(
        _dispatch_chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt)
    )


class _HistogramWindow(_pl.InflightWindow):
    """The descent's :class:`~mpi_k_selection_tpu.streaming.pipeline.
    InflightWindow` specialization: ``push`` dispatches the chunk's
    histogram(s) and returns a list of ZERO or ONE finished
    ``{prefix: int64 hist}`` dicts, merged by the callers strictly in
    chunk order (int64 addition is exact and order-invariant anyway — the
    window's fixed FIFO order is belt and braces, and keeps the
    replay-stability diagnostics reproducible)."""

    def __init__(self, window: int):
        super().__init__(window, _finish_chunk_histograms)

    def push(self, keys, shift, radix_bits, prefixes, method, kdt):
        return super().push(
            _dispatch_chunk_histograms(keys, shift, radix_bits, prefixes, method, kdt)
        )


def _np_walk(hist, kk, prefix, radix_bits):
    """Host bucket-walk step (the numpy twin of ops/radix.py:
    bucket_walk_step): pick the bucket containing the kk-th survivor,
    rebase kk, extend the prefix. Returns (prefix, kk, bucket_count)."""
    cum = np.cumsum(hist)
    b = int(np.searchsorted(cum, kk, side="left"))
    kk = int(kk - (cum[b - 1] if b else 0))
    prefix = ((int(prefix) << radix_bits) | b) if prefix is not None else b
    return prefix, kk, int(hist[b])


def _collect_survivors(
    src, dtype, specs, *, pipeline_depth=0, timer=None, devices=None,
    hist_method=None,
):
    """One streamed pass collecting survivors for EVERY ``(resolved_bits,
    prefix) -> expected population`` spec at once — the shared finish of
    the multi-rank descent (a single-rank descent passes one spec). Keys
    whose top ``resolved_bits`` equal ``prefix`` survive; device chunks are
    filtered ON device (eager boolean indexing) so only survivors cross
    back to the host. Returns ``{spec: host uint key array}``.

    The single-device pipelined path overlaps produce/encode with the
    filtering but never stages (``hist_method`` stays ``None``): the
    collect's device work is a data-dependent gather, not a fixed-shape
    kernel, so padding buys no compile reuse there. With > 1 ingest
    device (and a device ``hist_method`` — the host-exact routes keep
    filtering on host), chunks ARE staged round-robin so each device
    filters its own resident chunks: the host->device transfer rides the
    producer thread and only survivors cross back. Survivor order stays
    the chunk order either way (and the final ``np.partition`` is
    order-invariant over the collected multiset regardless)."""
    kdt = np.dtype(_dt.key_dtype(dtype))
    total_bits = _dt.key_bits(dtype)
    devs = _pl.resolve_stream_devices(devices)
    multi = len(devs) > 1 and _pl.validate_pipeline_depth(pipeline_depth) > 0
    out = {s: [] for s in specs}
    with _key_chunk_stream(
        src, dtype, pipeline_depth=pipeline_depth, timer=timer,
        hist_method=hist_method if multi else None,
        devices=devs if multi else None,
    ) as kc:
        for keys, _ in kc:
            staged = isinstance(keys, StagedKeys)
            kv = keys.valid() if staged else keys
            host = isinstance(kv, np.ndarray)
            for resolved, prefix in out:
                shift = total_bits - resolved
                if host:
                    surv = kv[(kv >> kdt.type(shift)) == kdt.type(prefix)]
                else:
                    import jax

                    m = jax.lax.shift_right_logical(
                        kv, kv.dtype.type(shift)
                    ) == kv.dtype.type(prefix)
                    surv = np.asarray(kv[m])  # eager boolean gather, device-side
                if surv.size:
                    out[(resolved, prefix)].append(np.asarray(surv, kdt))
            if staged:
                keys.release()
    collected = {}
    for spec, parts in out.items():
        c = np.concatenate(parts) if parts else np.empty((0,), kdt)
        if c.size != specs[spec]:  # pragma: no cover - source changed between passes
            raise RuntimeError(
                f"chunk source is not replay-stable: collected {c.size} "
                f"survivors, histogram pass counted {specs[spec]}. The source "
                "callable must yield identical data on every invocation."
            )
        collected[spec] = c
    return collected


def _validate_ks(ks, n):
    for k in ks:
        if not 1 <= k <= n:
            raise ValueError(f"k={k} out of range [1, {n}]")


def streaming_kselect(
    source,
    k,
    *,
    radix_bits: int = 8,
    hist_method: str = "auto",
    collect_budget: int = DEFAULT_COLLECT_BUDGET,
    sketch=None,
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
    timer=None,
    devices=None,
):
    """Exact k-th smallest (1-indexed) over a chunked stream.

    ``source`` per :func:`as_chunk_source`. ``k`` must be concrete (the
    loop is host-driven — there is nothing to trace). ``sketch`` is an
    optional :class:`~mpi_k_selection_tpu.streaming.sketch.RadixSketch`
    built over the SAME stream: its deepest exact level seeds the descent,
    skipping the first ``sketch.resolution_bits`` worth of passes (the
    ``refine`` fast path). Returns a host scalar of the stream's dtype —
    bit-exact, including float64 on TPU for host chunks (host key space
    end-to-end; see module docstring).

    ``collect_budget`` bounds host memory for the survivor collect (keys of
    at most that many elements are materialized at once); the streamed
    chunks themselves are never concatenated.

    ``pipeline_depth`` >= 1 overlaps chunk *i+1*'s production, host
    key-encode and host->device staging with chunk *i*'s histogram
    (streaming/pipeline.py; 2 = double buffering, the default). Depth 0 is
    the fully synchronous path — the correctness oracle the pipelined one
    is bit-identical to. ``timer`` (a utils/profiling.PhaseTimer) collects
    the pipeline's produce/encode/stage/stall phases for
    :func:`~mpi_k_selection_tpu.streaming.pipeline.ingest_hidden_frac`.

    ``devices`` spreads the pipelined ingest across chips (None/1 = the
    single-device path; an int takes the first p of ``jax.devices()``, a
    device sequence is used as given): staged chunks land round-robin and
    up to p histograms run concurrently, with the host int64 merge drained
    in chunk order — answers are bit-identical for EVERY device count and
    depth. Multi-device staging engages only with ``pipeline_depth >= 1``
    and a device histogram method (the host-exact 64-bit-no-x64 and
    f64-on-TPU routes stay host-side and ignore extra devices).
    """
    return streaming_kselect_many(
        source,
        [k],
        radix_bits=radix_bits,
        hist_method=hist_method,
        collect_budget=collect_budget,
        sketch=sketch,
        pipeline_depth=pipeline_depth,
        timer=timer,
        devices=devices,
    )[0]


def streaming_kselect_many(
    source,
    ks,
    *,
    radix_bits: int = 8,
    hist_method: str = "auto",
    collect_budget: int = DEFAULT_COLLECT_BUDGET,
    sketch=None,
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
    timer=None,
    devices=None,
):
    """Exact k-th smallest for EVERY (1-indexed) rank in ``ks``, sharing
    each streamed pass across ranks: the stream is replayed once per radix
    level plus one collect — NOT once per rank — with one histogram per
    DISTINCT surviving prefix at each level (ranks whose descents land in
    the same bucket share it). For out-of-core sources the replay is the
    dominant cost, so m quantiles over one stream cost roughly the passes
    of one. Per-rank semantics are exactly :func:`streaming_kselect`'s
    (including its ``pipeline_depth``/``timer``/``devices`` knobs);
    returns a list in input order.
    """
    src = as_chunk_source(source)
    pipeline_depth = _pl.validate_pipeline_depth(pipeline_depth)
    devs = _pl.resolve_stream_devices(devices)
    # one in-flight histogram slot per ingest device; the synchronous
    # (depth-0) oracle stays strictly serial regardless of the knob
    window = len(devs) if pipeline_depth > 0 else 1
    # None keeps the PR 3 uncommitted default-device staging; an explicit
    # knob (even a single device) commits staged chunks to its slots
    stream_kw = dict(
        pipeline_depth=pipeline_depth, timer=timer,
        devices=None if devices is None else devs,
    )
    ks = [int(k) for k in ks]
    if not ks:
        return []

    # per-rank descent state: [prefix, rebased_k, resolved_bits, population]
    if sketch is not None:
        # the sketch names the stream dtype (later passes validate every
        # chunk against it); check_stream validates divisibility of the
        # bits BELOW its resolved prefix — what the remaining passes walk
        dtype = sketch.dtype
        kdt = np.dtype(_dt.key_dtype(dtype))
        total_bits = _dt.key_bits(dtype)
        method = resolve_stream_hist(hist_method, dtype)
        sketch.check_stream(dtype, radix_bits)
        _validate_ks(ks, sketch.n)
        states = [list(sketch.walk(k)) for k in ks]
    else:
        # pass 0 triples as the length scan and the dtype probe: ONE
        # streamed histogram of the top digit (rank-independent — no prefix
        # filter yet), with dtype (hence key geometry and method) captured
        # from the first chunk — nothing is produced just to be discarded
        dtype = None
        n = 0
        win = _HistogramWindow(window)
        with _key_chunk_stream(src, hist_method=hist_method, **stream_kw) as kc:
            for keys, chunk in kc:
                if dtype is None:
                    dtype = np.dtype(chunk.dtype)
                    kdt = np.dtype(_dt.key_dtype(dtype))
                    total_bits = _dt.key_bits(dtype)
                    if total_bits % radix_bits:
                        raise ValueError(
                            f"radix_bits={radix_bits} must divide key bits "
                            f"{total_bits}"
                        )
                    method = resolve_stream_hist(hist_method, dtype)
                    shift0 = total_bits - radix_bits
                    hist = np.zeros((1 << radix_bits,), np.int64)
                n += int(keys.size)
                for h in win.push(keys, shift0, radix_bits, [None], method, kdt):
                    hist += h[None]
            for h in win.drain():
                hist += h[None]
        if n == 0:
            raise ValueError("streaming selection requires a non-empty stream")
        _validate_ks(ks, n)
        states = []
        for k in ks:
            prefix, kk, pop = _np_walk(hist, k, None, radix_bits)
            states.append([prefix, kk, radix_bits, pop])

    def _active(st):
        return st[2] < total_bits and st[3] > collect_budget

    while any(_active(st) for st in states):
        # active ranks advance in lockstep (a rank only ever EXITS the
        # active set), so they all sit at one resolved depth: one streamed
        # pass serves every distinct surviving prefix
        resolved = next(st[2] for st in states if _active(st))
        shift = total_bits - resolved - radix_bits
        prefixes = sorted({st[0] for st in states if _active(st)})
        expected = {st[0]: st[3] for st in states if _active(st)}
        hists = {p: np.zeros((1 << radix_bits,), np.int64) for p in prefixes}
        win = _HistogramWindow(window)
        with _key_chunk_stream(src, dtype, hist_method=method, **stream_kw) as kc:
            for keys, _ in kc:
                for hd in win.push(keys, shift, radix_bits, prefixes, method, kdt):
                    for p, h in hd.items():
                        hists[p] += h
            for hd in win.drain():
                for p, h in hd.items():
                    hists[p] += h
        for p in prefixes:
            # replay-stability check, mirroring _collect_survivors': this
            # pass's population under each surviving prefix must equal the
            # bucket count the PREVIOUS pass (or the seeding sketch)
            # established — a drifting source fails loudly here instead of
            # walking a corrupt histogram to a wrong answer
            if int(hists[p].sum()) != expected[p]:
                raise RuntimeError(
                    f"chunk source is not replay-stable: prefix {p:#x} holds "
                    f"{int(hists[p].sum())} elements this pass, previous "
                    f"pass counted {expected[p]}. The source callable must "
                    "yield identical data on every invocation."
                )
        for st in states:
            if _active(st):
                st[0], st[1], st[3] = _np_walk(hists[st[0]], st[1], st[0], radix_bits)
                st[2] = resolved + radix_bits

    specs = {}
    for prefix, _kk, resolved, pop in states:
        if resolved < total_bits:
            specs[(resolved, int(prefix))] = pop
    collected = (
        _collect_survivors(
            src, dtype, specs, pipeline_depth=pipeline_depth, timer=timer,
            devices=None if devices is None else devs, hist_method=method,
        )
        if specs
        else {}
    )

    answers = []
    for prefix, kk, resolved, _pop in states:
        if resolved == total_bits:
            # every key bit determined (either the schedule ran out or the
            # survivors are duplicates of one key): the prefix IS the answer
            ans_key = kdt.type(prefix)
        else:
            surv = collected[(resolved, int(prefix))]
            ans_key = np.partition(surv, kk - 1)[kk - 1]
        answers.append(
            _dt.np_from_sortable_bits(np.asarray([ans_key], kdt), dtype)[0]
        )
    return answers


def streaming_rank_certificate(
    source, value, *, pipeline_depth: int = DEFAULT_PIPELINE_DEPTH, timer=None,
    devices=None,
):
    """``(#elements < value, #elements <= value)`` streamed — the O(n)
    exactness proof of utils/debug.py:rank_certificate without residency:
    an answer for rank k is exact iff ``less < k <= leq``. Comparisons run
    in key space (total order: ties, -0.0/+0.0 and NaN behave exactly like
    the selection itself). ``pipeline_depth`` >= 1 overlaps chunk
    production/encode with the counting (single-device: no staging — the
    counts consume keys wherever they already live). ``devices`` > 1
    stages chunks round-robin so each device counts its own resident
    chunks, with the per-chunk int counts folded into the host int
    accumulators in chunk order (integer addition — order-exact either
    way); the host-exact 64-bit/f64-on-TPU routes keep counting on host."""
    src = as_chunk_source(source)
    devs = _pl.resolve_stream_devices(devices)
    multi = len(devs) > 1 and _pl.validate_pipeline_depth(pipeline_depth) > 0
    less = leq = 0
    vkey = None

    def _finish_counts(handle):
        staged, lt, le = handle
        counts = (int(lt), int(le))
        if staged is not None:
            staged.release()
        return counts

    win = _pl.InflightWindow(len(devs), _finish_counts)
    with _key_chunk_stream(
        src, pipeline_depth=pipeline_depth, timer=timer,
        hist_method="auto" if multi else None, devices=devs if multi else None,
    ) as kc:
        for keys, chunk in kc:
            if vkey is None:
                # key the probe value from the first chunk's dtype — no
                # chunk is produced just to learn it
                vkey = _dt.np_to_sortable_bits(
                    np.asarray([value], np.dtype(chunk.dtype))
                )[0]
            staged = isinstance(keys, StagedKeys)
            kv = keys.valid() if staged else keys
            if isinstance(kv, np.ndarray):
                less += int(np.count_nonzero(kv < vkey))
                leq += int(np.count_nonzero(kv <= vkey))
            else:
                import jax.numpy as jnp

                v = kv.dtype.type(vkey)
                # dispatch both counts async on the chunk's own device;
                # materialize FIFO once one count per device is in flight
                for lt, le in win.push(
                    (keys if staged else None, jnp.sum(kv < v), jnp.sum(kv <= v))
                ):
                    less += lt
                    leq += le
        for lt, le in win.drain():
            less += lt
            leq += le
    if vkey is None:
        raise ValueError("streaming_rank_certificate requires a non-empty stream")
    return less, leq
