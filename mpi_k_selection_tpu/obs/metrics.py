"""Metrics registry — counters, gauges and histograms with JSON and
Prometheus-text exposition.

The registry is the *numbers* half of the descent telemetry (obs/events.py
is the *shapes* half): StagingPool hit/miss totals, ``pipeline.stall``
seconds, InflightWindow occupancy samples, spilled bytes per descent, and
chunks-per-device counts — the quantities the TPU validation sweep and the
async-executor work (ROADMAP) need to read off a run instead of inferring
from wall clocks.

Design constraints:

- **Thread-safe**: the pipelined descent records from the producer thread
  (staging, spill tee) and the consumer thread (stall, merges)
  concurrently; every mutation takes the metric's registry lock.
- **Exact**: counters and gauges are plain Python ints/floats (no
  device round-trips, no float accumulation for counts), so a mirrored
  metric can be asserted EQUAL to its source counter
  (tests/test_multidevice_ingest.py, tests/test_spill.py).
- **Off by default**: a registry exists only when the caller passes one
  (via :class:`~mpi_k_selection_tpu.obs.Observability`); library code
  guards every record behind ``obs is None`` checks.

Exposition: :meth:`MetricsRegistry.as_dict` (JSON-ready),
:meth:`MetricsRegistry.to_json`, and
:meth:`MetricsRegistry.render_prometheus` (text format 0.0.4 — dots
become underscores, every name is prefixed ``ksel_``).
"""

from __future__ import annotations

import json
import math
import re
import threading

#: Default occupancy-style histogram buckets (small non-negative counts).
DEFAULT_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


class _Metric:
    """Shared plumbing: identity (name + sorted label pairs) and the
    registry lock every mutation runs under."""

    type_name = "untyped"

    def __init__(self, name: str, labels: tuple, lock: threading.Lock):
        self.name = name
        self.labels = labels  # sorted tuple of (key, value) pairs
        self._lock = lock

    def label_str(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in self.labels)
        return "{" + inner + "}"


class Counter(_Metric):
    """Monotone event count. ``set`` exists for COLLECTED mirrors of
    pre-existing counters (StagingPool.hits, a pass_log total) — the
    snapshot overwrites so repeated collections stay idempotent."""

    type_name = "counter"

    def __init__(self, name, labels, lock):
        super().__init__(name, labels, lock)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def set(self, value) -> None:
        with self._lock:
            self.value = value

    def as_dict(self) -> dict:
        return {"type": self.type_name, "value": self.value}


class Gauge(_Metric):
    """Point-in-time value (seconds, occupancy, fraction)."""

    type_name = "gauge"

    def __init__(self, name, labels, lock):
        super().__init__(name, labels, lock)
        self.value = 0

    def set(self, value) -> None:
        with self._lock:
            self.value = value

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def as_dict(self) -> dict:
        return {"type": self.type_name, "value": self.value}


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: ``le`` bounds,
    implicit ``+Inf``), plus exact count/sum/min/max."""

    type_name = "histogram"

    def __init__(self, name, labels, lock, buckets=DEFAULT_BUCKETS):
        super().__init__(name, labels, lock)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0
        self.min = None
        self.max = None

    def observe(self, value) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value
            for i, bound in enumerate(self.bounds):
                if value <= bound:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    def cumulative(self) -> list[int]:
        """Cumulative counts per ``le`` bound (+Inf last) — the
        Prometheus wire shape."""
        out, running = [], 0
        for c in self.bucket_counts:
            running += c
            out.append(running)
        return out

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def as_dict(self) -> dict:
        return {
            "type": self.type_name,
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.mean,
            "buckets": {
                **{str(b): c for b, c in zip(self.bounds, self.cumulative())},
                "+Inf": self.count,
            },
        }


class MetricsRegistry:
    """Get-or-create home for every metric of one run (or one process).

    Metrics are keyed by ``(name, labels)``; asking for an existing key
    returns the same object, so library code can fetch by name at record
    time without plumbing metric handles around. One lock serializes all
    mutation — metric cardinality here is tiny (tens), contention is not
    a concern at chunk granularity.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}

    @staticmethod
    def _key(name: str, labels):
        lab = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
        return name, lab

    def _get_or_create(self, cls, name, labels, **kwargs):
        key = self._key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, key[1], self._lock, **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.type_name}"
                )
            return m

    def counter(self, name: str, labels=None) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, labels=None) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, labels=None, buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, labels, buckets=buckets)

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- exposition --------------------------------------------------------

    def as_dict(self) -> dict:
        """``{name or name{labels}: metric dict}`` — the JSON-ready
        snapshot bench records and ``--metrics-json`` embed."""
        out = {}
        for m in self.metrics():
            out[m.name + m.label_str()] = m.as_dict()
        return out

    def to_json(self, indent=None) -> str:
        return json.dumps(self.as_dict(), indent=indent, sort_keys=True)

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4): names sanitized to
        ``ksel_<name_with_underscores>``, histograms as
        ``_bucket{le=...}``/``_sum``/``_count`` series."""
        by_name: dict = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            group = by_name[name]
            pname = "ksel_" + _NAME_RE.sub("_", name.replace(".", "_"))
            lines.append(f"# TYPE {pname} {group[0].type_name}")
            for m in sorted(group, key=lambda g: g.labels):
                if isinstance(m, Histogram):
                    for bound, c in zip(m.bounds, m.cumulative()):
                        lab = dict(m.labels)
                        lab["le"] = _format_float(bound)
                        inner = ",".join(
                            f'{k}="{v}"' for k, v in sorted(lab.items())
                        )
                        lines.append(f"{pname}_bucket{{{inner}}} {c}")
                    inf_lab = dict(m.labels)
                    inf_lab["le"] = "+Inf"
                    inner = ",".join(
                        f'{k}="{v}"' for k, v in sorted(inf_lab.items())
                    )
                    lines.append(f"{pname}_bucket{{{inner}}} {m.count}")
                    lines.append(f"{pname}_sum{m.label_str()} {_format_float(m.sum)}")
                    lines.append(f"{pname}_count{m.label_str()} {m.count}")
                else:
                    lines.append(
                        f"{pname}{m.label_str()} {_format_float(m.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


def _format_float(v) -> str:
    """Prometheus value formatting: ints stay integral, floats drop the
    trailing noise, infinities spell +Inf/-Inf."""
    if isinstance(v, bool):  # pragma: no cover - no bool metrics exist
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def collect_runtime(
    registry: MetricsRegistry,
    *,
    staging_pool=None,
    spill_store=None,
    timer=None,
) -> MetricsRegistry:
    """Snapshot the repo's pre-existing runtime counters into ``registry``
    — the ONE mapping from internal state to exported metric names, so
    the values are the originals by construction (asserted equal in
    tests/test_multidevice_ingest.py and tests/test_spill.py):

    - ``staging_pool.hits`` / ``staging_pool.misses`` (Counter) and
      ``staging_pool.resident_bytes`` (Gauge) from a
      :class:`~mpi_k_selection_tpu.streaming.pipeline.StagingPool`;
    - ``spill.passes`` / ``spill.bytes_read`` / ``spill.bytes_written`` /
      ``spill.keys_read`` / ``spill.keys_written`` (Counter) summed over a
      :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore`'s
      ``pass_log``, plus ``spill.generations_live`` (Gauge);
    - every :class:`~mpi_k_selection_tpu.utils.profiling.PhaseTimer`
      phase as ``phase.seconds{phase=...}`` / ``phase.calls{phase=...}``
      (the ``pipeline.stall`` seconds the ROADMAP items need ride here).

    Snapshots overwrite (``Counter.set``), so collecting twice is
    idempotent. Returns ``registry``.
    """
    if staging_pool is not None:
        registry.counter("staging_pool.hits").set(int(staging_pool.hits))
        registry.counter("staging_pool.misses").set(int(staging_pool.misses))
        registry.gauge("staging_pool.resident_bytes").set(
            int(staging_pool.resident_bytes)
        )
    if spill_store is not None:
        log = list(spill_store.pass_log)
        registry.counter("spill.passes").set(len(log))
        registry.counter("spill.bytes_read").set(
            sum(int(p.get("bytes_read", 0)) for p in log)
        )
        registry.counter("spill.keys_read").set(
            sum(int(p.get("keys_read", 0)) for p in log)
        )
        registry.counter("spill.bytes_written").set(
            sum(int(p.get("bytes_written", 0)) for p in log)
        )
        registry.counter("spill.keys_written").set(
            sum(int(p.get("keys_written", 0)) for p in log)
        )
        registry.gauge("spill.generations_live").set(
            len(getattr(spill_store, "generations", ()))
        )
    if timer is not None:
        for name, d in timer.as_dict().items():
            registry.gauge("phase.seconds", labels={"phase": name}).set(
                d["seconds"]
            )
            registry.gauge("phase.calls", labels={"phase": name}).set(d["calls"])
    return registry
