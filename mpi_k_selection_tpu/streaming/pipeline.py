"""Pipelined streaming ingest — double-buffered transfer/compute overlap,
round-robin across every ingest device.

The synchronous chunked descent (streaming/chunked.py) is strictly serial:
produce chunk *i* (source callable), key-encode it on the host
(utils/dtypes.py:np_to_sortable_bits), cross the host->device tunnel, run
the histogram kernel — and only then start chunk *i+1*. On an out-of-core
run the device idles for the entire host-side produce + encode + transfer
of every chunk, every radix pass. The reference CGM program's whole point
is hiding data movement behind local work (scatter once, O(1) communication
rounds); this module applies the same discipline across *time*: a
background producer thread runs chunk *i+1*'s production, host key-encode
and host->device staging while the consumer (the descent) histograms chunk
*i* on device.

With ``devices`` > 1 the same discipline also applies across *chips*: the
producer stages successive chunks round-robin onto the ingest device set
(chunk *j* lands on ``devices[j % p]`` via an explicit
``jax.device_put(..., device)``), so up to *p* chunks histogram
concurrently — the pipelined twin of ``parallel/sketch.py:
distributed_sketch``'s psum merge, with the per-device int32 partials
merged into the host int64 accumulator in chunk order
(streaming/executor.py:StreamExecutor + HistogramConsumer).

Design:

- :class:`ChunkPipeline` — a bounded-queue producer/consumer pair. The
  producer thread pulls chunks from the replayable source, validates and
  key-encodes them with the SAME helpers the synchronous path uses
  (streaming/chunked.py:_encode_chunk — per-stream dtype validation, the
  2^31 per-chunk guard and the host-exact f64-on-TPU route are identical
  by construction), and, when the resolved histogram method is a device
  method, stages host keys to the device eagerly — round-robin over the
  resolved ``devices`` tuple.
- :class:`StagedKeys` — a device-resident key buffer padded to a
  power-of-two bucket size, so the histogram kernel sees a handful of
  shapes and compiles once per bucket instead of once per ragged chunk.
  The pad keys are a known constant (0), and the padded counts are
  corrected host-side by an exact integer subtraction
  (streaming/chunked.py:_chunk_histograms) — bit-identical to the
  unpadded histogram.
- :class:`StagingPool` — a small-allocator free-list for the host pad
  buffers ``stage_keys`` fills before the transfer, keyed by
  ``(bucket, dtype, device)``. Once ``device_put`` has landed (the
  producer blocks on it), the host buffer is immediately reusable; the
  pool hands it back to the next same-bucket chunk instead of paying a
  fresh ``np.empty`` per chunk, every pass.
- ``pipeline_depth`` bounds the queue, and with it the staging memory: at
  peak ``depth + 2`` encoded/staged chunks exist at once (``depth``
  queued, plus one the producer holds while blocked on a full queue, plus
  the one the consumer is histogramming) — the "small ring of staging
  buffers". Depth 0 is the synchronous path (no thread), kept as the
  correctness oracle; depth 2 is classic double buffering and the
  default.
- Errors raised anywhere in the producer (drifting dtype, oversized
  chunk, a failing source) are re-raised in the consumer; the consumer
  closing the pipeline (normally or via an exception unwinding the
  ``_key_chunk_stream`` context manager) signals the producer to stop and
  joins the thread — no thread outlives its descent pass
  (tests/conftest.py enforces this after every test).

With ``workers`` > 1 the host side itself goes parallel — the **host data
plane**: ONE sequential puller thread (source order is a correctness
contract: one-shot sources consume exactly once, dtype drift raises at
the drifting chunk) pulls and validates chunks, pre-assigns each staged
chunk's round-robin device slot and stable fault index IN PULL ORDER,
and hands the expensive work — key-encode, the spill tee's v2 pack/CRC,
the staging ``device_put`` — to a pool of ``ksel-ingest-*`` workers. A
reorder sequencer releases finished chunks to the consumer strictly in
chunk order, so the chunk->device assignment, the FIFO
:class:`InflightWindow` discipline, spill record order/slots and every
bit-equality contract are identical at ANY worker count. ``workers=1``
(the default) runs byte-for-byte the legacy single-producer path.

Instrumentation rides :class:`~mpi_k_selection_tpu.utils.profiling.
PhaseTimer` (never raw clocks — KSL004): the producer records
``pipeline.produce`` / ``pipeline.encode`` / ``pipeline.stage`` (the
pooled plane adds ``pipeline.pack``, the tee's parallel pack/CRC, and
``pipeline.seq_wait``, time a finished worker waited for its release
turn), the consumer records ``pipeline.stall`` (time it blocked waiting
for a chunk). :func:`ingest_hidden_frac` turns those into the headline
number: the fraction of ingest wall time the overlap actually hid;
:func:`encode_hidden_frac` is the pooled plane's sharper cut — the
fraction of the parallelizable encode+pack+stage wall the consumer never
saw.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import os
import queue
import threading

import numpy as np

from mpi_k_selection_tpu.faults import policy as _fpol
from mpi_k_selection_tpu.faults.inject import maybe_fault as _maybe_fault
from mpi_k_selection_tpu.obs import ledger as _ledger
from mpi_k_selection_tpu.resource_protocols import (
    INGEST_THREAD_PREFIX,
    PIPELINE_THREAD_PREFIX,
)

#: Classic double buffering: chunk i+1 staged while chunk i computes.
DEFAULT_PIPELINE_DEPTH = 2

#: Queue-depth ceiling — deeper rings only add memory, never overlap.
MAX_PIPELINE_DEPTH = 64

#: Default for ``ingest_workers``: the legacy single-producer data plane,
#: byte-for-byte (the pooled plane is opt-in until the flip condition in
#: ROADMAP.md — a tpu_smoke run confirming the pooled win on silicon).
DEFAULT_INGEST_WORKERS = 1

#: Hard ceiling on the worker pool — far above any host-plane win point;
#: a larger ask is a knob typo, not a bigger machine.
MAX_INGEST_WORKERS = 64

#: ``ingest_workers="auto"`` resolves to ``min(this, cpu count)``: encode
#: + pack + stage saturate a handful of cores long before the sequential
#: puller or the device tunnel become the wall.
INGEST_WORKERS_AUTO_CAP = 4

#: Worker threads carry this prefix; tests assert none outlive their pass.
#: Canonical value lives in resource_protocols.py (the one registry the
#: conftest leak fixtures and the KSL021 lifecycle pass both import).
THREAD_NAME_PREFIX = PIPELINE_THREAD_PREFIX

#: Phases the producer side accounts against the shared PhaseTimer
#: (``pipeline.spill`` is the pass-0 tee writing records to the survivor
#: spill store; ``pipeline.pack`` is the pooled plane's parallel half of
#: the same tee — v2 prefix-pack + CRC — recorded per worker. The timer
#: sums across threads, so pooled runs accumulate genuine CPU-seconds of
#: ingest work, not wall time).
INGEST_PHASES = (
    "pipeline.produce", "pipeline.encode", "pipeline.pack",
    "pipeline.stage", "pipeline.spill",
)

#: Phase the consumer accounts: time spent blocked waiting on the queue.
STALL_PHASE = "pipeline.stall"

#: Phase a pooled worker accounts while a FINISHED chunk waits for its
#: in-order release turn. NOT ingest work (the chunk is done; the wait
#: only preserves chunk order), so it stays out of INGEST_PHASES —
#: identically absent at ``workers=1``, where no sequencer exists.
SEQ_WAIT_PHASE = "pipeline.seq_wait"

_DONE = object()


def resolve_ingest_workers(workers) -> int:
    """Resolve the ``ingest_workers`` knob to a concrete pool size.

    - ``None`` -> :data:`DEFAULT_INGEST_WORKERS` (the one place that
      default lives — every knob surface resolves it identically);
    - ``"auto"`` -> ``min(INGEST_WORKERS_AUTO_CAP, os.cpu_count())``;
    - an int in ``[1, MAX_INGEST_WORKERS]`` — ``1`` is byte-for-byte the
      legacy single-producer path, > 1 the pooled host data plane.

    Answers are bit-identical at every setting (the reorder sequencer
    preserves chunk order end to end); the knob trades host threads for
    ingest throughput only.
    """
    if workers is None:
        return DEFAULT_INGEST_WORKERS
    if workers == "auto":
        return min(INGEST_WORKERS_AUTO_CAP, os.cpu_count() or 1)
    if isinstance(workers, bool) or not isinstance(workers, (int, np.integer)):
        raise ValueError(
            f"ingest_workers must be 'auto' or an integer >= 1, "
            f"got {workers!r}"
        )
    w = int(workers)
    if not 1 <= w <= MAX_INGEST_WORKERS:
        raise ValueError(
            f"ingest_workers={w} out of range [1, {MAX_INGEST_WORKERS}]"
        )
    return w


def validate_pipeline_depth(depth) -> int:
    """Validate and normalize a ``pipeline_depth`` knob (int in
    [0, MAX_PIPELINE_DEPTH]; 0 = synchronous). ``None`` resolves to
    :data:`DEFAULT_PIPELINE_DEPTH` — the one place that default lives, so
    every knob surface (api, CLI, sketch) resolves it identically."""
    if depth is None:
        return DEFAULT_PIPELINE_DEPTH
    if isinstance(depth, bool) or not isinstance(depth, (int, np.integer)):
        raise ValueError(
            f"pipeline_depth must be an integer >= 0 "
            f"(0 = synchronous), got {depth!r}"
        )
    d = int(depth)
    if not 0 <= d <= MAX_PIPELINE_DEPTH:
        raise ValueError(
            f"pipeline_depth={d} out of range [0, {MAX_PIPELINE_DEPTH}]"
        )
    return d


def resolve_stream_devices(devices):
    """Resolve the ``devices`` ingest knob to a concrete device tuple.

    - ``None`` -> ``(None,)``: the single-slot default-device path —
      staging stays an UNCOMMITTED ``device_put`` honoring the caller's
      (thread-local) ``jax.default_device``, bit-for-bit the PR 3
      behavior.
    - an int ``p >= 1`` -> the first ``min(p, len(jax.devices()))``
      devices (the CLI's ``--devices`` cap semantics); ``1`` is the
      explicit single-device form of the default path.
    - a sequence of ``jax.Device`` objects -> used as given (order
      defines the round-robin slots, and with it the deterministic
      chunk->device assignment).

    Every resolution is consumed on the CALLER's thread before the
    producer starts, so the round-robin slot list is fixed for the whole
    pass and the host int64 merge can drain results in chunk order —
    answers are bit-identical for every device count.
    """
    if devices is None:
        return (None,)
    if isinstance(devices, bool):
        raise ValueError(f"devices must be an int >= 1 or a device sequence, got {devices!r}")
    if isinstance(devices, (int, np.integer)):
        p = int(devices)
        if p < 1:
            raise ValueError(f"devices={p} out of range (need >= 1)")
        import jax

        devs = jax.devices()
        return tuple(devs[: min(p, len(devs))])
    if isinstance(devices, (list, tuple)):
        devs = tuple(devices)
        if not devs:
            raise ValueError("devices sequence must not be empty")
        for d in devs:
            if not (hasattr(d, "platform") and hasattr(d, "id")):
                raise ValueError(
                    f"devices entries must be jax Device objects, got {d!r}"
                )
        return devs
    raise ValueError(
        f"devices must be None, an int >= 1, or a sequence of jax devices, "
        f"got {type(devices).__name__!r}"
    )


class StagingPool:
    """Free-list of host staging (pad) buffers, keyed by
    ``(bucket, dtype, device)``.

    ``stage_keys`` fills a pow2-padded host buffer per chunk before the
    transfer; the buffer becomes reusable when the consumer ``release()``s
    the staged slot (not at stage time — ``device_put`` may alias host
    memory on the CPU backend). Streams are dominated by equal-size chunks
    (every pass replays the same chunking), so without a pool every chunk
    of every pass pays a fresh ``np.empty`` of up to 2^30 elements — pure
    allocator churn. The pool retains up to ``max_per_key`` released
    buffers per key and evicts oldest-first past ``max_bytes`` total, so
    steady state is a small ring of resident buffers per distinct
    (bucket, dtype, device) slot.

    Thread-compatible (a lock guards the free lists): each pipeline's
    producer is a single thread, but concurrent passes may share the
    module-level pool.
    """

    def __init__(self, *, max_per_key: int = 4, max_bytes: int = 1 << 31):
        self._lock = threading.Lock()
        self._free: dict = {}  # ksel: guarded-by[_lock]
        self._order: list = []  # ksel: guarded-by[_lock] (eviction order of (key, nbytes))
        self._bytes = 0  # ksel: guarded-by[_lock]
        self.max_per_key = int(max_per_key)
        self.max_bytes = int(max_bytes)
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(bucket: int, dtype, device):
        dev = None if device is None else (device.platform, device.id)
        return (int(bucket), np.dtype(dtype).str, dev)

    def acquire(self, bucket: int, dtype, device=None) -> np.ndarray:
        """A ``bucket``-element host buffer of ``dtype`` — recycled when a
        same-key buffer was released, freshly allocated otherwise."""
        key = self._key(bucket, dtype, device)
        with self._lock:
            bufs = self._free.get(key)
            if bufs:
                buf = bufs.pop()
                self._bytes -= buf.nbytes
                self._order.remove((key, buf.nbytes))
                self.hits += 1
            else:
                self.misses += 1
                buf = None
            # gauge published while still holding the pool lock: two
            # interleaved acquire/release publishes outside it could
            # land last-writer-wins with the STALE footprint (the ledger
            # lock nests inside and acquires nothing, so no cycle)
            _ledger.LEDGER.set_bytes("staging_pool", None, self._bytes)
        if buf is not None:
            return buf
        return np.empty(int(bucket), np.dtype(dtype))

    def release(self, buf: np.ndarray, device=None) -> None:
        """Hand a staging buffer back for reuse (caller must be done with
        its contents — the device copy has landed)."""
        key = self._key(buf.shape[0], buf.dtype, device)
        with self._lock:
            bufs = self._free.setdefault(key, [])
            if len(bufs) >= self.max_per_key:
                return
            bufs.append(buf)
            self._order.append((key, buf.nbytes))
            self._bytes += buf.nbytes
            while self._bytes > self.max_bytes and self._order:
                old_key, nbytes = self._order.pop(0)
                old = self._free.get(old_key)
                if old:
                    old.pop(0)
                    self._bytes -= nbytes
            # under the lock: see acquire()
            _ledger.LEDGER.set_bytes("staging_pool", None, self._bytes)

    @property
    def resident_bytes(self) -> int:
        """Bytes currently held in the free lists (the pool's footprint) —
        the public form the obs registry exports."""
        with self._lock:
            return self._bytes

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._order.clear()
            self._bytes = 0
            # under the lock: see acquire()
            _ledger.LEDGER.set_bytes("staging_pool", None, 0)


#: Module-level pool: staging buckets recur across passes (every pass
#: replays the same chunking), so reuse across ChunkPipeline instances is
#: where the churn fix pays the most.
STAGING_POOL = StagingPool()

# live StagedKeys accounting: every stage_keys() increments, the FIRST
# release() decrements — a leak detector for the executor's
# release-at-handle-finish discipline (tests/conftest.py asserts the count
# returns to its pre-test baseline after every test, including raise paths
# with handles in flight).
_LIVE_STAGED_LOCK = threading.Lock()
_LIVE_STAGED = 0  # ksel: guarded-by[_LIVE_STAGED_LOCK]


def _live_staged_inc() -> None:
    global _LIVE_STAGED
    with _LIVE_STAGED_LOCK:
        _LIVE_STAGED += 1


def _live_staged_dec() -> None:
    global _LIVE_STAGED
    with _LIVE_STAGED_LOCK:
        _LIVE_STAGED -= 1


def _release_latch(staged) -> tuple:
    """Atomic test-and-set of a :class:`StagedKeys`' release latches
    under the live-staged lock: racing releases (an unwind path against
    the normal ring pop) each claim the pool hand-back and the tracked
    decrement AT MOST once — an unsynchronized check-then-set would let
    both threads see the flag, double-insert the host buffer into the
    pool and double-subtract the staging byte gauge. Returns
    ``(host_buf_to_release, won_tracked)``."""
    global _LIVE_STAGED
    with _LIVE_STAGED_LOCK:
        host_buf = None
        if staged.host_buf is not None and staged.pool is not None:
            host_buf = staged.host_buf
            object.__setattr__(staged, "host_buf", None)
        tracked = staged.tracked
        if tracked:
            object.__setattr__(staged, "tracked", False)
            _LIVE_STAGED -= 1
    return host_buf, tracked


def live_staged_keys() -> int:
    """Number of :class:`StagedKeys` buffers staged but not yet
    ``release()``d — 0 between passes; a nonzero steady state is a leaked
    ring slot."""
    with _LIVE_STAGED_LOCK:
        return _LIVE_STAGED


class InflightWindow:
    """FIFO window of in-flight device dispatches — at most ``window``
    handles pending, finished strictly in push order.

    The one multi-device consumption discipline, which every per-chunk
    consumer — histogram merge, survivor collect, rank-certificate count
    folds, spill tee, sketch deep folds — now rides through the async
    executor (streaming/executor.py:StreamExecutor): dispatch per-chunk
    device work asynchronously (one slot per ingest device), materialize
    the OLDEST handle once the window fills, drain the stragglers at end
    of stream.
    The strict FIFO order makes every host merge device-order-
    deterministic: results fold in chunk order no matter which device
    finishes first. With ``window=1`` every push finishes its own handle
    immediately — exactly the serial single-device behavior.

    ``occupancy`` (optional, an obs/metrics.py Histogram) samples the
    in-flight handle count at every push — the per-device window
    utilization the ROADMAP's async-executor work needs: a p-wide window
    that samples ~1 under load is the r6 serialization made measurable.
    Pure observation of a host int; never touches the handles.
    """

    def __init__(self, window: int, finish, occupancy=None):
        self._window = max(1, int(window))
        self._finish = finish
        self._occupancy = occupancy
        self._q: collections.deque = collections.deque()

    def push(self, handle) -> list:
        """Enqueue a dispatch handle; returns a list of ZERO or ONE
        finished results (a plain list, NOT a generator: the pop must
        happen at call time even if a caller drops the result)."""
        self._q.append(handle)
        if self._occupancy is not None:
            self._occupancy.observe(len(self._q))
        if len(self._q) >= self._window:
            return [self._finish(self._q.popleft())]
        return []

    def drain(self):
        """Finish every pending handle, oldest first."""
        while self._q:
            yield self._finish(self._q.popleft())

    def clear_pending(self) -> list:
        """Drop every pending handle WITHOUT finishing it, returning them
        oldest first — the unwind path (streaming/executor.py:
        StreamExecutor.abort) releases their resources without
        materializing in-flight device work."""
        items = list(self._q)
        self._q.clear()
        return items


@dataclasses.dataclass(frozen=True)
class StagedKeys:
    """Device-resident key chunk, padded to a fixed power-of-two bucket.

    ``data`` holds ``n_valid`` real keys followed by ``pad`` zero keys
    (key-space 0). Consumers either slice the valid prefix
    (:meth:`valid`) or histogram the whole buffer and subtract the exact
    pad contribution (streaming/chunked.py:_chunk_histograms) — padding
    never changes an answer bit.
    """

    data: object  # jax.Array, padded to bucket size
    n_valid: int
    # host pad buffer to recycle into `pool` on release (None = none: the
    # chunk was staged unpadded, or the buffer is pool-less). Held until
    # release because device_put may ALIAS the host buffer (CPU backend
    # zero-copy): reusing it while `data` lives would corrupt staged keys.
    host_buf: object = None
    pool: object = None
    device: object = None
    # set by stage_keys: this buffer participates in the live-staged leak
    # accounting (release() decrements exactly once)
    tracked: bool = False
    # False when `data` IS the caller's own device array (a device-resident
    # source chunk whose size already matches its pow2 bucket —
    # stage_device_keys wraps it without a copy): release() must not
    # delete a buffer the caller still owns
    own_data: bool = True

    @property
    def size(self) -> int:
        """Valid element count — mirrors ndarray/jax.Array ``.size`` so
        the descent's length accounting is residency-agnostic."""
        return self.n_valid

    @property
    def pad(self) -> int:
        return int(self.data.shape[0]) - self.n_valid

    def valid(self):
        """The unpadded device keys (a lazy slice)."""
        return self.data[: self.n_valid]

    def release(self) -> None:
        """Free the staging buffer eagerly (the ring slot's donation): safe
        once every result depending on it has materialized host-side. The
        host pad buffer goes back to its :class:`StagingPool` free-list
        here — not at stage time — because the device array may alias it.
        Idempotent: the pool hand-back and the live-staged decrement each
        happen exactly once (unwind paths — executor abort, pipeline
        close — may race a normal release on the same chunk)."""
        # padded-buffer bytes off the array METADATA, read before the
        # delete below invalidates the buffer (shape/dtype survive it) —
        # the ledger's staging gauge decrement must mirror stage-time's add
        nbytes = int(self.data.shape[0]) * np.dtype(self.data.dtype).itemsize
        delete = getattr(self.data, "delete", None)
        if delete is not None and self.own_data:
            try:
                delete()
            except Exception:  # pragma: no cover  # ksel: noqa[KSL012] -- release() is idempotent by contract: delete() of an already-consumed/donated buffer is the expected second-release path, and there is nothing to report or retry
                pass
        # both latches claimed atomically (_release_latch): unwind paths
        # may race the normal release on the same chunk, and each side
        # effect — pool hand-back, live-staged decrement, byte-gauge
        # subtraction — must happen exactly once
        host_buf, tracked = _release_latch(self)
        if host_buf is not None:
            self.pool.release(host_buf, self.device)
        if tracked:
            _ledger.LEDGER.adjust_bytes("staging", self.device, -nbytes)


def _bucket_elems(n: int) -> int:
    """Power-of-two staging-bucket size for an ``n``-element chunk: all
    equal-size chunks (and any ragged tail with the same ceiling) share
    one compiled histogram program. Chunks past 2^30 stay unpadded —
    their pow2 ceiling would cross the 2^31 per-chunk counter bound."""
    bucket = 1 << max(0, n - 1).bit_length()
    return n if bucket >= 1 << 31 else bucket


def stage_keys(
    keys: np.ndarray, device=None, pool: StagingPool | None = None,
    fault_index: int | None = None,
) -> StagedKeys:
    """Pad host ``keys`` to their pow2 bucket and transfer to ``device``
    (``None`` = the caller's default device, uncommitted — the single-slot
    path; a concrete device commits the buffer there, the round-robin
    path), blocking until the copy lands (that wait is the whole point: it
    happens on the producer thread, not in the descent). The pad buffer is
    drawn from ``pool`` (default: the module :data:`STAGING_POOL`) and
    recycled when the consumer ``release()``s the staged slot — so
    same-bucket chunks reuse a small ring of host buffers instead of
    re-allocating every chunk."""
    import jax

    # chaos hook (faults/inject.py; a no-op without an armed injector).
    # Raising kinds fire BEFORE any buffer is acquired, so a retried
    # stage re-runs this function whole — nothing to unwind.
    # ``fault_index`` is the caller's STABLE occurrence key (the
    # producer's staged-chunk counter): a retry of the same chunk must
    # advance the (site, index) ATTEMPT counter, not land on a fresh
    # index — that is what lets a plan schedule "chunk i fails attempt j
    # then recovers" (None = auto-index by call order, for un-retried
    # direct callers).
    _maybe_fault("stage", fault_index)
    n = int(keys.shape[0])
    bucket = _bucket_elems(n)
    if bucket == n:
        data = jax.device_put(keys, device)
        data.block_until_ready()
        _live_staged_inc()
        _ledger.LEDGER.adjust_bytes("staging", device, n * keys.dtype.itemsize)
        # device recorded even without a pad buffer: the spill tee keys
        # its records by the staged slot (chunk->device determinism)
        return StagedKeys(data, n, device=device, tracked=True)
    if pool is None:
        pool = STAGING_POOL
    buf = pool.acquire(bucket, keys.dtype, device)
    buf[:n] = keys
    buf[n:] = 0  # zero only the pad tail, not the whole bucket
    data = jax.device_put(buf, device)
    data.block_until_ready()
    _live_staged_inc()
    _ledger.LEDGER.adjust_bytes("staging", device, bucket * keys.dtype.itemsize)
    # the pad buffer is NOT recycled yet: device_put may alias host memory
    # (CPU zero-copy), so it rides the StagedKeys and returns to the pool
    # when the consumer release()s the slot
    return StagedKeys(
        data, n, host_buf=buf, pool=pool, device=device, tracked=True
    )


_DEVICE_PAD_FN = None


def _array_device(x):
    """The single device an array is committed to, or ``None`` (sharded /
    unknown) — the StagedKeys device slot for device-resident chunks."""
    devices = getattr(x, "devices", None)
    if devices is None:  # pragma: no cover - every jax.Array has .devices()
        return None
    ds = devices()
    return next(iter(ds)) if len(ds) == 1 else None


def stage_device_keys(keys, fault_index: int | None = None) -> StagedKeys:
    """Wrap a DEVICE-RESIDENT key chunk in the pow2 staging discipline —
    the device twin of :func:`stage_keys`, closing the last eager-gather
    class (KSL011): once a device source chunk is a :class:`StagedKeys`,
    the executor's deferred (and fused) fixed-shape programs consume it
    exactly like a host-staged chunk, instead of the retired per-chunk
    boolean gather.

    No host transfer happens: a ragged chunk is zero-padded to its pow2
    bucket ON its own device (pad keys are key-space 0, the exact-
    correction contract every consumer already honors; the pad program
    compiles once per (n, bucket) pair — equal-size chunks, the streaming
    steady state, share one). A chunk whose length already is its bucket
    is wrapped WITHOUT a copy, marked ``own_data=False`` so ``release()``
    never deletes the caller's array. The (producer-thread) block on the
    pad keeps the staging wait off the consuming descent, mirroring
    :func:`stage_keys`'s transfer block — as does the chaos discipline:
    the same ``"stage"`` fault site fires first (before any buffer
    exists, so a retried stage re-runs whole), with ``fault_index`` the
    producer's stable staged-chunk key exactly like :func:`stage_keys`'s."""
    import jax

    _maybe_fault("stage", fault_index)
    n = int(keys.shape[0])
    bucket = _bucket_elems(n)
    if bucket == n:
        _live_staged_inc()
        dev = _array_device(keys)
        _ledger.LEDGER.adjust_bytes(
            "staging", dev, n * np.dtype(keys.dtype).itemsize
        )
        return StagedKeys(keys, n, device=dev, tracked=True, own_data=False)
    global _DEVICE_PAD_FN
    if _DEVICE_PAD_FN is None:
        import jax.numpy as jnp

        _DEVICE_PAD_FN = jax.jit(
            lambda k, pad: jnp.pad(k, (0, pad)), static_argnums=1
        )
    data = _DEVICE_PAD_FN(keys, bucket - n)
    data.block_until_ready()
    _live_staged_inc()
    dev = _array_device(data)
    _ledger.LEDGER.adjust_bytes(
        "staging", dev, bucket * np.dtype(data.dtype).itemsize
    )
    return StagedKeys(data, n, device=dev, tracked=True)


@dataclasses.dataclass
class _Raised:
    exc: BaseException


@dataclasses.dataclass
class _IngestTask:
    """One pulled chunk's work order for the ingest pool. Everything
    order-sensitive is decided by the sequential puller BEFORE the task
    is handed to a worker: ``seq`` (the dense release index the reorder
    sequencer enforces), ``staged_slot`` (the round-robin — or replayed —
    device slot), and ``fault_index`` (the stable per-chunk chaos key, so
    seeded plans replay identically at any worker count). Workers only
    run the order-free work: encode, stage, pack."""

    seq: int
    chunk: object = None  # normalized chunk (None for an error task)
    dtype: object = None  # stream dtype at pull time (np.dtype)
    device_stage: bool = False  # device-resident chunk: pad on own device
    staged_slot: int | None = None  # host staging slot (None = unstaged)
    fault_index: int | None = None
    error: BaseException | None = None  # a puller error, released in order


def _phase(timer, name: str):
    return contextlib.nullcontext() if timer is None else timer.phase(name)


class ChunkPipeline:
    """Background producer of ``(keys, chunk)`` pairs — the pipelined twin
    of streaming/chunked.py:_iter_key_chunks (same pairs, same order, same
    validation, same errors).

    ``hist_method`` is the raw method string of the pass this pipeline
    feeds: the producer resolves it per the stream dtype exactly like the
    consumer does (streaming/chunked.py:resolve_stream_hist) and stages
    host keys to the device only when a device method will consume them.
    ``None`` disables staging (single-device collect and certificate
    passes: their device work is data-dependent gathers, not fixed-shape
    kernels).

    ``devices`` is the resolved ingest tuple
    (:func:`resolve_stream_devices`): staged chunk *j* commits to
    ``devices[j % p]`` with an explicit ``jax.device_put`` target —
    round-robin, so the consumer can keep one histogram in flight per
    device. ``(None,)`` (the default) is the single-slot uncommitted PR 3
    path. Replayed spill chunks (streaming/spill.py:SpillChunk) carry the
    slot their record was staged to originally; the producer honors it, so
    a replay re-stages every chunk onto the device that already compiled
    its bucket programs instead of re-dealing the round robin.

    ``spill`` is an optional
    :class:`~mpi_k_selection_tpu.streaming.spill.SpillWriter`: the pass-0
    tee. The producer appends each non-empty chunk's HOST encoded keys
    (plus the staged slot) to it right after staging — on this thread, so
    the disk write overlaps the consumer's device compute. The caller
    commits/aborts the writer after the stream closes (the thread is
    joined first, so there is no concurrent append).

    ``workers`` (:func:`resolve_ingest_workers`' RESOLVED value) selects
    the host data plane: ``1`` is the legacy single producer above,
    verbatim; > 1 splits it into the sequential puller + ``workers``
    ``ksel-ingest-*`` encode/pack/stage workers + the reorder sequencer.
    The pooled tee packs/CRCs records in parallel
    (``SpillWriter.prepare``) but WRITES them inside the sequencer's
    in-order turn (``append_prepared``), so record order, chunk indices
    and the ``spill.write`` fault indices match the legacy plane exactly.
    """

    _ids = itertools.count()

    def __init__(
        self, src, dtype=None, *, depth: int, hist_method=None, timer=None,
        devices=None, spill=None, retry=None, obs=None, workers: int = 1,
    ):
        self._src = src
        self._dtype = None if dtype is None else np.dtype(dtype)
        self._depth = validate_pipeline_depth(depth)
        self._pool_n = resolve_ingest_workers(workers)
        # staging-transfer retry policy (faults/policy.py; None = fail on
        # the first transient, the pre-resilience behavior) and the obs
        # bundle its retry events go to
        self._retry = retry
        self._obs = obs
        if self._depth == 0:
            raise ValueError(
                "ChunkPipeline requires pipeline_depth >= 1; depth 0 is "
                "the synchronous path (_iter_key_chunks)"
            )
        self._hist_method = hist_method
        self._timer = timer
        self._spill = spill
        # resolved on the CALLER's thread (jax.devices() may initialize the
        # backend; the slot order must be fixed before the producer starts)
        self._devices = resolve_stream_devices(devices)
        # jax's enable_x64 AND default_device context managers are
        # THREAD-LOCAL: capture the consumer's effective values here
        # (consumer thread) and re-establish them inside the producer, so
        # the worker encodes 64-bit device chunks, resolves the histogram
        # method, and commits staged buffers to the SAME device the
        # synchronous path would — not wherever a fresh thread defaults to
        import jax

        self._x64 = bool(jax.config.jax_enable_x64)
        self._device = getattr(jax.config, "jax_default_device", None)
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._workers: list = []  # pooled plane only; close() joins all
        pipe_id = next(self._ids)
        if self._pool_n == 1:
            self._thread = threading.Thread(
                target=self._produce,
                name=f"{THREAD_NAME_PREFIX}-{pipe_id}",
                daemon=True,
            )
        else:
            # pooled host data plane: bounded task queue (raw chunks only
            # — staged memory stays bounded by depth + workers in flight),
            # the reorder sequencer's condition + counters, and the abort
            # latch an erroring worker sets once its error has reached
            # the consumer (later chunks then drop instead of queueing)
            self._tasks: queue.Queue = queue.Queue(maxsize=self._pool_n)
            self._cond = threading.Condition()
            self._next_seq = 0  # ksel: guarded-by[_cond]
            self._total = None  # ksel: guarded-by[_cond] (task count, set at exhaustion)
            self._done_sent = False  # ksel: guarded-by[_cond]
            self._abort = threading.Event()
            self._thread = threading.Thread(
                target=self._pull,
                name=f"{THREAD_NAME_PREFIX}-{pipe_id}",
                daemon=True,
            )
            for w in range(self._pool_n):
                t = threading.Thread(
                    target=self._ingest_worker,
                    name=f"{INGEST_THREAD_PREFIX}-{pipe_id}-{w}",
                    daemon=True,
                )
                self._workers.append(t)
                t.start()
        self._thread.start()

    # -- producer thread ---------------------------------------------------

    def _put(self, item) -> bool:
        """Enqueue, yielding every 50 ms to honor a consumer-side close."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        import jax

        from mpi_k_selection_tpu.utils import compat

        dev_ctx = (
            jax.default_device(self._device)
            if self._device is not None
            else contextlib.nullcontext()
        )
        with compat.enable_x64(self._x64), dev_ctx:
            self._produce_inner()

    def _produce_inner(self) -> None:
        from mpi_k_selection_tpu.streaming import chunked as _chunked
        from mpi_k_selection_tpu.streaming import spill as _sp

        dtype = self._dtype
        method = None
        slot = 0  # round-robin staging cursor over the resolved devices
        staged_i = 0  # stable per-chunk fault key (retries share it)
        keys = None  # the chunk in hand; None once the consumer owns it
        try:
            it = iter(self._src())
            while not self._stop.is_set():
                with _phase(self._timer, "pipeline.produce"):
                    try:
                        chunk = next(it)
                    except StopIteration:
                        break
                with _phase(self._timer, "pipeline.encode"):
                    pair = _chunked._encode_chunk(chunk, dtype)
                if pair is None:  # empty chunk: a no-op, like the sync path
                    continue
                keys, c = pair
                if dtype is None:
                    dtype = np.dtype(c.dtype)
                if method is None and self._hist_method is not None:
                    method = _chunked.resolve_stream_hist(self._hist_method, dtype)
                # a replayed spill record re-stages onto its ORIGINAL slot
                # (the device that already compiled its bucket programs)
                replay_slot = (
                    chunk.device_slot
                    if isinstance(chunk, _sp.SpillChunk)
                    else None
                )
                host_keys = keys if isinstance(keys, np.ndarray) else None
                staged_slot = None
                if host_keys is None:
                    # a DEVICE-RESIDENT source chunk: route it through the
                    # staged/deferred path (pow2 pad on its own device, no
                    # transfer) whenever a device method will consume it —
                    # including the single-device collect/certificate
                    # passes, which hand hist_method=None (the host-exact
                    # 64-bit-no-x64 route still resolves to "numpy" and
                    # stays unstaged; the f64-on-TPU route encodes to host
                    # keys upstream and never reaches this branch)
                    dev_method = (
                        method
                        if self._hist_method is not None
                        else _chunked.resolve_stream_hist("auto", dtype)
                    )
                    if dev_method != "numpy":
                        with _phase(self._timer, "pipeline.stage"):
                            # same chaos/retry discipline as the host
                            # staging below: the "stage" fault site keyed
                            # by the shared staged-chunk counter, retried
                            # in place under the pass's policy
                            keys = _fpol.retry_call(
                                lambda dk=keys, i=staged_i: stage_device_keys(
                                    dk, fault_index=i
                                ),
                                self._retry, site="stage", obs=self._obs,
                            )
                            staged_i += 1
                if method not in (None, "numpy") and isinstance(keys, np.ndarray):
                    with _phase(self._timer, "pipeline.stage"):
                        if replay_slot is None:
                            # the slot advances ONLY on staged chunks, so
                            # the chunk->device assignment is a pure
                            # function of the staged sequence — identical
                            # on every replay
                            staged_slot = slot % len(self._devices)
                            slot += 1
                        else:
                            staged_slot = replay_slot % len(self._devices)
                        # a transient device_put failure retries IN PLACE
                        # (the host buffer is still in hand; re-issuing
                        # the transfer is free) under the pass's policy —
                        # exhaustion raises RetryExhaustedError through
                        # the consumer like any other producer error
                        dev = self._devices[staged_slot]
                        keys = _fpol.retry_call(
                            lambda hk=keys, d=dev, i=staged_i: stage_keys(
                                hk, d, fault_index=i
                            ),
                            self._retry, site="stage", obs=self._obs,
                        )
                        staged_i += 1
                if self._spill is not None:
                    try:
                        with _phase(self._timer, "pipeline.spill"):
                            # device-chunk keys live on device: land them
                            # host-side for the record (host chunks tee in
                            # place; a device-staged chunk lands its whole
                            # bucket and drops the pad host-side)
                            if host_keys is not None:
                                hk = host_keys
                            elif isinstance(keys, StagedKeys):
                                hk = np.asarray(keys.data)[: keys.n_valid]
                            else:
                                hk = np.asarray(keys)
                            self._spill.append(hk, dtype, device_slot=staged_slot)
                    except BaseException:
                        # a failing tee write (ENOSPC, a transient disk
                        # error) abandons the chunk in hand before it
                        # reaches the consumer: release its staged ring
                        # slot or the leak accounting never sees it
                        if isinstance(keys, StagedKeys):
                            keys.release()
                        raise
                # every consumer reads only `.dtype` off the companion (and
                # only on the first chunk): a zero-length stand-in keeps the
                # queue from pinning the full original chunk alongside its
                # keys — at the bench's 512 MB staged chunks that dead
                # weight would double the per-slot memory footprint
                if not self._put((keys, np.empty((0,), c.dtype))):
                    # consumer closed mid-put: the chunk we hold never
                    # reaches it — release its staged slot here, or the
                    # ring buffer (and the leak accounting) never sees it
                    if isinstance(keys, StagedKeys):
                        keys.release()
                    return
                keys = None  # the consumer owns it now (close() drains)
            self._put(_DONE)
        except BaseException as e:  # re-raised by the consumer
            # the chunk in hand never reached the queue: release its ring
            # slot before reporting (idempotent — the spill tee's unwind
            # may have released it already). close() drains only what was
            # ENQUEUED, so this handler is the one place that can see it;
            # before this release, any raise between staging and the put
            # leaked the slot (KSL019's first whole-repo run caught it)
            if isinstance(keys, StagedKeys):
                keys.release()
            self._put(_Raised(e))

    # -- pooled host data plane (workers > 1) -------------------------------

    def _halted(self) -> bool:
        """True once no further chunk may reach the consumer: the
        consumer closed (``_stop``) or an earlier error already reached
        it (``_abort`` — everything sequenced after an error is dead)."""
        return self._stop.is_set() or self._abort.is_set()

    def _submit_task(self, task) -> bool:
        """Bounded-queue put from the puller, yielding every 50 ms so a
        consumer-side close (or a released error) never deadlocks a full
        task queue."""
        while not self._halted():
            try:
                self._tasks.put(task, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _pull(self) -> None:
        import jax

        from mpi_k_selection_tpu.utils import compat

        dev_ctx = (
            jax.default_device(self._device)
            if self._device is not None
            else contextlib.nullcontext()
        )
        with compat.enable_x64(self._x64), dev_ctx:
            self._pull_inner()

    def _pull_inner(self) -> None:
        """The sequential half of the pooled plane: pull chunks IN SOURCE
        ORDER, run the cheap order-sensitive validation (dtype adopt +
        drift, the 2^31 guard, empty-skip — streaming/chunked.py:
        _normalize_chunk, the same contract the legacy producer enforces
        through _encode_chunk), and pre-assign each staged chunk's
        round-robin slot and stable fault index before any worker touches
        it. Everything a worker does afterwards is order-free."""
        from mpi_k_selection_tpu.streaming import chunked as _chunked
        from mpi_k_selection_tpu.streaming import spill as _sp

        dtype = self._dtype
        method = None
        slot = 0  # round-robin staging cursor over the resolved devices
        staged_i = 0  # stable per-chunk fault key (retries share it)
        seq = 0
        try:
            it = iter(self._src())
            while not self._halted():
                with _phase(self._timer, "pipeline.produce"):
                    try:
                        chunk = next(it)
                    except StopIteration:
                        break
                with _phase(self._timer, "pipeline.encode"):
                    c = _chunked._normalize_chunk(chunk, dtype)
                if c is None:  # empty chunk: a no-op, like the sync path
                    continue
                if dtype is None:
                    dtype = np.dtype(
                        c.orig_dtype
                        if isinstance(c, _sp.SpillChunk)
                        else c.dtype
                    )
                if method is None and self._hist_method is not None:
                    method = _chunked.resolve_stream_hist(
                        self._hist_method, dtype
                    )
                host_bound = _chunked._encodes_to_host(c)
                device_stage = False
                staged_slot = fault_index = None
                if not host_bound:
                    # device-resident chunk: same routing rule as the
                    # legacy producer — stage on its OWN device whenever
                    # a device method will consume it (no slot consumed)
                    dev_method = (
                        method
                        if self._hist_method is not None
                        else _chunked.resolve_stream_hist("auto", dtype)
                    )
                    if dev_method != "numpy":
                        device_stage = True
                        fault_index = staged_i
                        staged_i += 1
                elif method not in (None, "numpy"):
                    replay_slot = (
                        c.device_slot
                        if isinstance(c, _sp.SpillChunk)
                        else None
                    )
                    if replay_slot is None:
                        # the slot advances ONLY on staged chunks — the
                        # chunk->device assignment is a pure function of
                        # the staged sequence, identical at every worker
                        # count and on every replay
                        staged_slot = slot % len(self._devices)
                        slot += 1
                    else:
                        staged_slot = replay_slot % len(self._devices)
                    fault_index = staged_i
                    staged_i += 1
                task = _IngestTask(
                    seq=seq, chunk=c, dtype=dtype,
                    device_stage=device_stage, staged_slot=staged_slot,
                    fault_index=fault_index,
                )
                seq += 1
                if not self._submit_task(task):
                    return
            if not self._halted():
                self._finish_stream(seq)
        except BaseException as e:
            # a puller error (drifting dtype, oversized chunk, a failing
            # source) must reach the consumer AFTER every earlier chunk:
            # give it the next dense seq slot and let the sequencer
            # release it in turn — exactly the legacy error order
            if self._submit_task(_IngestTask(seq=seq, error=e)):
                self._finish_stream(seq + 1)
        finally:
            # one sentinel per worker, after every real task (FIFO): each
            # worker drains the tasks ahead, then exits on its sentinel
            for _ in range(self._pool_n):
                if not self._submit_task(None):
                    break  # halted: workers exit on the halt flags instead

    def _finish_stream(self, total: int) -> None:
        """Publish the final task count; whoever observes the sequencer
        reach it (a releasing worker — or this puller, for an empty
        stream) sends the ONE ``_DONE``."""
        send_done = False
        with self._cond:
            self._total = total
            if self._next_seq >= total and not self._done_sent:
                self._done_sent = True
                send_done = True
        if send_done:
            self._put(_DONE)

    def _advance_seq(self) -> None:
        """Release the sequencer turn after a chunk (or error) has been
        handed to the consumer queue."""
        send_done = False
        with self._cond:
            self._next_seq += 1
            if (
                self._total is not None
                and self._next_seq >= self._total
                and not self._done_sent
            ):
                self._done_sent = True
                send_done = True
            self._cond.notify_all()
        if send_done:
            self._put(_DONE)

    def _ingest_worker(self) -> None:
        import jax

        from mpi_k_selection_tpu.utils import compat

        # same thread-local discipline as the legacy producer: x64 and
        # the default device are re-established per worker, so encode
        # and uncommitted staging behave exactly like the caller's thread
        dev_ctx = (
            jax.default_device(self._device)
            if self._device is not None
            else contextlib.nullcontext()
        )
        with compat.enable_x64(self._x64), dev_ctx:
            while not self._stop.is_set():
                try:
                    task = self._tasks.get(timeout=0.05)
                except queue.Empty:
                    if self._abort.is_set():
                        return
                    continue
                if task is None:  # the puller's per-worker sentinel
                    return
                self._run_task(task)

    def _run_task(self, task: _IngestTask) -> None:
        """One worker's whole chunk: the order-free parallel section
        (encode -> stage -> tee pack/CRC), then the reorder sequencer's
        in-order release (tee record write -> consumer queue put)."""
        from mpi_k_selection_tpu.streaming import chunked as _chunked

        keys = comp_dtype = prep = None
        error = task.error
        if error is None and self._halted():
            return  # nothing staged yet; the chunk holds no resources
        if error is None:
            try:
                with _phase(self._timer, "pipeline.encode"):
                    keys, c = _chunked._encode_normalized(task.chunk)
                comp_dtype = c.dtype
                if task.device_stage:
                    with _phase(self._timer, "pipeline.stage"):
                        keys = _fpol.retry_call(
                            lambda dk=keys, i=task.fault_index: (
                                stage_device_keys(dk, fault_index=i)
                            ),
                            self._retry, site="stage", obs=self._obs,
                        )
                elif task.staged_slot is not None:
                    with _phase(self._timer, "pipeline.stage"):
                        dev = self._devices[task.staged_slot]
                        keys = _fpol.retry_call(
                            lambda hk=keys, d=dev, i=task.fault_index: (
                                stage_keys(hk, d, fault_index=i)
                            ),
                            self._retry, site="stage", obs=self._obs,
                        )
                if self._spill is not None:
                    # the tee's order-FREE half: pack + CRC on this
                    # worker; the record WRITE (index assignment, disk)
                    # stays inside the in-order turn below
                    with _phase(self._timer, "pipeline.pack"):
                        if isinstance(keys, StagedKeys):
                            hk = np.asarray(keys.data)[: keys.n_valid]
                        elif isinstance(keys, np.ndarray):
                            hk = keys
                        else:
                            hk = np.asarray(keys)
                        prep = self._spill.prepare(hk, task.dtype)
            except BaseException as e:
                if isinstance(keys, StagedKeys):
                    keys.release()
                keys, prep, error = None, None, e
        # -- reorder sequencer: wait for this chunk's release turn ------
        try:
            with _phase(self._timer, SEQ_WAIT_PHASE):
                with self._cond:
                    while self._next_seq != task.seq and not self._halted():
                        self._cond.wait(0.05)
                    my_turn = self._next_seq == task.seq
            if not my_turn:
                # halted while waiting: the consumer closed, or an
                # earlier error already reached it — this chunk can
                # never be consumed, so release its staged slot and
                # drop it
                if isinstance(keys, StagedKeys):
                    keys.release()
                return
            # -- the in-order section (only the turn holder runs it) ----
            if error is None and prep is not None:
                try:
                    with _phase(self._timer, "pipeline.spill"):
                        self._spill.append_prepared(
                            prep, device_slot=task.staged_slot
                        )
                except BaseException as e:
                    # a failing tee write abandons the chunk before it
                    # reaches the consumer: release its staged ring slot
                    if isinstance(keys, StagedKeys):
                        keys.release()
                    keys, error = None, e
            if error is None:
                if not self._put((keys, np.empty((0,), comp_dtype))):
                    # consumer closed mid-put: the chunk never reaches it
                    if isinstance(keys, StagedKeys):
                        keys.release()
                keys = None  # transferred (or released) either way
            else:
                # every error path above nulls keys after releasing; the
                # narrowing keeps that invariant checkable (KSL019)
                if isinstance(keys, StagedKeys):  # pragma: no cover
                    keys.release()
                self._put(_Raised(error))
                self._abort.set()  # everything sequenced after us is dead
        except BaseException:  # pragma: no cover - sequencer machinery
            # nothing above is expected to raise outside the handled
            # spots; if it does, unwind the staged slot and poison the
            # stream so the consumer fails loudly instead of hanging
            if isinstance(keys, StagedKeys):
                keys.release()
            self._abort.set()
            raise
        self._advance_seq()

    # -- consumer side -----------------------------------------------------

    def __iter__(self):
        while True:
            with _phase(self._timer, STALL_PHASE):
                while True:
                    try:
                        item = self._q.get(timeout=0.1)
                        break
                    except queue.Empty:
                        alive = self._thread.is_alive() or any(
                            t.is_alive() for t in self._workers
                        )
                        if not alive:
                            # the producer may have enqueued its final item
                            # (_DONE or _Raised) and exited between our
                            # timeout and this check: drain once more
                            # before declaring it dead
                            try:
                                item = self._q.get_nowait()
                                break
                            except queue.Empty:  # pragma: no cover
                                raise RuntimeError(
                                    "streaming pipeline producer died "
                                    "without a result — this is a bug"
                                ) from None
            if item is _DONE:
                return
            if isinstance(item, _Raised):
                raise item.exc
            yield item

    def close(self) -> None:
        """Stop the producer and join its thread: set the stop flag, drain
        the queue so a blocked put unblocks, then join. Idempotent; called
        by the ``_key_chunk_stream`` context manager on every exit path
        (including consumer-side exceptions like the replay-stability
        raise), so no thread outlives its pass."""
        self._stop.set()

        def _drain_queue():
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    return
                # staged chunks the consumer never saw: release their ring
                # slots (and live-staged accounting) instead of dropping
                # them on the floor
                if isinstance(item, tuple) and isinstance(item[0], StagedKeys):
                    item[0].release()

        _drain_queue()
        self._thread.join(timeout=10.0)
        for t in self._workers:
            _drain_queue()  # unblock a worker parked on a full queue
            t.join(timeout=10.0)
        # a final put may have landed between the drain above and the
        # producer observing the stop flag — sweep again after the join
        _drain_queue()
        for t in self._workers:
            if t.is_alive():  # pragma: no cover - 10 s stuck worker
                import warnings

                warnings.warn(
                    f"streaming ingest worker {t.name} did not stop within "
                    "10 s of close(); the thread has been abandoned (daemon)",
                    RuntimeWarning,
                    stacklevel=2,
                )
        if self._thread.is_alive():
            # a source blocked past the join timeout (slow disk/network
            # read): the no-thread-outlives-its-pass guarantee is violated
            # and the next pass may re-open the same resource mid-read —
            # make that observable instead of returning as if clean
            # (raising here would mask the consumer's original exception)
            import warnings

            warnings.warn(
                f"streaming pipeline producer {self._thread.name} did not "
                "stop within 10 s of close(); its chunk source is blocked "
                "mid-read and the thread has been abandoned (daemon)",
                RuntimeWarning,
                stacklevel=2,
            )

    def __enter__(self) -> "ChunkPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def ingest_hidden_frac(timer) -> float | None:
    """Fraction of producer-side ingest time (produce + encode + stage)
    that the overlap hid from the descent: 1 - stall/ingest, clamped to
    [0, 1]. ~1.0 means the consumer never waited (ingest fully hidden
    behind compute); ~0.0 means the consumer stalled for the whole ingest
    (no overlap — the synchronous regime). ``None`` when the timer carries
    no pipeline phases (e.g. a ``pipeline_depth=0`` run)."""
    ingest = sum(timer.phases.get(p, 0.0) for p in INGEST_PHASES)
    if ingest <= 0.0:
        return None
    stall = timer.phases.get(STALL_PHASE, 0.0)
    return max(0.0, min(1.0, 1.0 - stall / ingest))


def encode_hidden_frac(timer) -> float | None:
    """The pooled plane's sharper cut of :func:`ingest_hidden_frac`: the
    fraction of the PARALLELIZABLE host work — encode + pack + stage, the
    part the worker pool spreads across cores — the consumer never waited
    for (1 - stall/work, clamped to [0, 1]). ``pipeline.produce`` (the
    sequential puller, unparallelizable by contract) and
    ``pipeline.spill`` (the in-order tee write) are excluded, so the
    number answers the bench's question directly: did the pool hide the
    encode wall? ``None`` when the timer carries no such phases."""
    work = sum(
        timer.phases.get(p, 0.0)
        for p in ("pipeline.encode", "pipeline.pack", "pipeline.stage")
    )
    if work <= 0.0:
        return None
    stall = timer.phases.get(STALL_PHASE, 0.0)
    return max(0.0, min(1.0, 1.0 - stall / work))
