"""Public API dispatch + reference-semantics checks."""

import jax.numpy as jnp
import numpy as np
import pytest

import mpi_k_selection_tpu as ks
from mpi_k_selection_tpu.backends import get_backend, seq
from mpi_k_selection_tpu.utils import datagen


def test_kselect_dispatch():
    x = datagen.generate(3000, pattern="uniform", seed=1, dtype=np.int32)
    k = 1500
    want = int(seq.kselect(x, k))
    assert int(ks.kselect(jnp.asarray(x), k)) == want
    assert int(ks.kselect(jnp.asarray(x), k, algorithm="sort")) == want
    assert int(ks.kselect(jnp.asarray(x), k, algorithm="radix")) == want


def test_median_matches_reference_operating_point():
    # k = N/2, 1-indexed (kth-problem-seq.c~:24)
    x = datagen.generate(1000, pattern="uniform", seed=2, dtype=np.int32)
    want = int(np.sort(x)[1000 // 2 - 1])
    assert int(ks.median(jnp.asarray(x))) == want
    assert int(seq.median(x)) == want


def test_backend_registry():
    assert get_backend("seq") is seq
    with pytest.raises(ValueError):
        get_backend("cuda")


def test_reference_defaults_config():
    # the reference constants survive as defaults: N=1e8, k=250/150, c=500
    from mpi_k_selection_tpu import config

    assert config.REFERENCE_N == 100_000_000
    assert config.REFERENCE_K_SEQ == 250
    assert config.REFERENCE_K_CGM == 150
    assert config.REFERENCE_C == 500
