"""TPU backend (``--backend=tpu``) — JAX/XLA execution.

Single-chip selection dispatches to the radix/sort ops (ops/); when more than
one device is visible and the input is large, selection runs sharded over a
1-D device mesh via the distributed radix path (parallel/), which replaces
the reference's MPI scatter/iterate/gather protocol
(``TODO-kth-problem-cgm.c:103-293``) with XLA collectives over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu import api

NAME = "tpu"


def plan(n: int, algorithm: str = "auto", distribute: str = "auto"):
    """Resolve (effective_algorithm, distributed) for a selection of size n.

    The radix and cgm algorithms have distributed paths; an explicit
    ``algorithm='sort'`` therefore always runs single-chip, and asking for
    ``distribute='always'`` with it is an error rather than a silent switch.
    CGM is the reference's multi-rank protocol (``TODO-kth-problem-cgm.c``) —
    it is *only* distributed, so ``distribute='never'`` with it is an error
    (mirroring the reference's world_size >= 2 abort at ``:56-59``).
    """
    if distribute not in ("auto", "never", "always"):
        raise ValueError(
            f"distribute={distribute!r} must be one of 'auto', 'never', 'always'"
        )
    n_dev = len(jax.devices())
    if algorithm == "cgm":
        if distribute == "never":
            raise ValueError(
                "algorithm='cgm' is the distributed parity protocol and has "
                "no single-chip path (the reference aborts below 2 ranks, "
                "TODO-kth-problem-cgm.c:56-59); use algorithm='radix' or "
                "'sort' single-chip"
            )
        return "cgm", True
    distributable = algorithm in ("auto", "radix")
    if distribute == "always" and not distributable:
        # validated independently of the host's device count, so the error
        # surfaces in single-device CI too
        raise ValueError(
            f"algorithm={algorithm!r} has no distributed path; "
            "use algorithm='radix', 'cgm' (or 'auto') with distribute='always'"
        )
    use_mesh = {
        "auto": distributable and n_dev > 1 and n >= 1 << 20 and n % n_dev == 0,
        "never": False,
        "always": n_dev > 1,
    }[distribute]
    if use_mesh:
        return "radix", True
    if algorithm == "auto":
        algorithm = "sort" if n <= 1 << 14 else "radix"
    return algorithm, False


def kselect(x, k: int, *, algorithm: str = "auto", distribute: str = "auto", **kwargs):
    """Exact k-th smallest (1-indexed). ``distribute`` in {auto, never, always}."""
    n = np.asarray(x).size if not hasattr(x, "size") else x.size
    algorithm, use_mesh = plan(n, algorithm, distribute)
    if use_mesh:
        from mpi_k_selection_tpu.parallel import cgm as pcgm, radix as pradix

        if algorithm == "cgm":
            return pcgm.distributed_cgm_select(jnp.asarray(x), k, **kwargs)
        return pradix.distributed_radix_select(jnp.asarray(x), k, **kwargs)
    return api.kselect(jnp.asarray(x), k, algorithm=algorithm, **kwargs)


def topk(x, k: int, *, largest: bool = True, **kwargs):
    from mpi_k_selection_tpu.ops.topk import topk as _topk

    return _topk(jnp.asarray(x), k, largest=largest, **kwargs)


def median(x, **kwargs):
    x = jnp.asarray(x)
    return kselect(x, max(1, x.size // 2), **kwargs)
