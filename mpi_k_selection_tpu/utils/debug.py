"""Validation / sanitizer subsystem (SURVEY.md §5 "race detection").

JAX's functional model structurally excludes the data races the reference is
exposed to (its C has a latent use-after-free around the final Gatherv,
``TODO-kth-problem-cgm.c:250-270``). What remains worth checking is *input*
sanity — NaNs that break total ordering, out-of-range k, non-finite floats —
and *result* sanity (the selected value really has rank k). This module is
that checkable layer:

- :func:`validate_input` — host-side checks before a selection runs.
- :func:`checked_kselect` — selection + O(n) rank certificate: counts
  (#less, #less-or-equal) around the answer and asserts ``#less < k <=
  #less-or-equal`` — the same exactness predicate the reference's hit test
  uses (``TODO-…:194``), applied as a post-condition.
- :func:`checkify_kselect` — the jax.experimental.checkify-wrapped kernel
  for use under jit where host asserts cannot run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def check_concrete_k(k, n: int) -> None:
    """Raise ValueError when a *concrete* k is outside [1, n].

    Traced k passes through (it is clamped inside the ops — a traced value
    cannot raise at trace time). This is the one validation contract shared
    by every public entry point, matching the oracle's hard 1 <= k <= N
    semantics (``kth-problem-seq.c:24,33``).
    """
    if isinstance(k, jax.core.Tracer):
        return
    try:
        kv = int(k)
    except (TypeError, ValueError):  # non-scalar / non-integer-like: not ours
        return
    if not 1 <= kv <= n:
        raise ValueError(f"k={kv} out of range [1, {n}] (k is 1-indexed)")


def check_concrete_ks(ks, n: int) -> None:
    """Vector form of :func:`check_concrete_k` for multi-rank selection:
    every concrete k in ``ks`` must lie in [1, n]; a traced ``ks`` passes
    through (clamped inside the ops). Malformed inputs (ragged lists,
    non-numeric) still raise — only the tracer conversion is excused."""
    try:
        ks_concrete = np.asarray(ks)
    except jax.errors.TracerArrayConversionError:
        return  # traced: cannot validate at trace time
    for k in ks_concrete.ravel():
        check_concrete_k(int(k), n)


def validate_input(x, k: int, *, allow_nan: bool = False) -> None:
    """Raise ValueError on inputs that would make selection ill-defined."""
    x = np.asarray(x)
    if x.size == 0:
        raise ValueError("selection requires a non-empty input")
    if not 1 <= int(k) <= x.size:
        raise ValueError(f"k={k} out of range [1, {x.size}] (k is 1-indexed)")
    # jnp.issubdtype, not dtype.kind == 'f': ml_dtypes' bfloat16 has kind 'V'
    if not allow_nan and jnp.issubdtype(x.dtype, jnp.floating):
        probe = x if x.dtype.kind == "f" else x.astype(np.float32)
        if np.isnan(probe).any():
            raise ValueError(
                "input contains NaN: NaNs break total ordering; pass "
                "allow_nan=True to rank them with the IEEE-bits order "
                "(utils/dtypes.py) instead"
            )


def rank_certificate(x, value):
    """(#elements < value, #elements <= value) — the L / L+E of the exact-hit
    test, computed directly as a certificate."""
    from mpi_k_selection_tpu.ops.radix import select_count_dtype
    from mpi_k_selection_tpu.utils import dtypes as _dt

    x = jnp.asarray(x).ravel()
    u = _dt.to_sortable_bits(x)
    v = _dt.to_sortable_bits(jnp.asarray(value, x.dtype))
    cdt = select_count_dtype(x.size)  # loud error at n >= 2^31 without x64
    less = jnp.sum(u < v, dtype=cdt)
    leq = jnp.sum(u <= v, dtype=cdt)
    return less, leq


def checked_kselect(x, k: int, **kwargs):
    """kselect + rank certificate. Raises AssertionError if the returned
    value is not the exact k-th order statistic."""
    from mpi_k_selection_tpu import api

    validate_input(x, k, allow_nan=kwargs.pop("allow_nan", False))
    value = api.kselect(jnp.asarray(x), k, **kwargs)
    less, leq = rank_certificate(x, value)
    less, leq = int(less), int(leq)
    if not less < k <= leq:
        raise AssertionError(
            f"selection certificate failed: value {value} has rank range "
            f"({less}, {leq}] but k={k} — please report this"
        )
    return value


def checkify_kselect(x, k, **kwargs):
    """Selection under jax.experimental.checkify: returns (error, value);
    ``error.throw()`` re-raises any failed in-kernel check on the host."""
    from jax.experimental import checkify

    from mpi_k_selection_tpu import api

    def run(x, k):
        checkify.check(k >= 1, "k must be >= 1, got {k}", k=k)
        checkify.check(
            k <= x.size, "k must be <= n={n}, got {k}", k=k, n=jnp.asarray(x.size)
        )
        # clamp so execution proceeds past a failed check (the error is
        # carried in the checkify state and raised by err.throw())
        return api.kselect(x, jnp.clip(k, 1, x.size), **kwargs)

    checked = checkify.checkify(run)
    return checked(jnp.asarray(x), jnp.asarray(k))
