"""Seeded input generators shared by every backend.

The reference uses two *different, unseeded* C ``rand()`` streams — the
sequential program (``kth-problem-seq.c:26-28``, pattern
``i + rand() - rand()%i``) and the CGM program (``TODO-kth-problem-cgm.c:10-17``,
``rand() % 99999999 + 1``) — so its two answers are never directly comparable
(SURVEY.md §4). This module fixes that: one seeded NumPy generator feeds all
backends, so exact-match checks ``tpu == mpi == seq`` are meaningful.

Patterns provided (reference provenance in parens):

- ``uniform``     — ``rand() % 99999999 + 1`` analogue (``TODO-…:15``)
- ``seqlike``     — the ``i + rand() - rand()%i`` arithmetic of
  ``kth-problem-seq.c:27`` reproduced with NumPy arithmetic (values clipped to
  the dtype instead of tolerating the reference's signed-overflow UB)
- ``descending``  — the commented-out adversarial generator ``TODO-…:67-68``
- ``sequential``  — the commented-out ascending generator ``TODO-…:69-70``
- ``equal``       — all-equal stress input (exercises the duplicate/E>1 path
  of the exact-hit test at ``TODO-…:194``)
- ``normal`` / ``funiform`` — float workloads for the top-k configs
  (MoE router logits, beam-search scores; BASELINE.md)
"""

from __future__ import annotations

import numpy as np

PATTERNS = (
    "uniform",
    "seqlike",
    "descending",
    "sequential",
    "equal",
    "normal",
    "funiform",
)


def generate(
    n: int,
    *,
    pattern: str = "uniform",
    seed: int = 0,
    dtype=np.int32,
    batch: tuple[int, ...] = (),
) -> np.ndarray:
    """Generate a seeded input array of shape ``(*batch, n)``."""
    dtype = np.dtype(dtype)
    rng = np.random.default_rng(seed)
    shape = (*batch, n)
    if pattern == "uniform":
        if dtype.kind in "iu":
            hi = min(99_999_999, np.iinfo(dtype).max - 1)
            out = rng.integers(1, hi + 1, size=shape, dtype=np.int64)
        else:
            out = rng.uniform(1.0, 99_999_999.0, size=shape)
    elif pattern == "seqlike":
        i = np.arange(n, 0, -1, dtype=np.int64)
        i = np.broadcast_to(i, shape)
        r1 = rng.integers(0, 2**31, size=shape, dtype=np.int64)
        r2 = rng.integers(0, 2**31, size=shape, dtype=np.int64)
        out = i + r1 - r2 % np.maximum(i, 1)
    elif pattern == "descending":
        out = np.broadcast_to(np.arange(n, 0, -1, dtype=np.int64), shape)
    elif pattern == "sequential":
        out = np.broadcast_to(np.arange(1, n + 1, dtype=np.int64), shape)
    elif pattern == "equal":
        out = np.full(shape, 42, dtype=np.int64)
    elif pattern == "normal":
        out = rng.standard_normal(size=shape)
    elif pattern == "funiform":
        out = rng.uniform(-1.0, 1.0, size=shape)
    else:
        raise ValueError(f"unknown pattern {pattern!r}; choose from {PATTERNS}")
    if dtype.kind in "iu":
        if np.dtype(np.result_type(out)).kind == "f":
            out = np.rint(out)
        # narrow-dtype casts clip rather than wrap (module policy: no
        # silent modular sawtooth in "adversarial" monotone patterns)
        info = np.iinfo(dtype)
        out = np.clip(out, info.min, info.max)
    return np.ascontiguousarray(out.astype(dtype))


def adversarial_fixtures(n: int, dtype=np.int32, seed: int = 0):
    """The SURVEY.md §4 adversarial fixture set: (name, array) pairs."""
    fixtures = []
    for pattern in ("uniform", "seqlike", "descending", "sequential", "equal"):
        fixtures.append((pattern, generate(n, pattern=pattern, seed=seed, dtype=dtype)))
    dtype = np.dtype(dtype)
    if dtype.kind in "iu":
        info = np.iinfo(dtype)
        rng = np.random.default_rng(seed + 1)
        extremes = rng.choice(
            np.array([info.min, info.min + 1, -1, 0, 1, info.max - 1, info.max], dtype=dtype)
            if dtype.kind == "i"
            else np.array([0, 1, info.max - 1, info.max], dtype=dtype),
            size=n,
        )
        fixtures.append(("extremes", extremes.astype(dtype)))
    else:
        rng = np.random.default_rng(seed + 1)
        specials = rng.choice(
            np.array([0.0, -0.0, 1.5, -1.5, np.finfo(dtype).max, np.finfo(dtype).min], dtype=dtype),
            size=n,
        )
        fixtures.append(("extremes", specials.astype(dtype)))
    return fixtures
