"""Native runtime tests: std::nth_element oracle engine + forked-rank CGM.

SURVEY.md §4: backend-equivalence on identical seeded data; adversarial
fixtures (sorted, reverse, all-equal, k=1, k=N); the duplicates/E>1 path.
"""

import numpy as np
import pytest

from mpi_k_selection_tpu.utils import datagen

pytestmark = pytest.mark.skipif(
    __import__("mpi_k_selection_tpu.native.loader", fromlist=["get_lib"]).get_lib()
    is None,
    reason="native runtime unavailable (no C++ compiler)",
)


def _lib():
    from mpi_k_selection_tpu.native import loader

    return loader.get_lib()


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32, np.float64])
def test_nth_element_matches_numpy(rng, dtype):
    x = (rng.standard_normal(50_001) * 1e6).astype(dtype)
    for k in (1, 2, 25_000, 50_000, 50_001):
        assert _lib().nth_element(x, k) == np.sort(x)[k - 1]


def test_nth_element_bad_k(rng):
    x = rng.integers(0, 100, size=100, dtype=np.int32)
    with pytest.raises(ValueError):
        _lib().nth_element(x, 0)
    with pytest.raises(ValueError):
        _lib().nth_element(x, 101)


def test_seq_backend_uses_native(rng):
    from mpi_k_selection_tpu.backends import seq

    x = rng.integers(-(2**31), 2**31, size=1 << 17, dtype=np.int32)
    k = 777
    assert int(seq.kselect(x, k)) == int(np.sort(x)[k - 1])


@pytest.mark.parametrize("num_procs", [2, 3, 5])
@pytest.mark.parametrize("pattern", ["uniform", "descending", "sequential", "equal"])
def test_cgm_matches_oracle(num_procs, pattern):
    x = datagen.generate(40_013, pattern=pattern, seed=num_procs, dtype=np.int32)
    want = np.sort(x)
    for k in (1, 150, 20_007, 40_013):
        a, _, _, _ = _lib().cgm_kselect(x, k, num_procs=num_procs, c=500)
        assert a == want[k - 1], (pattern, num_procs, k)


def test_cgm_found_early_path():
    # huge c forces threshold ~ n, so round 1 must hit the exact test or
    # immediately fall through to the gather path; both must stay exact
    x = datagen.generate(10_001, pattern="uniform", seed=9, dtype=np.int32)
    a, rounds, _, _ = _lib().cgm_kselect(x, 5_000, num_procs=2, c=1)
    assert a == np.sort(x)[4_999]


def test_cgm_rejects_single_rank():
    x = np.arange(100, dtype=np.int32)
    with pytest.raises(ValueError, match="num_procs"):
        _lib().cgm_kselect(x, 1, num_procs=1, c=500)


def test_mpi_backend_roundtrip():
    from mpi_k_selection_tpu.backends import mpi as mpi_backend

    x = datagen.generate(30_000, pattern="uniform", seed=4, dtype=np.int32)
    got = int(mpi_backend.kselect(x, 12_345, num_procs=3))
    assert got == int(np.sort(x)[12_344])


def test_mpi_backend_rejects_non_int32():
    from mpi_k_selection_tpu.native import cgm_driver

    with pytest.raises(ValueError, match="int32"):
        cgm_driver.kselect(np.arange(10, dtype=np.float32), 5, num_procs=2)
