"""Typed errors of the resident-dataset query server (serve/).

The serving layer fronts many concurrent clients, so its failures must be
distinguishable without string matching: the HTTP front maps each class to
a status code (registry misses are 404s, malformed queries 400s, a closed
server 503) and the in-process API lets callers catch exactly the case
they can handle. All inherit :class:`ServeError` so "anything the server
raised" is one except clause.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for every serving-layer error."""


class DatasetNotFoundError(ServeError):
    """No dataset registered under the requested id (HTTP 404)."""


class DatasetExistsError(ServeError):
    """A dataset id was registered twice. Resident shards are immutable —
    replacing data under a live id would race in-flight queries; drop the
    id first, then add the new data."""


class QueryError(ServeError, ValueError):
    """A malformed or unanswerable query: unknown tier/op, out-of-range
    rank or quantile, a sketch tier against a dataset with no resident
    sketch, top-k against a stream-resident dataset (HTTP 400)."""


class ServerClosedError(ServeError):
    """The server (or its dispatch thread) has been closed; no further
    queries are accepted and queued ones are failed with this (HTTP 503)."""
