"""Concurrency analysis (KSL015-KSL017): thread-reachability call graph,
per-class lock models, lock-discipline lint, and the static lock-order
graph.

The codebase runs real concurrent machinery — ``ksel-pipeline-*``
producer threads, the serve dispatch thread plus ``ThreadingHTTPServer``
request threads, monitor metric servers, and the process-wide
``FaultInjector`` — and the reference's only concurrency model was
``mpirun``'s process isolation. A shared-memory server needs the
discipline the MPI runtime gave for free, as a checkable contract:

- **KSL015** — guard consistency. A class (or module-global group) that
  owns a lock declares cross-thread intent; an attribute written under
  ``with self._lock:`` in one method establishes ``_lock`` as its
  *inferred guard*, and any other write / mutating call / iteration-read
  of that attribute outside the guard is a finding. Intent is
  declarable up front with ``# ksel: guarded-by[<lock-attr>]`` on the
  attribute's init line (the annotation then drives enforcement even
  before any locked write exists, and a stale annotation — naming a
  lock the class does not own — is itself a finding).
- **KSL016** — static lock-order graph. Every ``with <lock>:`` nested
  inside another lock's body (directly, or via a module-local call made
  while holding) contributes an acquired-while-holding edge; a cycle in
  the package-wide union graph is a potential deadlock, reported with
  both lock sites. The same graph is exported by
  ``kselect-lint --concurrency-report`` and cross-checked at runtime by
  the lock-order sanitizer (analysis/lockorder.py).
- **KSL017** — blocking call while holding a lock: ``Queue.get()`` /
  ``Event.wait()`` / ``Thread.join()`` without a timeout, socket
  ``recv``/``accept``, any ``sleep``, or a ``maybe_fault`` stall site
  lexically inside a lock-held region. A blocked lock holder stalls
  every thread behind that lock — and a ``maybe_fault`` stall under a
  lock turns an injected chaos delay into a whole-process convoy.

Scope and honesty bounds (mirroring the KSL001 family): all three rules
scan library code under ``mpi_k_selection_tpu/`` only (tests poke
internals freely), analysis is module-local and lexical — a lock
released through an alias, or an attribute mutated through a local
variable bound to it, is out of scope (the runtime sanitizer is the
complementary dynamic check). Methods named ``*_locked`` follow the
repo convention "caller holds the lock": their accesses count as
guarded by the class's sole lock, and blocking calls inside them are
still flagged. ``queue.Queue`` / ``collections.deque`` /
``threading.Event`` attributes are self-synchronizing and are exempt
from guard inference and violation checks.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

from mpi_k_selection_tpu.analysis.ast_rules import (
    _function_defs,
    _is_test_file,
    dotted_name,
)
from mpi_k_selection_tpu.analysis.core import (
    Rule,
    SourceModule,
    iter_python_files,
    load_module,
    register,
)

# ---------------------------------------------------------------------------
# shared vocabulary

_GUARDED_BY_RE = re.compile(
    r"#\s*ksel:\s*guarded-by\[(?P<lock>[A-Za-z_][A-Za-z0-9_]*)\]"
)

#: Factory calls whose result is a lock object. ``threading.Condition``
#: lives here (not in the self-sync set): a Condition IS its lock —
#: ``with self._cond:`` guards state exactly like a Lock, ``guarded-by``
#: annotations may name it, and it participates in the lock-order graph
#: (the ingest pool's reorder sequencer is ordered against every other
#: package lock through it).
_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    "threading.Condition", "Condition",
    "multiprocessing.Lock", "multiprocessing.RLock",
}

#: Factory calls whose result synchronizes itself — exempt from guard
#: inference AND from violation checks (their methods are atomic).
_SELF_SYNC_FACTORIES = {
    "queue.Queue", "Queue", "queue.SimpleQueue", "SimpleQueue",
    "queue.LifoQueue", "queue.PriorityQueue",
    "collections.deque", "deque",
    "threading.Event", "Event",
    "threading.Semaphore", "Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "Barrier",
}

#: Attribute-name heuristic: ``with self._lock:`` identifies a lock even
#: when it was assigned from a parameter (obs/metrics.py hands every
#: metric the registry's lock).
_LOCKY_NAME = re.compile(r"lock", re.IGNORECASE)

#: Mutating container/collection methods (a call on a guarded attribute).
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "add", "discard", "update", "setdefault", "sort", "reverse",
    "appendleft", "move_to_end",
}

#: Reads that traverse the whole structure — torn mid-write they raise
#: (dict changed size during iteration) or return an inconsistent
#: snapshot; bare scalar reads stay out of scope (GIL-atomic).
_ITER_METHODS = {"items", "values", "keys"}

#: Methods exempt from guard-violation checks: the object is not shared
#: yet (or is being torn down single-threaded).
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}

#: Blocking calls flagged under a held lock only when UNBOUNDED (no
#: positional timeout argument and no timeout=/block= keyword).
_BLOCKING_IF_UNBOUNDED = {"get", "join", "wait"}

#: Blocking calls flagged under a held lock regardless of arguments.
_BLOCKING_ALWAYS = {"recv", "accept", "sleep", "select"}

_THREAD_FACTORIES = {
    "threading.Thread", "Thread", "threading.Timer", "Timer",
}

_HANDLER_BASES = ("BaseHTTPRequestHandler",)
_SERVER_BASES = ("ThreadingHTTPServer", "ThreadingMixIn", "socketserver.ThreadingMixIn")


def _in_package(mod: SourceModule) -> bool:
    p = pathlib.Path(mod.path).resolve().as_posix()
    return "/mpi_k_selection_tpu/" in p and not _is_test_file(mod)


def _pkg_relpath(mod: SourceModule) -> str:
    """Package-relative path (``mpi_k_selection_tpu/...``) independent of
    the scan's cwd/root — the SAME normalization the runtime sanitizer's
    ``_creation_label`` applies, so static node sites and runtime lock
    labels join on identical strings no matter where the lint ran."""
    p = pathlib.Path(mod.path).resolve().as_posix()
    idx = p.rfind("mpi_k_selection_tpu")
    return p[idx:] if idx >= 0 else mod.relpath


def _self_attr(node: ast.AST) -> str | None:
    """``X`` for a plain ``self.X`` attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _receiver_self_attr(node: ast.AST) -> str | None:
    """The underlying ``self.X`` of a receiver chain: ``self.X``,
    ``self.X[...]`` — the shapes a guarded-container mutation takes."""
    if isinstance(node, ast.Subscript):
        node = node.value
    return _self_attr(node)


def _own_scope_nodes(fn: ast.AST):
    """The nodes of ``fn``'s own lexical scope — nested defs/lambdas run
    later on their own terms and are skipped (the KSL014 discipline)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# per-class / per-module lock models


@dataclasses.dataclass
class Access:
    attr: str
    line: int
    kind: str  # "write" | "mutate" | "iter-read"
    held: tuple  # lock-attr names held lexically at the access
    method: str


@dataclasses.dataclass
class ClassModel:
    name: str
    line: int
    lock_attrs: dict  # lock attr -> definition line
    self_sync_attrs: set  # queue/deque/event attrs: exempt
    annotations: dict  # data attr -> (lock attr, annotation line)
    accesses: list  # list[Access] (self.* only)
    guards: dict = dataclasses.field(default_factory=dict)  # attr -> lock

    def sole_lock(self) -> str | None:
        return next(iter(self.lock_attrs)) if len(self.lock_attrs) == 1 else None


@dataclasses.dataclass
class LockNode:
    key: str  # stable graph identity
    name: str  # human form ("QueryBatcher._submit_lock")
    site: str  # "relpath:lineno" of the lock's definition (or first use)


@dataclasses.dataclass
class LockEdge:
    src: str  # LockNode.key
    dst: str
    mod: SourceModule
    line: int  # the inner acquisition (or call) site


@dataclasses.dataclass
class ModuleConcurrency:
    mod: SourceModule
    classes: dict  # class name -> ClassModel
    global_locks: dict  # NAME -> def line
    global_annotations: dict  # NAME -> (lock NAME, line)
    global_accesses: list  # list[Access] (module globals, via `global X`)
    global_guards: dict = dataclasses.field(default_factory=dict)
    lock_nodes: dict = dataclasses.field(default_factory=dict)  # key -> LockNode
    lock_edges: list = dataclasses.field(default_factory=list)
    blocking: list = dataclasses.field(default_factory=list)  # (line, msg)
    thread_roots: list = dataclasses.field(default_factory=list)  # qualnames
    thread_reachable: list = dataclasses.field(default_factory=list)


def _guarded_by_annotations(mod: SourceModule) -> dict:
    """``{lineno: lock_attr}`` for every guarded-by comment in the file."""
    out = {}
    for lineno, line in enumerate(mod.lines, start=1):
        m = _GUARDED_BY_RE.search(line)
        if m:
            out[lineno] = m.group("lock")
    return out


def _is_lock_factory(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in _LOCK_FACTORIES


def _is_self_sync_factory(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in _SELF_SYNC_FACTORIES
    )


def _field_default_factory(node: ast.AST) -> str:
    """Dotted name of ``dataclasses.field(default_factory=...)``'s
    factory, '' otherwise."""
    if isinstance(node, ast.Call) and dotted_name(node.func) in (
        "field", "dataclasses.field",
    ):
        for kw in node.keywords:
            if kw.arg == "default_factory":
                return dotted_name(kw.value)
    return ""


class _MethodWalker:
    """One lexical walk of a function/method body tracking the stack of
    held locks through ``with`` statements, collecting guarded-attribute
    accesses, lock-order edges, and blocking-while-holding calls."""

    def __init__(self, analyzer: "_ModuleAnalyzer", cls: ClassModel | None,
                 method_name: str, global_names: set):
        self.an = analyzer
        self.cls = cls
        self.method = method_name
        self.globals_declared = set(global_names)
        self.accesses: list[Access] = []
        self.global_accesses: list[Access] = []

    # -- lock resolution ---------------------------------------------------

    def _resolve_lock(self, expr: ast.AST):
        """LockNode (registered) for a with-context expression, or None
        when the expression is not a recognizable lock."""
        an = self.an
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            if attr in self.cls.lock_attrs or _LOCKY_NAME.search(attr):
                return an.class_lock_node(self.cls, attr), ("self", attr)
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in an.module.global_locks or _LOCKY_NAME.search(name):
                return an.global_lock_node(name), ("global", name)
        if isinstance(expr, ast.Attribute) and _LOCKY_NAME.search(expr.attr):
            # <var>.X / <obj.path>.X — resolve by unique ownership of the
            # lock attr among this module's classes
            owners = [
                c for c in an.module.classes.values()
                if expr.attr in c.lock_attrs
            ]
            if len(owners) == 1:
                return an.class_lock_node(owners[0], expr.attr), (
                    "var", expr.attr
                )
            return an.anon_lock_node(expr.attr), ("var", expr.attr)
        return None

    # -- the walk ----------------------------------------------------------

    def walk(self, body, held):
        for node in body:
            self._visit(node, held)

    def _visit(self, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # a nested def runs later, on an unknown thread, with no lock
            # lexically held — reset, but keep collecting its accesses
            inner = _MethodWalker(
                self.an, self.cls, self.method, self.globals_declared
            )
            body = node.body if not isinstance(node, ast.Lambda) else [
                ast.Expr(node.body)
            ]
            inner.walk(body, [])
            self.accesses.extend(inner.accesses)
            self.global_accesses.extend(inner.global_accesses)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                resolved = self._resolve_lock(item.context_expr)
                if resolved is not None:
                    lock_node, tag = resolved
                    for prev_node, _prev_tag in held + acquired:
                        if prev_node.key != lock_node.key:
                            self.an.add_edge(
                                prev_node, lock_node, item.context_expr.lineno
                            )
                    acquired.append((lock_node, tag))
                else:
                    self._visit_expr(item.context_expr, held)
                if item.optional_vars is not None:
                    self._visit_expr(item.optional_vars, held)
            self.walk(node.body, held + acquired)
            return
        self._record_statement(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _visit_expr(self, node, held):
        self._record_statement(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    # -- collection --------------------------------------------------------

    def _held_self(self, held) -> tuple:
        return tuple(
            tag[1] for _n, tag in held if tag[0] == "self"
        )

    def _held_global(self, held) -> tuple:
        return tuple(tag[1] for _n, tag in held if tag[0] == "global")

    def _add_access(self, attr, line, kind, held):
        if self.cls is None or attr is None:
            return
        if attr in self.cls.lock_attrs or attr in self.cls.self_sync_attrs:
            return
        a = Access(attr, line, kind, self._held_self(held), self.method)
        # a subscript-assign target walk yields both the Subscript and
        # its inner Attribute — record the access once
        if self.accesses and self.accesses[-1] == a:
            return
        self.accesses.append(a)

    def _add_global_access(self, name, line, kind, held):
        if name in self.globals_declared:
            self.global_accesses.append(
                Access(name, line, kind, self._held_global(held), self.method)
            )

    def _record_statement(self, node, held):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for el in ast.walk(t):
                    attr = _receiver_self_attr(el)
                    if attr is not None:
                        self._add_access(attr, node.lineno, "write", held)
                    if isinstance(el, ast.Name) and isinstance(
                        el.ctx, ast.Store
                    ):
                        self._add_global_access(
                            el.id, node.lineno, "write", held
                        )
                    # global containers mutated by subscript assignment
                    if isinstance(el, ast.Subscript) and isinstance(
                        el.value, ast.Name
                    ):
                        self._add_global_access(
                            el.value.id, node.lineno, "write", held
                        )
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _receiver_self_attr(t)
                if attr is not None:
                    self._add_access(attr, node.lineno, "write", held)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            attr = _self_attr(node.iter)
            if attr is not None:
                self._add_access(attr, node.lineno, "iter-read", held)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                attr = _self_attr(gen.iter)
                if attr is not None:
                    self._add_access(attr, node.lineno, "iter-read", held)
        elif isinstance(node, ast.Call):
            self._record_call(node, held)

    def _record_call(self, node: ast.Call, held):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _MUTATORS:
                attr = _receiver_self_attr(fn.value)
                if attr is not None:
                    self._add_access(attr, node.lineno, "mutate", held)
                if isinstance(fn.value, ast.Name):
                    self._add_global_access(
                        fn.value.id, node.lineno, "mutate", held
                    )
            elif fn.attr in _ITER_METHODS:
                attr = _self_attr(fn.value)
                if attr is not None:
                    self._add_access(attr, node.lineno, "iter-read", held)
        if held:
            self._check_blocking(node, held)
        # interprocedural lock-order edges: a module-local call made
        # while holding propagates the callee's (transitive) acquisitions
        if held:
            callee = self._local_callee(node)
            if callee is not None:
                self.an.record_held_call(
                    [n for n, _t in held], callee, node.lineno
                )

    def _local_callee(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in self.an.defs:
            return fn.id
        attr = _self_attr(fn)
        if attr is not None and attr in self.an.defs:
            return attr
        return None

    def _check_blocking(self, node: ast.Call, held):
        name = dotted_name(node.func)
        msg = None
        last = name.split(".")[-1] if name else ""
        if last in ("maybe_fault", "_maybe_fault"):
            msg = (
                f"`{last}()` (an injectable stall site) while holding "
                "a lock — a chaos stall under a lock convoys every "
                "thread behind it"
            )
        elif name == "time.sleep":
            msg = "`time.sleep()` while holding a lock"
        elif isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if isinstance(node.func.value, ast.Constant):
                return  # "sep".join(...) and friends
            if meth in _BLOCKING_ALWAYS:
                msg = f"blocking `.{meth}(...)` while holding a lock"
            elif meth in _BLOCKING_IF_UNBOUNDED:
                bounded = bool(node.args) or any(
                    kw.arg in ("timeout", "block") for kw in node.keywords
                )
                if not bounded:
                    msg = (
                        f"unbounded blocking `.{meth}()` (no timeout) "
                        "while holding a lock"
                    )
        if msg is not None:
            locks = ", ".join(
                f"`{n.name}`" for n, _t in held
            )
            self.an.module.blocking.append(
                (
                    node.lineno,
                    f"{msg} (held: {locks}) — release the lock before "
                    "blocking, or bound the wait with a timeout; a "
                    "blocked holder stalls every thread contending for "
                    "that lock (KSL016's runtime twin, "
                    "analysis/lockorder.py, would show the convoy)",
                )
            )


class _ModuleAnalyzer:
    """One pass over one module: builds the ClassModels, the lock graph
    fragment, the blocking-call list, and the thread-reachability sets."""

    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.rel = _pkg_relpath(mod)
        self.defs = _function_defs(mod.tree)
        self.annotations = _guarded_by_annotations(mod)
        self.module = ModuleConcurrency(
            mod, classes={}, global_locks={}, global_annotations={},
            global_accesses=[],
        )
        self._held_calls = []  # (held lock nodes, callee name, line)
        self._fn_acquires: dict[str, set] = {}  # fn name -> lock keys
        self._fn_calls: dict[str, set] = {}  # fn name -> callee names
        self._analyze()

    # -- lock node registry ------------------------------------------------

    def _node(self, key, name, site_line) -> LockNode:
        node = self.module.lock_nodes.get(key)
        if node is None:
            node = LockNode(key, name, f"{self.rel}:{site_line}")
            self.module.lock_nodes[key] = node
        return node

    def class_lock_node(self, cls: ClassModel, attr: str) -> LockNode:
        line = cls.lock_attrs.get(attr, cls.line)
        return self._node(
            f"{self.rel}::{cls.name}.{attr}",
            f"{cls.name}.{attr}",
            line,
        )

    def global_lock_node(self, name: str) -> LockNode:
        line = self.module.global_locks.get(name, 1)
        return self._node(
            f"{self.rel}::{name}", name, line
        )

    def anon_lock_node(self, attr: str) -> LockNode:
        return self._node(
            f"{self.rel}::?.{attr}", f"?.{attr}", 1
        )

    def add_edge(self, src: LockNode, dst: LockNode, line: int) -> None:
        self.module.lock_edges.append(
            LockEdge(src.key, dst.key, self.mod, line)
        )

    def record_held_call(self, held_nodes, callee, line) -> None:
        self._held_calls.append((list(held_nodes), callee, line))

    # -- analysis ----------------------------------------------------------

    def _analyze(self) -> None:
        tree = self.mod.tree
        # module-level lock globals + guarded-by annotations on globals
        for node in tree.body:
            t = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                t, value = node.target, node.value
            if t is not None and isinstance(t, ast.Name):
                if _is_lock_factory(value):
                    self.module.global_locks[t.id] = node.lineno
                else:
                    ann = self.annotations.get(node.lineno)
                    if ann is not None:
                        self.module.global_annotations[t.id] = (
                            ann, node.lineno
                        )
        # classes: first collect every class's own lock/self-sync attrs,
        # then merge module-local BASE classes' attrs (obs/metrics.py's
        # _Metric hands its subclasses the registry lock — the `*_locked`
        # convention and guard inference must see inherited locks), then
        # walk methods
        class_nodes = [
            n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
        ]
        for node in class_nodes:
            self._collect_class_attrs(node)
        for _ in range(2):  # two rounds cover grandparent chains in order
            for node in class_nodes:
                cls = self.module.classes[node.name]
                for b in node.bases:
                    base = self.module.classes.get(
                        dotted_name(b).split(".")[-1]
                    )
                    if base is not None:
                        for attr, line in base.lock_attrs.items():
                            cls.lock_attrs.setdefault(attr, line)
                        cls.self_sync_attrs |= base.self_sync_attrs
        for node in class_nodes:
            cls = self.module.classes[node.name]
            for meth in self._class_methods(node):
                self._walk_function(meth, cls=cls)
            self._infer_guards(cls)
        # module-level functions (globals discipline + lock graph + KSL017)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(node, cls=None)
        self._close_interprocedural()
        self._thread_graph()

    def _class_methods(self, node: ast.ClassDef):
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield item

    def _collect_class_attrs(self, node: ast.ClassDef) -> None:
        cls = ClassModel(
            name=node.name, line=node.lineno, lock_attrs={},
            self_sync_attrs=set(), annotations={}, accesses=[],
        )
        # lock attrs + self-sync attrs + guarded-by annotations, from
        # every `self.X = ...` assignment and every dataclass field
        for item in node.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                item.target, ast.Name
            ):
                factory = (
                    _field_default_factory(item.value)
                    if item.value is not None
                    else ""
                )
                if factory in _LOCK_FACTORIES:
                    cls.lock_attrs[item.target.id] = item.lineno
                elif factory in _SELF_SYNC_FACTORIES:
                    cls.self_sync_attrs.add(item.target.id)
                else:
                    ann = self.annotations.get(item.lineno)
                    if ann is not None:
                        cls.annotations[item.target.id] = (ann, item.lineno)
        for meth in self._class_methods(node):
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        if _is_lock_factory(sub.value):
                            cls.lock_attrs[attr] = sub.lineno
                        elif _is_self_sync_factory(sub.value):
                            cls.self_sync_attrs.add(attr)
                        elif (
                            isinstance(sub.value, ast.Name)
                            and _LOCKY_NAME.search(attr)
                        ):
                            # `self._lock = lock` — a lock handed in
                            cls.lock_attrs.setdefault(attr, sub.lineno)
                        else:
                            ann = self.annotations.get(sub.lineno)
                            if ann is not None:
                                cls.annotations[attr] = (ann, sub.lineno)
                elif isinstance(sub, ast.AnnAssign):
                    attr = _self_attr(sub.target)
                    if attr is not None and sub.value is not None:
                        if _is_lock_factory(sub.value):
                            cls.lock_attrs[attr] = sub.lineno
                        elif _is_self_sync_factory(sub.value):
                            cls.self_sync_attrs.add(attr)
                        else:
                            ann = self.annotations.get(sub.lineno)
                            if ann is not None:
                                cls.annotations[attr] = (ann, sub.lineno)
        self.module.classes[node.name] = cls

    def _walk_function(self, fn, cls: ClassModel | None) -> None:
        global_names = {
            n
            for sub in ast.walk(fn)
            if isinstance(sub, ast.Global)
            for n in sub.names
        }
        walker = _MethodWalker(self, cls, fn.name, global_names)
        held = []
        # repo convention: `*_locked` methods run under the caller's
        # lock — the class's sole lock when unambiguous
        if cls is not None and fn.name.endswith("_locked"):
            sole = cls.sole_lock()
            if sole is not None:
                held = [(self.class_lock_node(cls, sole), ("self", sole))]
        walker.walk(fn.body, held)
        if cls is not None:
            cls.accesses.extend(walker.accesses)
        self.module.global_accesses.extend(walker.global_accesses)
        # per-function acquisition/call sets for the interprocedural
        # closure — OWN scope only: a lock taken inside a nested def
        # belongs to the closure (which runs later, with nothing held),
        # not to this function (the same reset _MethodWalker applies)
        acquires = set()
        calls = set()
        for sub in _own_scope_nodes(fn):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    resolved = walker._resolve_lock(item.context_expr)
                    if resolved is not None:
                        acquires.add(resolved[0].key)
            elif isinstance(sub, ast.Call):
                callee = walker._local_callee(sub)
                if callee is not None:
                    calls.add(callee)
        self._fn_acquires.setdefault(fn.name, set()).update(acquires)
        self._fn_calls.setdefault(fn.name, set()).update(calls)

    def _infer_guards(self, cls: ClassModel) -> None:
        votes: dict[str, dict[str, int]] = {}
        for a in cls.accesses:
            if a.kind in ("write", "mutate") and a.held:
                lock = a.held[-1]  # innermost
                votes.setdefault(a.attr, {}).setdefault(lock, 0)
                votes[a.attr][lock] += 1
        for attr, by_lock in votes.items():
            cls.guards[attr] = max(by_lock.items(), key=lambda kv: kv[1])[0]
        # annotations override / extend inference
        for attr, (lock, _line) in cls.annotations.items():
            cls.guards[attr] = lock

    def _close_interprocedural(self) -> None:
        """Transitive may-acquire closure over module-local calls, then
        edges for every call made while holding. Computed as a FIXPOINT
        (not a memoized DFS): mutually-recursive functions would truncate
        a recursive walk at the cycle cut and memoize the partial set,
        silently dropping edges — a false NEGATIVE in a deadlock
        detector."""
        closure: dict[str, set] = {
            f: set(acq) for f, acq in self._fn_acquires.items()
        }
        for f in self._fn_calls:
            closure.setdefault(f, set())
        changed = True
        while changed:
            changed = False
            for f, callees in self._fn_calls.items():
                s = closure[f]
                before = len(s)
                for callee in callees:
                    s |= closure.get(callee, set())
                if len(s) != before:
                    changed = True

        for held_nodes, callee, line in self._held_calls:
            for key in closure.get(callee, ()):
                for src in held_nodes:
                    if src.key != key:
                        self.module.lock_edges.append(
                            LockEdge(src.key, key, self.mod, line)
                        )
                        # the callee's nodes live in this module's registry
                        # already (resolve_lock registered them)

    # -- thread reachability ----------------------------------------------

    def _thread_graph(self) -> None:
        tree = self.mod.tree
        qual: dict[int, str] = {}  # id(def node) -> qualname
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for meth in self._class_methods(node):
                    qual[id(meth)] = f"{node.name}.{meth.name}"
        for name, nodes in self.defs.items():
            for d in nodes:
                qual.setdefault(id(d), name)

        roots: list = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and dotted_name(
                node.func
            ) in _THREAD_FACTORIES:
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None and node.args:
                    # Timer(interval, function)
                    target = node.args[1] if len(node.args) > 1 else None
                if isinstance(target, ast.Name) and target.id in self.defs:
                    roots.extend(self.defs[target.id])
                else:
                    attr = _self_attr(target) if target is not None else None
                    if attr is not None and attr in self.defs:
                        roots.extend(self.defs[attr])
            elif isinstance(node, ast.ClassDef):
                base_names = [dotted_name(b) for b in node.bases]
                if any(
                    any(h in (b or "") for h in _HANDLER_BASES)
                    for b in base_names
                ):
                    roots.extend(
                        m for m in self._class_methods(node)
                        if m.name.startswith("do_")
                    )
                if any(
                    any(s in (b or "") for s in _SERVER_BASES)
                    for b in base_names
                ):
                    roots.extend(
                        m for m in self._class_methods(node)
                        if m.name in ("process_request_thread",)
                    )
        # closure over module-local Name refs and self.<m> refs
        reached: set[int] = set()
        frontier = list(roots)
        by_id = {}
        for name, nodes in self.defs.items():
            for d in nodes:
                by_id[id(d)] = d
        while frontier:
            fn = frontier.pop()
            if id(fn) in reached:
                continue
            reached.add(id(fn))
            for sub in ast.walk(fn):
                targets = []
                if isinstance(sub, ast.Name) and sub.id in self.defs:
                    targets = self.defs[sub.id]
                else:
                    attr = _self_attr(sub)
                    if attr is not None and attr in self.defs:
                        targets = self.defs[attr]
                for t in targets:
                    if id(t) not in reached:
                        frontier.append(t)
        self.module.thread_roots = sorted(
            {qual.get(id(r), getattr(r, "name", "?")) for r in roots}
        )
        self.module.thread_reachable = sorted(
            {qual.get(i, "?") for i in reached}
        )


# one analysis per module per scan (rules run back to back on the same
# SourceModule objects; the cache is keyed by object identity)
_CACHE: dict[int, ModuleConcurrency] = {}


def analyze_module(mod: SourceModule) -> ModuleConcurrency:
    got = _CACHE.get(id(mod))
    if got is None or got.mod is not mod:
        if len(_CACHE) > 4096:
            _CACHE.clear()
        got = _ModuleAnalyzer(mod).module
        _CACHE[id(mod)] = got
    return got


# ---------------------------------------------------------------------------
# KSL015 — guard consistency


@register
class GuardConsistency(Rule):
    id = "KSL015"
    title = (
        "guarded attribute accessed outside its lock (inferred or "
        "# ksel: guarded-by[...]), or a stale guarded-by annotation"
    )
    rationale = (
        "A class that owns a lock declares cross-thread intent; an "
        "attribute written under `with self._lock:` in one method and "
        "mutated or iterated bare in another is exactly the race class "
        "review keeps catching by hand (the PhaseTimer report() "
        "iteration this rule's first run flagged raises `dict changed "
        "size during iteration` when a producer thread lands a phase "
        "mid-report). Declare intent with `# ksel: guarded-by[<lock>]` "
        "on the attribute's init line; the rule enforces it everywhere "
        "and flags annotations whose lock the class does not own."
    )

    def check_module(self, mod: SourceModule):
        if not _in_package(mod):
            return
        mc = analyze_module(mod)
        for cls in mc.classes.values():
            # stale annotations first
            for attr, (lock, line) in cls.annotations.items():
                if lock not in cls.lock_attrs:
                    yield line, (
                        f"stale guarded-by annotation on `{cls.name}."
                        f"{attr}`: `{lock}` is not a lock attribute of "
                        f"`{cls.name}` (known locks: "
                        f"{sorted(cls.lock_attrs) or 'none'}) — fix the "
                        "annotation or add the lock"
                    )
            for a in cls.accesses:
                guard = cls.guards.get(a.attr)
                if guard is None or guard not in cls.lock_attrs:
                    continue
                if a.method in _EXEMPT_METHODS:
                    continue
                if guard in a.held:
                    continue
                how = {
                    "write": "written",
                    "mutate": "mutated",
                    "iter-read": "iterated",
                }[a.kind]
                src = (
                    "declared by its guarded-by annotation"
                    if a.attr in cls.annotations
                    else "inferred from its locked writes"
                )
                yield a.line, (
                    f"`{cls.name}.{a.attr}` {how} in `{a.method}` without "
                    f"holding `{guard}` ({src}) — another thread mutating "
                    "under the lock makes this access a torn read or a "
                    "lost update; hold the guard or snapshot under it"
                )
        # module globals
        for name, (lock, line) in mc.global_annotations.items():
            if lock not in mc.global_locks:
                yield line, (
                    f"stale guarded-by annotation on module global "
                    f"`{name}`: `{lock}` is not a module-level lock "
                    "in this file"
                )
        votes: dict[str, dict[str, int]] = {}
        for a in mc.global_accesses:
            if a.kind in ("write", "mutate") and a.held:
                votes.setdefault(a.attr, {}).setdefault(a.held[-1], 0)
                votes[a.attr][a.held[-1]] += 1
        guards = {
            attr: max(by.items(), key=lambda kv: kv[1])[0]
            for attr, by in votes.items()
        }
        for name, (lock, _line) in mc.global_annotations.items():
            if lock in mc.global_locks:
                guards[name] = lock
        for a in mc.global_accesses:
            guard = guards.get(a.attr)
            if guard is None or guard in a.held:
                continue
            how = {
                "write": "written",
                "mutate": "mutated",
                "iter-read": "iterated",
            }[a.kind]
            yield a.line, (
                f"module global `{a.attr}` {how} in `{a.method}` "
                f"without holding `{guard}` (its guard everywhere else) "
                "— take the lock or route through the guarded helper"
            )


# ---------------------------------------------------------------------------
# KSL016 — static lock-order cycles


def build_lock_graph(mods) -> tuple[dict, list]:
    """The package-wide union lock graph: ``(nodes, edges)`` with nodes
    keyed stably (``relpath::Class.attr`` / ``relpath::GLOBAL``) and
    edges as LockEdge records (src held while dst acquired)."""
    nodes: dict[str, LockNode] = {}
    edges: list[LockEdge] = []
    for mod in mods:
        if not _in_package(mod):
            continue
        mc = analyze_module(mod)
        nodes.update(mc.lock_nodes)
        edges.extend(mc.lock_edges)
    return nodes, edges


def cycles_from_pairs(pairs) -> list[list[str]]:
    """WITNESS cycles in a directed graph given as (src, dst) pairs —
    each reported once, rotated to its lexicographically-smallest node.
    The list is empty IFF the graph is acyclic (that emptiness is the
    gate property), and carries at least one witness per strongly-
    connected tangle — it is NOT an exhaustive simple-cycle enumeration
    (two cycles sharing nodes may surface one witness; fixing it and
    re-running the lint surfaces the next). The ONE cycle finder: the
    static KSL016 graph and the runtime sanitizer's observed graph
    (analysis/lockorder.py) both use it, so their cycle reporting can
    never diverge."""
    adj: dict[str, set] = {}
    for a, b in pairs:
        adj.setdefault(a, set()).add(b)
    cycles = []
    seen_keys = set()
    state: dict[str, int] = {}  # 0 unvisited / 1 on stack / 2 done
    stack: list[str] = []

    def dfs(u):
        state[u] = 1
        stack.append(u)
        for v in sorted(adj.get(u, ())):
            if state.get(v, 0) == 0:
                dfs(v)
            elif state.get(v) == 1:
                i = stack.index(v)
                cyc = stack[i:]
                rot = cyc.index(min(cyc))
                canon = tuple(cyc[rot:] + cyc[:rot])
                if canon not in seen_keys:
                    seen_keys.add(canon)
                    cycles.append(list(canon))
        stack.pop()
        state[u] = 2

    for u in sorted(adj):
        if state.get(u, 0) == 0:
            dfs(u)
    return cycles


def find_cycles(nodes: dict, edges: list) -> list[list[str]]:
    """Cycles in the static lock graph (LockEdge records)."""
    return cycles_from_pairs((e.src, e.dst) for e in edges)


@register
class LockOrderCycles(Rule):
    id = "KSL016"
    title = "cycle in the static acquired-while-holding lock-order graph"
    rationale = (
        "Two threads taking the same pair of locks in opposite orders is "
        "the classic deadlock; the static graph records every `with "
        "lockB:` nested (directly or through a module-local call) inside "
        "`with lockA:` as an edge A->B, and a cycle means some "
        "interleaving can deadlock — found at lint time, not in a hung "
        "prod server. The runtime sanitizer (analysis/lockorder.py) "
        "builds the same graph from the real concurrency tests and the "
        "gate asserts the two agree."
    )

    def check_tree(self, mods):
        nodes, edges = build_lock_graph(mods)
        edge_sites: dict[tuple, LockEdge] = {}
        for e in edges:
            edge_sites.setdefault((e.src, e.dst), e)
        for cyc in find_cycles(nodes, edges):
            pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
            sites = []
            for a, b in pairs:
                e = edge_sites[(a, b)]
                sites.append(
                    f"{nodes[a].name} -> {nodes[b].name} at "
                    f"{_pkg_relpath(e.mod)}:{e.line}"
                )
            first = edge_sites[pairs[0]]
            yield first.mod, first.line, (
                "potential deadlock: lock-order cycle "
                + " ; ".join(sites)
                + " — impose one global acquisition order (or drop to a "
                "single lock); both sites must agree on which lock is "
                "outer"
            )


# ---------------------------------------------------------------------------
# KSL017 — blocking while holding


@register
class BlockingWhileHolding(Rule):
    id = "KSL017"
    title = (
        "blocking call (unbounded get/wait/join, socket recv/accept, "
        "sleep, maybe_fault stall) while holding a lock"
    )
    rationale = (
        "A lock holder that blocks — a `Queue.get()` with no timeout, an "
        "`Event.wait()`, a `Thread.join()`, a socket accept, a sleep, or "
        "an injectable `maybe_fault` stall — convoys every thread "
        "contending for that lock behind an unbounded wait, and pairs of "
        "such sites are how lock-order cycles actually hang. Bound the "
        "wait with a timeout or move it outside the critical section "
        "(the pattern serve/http.py's server_close already follows: "
        "swap the list under the lock, join outside it)."
    )

    def check_module(self, mod: SourceModule):
        if not _in_package(mod):
            return
        mc = analyze_module(mod)
        seen = set()
        for line, msg in mc.blocking:
            if (line, msg) in seen:
                continue
            seen.add((line, msg))
            yield line, msg


# ---------------------------------------------------------------------------
# the exported report (kselect-lint --concurrency-report)


def build_concurrency_report(paths, root=None, mods=None) -> dict:
    """Thread-reachability and lock-order graphs as one JSON-ready dict —
    the artifact ``kselect-lint --concurrency-report <path>`` writes and
    the runtime sanitizer's consistency check consumes. Pass ``mods``
    (an already-loaded SourceModule list, e.g. ``Report.modules``) to
    skip re-parsing the tree; ``paths`` is ignored then."""
    if mods is None:
        mods = []
        for f in iter_python_files(paths):
            try:
                mods.append(load_module(f, root=root))
            except SyntaxError:
                continue
    nodes, edges = build_lock_graph(mods)
    threads = {}
    guards = {}
    for mod in mods:
        if not _in_package(mod):
            continue
        mc = analyze_module(mod)
        if mc.thread_roots:
            threads[_pkg_relpath(mod)] = {
                "roots": mc.thread_roots,
                "reachable": mc.thread_reachable,
            }
        for cls in mc.classes.values():
            if cls.guards:
                guards[f"{_pkg_relpath(mod)}::{cls.name}"] = {
                    attr: lock for attr, lock in sorted(cls.guards.items())
                }
    edge_list = sorted(
        {
            (e.src, e.dst, f"{_pkg_relpath(e.mod)}:{e.line}")
            for e in edges
        }
    )
    return {
        "threads": threads,
        "lock_graph": {
            "nodes": {
                k: {"name": n.name, "site": n.site}
                for k, n in sorted(nodes.items())
            },
            "edges": [
                {"src": a, "dst": b, "site": s} for a, b, s in edge_list
            ],
            "cycles": find_cycles(nodes, edges),
        },
        "guards": guards,
    }
