"""Resident-dataset query server: shard once, answer many.

The serving layer the north star adds on top of the reproduction
(PAPER.md's L3 gap: the reference has no driver/service layer — every
parameter is a compile-time constant). One long-lived process loads (or
streams/sketches) each dataset once and answers kselect / quantile /
top-k / rank-certificate queries from many concurrent clients:

- **registry** (serve/registry.py) — immutable resident shards keyed by
  dataset id + the ``StagingPool``-style keyed program cache (compiled
  walk closures, cached sorts) so repeat query shapes never recompile;
- **batcher + lanes** (serve/batcher.py, serve/lanes.py) — one
  supervised dispatch lane per execution device; each lane's bounded
  coalescing window turns concurrent rank queries against its datasets
  into one shared-pass ``kselect_many`` walk, bit-identical to serial
  execution (``lanes=1`` is the single-thread degenerate case);
- **tiers** (serve/tiers.py) — ``sketch`` (instant — answered on the
  request thread with the default ``fast_path=True`` — with exact error
  bounds attached), ``exact`` (the real descent), ``auto`` (sketch when
  it already pins the answer, escalate otherwise);
- **http** (serve/http.py) — stdlib JSON-over-HTTP front +
  ``/metrics`` Prometheus exposition; CLI: ``python -m
  mpi_k_selection_tpu serve ...``.

Docs: docs/API.md "Serving"; metric catalog: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from mpi_k_selection_tpu.serve.batcher import (
    PendingQuery,
    QueryBatcher,
    SERVE_THREAD_PREFIX,
)
from mpi_k_selection_tpu.serve.errors import (
    DatasetExistsError,
    DatasetNotFoundError,
    DeadlineExceededError,
    DispatchCrashedError,
    QueryError,
    ServeError,
    ServerClosedError,
    ServerOverloadedError,
)
from mpi_k_selection_tpu.serve.http import (
    KSelectHTTPServer,
    start_http_server,
)
from mpi_k_selection_tpu.serve.lanes import LaneDispatcher, lane_key_for
from mpi_k_selection_tpu.serve.registry import (
    DatasetRegistry,
    ProgramCache,
    ResidentDataset,
)
from mpi_k_selection_tpu.serve.server import KSelectServer
from mpi_k_selection_tpu.serve.tiers import TIERS, RankAnswer

__all__ = [
    "DatasetExistsError",
    "DatasetNotFoundError",
    "DatasetRegistry",
    "DeadlineExceededError",
    "DispatchCrashedError",
    "KSelectHTTPServer",
    "KSelectServer",
    "LaneDispatcher",
    "PendingQuery",
    "ProgramCache",
    "QueryBatcher",
    "QueryError",
    "RankAnswer",
    "ResidentDataset",
    "SERVE_THREAD_PREFIX",
    "ServeError",
    "ServerClosedError",
    "ServerOverloadedError",
    "TIERS",
    "lane_key_for",
    "start_http_server",
]
