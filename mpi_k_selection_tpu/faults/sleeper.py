"""Injectable sleepers — the ONE sanctioned ``time.sleep`` surface.

KSL004 keeps raw clocks out of library code (utils/timing.py and
utils/profiling.py own them); this module is the matching discipline for
*waiting*: every backoff, stall injection, and pacing delay in the
package goes through a :class:`Sleeper` so tests and the seeded chaos
harness can replace real waiting with a recorded, deterministic no-op —
a retry ladder that actually slept through its exponential backoff would
turn the chaos grid into a minutes-long suite and make every timing
assertion flaky. Lint rule KSL012 flags ``time.sleep`` anywhere else in
the package (docs/ANALYSIS.md).
"""

from __future__ import annotations

import threading
import time


class Sleeper:
    """Sleeper protocol: ``sleep(seconds)`` blocks (or pretends to) for
    the requested duration. Implementations must be thread-safe — retry
    policies sleep on producer threads and request threads alike."""

    def sleep(self, seconds: float) -> None:  # pragma: no cover - protocol
        raise NotImplementedError


class RealSleeper(Sleeper):
    """Actually sleeps. The package-wide default
    (:data:`DEFAULT_SLEEPER`); the one place ``time.sleep`` is allowed
    (KSL012)."""

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class VirtualSleeper(Sleeper):
    """Records every requested sleep without blocking — the test/chaos
    form: backoff schedules stay assertable (``slept`` holds the exact
    durations, in call order) and the chaos grid runs at full speed.
    Thread-safe append."""

    def __init__(self):
        self._lock = threading.Lock()
        self.slept: list[float] = []  # ksel: guarded-by[_lock]

    def sleep(self, seconds: float) -> None:
        with self._lock:
            self.slept.append(float(seconds))

    @property
    def total(self) -> float:
        """Sum of requested sleep seconds (what a RealSleeper would have
        cost)."""
        with self._lock:
            return sum(self.slept)


#: The package default: real waiting. Policies and injectors resolve a
#: ``sleeper=None`` knob to this.
DEFAULT_SLEEPER = RealSleeper()


def resolve_sleeper(sleeper) -> Sleeper:
    """``None`` -> :data:`DEFAULT_SLEEPER`; anything with a ``sleep``
    callable passes through; everything else is rejected."""
    if sleeper is None:
        return DEFAULT_SLEEPER
    if callable(getattr(sleeper, "sleep", None)):
        return sleeper
    raise ValueError(
        f"sleeper must expose a sleep(seconds) method, got {sleeper!r}"
    )
