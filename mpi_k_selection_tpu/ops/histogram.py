"""Radix-digit histograms — the hot primitive of TPU k-selection.

This replaces the reference's hot local compute: the per-shard ``qsort``
(``TODO-kth-problem-cgm.c:115``, ``vector.c:239-241``) and the linear
less/equal/greater counting sweep (``TODO-kth-problem-cgm.c:175-185``). On
TPU, counting digit occurrences is the entire inner loop of radix select:
per pass, ``hist[b] = #{ i : active(i) and digit(i) == b }`` where
``digit(i) = (key >> shift) & (R-1)`` and ``active(i)`` means the key's
higher bits equal the current prefix.

Methods:

- ``scatter`` — ``zeros(R).at[digit].add(1)``; best on CPU, where XLA lowers
  it to a tight serial loop. Used by the unit-test/oracle path.
- ``onehot`` — chunked compare-and-reduce: each chunk materializes
  ``(chunk, R)`` equality bits in registers/VMEM and reduces over the chunk
  axis. XLA fuses the compare into the reduction; on TPU this feeds the
  VPU/MXU and streams the input at HBM bandwidth.
- ``pallas`` — the hand-written TPU kernel (ops/pallas/histogram.py), used by
  the production TPU path.

Counts use ``count_dtype`` (int32 by default — exact for n < 2^31; pass int64
under x64 for larger n, per SURVEY.md §7 "int overflow hygiene").

Composition note: the streaming descent's fused single-read ingest
(ops/pallas/fused_ingest.py) calls :func:`multi_masked_radix_histogram`
INSIDE its one-program-per-staged-bucket trace, alongside the survivor
compactions — the histogram sub-jaxpr is identical either way, which is
what makes the fused and unfused paths bit-interchangeable (the
``fused="off"`` oracle in streaming/executor.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def check_block_rows(block_rows: int) -> None:
    """The kernel tiling geometry contract, shared by every select entry
    point: a power of two >= 8. The SWAR group loop consumes whole 8-row
    groups (a non-multiple would silently drop tail rows), and the VMEM
    caps (4096/1024) must divide the prepared tiling in whichever direction
    the min() resolves. Lives here (not in ops/pallas) so the pure-XLA
    paths can validate without importing jax.experimental.pallas."""
    if block_rows < 8 or block_rows & (block_rows - 1):
        raise ValueError(f"block_rows={block_rows} must be a power of two >= 8")


def _digit_and_mask(keys, shift, radix_bits, prefix):
    kdt = keys.dtype
    digits = jax.lax.shift_right_logical(keys, kdt.type(shift))
    digits = (digits & kdt.type((1 << radix_bits) - 1)).astype(jnp.int32)
    if prefix is None:
        return digits, None
    high = jax.lax.shift_right_logical(keys, kdt.type(shift + radix_bits))
    return digits, high == jnp.asarray(prefix, kdt)


def _hist_scatter(digits, mask, nbuckets, count_dtype):
    if mask is None:
        weights = jnp.ones(digits.shape, count_dtype)
    else:
        weights = mask.astype(count_dtype)
    return jnp.zeros((nbuckets,), count_dtype).at[digits].add(weights)


def _chunk_hist(digits, mask, nbuckets, count_dtype):
    iota = jnp.arange(nbuckets, dtype=digits.dtype)
    eq = digits[:, None] == iota[None, :]
    if mask is not None:
        eq = jnp.logical_and(eq, mask[:, None])
    return jnp.sum(eq, axis=0, dtype=count_dtype)


def _hist_onehot(digits, mask, nbuckets, count_dtype, chunk):
    n = digits.shape[0]
    main = (n // chunk) * chunk
    hist = jnp.zeros((nbuckets,), count_dtype)
    if main:
        dm = digits[:main].reshape(-1, chunk)
        mm = None if mask is None else mask[:main].reshape(-1, chunk)

        def body(i, h):
            m = None if mm is None else mm[i]
            return h + _chunk_hist(dm[i], m, nbuckets, count_dtype)

        hist = jax.lax.fori_loop(0, dm.shape[0], body, hist)
    if n - main:
        m = None if mask is None else mask[main:]
        hist = hist + _chunk_hist(digits[main:], m, nbuckets, count_dtype)
    return hist


def prepare_keys(hist_method: str, keys: jax.Array, block_rows: int = 4096):
    """``(tiles, n)`` for the resolved pallas method, or ``(None, None)``.

    Pass-loop callers (ops/radix.py, parallel/radix.py) call this once up
    front and thread the result through ``masked_radix_histogram(...,
    tiles=..., orig_n=...)``. Preparing per call costs twice: the 64-bit
    plane deinterleave re-materializes every pass (~5x the kernel cost on
    v5e), and at 1B-element scale the per-pass pad/reshape views make XLA
    hold/remat several extra full-size temporaries — enough to blow a 16 GB
    HBM on their own. ``tiles`` is a 1-tuple (32-bit) or 2-tuple (64-bit
    hi/lo) of ``(rows, 128)`` uint32 arrays (the kernels enforce uint32 —
    see prepare_tiles32 for why the dtype is load-bearing); ``n`` is the
    unpadded length.

    Returns ``(None, None)`` when the resolved method is not a pallas
    variant or the dtype does not match it (e.g. an explicitly forced
    ``hist_method='pallas64'`` on 32-bit data, which then fails in the
    kernel with its own clear dtype error).
    """
    method = resolve_hist_method(hist_method, keys.dtype)
    if method in ("pallas", "pallas_compare") and keys.dtype.itemsize <= 4:
        from mpi_k_selection_tpu.ops.pallas.histogram import prepare_tiles32

        tiles, n = prepare_tiles32(keys, block_rows)
        return (tiles,), n
    if method in ("pallas64", "pallas64_compare") and keys.dtype == jnp.uint64:
        from mpi_k_selection_tpu.ops.pallas.histogram import prepare_tiles64

        hi2, lo2, n = prepare_tiles64(keys, block_rows)
        return (hi2, lo2), n
    return None, None


def prepare_raw(hist_method: str, x: jax.Array, block_rows: int = 4096):
    """``(tiles, n, key_op, key_xor)`` for the raw-bits kernel fast path, or
    ``None`` when it does not apply (non-pallas method, or a dtype without
    an in-kernel key transform — see utils/dtypes.py:key_fold).

    The fast path feeds the kernels the input's raw bit patterns and applies
    the sortable-key transform in kernel, removing the full-array
    ``to_sortable_bits`` pass that the prepared-tiles path still pays
    (measured 1.63 ms of a 7.5 ms select at N=2^27 on v5e — the transform
    cannot fuse into an opaque Pallas custom call). Callers thread the
    result through ``masked_radix_histogram(..., tiles=..., orig_n=...,
    key_op=..., key_xor=...)``; prefixes and walk results stay in key space.
    """
    from mpi_k_selection_tpu.utils import dtypes as _dt

    fold = _dt.key_fold(x.dtype)
    if fold is None:
        return None
    key_op = fold[0]
    key_xor = fold[1] if key_op == "xor" else 0
    method = resolve_hist_method(hist_method, _dt.key_dtype(x.dtype))
    itemsize = np.dtype(x.dtype).itemsize
    if method in ("pallas", "pallas_compare") and itemsize == 4:
        from mpi_k_selection_tpu.ops.pallas.histogram import prepare_raw_tiles32

        tiles, n = prepare_raw_tiles32(x, block_rows)
        return (tiles,), n, key_op, key_xor
    if method in ("pallas64", "pallas64_compare") and itemsize == 8:
        from mpi_k_selection_tpu.ops.pallas.histogram import prepare_raw_tiles64

        hi2, lo2, n = prepare_raw_tiles64(x, block_rows)
        return (hi2, lo2), n, key_op, key_xor
    return None


@functools.partial(
    jax.jit,
    static_argnames=(
        "shift", "radix_bits", "method", "count_dtype", "chunk", "orig_n",
        "key_op", "key_xor", "block_rows",
    ),
)
def multi_masked_radix_histogram(
    keys,
    *,
    shift: int,
    radix_bits: int,
    prefixes,
    method: str = "auto",
    count_dtype=jnp.int32,
    chunk: int = 32768,
    tiles=None,
    orig_n: int | None = None,
    key_op: str = "none",
    key_xor: int = 0,
    block_rows: int = 4096,
) -> jax.Array:
    """``(K, 2**radix_bits)`` histograms, one per key-space prefix in
    ``prefixes`` (shape (K,), traced) — the shared-sweep primitive of
    multi-rank selection. On the pallas methods all K queries ride ONE
    read of the data (ops/pallas/histogram.py multi kernels); other
    methods fall back to K single-prefix histograms (correct, K reads).
    """
    kd = keys.dtype if keys is not None else (
        jnp.uint64 if len(tiles) == 2 else jnp.uint32
    )
    method = resolve_hist_method(method, kd)
    if method in ("pallas", "pallas_compare"):
        from mpi_k_selection_tpu.ops.pallas.histogram import (
            pallas_radix_histogram_multi,
        )

        if tiles is None:
            from mpi_k_selection_tpu.ops.pallas.histogram import prepare_tiles32

            tiles_, orig_n = prepare_tiles32(keys.ravel(), block_rows)
            tiles = (tiles_,)
        return pallas_radix_histogram_multi(
            shift=shift,
            radix_bits=radix_bits,
            prefixes=prefixes,
            count_dtype=count_dtype,
            tiles=tiles[0],
            orig_n=orig_n,
            key_op=key_op,
            key_xor=key_xor,
            block_rows=block_rows,
        )
    if method in ("pallas64", "pallas64_compare"):
        from mpi_k_selection_tpu.ops.pallas.histogram import (
            pallas_radix_histogram64_multi,
        )

        if tiles is None:
            from mpi_k_selection_tpu.ops.pallas.histogram import prepare_tiles64

            hi2, lo2, orig_n = prepare_tiles64(keys.ravel(), block_rows)
            tiles = (hi2, lo2)
        return pallas_radix_histogram64_multi(
            shift=shift,
            radix_bits=radix_bits,
            prefixes=prefixes,
            count_dtype=count_dtype,
            tiles=(tiles[0], tiles[1]),
            orig_n=orig_n,
            key_op=key_op,
            key_xor=key_xor,
            block_rows=block_rows,
        )
    if key_op != "none":
        raise ValueError("key_op/raw tiles require a pallas histogram method")
    # fallback: one masked histogram per query (K unrolled calls)
    nq = int(prefixes.shape[0])
    hists = [
        masked_radix_histogram(
            keys,
            shift=shift,
            radix_bits=radix_bits,
            prefix=prefixes[q],
            method=method,
            count_dtype=count_dtype,
            chunk=chunk,
        )
        for q in range(nq)
    ]
    return jnp.stack(hists)


def resolve_hist_method(method: str, key_dtype=None) -> str:
    if method != "auto":
        return method
    if jax.default_backend() == "tpu":
        # the Pallas kernels are the production path; TPU vector lanes are
        # 32-bit, so 64-bit keys run as two u32 planes ("pallas64")
        if key_dtype is None or np.dtype(key_dtype).itemsize <= 4:
            return "pallas"
        return "pallas64"
    return "scatter"


@functools.partial(
    jax.jit,
    static_argnames=(
        "shift", "radix_bits", "method", "count_dtype", "chunk", "orig_n",
        "key_op", "key_xor", "block_rows",
    ),
)
def masked_radix_histogram(
    keys: jax.Array,
    *,
    shift: int,
    radix_bits: int,
    prefix=None,
    method: str = "auto",
    count_dtype=jnp.int32,
    chunk: int = 32768,
    tiles=None,
    orig_n: int | None = None,
    key_op: str = "none",
    key_xor: int = 0,
    block_rows: int = 4096,
) -> jax.Array:
    """Histogram of the ``radix_bits``-wide digit at ``shift`` over active keys.

    ``keys`` must be unsigned (see utils/dtypes.py). An element is active when
    ``keys >> (shift + radix_bits) == prefix``; ``prefix=None`` means all
    elements are active (the first radix pass).

    ``tiles``/``orig_n`` (from :func:`prepare_keys`, or :func:`prepare_raw`
    with ``key_op``/``key_xor``) let pass-loop callers build the pallas
    kernels' tiled views once instead of per call; ignored by the non-pallas
    methods, which read ``keys`` directly. ``key_op != "none"`` marks the
    tiles as raw bit patterns with the key transform applied in kernel —
    pallas methods only.
    """
    nbuckets = 1 << radix_bits
    kd = keys.dtype if keys is not None else (
        jnp.uint64 if len(tiles) == 2 else jnp.uint32
    )
    if keys is not None:
        keys = keys.ravel()
    method = resolve_hist_method(method, kd)
    if key_op != "none" and method not in (
        "pallas", "pallas_compare", "pallas64", "pallas64_compare"
    ):
        raise ValueError("key_op/raw tiles require a pallas histogram method")
    if method in ("pallas", "pallas_compare"):
        from mpi_k_selection_tpu.ops.pallas.histogram import pallas_radix_histogram

        return pallas_radix_histogram(
            keys if tiles is None else None,
            shift=shift,
            radix_bits=radix_bits,
            prefix=prefix,
            count_dtype=count_dtype,
            packed=method == "pallas",
            tiles=None if tiles is None else tiles[0],
            orig_n=orig_n,
            key_op=key_op,
            key_xor=key_xor,
            block_rows=block_rows,
        )
    if method in ("pallas64", "pallas64_compare"):
        if prefix is not None or shift + radix_bits == 64:
            from mpi_k_selection_tpu.ops.pallas.histogram import (
                pallas_radix_histogram64,
            )

            return pallas_radix_histogram64(
                keys if tiles is None else None,
                shift=shift,
                radix_bits=radix_bits,
                prefix=prefix,
                count_dtype=count_dtype,
                packed=method == "pallas64",
                tiles=None if tiles is None else (tiles[0], tiles[1]),
                orig_n=orig_n,
                key_op=key_op,
                key_xor=key_xor,
                block_rows=block_rows,
            )
        if key_op != "none":
            # the XLA fallback below reads `keys` in key space; raw tiles
            # have no keys to fall back to (pass loops never hit this
            # shape — prefix-free digits only occur on the top pass)
            raise ValueError(
                "prefix-free mid-key histograms are not supported on raw "
                "tiles (key_op != 'none'); pass key-space keys instead"
            )
        method = "onehot"  # prefix-free mid-key shape: rare, XLA fallback
    digits, mask = _digit_and_mask(keys, shift, radix_bits, prefix)
    if method == "scatter":
        return _hist_scatter(digits, mask, nbuckets, count_dtype)
    if method == "onehot":
        return _hist_onehot(digits, mask, nbuckets, count_dtype, chunk)
    raise ValueError(f"unknown histogram method {method!r}")
