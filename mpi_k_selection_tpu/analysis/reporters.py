"""Report rendering: human text and machine JSON, sharing one Report.

The JSON form is the gate's debugging artifact (tests/test_analysis.py
writes it to /tmp/kselect_lint.json on every tier-1 run) and doubles as
the suppression ledger: suppressed findings stay in the report with their
written justification.
"""

from __future__ import annotations

import json

from mpi_k_selection_tpu.analysis.core import Report


def render_text(report: Report, *, verbose: bool = False) -> str:
    lines: list[str] = []
    shown = report.findings if verbose else report.unsuppressed
    for f in shown:
        lines.append(f.render())
    for d in report.dead_suppressions:
        lines.append(
            f"{d['path']}:{d['line']}: stale noqa[{d['rule']}] "
            f"({d['scope']}-scope) — the rule no longer fires here; "
            "drop the suppression"
        )
    nsup = len(report.findings) - len(report.unsuppressed)
    summary = (
        f"{len(report.unsuppressed)} finding(s) "
        f"({nsup} suppressed, {len(report.dead_suppressions)} stale "
        f"suppression(s)) in {len(report.files)} file(s); "
        f"checks: {', '.join(report.checks_run)}"
    )
    lines.append(summary)
    return "\n".join(lines)


def render_json(report: Report) -> str:
    from mpi_k_selection_tpu.analysis.core import all_rules

    return json.dumps(
        {
            "findings": [f.to_dict() for f in report.findings],
            "unsuppressed": len(report.unsuppressed),
            "suppressed": len(report.findings) - len(report.unsuppressed),
            "files_scanned": report.files,
            "checks_run": report.checks_run,
            "dead_suppressions": report.dead_suppressions,
            "rules": {
                rid: {"title": r.title, "rationale": r.rationale}
                for rid, r in sorted(all_rules().items())
            },
            "exit_code": report.exit_code,
        },
        indent=2,
    )
