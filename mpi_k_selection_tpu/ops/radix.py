"""Exact k-selection by radix descent — the TPU-native core algorithm.

This is the TPU replacement for the reference's selection engines: the
sequential sort-then-index (``kth-problem-seq.c:32-33``), the hand-rolled
quicksort partition (``vector.c:23-50``), and the CGM pivot-count-discard loop
(``TODO-kth-problem-cgm.c:122-232``). Instead of physically discarding
elements (``VecErase`` swap-deletes, ``TODO-…:204-225``) — impossible under
XLA's static shapes — radix descent never moves data at all: each pass counts
digit occurrences among the elements that still match the current bit prefix,
narrows the prefix by ``radix_bits`` bits, and rescales k. After
``key_bits / radix_bits`` passes the answer's bits are fully determined.

Properties that make this the right TPU design (SURVEY.md §7):

- fixed trip count (4 passes for 32-bit at radix 256) — no data-dependent
  control flow, everything jits into one XLA program;
- static shapes throughout — the "discard" is implicit in the prefix mask;
- the only cross-pass state is (prefix, k): two scalars, so the distributed
  version needs just one psum of the histogram per pass
  (parallel/radix.py), mirroring how the reference's per-round traffic is
  O(p) scalars (SURVEY.md §3.2) but with even fewer rounds.

Exactness: counts are integer and exact, so the returned value is always the
true k-th smallest (1-indexed, duplicates included) — the same guarantee the
reference's ``L < k <= L+E`` test provides (``TODO-…:194``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
from mpi_k_selection_tpu.utils import dtypes as _dt


def default_radix_bits(dtype, hist_method: str = "auto") -> int:
    """4 on the TPU Pallas path (8 memory-bound passes beat 4 compute-bound
    ones on the VPU — see ops/pallas/histogram.py), 8 elsewhere (fewer
    passes; the scatter/onehot paths scale fine to 256 buckets)."""
    from mpi_k_selection_tpu.ops.histogram import resolve_hist_method

    method = resolve_hist_method(hist_method, _dt.key_dtype(dtype))
    return 4 if method in ("pallas", "pallas64") else 8


def select_count_dtype(n: int):
    """int32 counts are exact for n < 2^31; beyond that int64 (requires x64)."""
    if n < 2**31:
        return jnp.int32
    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"n={n} needs int64 counters; enable jax_enable_x64 "
            "(SURVEY.md §7: int overflow hygiene)"
        )
    return jnp.int64


@functools.partial(jax.jit, static_argnames=("radix_bits", "hist_method", "chunk"))
def radix_select(
    x: jax.Array,
    k,
    *,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
) -> jax.Array:
    """Exact k-th smallest element of ``x`` (k is 1-indexed, reference semantics).

    ``x`` may have any shape (flattened); ``k`` may be a traced scalar.
    """
    x = x.ravel()
    n = x.shape[0]
    if radix_bits is None:
        radix_bits = default_radix_bits(x.dtype, hist_method)
    total_bits = _dt.key_bits(x.dtype)
    if total_bits % radix_bits:
        raise ValueError(f"radix_bits={radix_bits} must divide key bits {total_bits}")
    cdt = select_count_dtype(n)
    u = _dt.to_sortable_bits(x)
    kdt = u.dtype

    kk = jnp.clip(jnp.asarray(k, cdt), 1, n)
    prefix = None
    for p in range(total_bits // radix_bits):
        shift = total_bits - (p + 1) * radix_bits
        hist = masked_radix_histogram(
            u,
            shift=shift,
            radix_bits=radix_bits,
            prefix=prefix,
            method=hist_method,
            count_dtype=cdt,
            chunk=chunk,
        )
        cum = jnp.cumsum(hist)
        bucket = jnp.argmax(cum >= kk)
        kk = kk - (cum[bucket] - hist[bucket])
        bkey = bucket.astype(kdt)
        if prefix is None:
            prefix = bkey
        else:
            prefix = jax.lax.shift_left(prefix, kdt.type(radix_bits)) | bkey
    return _dt.from_sortable_bits(prefix, x.dtype)
