"""Sequential CPU oracle backend (``--backend=seq``).

Ground truth for every other backend, reproducing the reference's sequential
program semantics: sort ascending, answer = element ``k-1`` for 1-indexed k
(``kth-problem-seq.c:32-33``). Two paths:

- :func:`kselect` — ``np.partition`` (introselect), the fast oracle; same
  answer as sort-then-index for every input, O(n) expected.
- :func:`kselect_sort` — literal sort-then-index, bit-for-bit the reference
  algorithm (used to cross-check the partition path in tests).

When the native C++ runtime is built (native/), :func:`kselect` dispatches to
``std::nth_element`` for large int32/int64/float32 arrays — the compiled
equivalent of the reference's C oracle, measurably faster than NumPy.
"""

from __future__ import annotations

import numpy as np

NAME = "seq"


def _native():
    try:
        from mpi_k_selection_tpu.native import loader

        return loader.get_lib()
    except Exception:
        return None


def kselect(x: np.ndarray, k: int):
    """Exact k-th smallest (1-indexed)."""
    x = np.asarray(x).ravel()
    n = x.size
    if not 1 <= k <= n:
        raise ValueError(f"k={k} out of range [1, {n}]")
    lib = _native() if n >= 1 << 16 else None
    if lib is not None:
        result = lib.nth_element(x, k)
        if result is not None:
            return result
    return np.partition(x, k - 1)[k - 1]


def kselect_sort(x: np.ndarray, k: int):
    """Literal reference algorithm: full sort then index (kth-problem-seq.c:32-33)."""
    x = np.asarray(x).ravel()
    if not 1 <= k <= x.size:
        raise ValueError(f"k={k} out of range [1, {x.size}]")
    return np.sort(x, kind="stable")[k - 1]


def topk(x: np.ndarray, k: int, *, largest: bool = True):
    """Top-k along the last axis; returns (values, indices) sorted by rank."""
    x = np.asarray(x)
    d = x.shape[-1]
    if not 1 <= k <= d:
        raise ValueError(f"k={k} out of range [1, {d}]")
    # Note: no negation tricks — ``-x`` wraps for unsigned dtypes and INT_MIN.
    if largest:
        part = np.argpartition(x, d - k, axis=-1)[..., d - k :]
        vals = np.take_along_axis(x, part, axis=-1)
        order = np.argsort(vals, axis=-1, kind="stable")[..., ::-1]
    else:
        part = np.argpartition(x, k - 1, axis=-1)[..., :k]
        vals = np.take_along_axis(x, part, axis=-1)
        order = np.argsort(vals, axis=-1, kind="stable")
    idx = np.take_along_axis(part, order, axis=-1)
    return np.take_along_axis(x, idx, axis=-1), idx


def median(x: np.ndarray):
    """Lower median (k = n//2), the reference's median operating point."""
    x = np.asarray(x).ravel()
    return kselect(x, max(1, x.size // 2))
