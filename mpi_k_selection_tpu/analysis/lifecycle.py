"""Resource-lifecycle dataflow pass (KSL019-KSL021): prove every acquire
reaches its release on every path.

The repo's leak discipline was entirely runtime before this module: the
conftest fixtures fail any test that leaks a ``ksel-*`` thread, a staged
ring slot (``live_staged_keys()``) or a ``ksel-spill-*`` dir, and the
runtime ledger (obs/ledger.py) measures byte leaks after the fact. This
pass is the static complement — a per-function CFG (branches, loops,
try/except/finally, with-blocks, early returns) plus an ownership/escape
analysis over the package's resource protocols
(mpi_k_selection_tpu/resource_protocols.py, the SAME registry the
conftest fixtures match against), proving at lint time that:

- **KSL019** — a ``stage_keys``/``stage_device_keys`` result reaches
  ``StagedKeys.release()`` (or ``release_staged``) on every CFG path, or
  provably escapes into a sanctioned owner: the executor/window FIFO
  (``push`` — released at bundle finish), the pipeline queue
  (``put``/``_put`` — close() drains and releases), or the caller
  (``return``/``yield``).
- **KSL020** — an internally-constructed ``SpillStore`` / generation
  writer (``new_generation()``) / ``TemporaryDirectory``/``mkdtemp``
  reaches its cleanup (``close``/``abort``/``commit``/``cleanup``) on
  every exit path INCLUDING the raise edges, unless returned or handed
  to a caller-owned store.
- **KSL021** — a constructed ``threading.Thread`` with a ``ksel-`` name
  reaches ``join()`` on all exits or is registered with a tracked
  supervisor (the conftest-recognized owner slots: ``_thread``,
  ``_serve_thread``, ``_req_threads``). An UNSTARTED Thread object holds
  no OS resources, so the obligation arms at ``.start()``.

Ownership transfers the lexical analysis cannot see are declarable with
``# ksel: owner[<site>]`` on the transferring line; ``<site>`` must name
a registered owner (resource_protocols.OWNER_SITES), and an annotation
on a line where no tracked resource moves — or naming an unknown site —
is itself a finding (the ``guarded-by`` staleness contract applied to
ownership; audit findings report under KSL019, the umbrella lifecycle
rule).

Engine semantics (a may-leak abstract interpretation, not a full path
enumeration):

- The state maps local names to live resources. Branch joins take the
  UNION (a resource alive on any incoming path is may-live), so "exists
  a path to this exit where the resource is still live" is exactly what
  a finding claims.
- Every statement that contains a call (or is a ``raise``/``assert``)
  contributes an exception edge carrying its post-state; edges route to
  the enclosing ``try``'s handlers (a broad handler absorbs them; typed
  handlers also propagate — the type may not match), through every
  ``finally``, and ultimately to the function's exception exit.
- ``isinstance(r, T)`` / ``r is None`` / ``r is not None`` tests narrow
  the state per branch using the protocol's type vocabulary — the
  ``if isinstance(keys, StagedKeys): keys.release()`` unwind idiom
  proves clean, not "conditionally released".
- Rebinding (or ``del``-ing) a name whose resource is still live —
  including across a loop back edge, the loop-carried-acquire class —
  leaks the old resource and is reported at the rebind site.
- Acquires are recognized THROUGH immediately-invoked wrappers
  (``retry_call(lambda: stage_keys(...), ...)`` — the staging-retry
  idiom), and interprocedurally one hop: a module-local function that
  returns a live resource is itself an acquire site for its callers'
  single-name assignments.
- ``with`` context managers auto-release their managed resource
  (``with SpillStore(...) as s:`` is the sanctioned scoped form).

Honesty bounds (mirroring the KSL015 family): analysis is lexical and
module-local; aliasing (``r2 = r``), resources carried in containers
(``[stage_keys(c) for c in ...]``), tuple-unpacked acquire returns, and
cross-object flows are out of scope — the runtime conftest fixtures are
the complementary dynamic check. Library code only; tests poke
lifecycles freely.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from mpi_k_selection_tpu import resource_protocols as _rp
from mpi_k_selection_tpu.analysis.ast_rules import dotted_name
from mpi_k_selection_tpu.analysis.concurrency import _in_package, _pkg_relpath
from mpi_k_selection_tpu.analysis.core import (
    Rule,
    SourceModule,
    iter_python_files,
    load_module,
    register,
)

_OWNER_RE = re.compile(
    r"#\s*ksel:\s*owner\[(?P<site>[A-Za-z_][A-Za-z0-9_.]*)\]"
)

#: Calls that run their function argument IMMEDIATELY and return its
#: result — an acquire inside their lambda argument is an acquire of the
#: call's result (the staging-retry idiom, faults/policy.py:retry_call).
_IMMEDIATE_WRAPPERS = frozenset({"retry_call"})

#: Receiver-method names that add their argument to a container.
_CONTAINER_ADDERS = frozenset({"append", "add", "appendleft"})

_KSEL_NAME_RE = re.compile(r"ksel-|THREAD_PREFIX|THREAD_NAME")

#: Calls that cannot realistically raise — without this, the sanctioned
#: narrow-then-release unwind (``if isinstance(keys, StagedKeys):
#: keys.release()``) would itself spawn an exception edge carrying the
#: still-live resource out of the handler.
_NO_RAISE_BUILTINS = frozenset(
    {"isinstance", "issubclass", "len", "id", "type", "callable"}
)


@dataclasses.dataclass(frozen=True)
class Protocol:
    """One resource family's lifecycle vocabulary (see
    resource_protocols.py for the canonical constants)."""

    kind: str
    rule: str
    noun: str
    acquire_calls: frozenset
    release_methods: frozenset
    release_funcs: frozenset
    owner_calls: frozenset
    owner_attrs: frozenset
    types: frozenset
    armed_at_acquire: bool
    remedy: str


PROTOCOLS = (
    Protocol(
        kind="staged",
        rule="KSL019",
        noun="staged key buffer",
        acquire_calls=_rp.STAGED_ACQUIRE_CALLS,
        release_methods=_rp.STAGED_RELEASE_METHODS,
        release_funcs=_rp.STAGED_RELEASE_FUNCS,
        owner_calls=_rp.STAGED_OWNER_CALLS,
        owner_attrs=frozenset(),
        types=_rp.STAGED_TYPES,
        armed_at_acquire=True,
        remedy=(
            "release() it (or release_staged), hand it to a sanctioned "
            "owner (executor/window push, the pipeline queue, return it "
            "to the caller), or declare the transfer with "
            "`# ksel: owner[<site>]`"
        ),
    ),
    Protocol(
        kind="spill",
        rule="KSL020",
        noun="spill store/writer/temp dir",
        acquire_calls=_rp.SPILL_ACQUIRE_CALLS,
        release_methods=_rp.SPILL_RELEASE_METHODS,
        release_funcs=_rp.SPILL_RELEASE_FUNCS,
        owner_calls=_rp.SPILL_OWNER_CALLS,
        owner_attrs=_rp.SPILL_OWNER_ATTRS,
        types=_rp.SPILL_TYPES,
        armed_at_acquire=True,
        remedy=(
            "close()/abort()/commit()/cleanup() it on every exit path "
            "(try/finally, or an except-release-raise unwind), return "
            "it, or declare the transfer with `# ksel: owner[<site>]`"
        ),
    ),
    Protocol(
        kind="thread",
        rule="KSL021",
        noun="ksel- worker thread",
        acquire_calls=_rp.THREAD_ACQUIRE_CALLS,
        release_methods=_rp.THREAD_RELEASE_METHODS,
        release_funcs=_rp.THREAD_RELEASE_FUNCS,
        owner_calls=_rp.THREAD_OWNER_CALLS,
        owner_attrs=_rp.THREAD_OWNER_ATTRS,
        types=_rp.THREAD_TYPES,
        armed_at_acquire=False,  # arms at .start(): no OS thread before
        remedy=(
            "join() it on every exit, register it with a tracked "
            "supervisor slot (self._thread / _serve_thread / a tracked "
            "_req_threads list), or declare the transfer with "
            "`# ksel: owner[<site>]`"
        ),
    ),
)

_ALL_RELEASE_FUNCS = frozenset().union(*(p.release_funcs for p in PROTOCOLS))


@dataclasses.dataclass
class Resource:
    """One tracked acquisition, bound to a local name."""

    var: str
    proto: Protocol
    line: int
    func: str
    armed: bool


def _last_seg(name: str) -> str:
    return name.split(".")[-1] if name else ""


def _expr_nodes(root):
    """Own-scope expression nodes: nested lambdas/defs run later and are
    skipped (release/escape effects inside them are not this
    statement's)."""
    stack = [root]
    while stack:
        node = stack.pop()
        if node is None:
            continue
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _own_stmt_nodes(stmt):
    """Own-scope nodes of a statement (for may-raise detection) — nested
    defs/lambdas don't execute here."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _names_in(expr) -> set:
    """Plain Name identifiers referenced in an expression's own scope
    (lambda default values ARE evaluated at the call site, so walk
    lambda args' defaults but not bodies — handled by _expr_nodes plus
    an explicit defaults walk)."""
    out = set()
    stack = [expr]
    while stack:
        node = stack.pop()
        if node is None:
            continue
        if isinstance(node, ast.Lambda):
            # default values evaluate NOW (the `lambda hk=keys: ...`
            # binding idiom); the body runs later
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Name):
            out.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _merge(*states):
    """Union join: live in the merge iff live in ANY incoming state
    (may-leak semantics). ``None`` entries (dead paths) are skipped;
    returns None when every path is dead."""
    live = [s for s in states if s is not None]
    if not live:
        return None
    out: dict = {}
    for s in live:
        for var, r in s.items():
            prev = out.get(var)
            if prev is None or (not prev.armed and r.armed):
                out[var] = r
    return out


def _outcomes():
    return {
        "fall": None,
        "returns": [],
        "raises": [],
        "breaks": [],
        "continues": [],
    }


class _FunctionLifecycle:
    """One function's abstract interpretation."""

    def __init__(self, an: "_ModuleLifecycleAnalyzer", fn, qualname: str):
        self.an = an
        self.fn = fn
        self.qual = qualname
        # (var, line, proto) -> set of leaking exit kinds
        self.leaks: dict = {}
        self.returns_resource: Protocol | None = None

    # -- entry point -------------------------------------------------------

    def run(self) -> None:
        out = self._seq(self.fn.body, {})
        if out["fall"] is not None:
            self._exit_leaks(out["fall"], "fall-through return")
        for s in out["returns"]:
            self._exit_leaks(s, "return")
        for s in out["raises"]:
            self._exit_leaks(s, "exception")
        self._emit_leaks()

    def _exit_leaks(self, state, kind: str) -> None:
        for r in state.values():
            if r.armed:
                self.leaks.setdefault((r.var, r.line, r.proto), set()).add(kind)

    def _emit_leaks(self) -> None:
        for (var, line, proto), kinds in sorted(
            self.leaks.items(), key=lambda kv: (kv[0][1], kv[0][0])
        ):
            paths = ", ".join(sorted(kinds))
            self.an.finding(
                line,
                proto.rule,
                f"{proto.noun} `{var}` acquired in `{self.qual}` never "
                f"reaches its release on the {paths} path(s) — "
                f"{proto.remedy}",
            )

    # -- statement sequencing ----------------------------------------------

    def _seq(self, stmts, state):
        out = _outcomes()
        cur = dict(state)
        alive = True
        for st in stmts:
            if not alive:
                break
            res = self._stmt(st, cur)
            for k in ("returns", "raises", "breaks", "continues"):
                out[k].extend(res[k])
            cur = res["fall"]
            if cur is None:
                alive = False
        out["fall"] = cur if alive else None
        return out

    def _may_raise(self, node) -> bool:
        for n in _own_stmt_nodes(node):
            if isinstance(n, (ast.Raise, ast.Assert)):
                return True
            if isinstance(n, ast.Call) and (
                _last_seg(dotted_name(n.func)) not in _NO_RAISE_BUILTINS
            ):
                return True
        return False

    def _simple(self, node, state):
        """Shared tail for simple statements: owner annotations applied,
        then an exception edge when the statement can raise."""
        self._apply_owner_annotation(node, state)
        out = _outcomes()
        out["fall"] = state
        if self._may_raise(node):
            out["raises"].append(dict(state))
        return out

    # -- the dispatcher ----------------------------------------------------

    def _stmt(self, node, state):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            out = _outcomes()
            out["fall"] = state
            return out
        if isinstance(node, ast.Return):
            if node.value is not None:
                self._effects(node.value, state, node)
                self._escape_names(node.value, state, "caller", node.lineno)
            self._apply_owner_annotation(node, state)
            out = _outcomes()
            out["returns"].append(dict(state))
            return out
        if isinstance(node, ast.Raise):
            if node.exc is not None:
                self._effects(node.exc, state, node)
            self._apply_owner_annotation(node, state)
            out = _outcomes()
            out["raises"].append(dict(state))
            return out
        if isinstance(node, ast.Break):
            out = _outcomes()
            out["breaks"].append(dict(state))
            return out
        if isinstance(node, ast.Continue):
            out = _outcomes()
            out["continues"].append(dict(state))
            return out
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return self._assign(node, state)
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in state:
                    self._overwrite(state.pop(t.id), node.lineno, "del")
            return self._simple(node, state)
        if isinstance(node, ast.If):
            return self._if(node, state)
        if isinstance(node, (ast.While,)):
            return self._while(node, state)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            return self._for(node, state)
        if isinstance(node, ast.Try):
            return self._try(node, state)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            return self._with(node, state)
        if isinstance(node, (ast.Expr, ast.Assert)):
            pre = dict(state)
            self._effects(
                node.value if isinstance(node, ast.Expr) else node.test,
                state,
                node,
            )
            self._apply_owner_annotation(node, state)
            out = _outcomes()
            out["fall"] = state
            if self._may_raise(node):
                # the exception edge keeps the optimistic releases and
                # escapes, but rolls back ARMING: a `t.start()` that
                # raises never created the OS thread, so the obligation
                # never armed on that path
                edge = dict(state)
                for var, old in pre.items():
                    cur = edge.get(var)
                    if cur is not None and cur.armed and not old.armed:
                        edge[var] = old
                out["raises"].append(edge)
            return out
        # Pass, Import, Global, Nonlocal, ...
        return self._simple(node, state)

    # -- assignment / acquisition -------------------------------------------

    def _assign(self, node, state):
        value = node.value
        if value is None:  # a bare annotation (`x: int`) binds nothing
            return self._simple(node, state)
        self._effects(value, state, node)
        # the statement's exception edge carries the PRE-BIND state: if
        # the acquire call itself raises, nothing was ever bound, so
        # there is nothing to release (without this, every bare
        # `store = SpillStore(...)` would be an "exception path" leak)
        pre = dict(state)
        proto = self._find_acquire(value)
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        # a live resource VALUE stored somewhere: `obj.attr = r`
        value_res = (
            state.get(value.id)
            if isinstance(value, ast.Name) and value.id in state
            else None
        )
        for t in targets:
            if isinstance(t, ast.Name):
                if t.id in state:
                    self._overwrite(state.pop(t.id), node.lineno, "rebound")
                if proto is not None:
                    self._acquire(t.id, proto, node.lineno, state)
            elif isinstance(t, ast.Attribute):
                attr = t.attr
                if proto is not None or value_res is not None:
                    p = proto if proto is not None else value_res.proto
                    line = node.lineno
                    if attr in p.owner_attrs:
                        self._record_escape(
                            value_res.var if value_res else "<new>",
                            p, line, f"owner attribute `{attr}`",
                        )
                        if value_res is not None:
                            state.pop(value_res.var, None)
                    elif self._annotated_site(node) is not None:
                        site = self._annotated_site(node)
                        self._use_annotation(node, state)
                        if site not in _rp.OWNER_SITES:
                            self.an.finding(
                                line,
                                "KSL019",
                                f"`# ksel: owner[{site}]` names an "
                                "unregistered owner site (registered: "
                                f"{sorted(_rp.OWNER_SITES)}) — register it "
                                "in resource_protocols.OWNER_SITES or fix "
                                "the name",
                            )
                        self._record_escape(
                            value_res.var if value_res else "<new>",
                            p, line, f"declared owner `{site}`",
                        )
                        if value_res is not None:
                            state.pop(value_res.var, None)
                    else:
                        self.an.finding(
                            line,
                            p.rule,
                            f"{p.noun} escapes into attribute `{attr}`, "
                            "which is not a sanctioned owner slot "
                            f"(tracked owners: "
                            f"{sorted(p.owner_attrs) or 'none'}) — "
                            "register the slot in resource_protocols.py "
                            "(and join/clean it on the owner's close "
                            "path) or declare the transfer with "
                            "`# ksel: owner[<site>]`",
                        )
                        if value_res is not None:
                            state.pop(value_res.var, None)
            elif isinstance(t, (ast.Tuple, ast.List)):
                # tuple-unpack: rebinding live names still leaks; a
                # tuple-carried acquire is out of scope (honesty bound)
                for el in ast.walk(t):
                    if isinstance(el, ast.Name) and el.id in state:
                        self._overwrite(
                            state.pop(el.id), node.lineno, "rebound"
                        )
        self._apply_owner_annotation(node, state)
        out = _outcomes()
        out["fall"] = state
        if self._may_raise(node):
            out["raises"].append(pre)
        return out

    def _find_acquire(self, expr) -> Protocol | None:
        """The protocol acquired by evaluating ``expr``, looking through
        immediately-invoked wrappers (retry_call lambdas) and
        conditional expressions."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            last = _last_seg(dotted_name(node.func))
            proto = self._match_acquire_name(last, node)
            if proto is not None:
                return proto
        return None

    def _match_acquire_name(self, last, call) -> Protocol | None:
        for proto in PROTOCOLS:
            if last not in proto.acquire_calls:
                continue
            # interprocedural hop: module-local acquire-returning fns
            if proto.kind == "thread" and not self._ksel_thread(call):
                continue
            return proto
        extra = self.an.extra_acquirers.get(last)
        if extra is not None and isinstance(call.func, ast.Name):
            return extra
        return None

    def _ksel_thread(self, call) -> bool:
        for kw in call.keywords:
            if kw.arg == "name":
                seg = self.an.mod.segment(kw.value)
                return bool(_KSEL_NAME_RE.search(seg or ""))
        return False

    def _acquire(self, var, proto, line, state) -> None:
        state[var] = Resource(var, proto, line, self.qual, proto.armed_at_acquire)
        self.an.acquires.append(
            {
                "kind": proto.kind,
                "rule": proto.rule,
                "var": var,
                "line": line,
                "function": self.qual,
            }
        )

    def _overwrite(self, res: Resource, line: int, how: str) -> None:
        if not res.armed:
            return
        self.an.finding(
            line,
            res.proto.rule,
            f"`{res.var}` ({res.proto.noun} acquired at line {res.line} "
            f"in `{self.qual}`) is {how} while still live — the previous "
            f"acquisition can no longer be released; {res.proto.remedy}",
        )

    # -- expression effects: releases, escapes, arming -----------------------

    def _effects(self, expr, state, stmt) -> None:
        if expr is None:
            return
        for node in _expr_nodes(expr):
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                if node.value is not None:
                    self._escape_names(
                        node.value, state, "caller", node.lineno
                    )
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            last = _last_seg(fname)
            recv = (
                node.func.value
                if isinstance(node.func, ast.Attribute)
                else None
            )
            recv_name = recv.id if isinstance(recv, ast.Name) else None
            # r.release() / store.close() / writer.abort() / t.join()
            if recv_name is not None and recv_name in state:
                res = state[recv_name]
                if node.func.attr in res.proto.release_methods:
                    self._release(res, node.lineno, state)
                    continue
                if res.proto.kind == "thread" and node.func.attr == "start":
                    # replace, never mutate: state snapshots on earlier
                    # edges/branches share Resource objects, and arming
                    # in place would arm them retroactively
                    state[recv_name] = dataclasses.replace(res, armed=True)
                    continue
            # release_staged(r)-style helpers
            if last in _ALL_RELEASE_FUNCS:
                for name in _names_in_call_args(node):
                    res = state.get(name)
                    if res is not None and last in res.proto.release_funcs:
                        self._release(res, node.lineno, state)
                continue
            # sanctioned owner calls: win.push(r), q.put(r), self._put(r)
            arg_names = _names_in_call_args(node)
            tracked = [state[n] for n in arg_names if n in state]
            if tracked:
                attr_or_last = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else last
                )
                for res in tracked:
                    if attr_or_last in res.proto.owner_calls:
                        self._record_escape(
                            res.var, res.proto, node.lineno,
                            f"owner call `{attr_or_last}`",
                        )
                        state.pop(res.var, None)
                    elif (
                        attr_or_last in _CONTAINER_ADDERS
                        and isinstance(node.func, ast.Attribute)
                        and self._receiver_owner_attr(node.func.value, res)
                    ):
                        self._record_escape(
                            res.var, res.proto, node.lineno,
                            "owner container "
                            f"`{self._receiver_owner_attr(node.func.value, res)}`",
                        )
                        state.pop(res.var, None)

    @staticmethod
    def _receiver_owner_attr(recv, res: Resource):
        """`_req_threads` for ``self._req_threads.append(t)`` when that
        attribute is a sanctioned owner slot of the resource's protocol."""
        if isinstance(recv, ast.Attribute) and recv.attr in res.proto.owner_attrs:
            return recv.attr
        return None

    def _release(self, res: Resource, line: int, state) -> None:
        self.an.releases.append(
            {
                "kind": res.proto.kind,
                "var": res.var,
                "line": line,
                "acquired_line": res.line,
                "function": self.qual,
            }
        )
        state.pop(res.var, None)

    def _record_escape(self, var, proto, line, to) -> None:
        self.an.escapes.append(
            {
                "kind": proto.kind,
                "var": var,
                "line": line,
                "to": to,
                "function": self.qual,
            }
        )

    def _escape_names(self, expr, state, to, line) -> None:
        for name in _names_in(expr):
            res = state.get(name)
            if res is not None:
                self._record_escape(res.var, res.proto, line, to)
                state.pop(name, None)
                if to == "caller" and self.returns_resource is None:
                    self.returns_resource = res.proto

    # -- owner annotations ---------------------------------------------------

    def _annotated_site(self, node):
        return self.an.owner_ann.get(getattr(node, "lineno", None))

    def _use_annotation(self, node, state) -> None:
        self.an.ann_used.add(node.lineno)

    def _apply_owner_annotation(self, node, state) -> None:
        """A `# ksel: owner[<site>]` on a statement's first line
        transfers every tracked resource referenced by the statement to
        the named site (which must be registered)."""
        line = getattr(node, "lineno", None)
        site = self.an.owner_ann.get(line)
        if site is None:
            return
        names = _names_in(node) & set(state)
        if not names:
            return
        self.an.ann_used.add(line)
        if site not in _rp.OWNER_SITES:
            self.an.finding(
                line,
                "KSL019",
                f"`# ksel: owner[{site}]` names an unregistered owner "
                "site (registered: "
                f"{sorted(_rp.OWNER_SITES)}) — register it in "
                "resource_protocols.OWNER_SITES or fix the name",
            )
        for name in sorted(names):
            res = state.pop(name)
            self._record_escape(
                res.var, res.proto, line, f"declared owner `{site}`"
            )

    # -- compound statements -------------------------------------------------

    def _if(self, node, state):
        self._effects(node.test, state, node)
        self._apply_owner_annotation(node, state)
        out = _outcomes()
        if self._may_raise(node.test):
            out["raises"].append(dict(state))
        t_state, e_state = self._narrow(node.test, state)
        b1 = self._seq(node.body, t_state)
        b2 = self._seq(node.orelse, e_state)
        for k in ("returns", "raises", "breaks", "continues"):
            out[k].extend(b1[k])
            out[k].extend(b2[k])
        out["fall"] = _merge(b1["fall"], b2["fall"])
        return out

    def _while(self, node, state):
        self._effects(node.test, state, node)
        out = _outcomes()
        if self._may_raise(node.test):
            out["raises"].append(dict(state))
        then_state, else_state = self._narrow(node.test, state)
        b1 = self._seq(node.body, dict(then_state))
        back = _merge(b1["fall"], *b1["continues"])
        entry2 = _merge(then_state, back)
        b2 = self._seq(node.body, dict(entry2)) if entry2 is not None else b1
        for k in ("returns", "raises"):
            out[k].extend(b1[k])
            out[k].extend(b2[k])
        infinite = (
            isinstance(node.test, ast.Constant) and bool(node.test.value)
        )
        exits = list(b2["breaks"])
        if not infinite:
            exits.append(else_state)
            exits.append(_merge(b2["fall"], *b2["continues"]))
        if node.orelse:
            oe = self._seq(node.orelse, _merge(*exits) or {})
            for k in ("returns", "raises", "breaks", "continues"):
                out[k].extend(oe[k])
            out["fall"] = oe["fall"]
        else:
            out["fall"] = _merge(*exits) if exits else None
        return out

    def _for(self, node, state):
        self._effects(node.iter, state, node)
        out = _outcomes()
        if self._may_raise(node.iter):
            out["raises"].append(dict(state))

        def bind_target(s):
            for el in ast.walk(node.target):
                if isinstance(el, ast.Name) and el.id in s:
                    self._overwrite(s.pop(el.id), node.lineno, "rebound")

        entry = dict(state)
        bind_target(entry)
        b1 = self._seq(node.body, dict(entry))
        back = _merge(b1["fall"], *b1["continues"])
        entry2 = _merge(entry, back)
        if entry2 is not None:
            entry2 = dict(entry2)
            bind_target(entry2)  # the loop-carried rebind check
            b2 = self._seq(node.body, entry2)
        else:
            b2 = b1
        for k in ("returns", "raises"):
            out[k].extend(b1[k])
            out[k].extend(b2[k])
        exits = list(b2["breaks"]) + [
            dict(state), _merge(b2["fall"], *b2["continues"])
        ]
        if node.orelse:
            oe = self._seq(node.orelse, _merge(*exits) or {})
            for k in ("returns", "raises", "breaks", "continues"):
                out[k].extend(oe[k])
            out["fall"] = oe["fall"]
        else:
            out["fall"] = _merge(*exits)
        return out

    def _with(self, node, state):
        out = _outcomes()
        for item in node.items:
            self._effects(item.context_expr, state, node)
            # a context-managed acquire (`with SpillStore() as s:`) is
            # the sanctioned scoped form — __exit__ releases on every
            # path, so it is never ADDED to the state; OTHER live
            # resources still ride the context expressions' raise edges
        self._apply_owner_annotation(node, state)
        if any(self._may_raise(item.context_expr) for item in node.items):
            out["raises"].append(dict(state))
        body = self._seq(node.body, state)
        for k in ("returns", "raises", "breaks", "continues"):
            out[k].extend(body[k])
        out["fall"] = body["fall"]
        return out

    def _try(self, node, state):
        body = self._seq(node.body, state)
        raise_entry = _merge(*body["raises"]) if body["raises"] else None
        out = _outcomes()
        handler_falls = []
        broad = False
        for h in node.handlers:
            broad = broad or self._is_broad(h)
            if raise_entry is None:
                continue
            ho = self._seq(h.body, dict(raise_entry))
            for k in ("returns", "raises", "breaks", "continues"):
                out[k].extend(ho[k])
            handler_falls.append(ho["fall"])
        # else-clause runs on the body's normal fall
        if node.orelse and body["fall"] is not None:
            oe = self._seq(node.orelse, body["fall"])
            for k in ("returns", "raises", "breaks", "continues"):
                out[k].extend(oe[k])
            normal_fall = oe["fall"]
        else:
            normal_fall = body["fall"]
        for k in ("returns", "breaks", "continues"):
            out[k].extend(body[k])
        # an exception may dodge every TYPED handler; only a broad
        # handler (bare / Exception / BaseException) absorbs the edge
        if raise_entry is not None and (not node.handlers or not broad):
            out["raises"].append(dict(raise_entry))
        pre_fall = _merge(normal_fall, *handler_falls)
        if not node.finalbody:
            out["fall"] = pre_fall
            return out
        # finally: applied to every outcome
        final_out = _outcomes()

        def through_finally(s):
            if s is None:
                return None
            f = self._seq(node.finalbody, dict(s))
            for k in ("returns", "raises", "breaks", "continues"):
                final_out[k].extend(f[k])
            return f["fall"]

        final_out["fall"] = through_finally(pre_fall)
        for k in ("returns", "raises", "breaks", "continues"):
            for s in out[k]:
                fs = through_finally(s)
                if fs is not None:
                    final_out[k].append(fs)
        return final_out

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        types = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple)
            else [handler.type]
        )
        return any(
            _last_seg(dotted_name(t)) in ("Exception", "BaseException")
            for t in types
        )

    # -- branch narrowing ----------------------------------------------------

    def _narrow(self, test, state):
        then, els = dict(state), dict(state)
        self._narrow_into(test, then, els)
        return then, els

    def _narrow_into(self, test, then, els) -> None:
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self._narrow_into(test.operand, els, then)
            return
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            # every conjunct narrows the then-branch; the else branch
            # stays unnarrowed (any conjunct may have failed)
            for v in test.values:
                self._narrow_into(v, then, dict(els))
            return
        if (
            isinstance(test, ast.Call)
            and _last_seg(dotted_name(test.func)) == "isinstance"
            and len(test.args) == 2
            and isinstance(test.args[0], ast.Name)
        ):
            var = test.args[0].id
            res = then.get(var) or els.get(var)
            if res is None:
                return
            tnames = {
                _last_seg(dotted_name(t))
                for t in (
                    test.args[1].elts
                    if isinstance(test.args[1], ast.Tuple)
                    else [test.args[1]]
                )
            }
            if tnames & res.proto.types:
                # tracked value IS of the protocol type: the else branch
                # never sees it
                els.pop(var, None)
            else:
                then.pop(var, None)
            return
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            var = test.left.id
            if isinstance(test.ops[0], ast.Is):
                then.pop(var, None)  # tracked resource is never None
            elif isinstance(test.ops[0], ast.IsNot):
                els.pop(var, None)


def _names_in_call_args(call: ast.Call) -> set:
    out = set()
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        out |= _names_in(arg)
    return out


# ---------------------------------------------------------------------------
# module orchestration


@dataclasses.dataclass
class ModuleLifecycle:
    mod: SourceModule
    findings: set  # {(line, rule, message)}
    acquires: list
    releases: list
    escapes: list
    annotations: list  # [{"line", "site", "used"}]


class _ModuleLifecycleAnalyzer:
    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.rel = _pkg_relpath(mod)
        self._findings: set = set()
        self.acquires: list = []
        self.releases: list = []
        self.escapes: list = []
        self.ann_used: set = set()
        self.extra_acquirers: dict = {}
        in_string = mod.string_literal_lines()
        self.owner_ann = {
            lineno: m.group("site")
            for lineno, line in enumerate(mod.lines, start=1)
            if lineno not in in_string
            for m in [_OWNER_RE.search(line)]
            if m is not None
        }
        # pass 1: discover module-local acquire-returning functions
        returns = self._run_all()
        if returns:
            # pass 2: their single-name-assignment callers are acquirers
            self.extra_acquirers = returns
            self._reset()
            self._run_all()
        self._audit_annotations()

    def _reset(self) -> None:
        self._findings.clear()
        self.acquires.clear()
        self.releases.clear()
        self.escapes.clear()
        self.ann_used.clear()

    def finding(self, line, rule, message) -> None:
        self._findings.add((line, rule, message))

    def _functions(self):
        """Every function def with a qualname (Class.method for methods,
        bare name elsewhere — matching the concurrency pass)."""
        qual: dict[int, str] = {}
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        qual[id(item)] = f"{node.name}.{item.name}"
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, qual.get(id(node), node.name)

    def _run_all(self) -> dict:
        returns: dict = {}
        for fn, qualname in self._functions():
            w = _FunctionLifecycle(self, fn, qualname)
            w.run()
            if w.returns_resource is not None:
                returns[fn.name] = w.returns_resource
        return returns

    def _audit_annotations(self) -> None:
        for line, site in sorted(self.owner_ann.items()):
            if line in self.ann_used:
                continue
            known = site in _rp.OWNER_SITES
            detail = (
                "no tracked resource moves on this line"
                if known
                else f"unregistered site (registered: {sorted(_rp.OWNER_SITES)})"
            )
            self.finding(
                line,
                "KSL019",
                f"stale `# ksel: owner[{site}]` annotation: {detail} — "
                "drop the annotation or fix the transfer (the guarded-by "
                "staleness contract, applied to ownership)",
            )

    @staticmethod
    def _dedupe(records: list) -> list:
        """The loop fixpoint walks bodies twice; the report carries each
        site once."""
        seen, out = set(), []
        for r in records:
            key = tuple(sorted(r.items()))
            if key not in seen:
                seen.add(key)
                out.append(r)
        return out

    def result(self) -> ModuleLifecycle:
        annotations = [
            {
                "line": line,
                "site": site,
                "used": line in self.ann_used,
            }
            for line, site in sorted(self.owner_ann.items())
        ]
        return ModuleLifecycle(
            self.mod,
            self._findings,
            self._dedupe(self.acquires),
            self._dedupe(self.releases),
            self._dedupe(self.escapes),
            annotations,
        )


# one analysis per module per scan (rules run back to back on the same
# SourceModule objects; keyed by object identity like the concurrency
# pass's cache)
_CACHE: dict[int, ModuleLifecycle] = {}


def analyze_lifecycle(mod: SourceModule) -> ModuleLifecycle:
    got = _CACHE.get(id(mod))
    if got is None or got.mod is not mod:
        if len(_CACHE) > 4096:
            _CACHE.clear()
        got = _ModuleLifecycleAnalyzer(mod).result()
        _CACHE[id(mod)] = got
    return got


# ---------------------------------------------------------------------------
# the rules


class _LifecycleRule(Rule):
    def check_module(self, mod: SourceModule):
        if not _in_package(mod):
            return
        lc = analyze_lifecycle(mod)
        for line, rule, message in sorted(lc.findings):
            if rule == self.id:
                yield line, message


@register
class StagedBufferLifecycle(_LifecycleRule):
    id = "KSL019"
    title = (
        "staged key buffer (stage_keys/stage_device_keys) not released "
        "or escaped to a sanctioned owner on every CFG path; also the "
        "owner-annotation staleness audit"
    )
    rationale = (
        "A StagedKeys ring slot pins a device buffer (and often a "
        "StagingPool host buffer) until release(); a path that drops one "
        "— an exception edge out of the producer, a rebound loop "
        "variable — leaks exactly the memory the multi-tenant budgeting "
        "work needs to account, and the runtime fixture only sees it "
        "when a test happens to walk that path. This pass proves the "
        "discipline on EVERY path at lint time; the first whole-repo run "
        "found the producer's outer exception handler dropping the "
        "chunk in hand (streaming/pipeline.py, fixed with a release on "
        "the raise edge + a regression test)."
    )


@register
class SpillLifecycle(_LifecycleRule):
    id = "KSL020"
    title = (
        "internally-constructed SpillStore/generation writer/temp dir "
        "not cleaned up (close/abort/commit/cleanup) on every exit path "
        "including raise edges"
    )
    rationale = (
        "An internally-created spill store owns a ksel-spill-* directory "
        "holding up to ~2N key bytes; a writer owns an uncommitted "
        "generation. An exit path that skips close()/abort() strands "
        "that disk — the conftest dir fixture catches it only on paths "
        "tests actually take, and a long-lived server leaks until "
        "restart. The first whole-repo run found the CLI building its "
        "--spill=force store BEFORE entering the try whose finally "
        "closes it (a chaos-armed constructor failure stranded the dir; "
        "fixed by hoisting the try)."
    )


@register
class ThreadLifecycle(_LifecycleRule):
    id = "KSL021"
    title = (
        "started ksel-named thread neither join()ed on every exit nor "
        "registered with a tracked supervisor slot"
    )
    rationale = (
        "Every package worker thread carries the ksel- prefix precisely "
        "so the conftest fixture can fail tests that leak one; a START "
        "site whose thread object reaches no join and no supervisor "
        "slot (ChunkPipeline._thread, the servers' _serve_thread / "
        "_req_threads) has no close path AT ALL — the leak is "
        "structural, not a missed branch. Unstarted Thread objects hold "
        "no OS resources, so the obligation arms at .start(); the "
        "supervisor slots are the same registry "
        "(resource_protocols.THREAD_OWNER_ATTRS) the runtime fixture "
        "vocabulary comes from."
    )


# ---------------------------------------------------------------------------
# the exported report (kselect-lint --lifecycle-report)


def build_lifecycle_report(paths, root=None, mods=None) -> dict:
    """The package ownership graph as one JSON-ready dict — acquire
    sites, release sites and escape edges per module, the owner-site
    registry, and the annotation ledger. Paths are package-relative
    (``mpi_k_selection_tpu/...``) and cwd-independent, exactly like the
    concurrency report. Pass ``mods`` (an already-loaded SourceModule
    list, e.g. ``Report.modules``) to skip re-parsing."""
    if mods is None:
        mods = []
        for f in iter_python_files(paths):
            try:
                mods.append(load_module(f, root=root))
            except SyntaxError:
                continue
    resources: dict = {}
    annotations: dict = {}
    for mod in mods:
        if not _in_package(mod):
            continue
        lc = analyze_lifecycle(mod)
        rel = _pkg_relpath(mod)
        if lc.acquires or lc.releases or lc.escapes:
            resources[rel] = {
                "acquires": lc.acquires,
                "releases": lc.releases,
                "escapes": lc.escapes,
            }
        if lc.annotations:
            annotations[rel] = lc.annotations
    return {
        "resources": resources,
        "annotations": annotations,
        "owners": {
            "sites": dict(sorted(_rp.OWNER_SITES.items())),
            "thread_owner_attrs": sorted(_rp.THREAD_OWNER_ATTRS),
            "staged_owner_calls": sorted(_rp.STAGED_OWNER_CALLS),
            "spill_owner_attrs": sorted(_rp.SPILL_OWNER_ATTRS),
        },
        "prefixes": {
            "threads": list(_rp.THREAD_PREFIXES),
            "spill_dirs": _rp.SPILL_DIR_PREFIX,
            "flight_files": _rp.FLIGHT_FILE_PREFIX,
        },
    }
