"""Cross-request batcher — one dispatch thread, bounded coalescing window.

Many clients issue small rank queries against the same resident dataset;
the backend's cheapest shape for that is ONE shared-pass
``kselect_many`` walk (ops/radix.py shares the prepared key view and
every histogram pass across all ranks, and
``api.many_sort_dispatch_queries`` already says when a wide-enough batch
should flip to one sort). This module turns concurrent arrivals into
that shape:

- **One dispatch thread per batcher** (``ksel-serve-dispatch-*``, or a
  lane name when serve/lanes.py owns it) owns all device work routed to
  it. Requests enqueue and block on a per-request event; the thread
  drains the queue, coalesces, executes, and wakes them. Serializing a
  dataset's device work on one thread is what makes concurrent answers
  bit-identical to serial execution: there is no interleaving to vary.
  The server composes one batcher per execution device
  (:class:`~mpi_k_selection_tpu.serve.lanes.LaneDispatcher`) — each
  dataset always lands in the same lane, so per-dataset serialization
  (the determinism requirement) is preserved while datasets resident on
  different chips answer concurrently.
- **Bounded coalescing window**: when the first request of a batch
  arrives the thread waits at most ``window`` seconds (a plain
  ``Event.wait`` — KSL004: no raw clock reads here) for more to arrive,
  then drains up to ``max_batch`` pending requests. ``window=0`` is the
  no-coalescing extreme (every request dispatches alone — the latency
  floor); a large window is the full-coalescing extreme (every
  concurrent request rides one walk — the throughput ceiling). Answers
  are bit-identical at every window because exact order statistics do
  not depend on which batch computed them.
- **Grouping**: drained requests coalesce only within (dataset, kind) —
  rank queries (kselect/quantiles, already rank-converted by the
  server) against the same dataset merge their ks into one
  ``select_many`` call; non-rank ops (topk, rank certificates) execute
  one at a time, still on the dispatch thread. Arrival order is
  preserved within and across groups.

Resilience (docs/ROBUSTNESS.md):

- **Deadlines**: a request may carry a
  :class:`~mpi_k_selection_tpu.utils.timing.Deadline`; the waiter times
  out with a typed :class:`DeadlineExceededError` (HTTP 504), and the
  dispatch thread drops already-expired queries BEFORE executing their
  group — a dead client's walk must not delay live ones.
- **Admission control**: ``max_depth`` bounds the dispatch queue;
  arrivals past it are shed with :class:`ServerOverloadedError` (HTTP
  503 + ``Retry-After``) instead of queueing unboundedly — under
  sustained overload, bounded latency for admitted queries beats
  unbounded latency for all.
- **Supervision**: the dispatch loop runs under a supervisor — a crash
  in the loop machinery (NOT per-group execution errors, which are
  already isolated) fails ONLY the in-flight batch with
  :class:`DispatchCrashedError`, increments the restart counter
  (``serve.dispatch_restarts``), and resumes the loop; queued and
  future queries are unaffected.
- **Graceful drain**: ``close()`` stops admissions, lets the dispatch
  thread finish everything already queued, joins it, and fails only
  stragglers that raced the shutdown.

The thread is joined on ``close()`` on every exit path — the conftest
leaked-thread fixture enforces the same discipline as for
``ksel-pipeline-*`` producers.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading

from mpi_k_selection_tpu.faults.inject import maybe_fault as _maybe_fault
from mpi_k_selection_tpu.resource_protocols import SERVE_THREAD_PREFIX
from mpi_k_selection_tpu.serve.errors import (
    DeadlineExceededError,
    DispatchCrashedError,
    ServerClosedError,
    ServerOverloadedError,
)

# SERVE_THREAD_PREFIX (imported above) names every serving-layer thread
# (dispatch, HTTP serve loop, HTTP request handlers); tests assert none
# outlives its server. Canonical value: resource_protocols.py (the one
# registry the conftest leak fixtures and the KSL021 pass both import).

#: Coalescing-window ceiling (seconds) — a minute-long window is a
#: misconfiguration, not a batching strategy.
MAX_WINDOW = 60.0

#: Queue-drain ceiling per dispatch round.
DEFAULT_MAX_BATCH = 1024


@dataclasses.dataclass
class PendingQuery:
    """One enqueued request. ``kind`` is ``"rank"`` (ks carries the
    1-indexed ranks) or an op name executed singly. ``ds`` is the
    RESOLVED ResidentDataset the request validated against — carried by
    object so a concurrent drop+re-add of the same id cannot swap the
    data (and its n) out from under an in-flight request. ``run`` is the
    server-provided executor for non-rank ops. The dispatch thread fills
    exactly one of ``result``/``error`` and sets ``done``."""

    dataset_id: str
    kind: str
    ks: tuple = ()
    ds: object = None
    run: object = None
    #: request-correlation id (docs/OBSERVABILITY.md "Trace IDs"): minted
    #: or honored by the server per query, carried through the coalesced
    #: group so the walk's batch event/span name every rider
    trace_id: str | None = None
    #: optional utils/timing.Deadline — the waiter times out against it,
    #: and the dispatch thread drops the query once it expires
    deadline: object = None
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: object = None
    error: BaseException | None = None
    #: set by a timed-out waiter, so the dispatch thread's expiry drop
    #: does not count the SAME query's deadline twice in the metrics;
    #: ``_dl_lock`` makes abandon-vs-drop a real test-and-set (the two
    #: threads race on exactly this decision)
    abandoned: bool = False  # ksel: guarded-by[_dl_lock]
    _dl_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock
    )

    def wait(self):
        """Block until dispatched (bounded by ``deadline`` when set);
        re-raise the dispatch error here (on the REQUEST thread), raise
        the typed :class:`DeadlineExceededError` on timeout, or return
        the result."""
        if self.deadline is None:
            self.done.wait()
        elif not self.done.wait(timeout=self.deadline.remaining()):
            # the dispatch thread may still execute this query (its
            # result is discarded); its own expiry check drops it when
            # the group has not started yet. Decide atomically who
            # accounts the expiry: if the dispatch thread completed/
            # dropped the query between our timeout and here, fall
            # through to ITS outcome (one count, on its side)
            with self._dl_lock:
                if not self.done.is_set():
                    self.abandoned = True
                    raise DeadlineExceededError(
                        "query deadline expired before dispatch completed"
                    )
        if self.error is not None:
            raise self.error
        return self.result


def validate_window(window) -> float:
    w = float(window)
    if not 0.0 <= w <= MAX_WINDOW:
        raise ValueError(f"window={w} out of range [0, {MAX_WINDOW}] seconds")
    return w


class QueryBatcher:
    """The dispatch thread + queue. ``execute_ranks(items)``
    (server-provided) runs one coalesced rank group — all items share
    one resolved dataset object — and must fill every item's
    ``result``; ``observe`` hooks (queue depth at submit, batch width
    at dispatch, shed/expired/restart counts) are optional metrics
    callbacks. ``max_depth`` bounds the queue (None = unbounded, the
    historical behavior); arrivals past it are shed with
    :class:`ServerOverloadedError` carrying ``retry_after``."""

    _ids = itertools.count()

    def __init__(
        self,
        execute_ranks,
        *,
        window: float = 0.0,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_depth: int | None = None,
        retry_after: float = 1.0,
        observe_depth=None,
        observe_width=None,
        observe_shed=None,
        observe_expired=None,
        observe_restart=None,
        name: str | None = None,
    ):
        self._execute_ranks = execute_ranks
        self.window = validate_window(window)
        self.max_batch = max(1, int(max_batch))
        self.max_depth = None if max_depth is None else max(1, int(max_depth))
        self.retry_after = float(retry_after)
        self._observe_depth = observe_depth
        self._observe_width = observe_width
        self._observe_shed = observe_shed
        self._observe_expired = observe_expired
        self._observe_restart = observe_restart
        #: dispatch-loop supervisor restarts (serve.dispatch_restarts)
        self.restarts = 0
        #: queries admitted by submit() (per-lane occupancy figure)
        self.submitted = 0  # ksel: guarded-by[_submit_lock]
        self._inflight: list = []  # the batch being dispatched right now
        self._q: queue.Queue = queue.Queue()
        # serializes submit's check+put against close's final drain, so a
        # submit racing close() either raises or its item is seen by the
        # drain — a queued request can never be left waiting forever
        self._submit_lock = threading.Lock()
        self._stop = threading.Event()
        # a lane owner (serve/lanes.py) passes its lane name; the prefix
        # contract (conftest leak fixture + KSL021) holds either way
        if name is None:
            name = f"{SERVE_THREAD_PREFIX}-dispatch-{next(self._ids)}"
        elif not name.startswith(SERVE_THREAD_PREFIX):
            raise ValueError(
                f"dispatch thread name {name!r} must carry the "
                f"{SERVE_THREAD_PREFIX!r} prefix (conftest leak contract)"
            )
        self._thread = threading.Thread(
            target=self._run,
            name=name,
            daemon=True,
        )
        self._thread.start()

    # -- request side ------------------------------------------------------

    def submit(self, item: PendingQuery) -> PendingQuery:
        with self._submit_lock:
            if self._stop.is_set():
                raise ServerClosedError("server is closed; query rejected")
            depth = self._q.qsize()
            if self.max_depth is not None and depth >= self.max_depth:
                # shed instead of queueing unboundedly: under sustained
                # overload a bounded queue keeps admitted-query latency
                # bounded; the client backs off and retries
                if self._observe_shed is not None:
                    self._observe_shed()
                raise ServerOverloadedError(
                    f"dispatch queue at its depth bound ({self.max_depth}); "
                    "query shed — retry after backoff",
                    retry_after=self.retry_after,
                )
            if self._observe_depth is not None:
                self._observe_depth(depth)
            self.submitted += 1
            self._q.put(item)
        return item

    # -- dispatch thread ---------------------------------------------------

    def _run(self) -> None:
        """Supervisor shell around the serve loop: a crash in the loop
        machinery fails ONLY the batch in flight (each unanswered item
        gets a typed :class:`DispatchCrashedError`), counts a restart,
        and resumes — the thread itself never dies of an exception, so
        queued and future queries keep being served."""
        while True:
            try:
                self._serve_loop()
                return
            except BaseException as e:
                inflight, self._inflight = self._inflight, []
                for item in inflight:
                    if not item.done.is_set():
                        item.error = DispatchCrashedError(
                            f"dispatch loop crashed while this query was in "
                            f"flight ({type(e).__name__}: {e}); the loop was "
                            "restarted"
                        )
                        item.done.set()
                self.restarts += 1
                if self._observe_restart is not None:
                    self._observe_restart(e)
                if self._stop.is_set():
                    return

    def _serve_loop(self) -> None:
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            batch = [first]
            if self.window > 0.0:
                # bounded coalescing: wait once for concurrent arrivals
                # (Event.wait honors close() immediately), then drain
                self._stop.wait(self.window)
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._q.get_nowait())
                    except queue.Empty:
                        break
            # the supervisor fails exactly this list on a loop crash
            self._inflight = batch
            # chaos hook: the i-th dispatch round — OUTSIDE the per-group
            # isolation below, so an injected raise exercises the
            # supervisor-restart path (faults/inject.py)
            _maybe_fault("serve.dispatch")
            self._dispatch(batch)
            self._inflight = []
            if self._stop.is_set() and self._q.empty():
                return

    def _drop_expired(self, items) -> list:
        """Fail every already-expired query with the typed error and
        return the live remainder. Expired queries never execute: their
        waiters already gave up, and running their walk would only delay
        the live queries behind them."""
        live = []
        for item in items:
            if item.deadline is not None and item.deadline.expired:
                # decide atomically against the waiter's own timeout: a
                # waiter that already abandoned counted this query's
                # deadline itself — observe only the drops it didn't
                with item._dl_lock:
                    abandoned = item.abandoned
                    item.error = DeadlineExceededError(
                        "query deadline expired before dispatch; dropped unrun"
                    )
                    item.done.set()
                if self._observe_expired is not None and not abandoned:
                    self._observe_expired()
                continue
            live.append(item)
        return live

    def _dispatch(self, batch) -> None:
        """Group a drained batch by (dataset, kind) preserving arrival
        order, execute each group, and wake every request exactly once.
        Expired queries are dropped without execution — re-checked per
        GROUP, not only at batch start, so a deadline that expires while
        an earlier group's slow walk runs still fails fast."""
        groups: dict = {}
        order = []
        for item in self._drop_expired(batch):
            # identity includes the dataset OBJECT: two requests that
            # resolved the same id across a drop+re-add must not share
            # one walk over whichever dataset happens to be current
            key = (item.dataset_id, item.kind, id(item.ds))
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(item)
        for key in order:
            kind = key[1]
            # an earlier group's slow walk may have outlived this
            # group's deadlines: re-check before spending device time
            items = self._drop_expired(groups[key])
            if not items:
                continue
            try:
                if kind == "rank":
                    if self._observe_width is not None:
                        self._observe_width(sum(len(i.ks) for i in items))
                    self._execute_ranks(items)
                else:
                    for item in items:
                        item.result = item.run()
            except BaseException as e:
                for item in items:
                    if item.result is None:
                        item.error = e
            finally:
                for item in items:
                    item.done.set()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop accepting queries, let the dispatch thread finish what is
        queued, join it, and fail anything still pending (a request that
        raced the close) with :class:`ServerClosedError` so no client
        thread blocks forever. Idempotent."""
        self._stop.set()
        self._thread.join(timeout=30.0)
        # drain under the submit lock: any submit that won the race into
        # the queue is failed here; any submit after sees the stop flag
        with self._submit_lock:
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                item.error = ServerClosedError("server closed before dispatch")
                item.done.set()

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    @property
    def depth(self) -> int:
        """Current dispatch-queue depth (approximate — the queue moves)."""
        return self._q.qsize()
