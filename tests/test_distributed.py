"""Distributed radix + CGM selection on the 8-device virtual CPU mesh.

The JAX analogue of the reference's local ``mpirun -np P`` testing
(SURVEY.md §4): the full collective code path runs on
xla_force_host_platform_device_count=8 CPU devices.
"""

import jax
import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.parallel import (
    distributed_cgm_select,
    distributed_kselect,
    distributed_radix_select,
    make_mesh,
)
from mpi_k_selection_tpu.utils import datagen


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


N = 1 << 16


@pytest.mark.parametrize("pattern", ["uniform", "seqlike", "descending", "equal"])
def test_distributed_radix_matches_oracle(mesh8, pattern):
    x = datagen.generate(N, pattern=pattern, seed=21, dtype=np.int32)
    for k in (1, N // 2, N):
        got = int(distributed_radix_select(x, k, mesh=mesh8))
        assert got == int(seq.kselect(x, k)), (pattern, k)


@pytest.mark.parametrize("pattern", ["uniform", "seqlike", "descending", "equal"])
def test_distributed_cgm_matches_oracle(mesh8, pattern):
    x = datagen.generate(N, pattern=pattern, seed=22, dtype=np.int32)
    for k in (1, N // 2, N):
        got = int(distributed_cgm_select(x, k, mesh=mesh8))
        assert got == int(seq.kselect(x, k)), (pattern, k)


def test_cgm_terminates_and_reports_rounds(mesh8):
    x = datagen.generate(N, pattern="uniform", seed=23, dtype=np.int32)
    val, rounds = distributed_cgm_select(x, N // 3, mesh=mesh8, return_rounds=True)
    assert int(val) == int(seq.kselect(x, N // 3))
    # true-median pivots: convergence must be logarithmic, not linear
    assert 1 <= int(rounds) <= 64


def test_unpadded_n_not_divisible(mesh8):
    # n % 8 != 0 exercises the sentinel padding path (pad_to_multiple)
    n = N + 5
    x = datagen.generate(n, pattern="uniform", seed=24, dtype=np.int32)
    for k in (1, n // 2, n):
        assert int(distributed_radix_select(x, k, mesh=mesh8)) == int(seq.kselect(x, k))
        assert int(distributed_cgm_select(x, k, mesh=mesh8)) == int(seq.kselect(x, k))


def test_distributed_float32(mesh8):
    x = datagen.generate(N, pattern="normal", seed=25, dtype=np.float32)
    k = N // 2
    assert float(distributed_radix_select(x, k, mesh=mesh8)) == float(seq.kselect(x, k))
    assert float(distributed_cgm_select(x, k, mesh=mesh8)) == float(seq.kselect(x, k))


def test_distributed_duplicates(mesh8):
    rng = np.random.default_rng(4)
    x = rng.integers(0, 5, size=N, dtype=np.int32)
    for k in (1, N // 2, N):
        assert int(distributed_cgm_select(x, k, mesh=mesh8)) == int(seq.kselect(x, k))


def test_distributed_kselect_dispatch(mesh8):
    x = datagen.generate(1 << 12, pattern="uniform", seed=26, dtype=np.int32)
    k = 1 << 11
    want = int(seq.kselect(x, k))
    assert int(distributed_kselect(x, k, algorithm="radix", mesh=mesh8)) == want
    assert int(distributed_kselect(x, k, algorithm="cgm", mesh=mesh8)) == want
    with pytest.raises(ValueError):
        distributed_kselect(x, k, algorithm="quickselect", mesh=mesh8)


def test_min_devices_guard():
    # the reference aborts on world_size < 2 (TODO-…:56-59)
    mesh1 = make_mesh(1)
    x = datagen.generate(1024, pattern="uniform", seed=1, dtype=np.int32)
    with pytest.raises(ValueError, match="devices"):
        distributed_radix_select(x, 5, mesh=mesh1)


def test_int64_distributed(mesh8):
    from mpi_k_selection_tpu.utils import x64

    with x64.enable_x64():
        rng = np.random.default_rng(31)
        x = rng.integers(-(2**62), 2**62, size=1 << 14, dtype=np.int64)
        k = 1 << 13
        assert int(distributed_radix_select(x, k, mesh=make_mesh(8))) == int(
            seq.kselect(x, k)
        )


def test_concrete_k_raises_everywhere(mesh8):
    """Unified validation contract: concrete out-of-range k raises ValueError
    from all four public entry points (oracle semantics, kth-problem-seq.c:24,33)."""
    from mpi_k_selection_tpu import api
    from mpi_k_selection_tpu.parallel import distributed_topk

    x = datagen.generate(1 << 12, pattern="uniform", seed=3, dtype=np.int32)
    n = x.size
    for bad_k in (0, -5, n + 1):
        with pytest.raises(ValueError, match="out of range"):
            api.kselect(x, bad_k)
        with pytest.raises(ValueError, match="out of range"):
            distributed_radix_select(x, bad_k, mesh=mesh8)
        with pytest.raises(ValueError, match="out of range"):
            distributed_cgm_select(x, bad_k, mesh=mesh8)
        with pytest.raises(ValueError, match="out of range"):
            distributed_topk(x, bad_k, mesh=mesh8)


def test_distributed_radix_select_many(mesh8, rng):
    from mpi_k_selection_tpu.parallel import distributed_radix_select_many

    n = 40001  # non-divisible by 8: sentinel-padding path
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int32)
    ks_q = np.array([1, 7, n // 2, n - 1, n])
    got = np.asarray(distributed_radix_select_many(x, ks_q, mesh=mesh8))
    np.testing.assert_array_equal(got, np.sort(x, kind="stable")[ks_q - 1])


def test_distributed_radix_select_many_rejects_bad_k(mesh8, rng):
    from mpi_k_selection_tpu.parallel import distributed_radix_select_many

    x = rng.integers(0, 100, size=1000, dtype=np.int32)
    with pytest.raises(ValueError):
        distributed_radix_select_many(x, [1, 1001], mesh=mesh8)


def test_distributed_radix_select_many_2d_ks(mesh8, rng):
    from mpi_k_selection_tpu.parallel import distributed_radix_select_many

    x = rng.integers(-(2**31), 2**31, size=9000, dtype=np.int32)
    ks_2d = np.array([[1, 2], [4000, 9000]])
    got = np.asarray(distributed_radix_select_many(x, ks_2d, mesh=mesh8))
    np.testing.assert_array_equal(got, np.sort(x, kind="stable")[ks_2d - 1])


def _assert_replicated(arr):
    """Every device's buffer of a nominally-replicated output must be equal —
    the dynamic check for the two check_vma=False shard_map bodies (a
    replication bug would make devices disagree silently)."""
    shards = list(arr.addressable_shards)
    assert len(shards) > 1, "expected a multi-device output"
    ref = np.asarray(shards[0].data)
    for s in shards[1:]:
        np.testing.assert_array_equal(np.asarray(s.data), ref)


def test_cgm_outputs_replicated_on_all_devices(mesh8):
    x = datagen.generate(N, pattern="uniform", seed=41, dtype=np.int32)
    val, rounds = distributed_cgm_select(x, N // 2, mesh=mesh8, return_rounds=True)
    _assert_replicated(val)
    _assert_replicated(rounds)
    assert int(val) == int(seq.kselect(x, N // 2))


def test_distributed_topk_outputs_replicated_on_all_devices(mesh8):
    from mpi_k_selection_tpu.parallel import distributed_topk

    x = datagen.generate(N, pattern="normal", seed=42, dtype=np.float32)
    vals, idx = distributed_topk(x, 16, mesh=mesh8)
    _assert_replicated(vals)
    _assert_replicated(idx)
    np.testing.assert_array_equal(np.asarray(vals), np.sort(x)[::-1][:16])


# ---------------------------------------------------------------------------
# Distributed cutover ladder (the reference CGM's sequential finish,
# TODO-kth-problem-cgm.c:122, 236-280, rebuilt as collect + all_gather):
# forced small-n cutovers so every rung runs in CI — auto disables the
# cutover below 2^20 elements.
# ---------------------------------------------------------------------------


def test_distributed_cutover_rung1(mesh8, rng):
    n = 100_003  # ragged: sentinel padding composes with the collect
    x = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    want = np.sort(x, kind="stable")
    for k in (1, n // 2, n):
        got = int(distributed_radix_select(x, k, mesh=mesh8, cutover=2))
        assert got == want[k - 1], k


def test_distributed_cutover_rung2_and_full_branch(mesh8, rng):
    n = 100_003
    x = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    # budget 64: rung 1 overflows (~n/256 survivors), rung 2 fits (~n/4096)
    got = int(
        distributed_radix_select(x, n // 2, mesh=mesh8, cutover=2, cutover_budget=64)
    )
    assert got == np.sort(x, kind="stable")[n // 2 - 1]
    # dense data: both rungs overflow, the remaining fixed passes finish
    xd = rng.integers(0, 200, size=50_001, dtype=np.int32)
    got = int(
        distributed_radix_select(xd, 25_000, mesh=mesh8, cutover=2, cutover_budget=64)
    )
    assert got == np.sort(xd, kind="stable")[24_999]


def test_distributed_cutover_int64(mesh8, rng):
    from mpi_k_selection_tpu.utils import x64

    with x64.enable_x64():
        n = 77_777
        x = rng.integers(-(2**62), 2**62, size=n, dtype=np.int64)
        want = np.sort(x, kind="stable")
        for k in (1, n // 2, n):
            got = int(distributed_radix_select(x, k, mesh=mesh8, cutover=3))
            assert got == want[k - 1], k


def test_distributed_select_many_cutover(mesh8, rng):
    from mpi_k_selection_tpu.parallel import distributed_radix_select_many
    from mpi_k_selection_tpu.utils import x64

    with x64.enable_x64():
        n = 77_777
        x = rng.integers(-(2**62), 2**62, size=n, dtype=np.int64)
        ks = np.array([1, n // 4, n // 2, n])
        want = np.sort(x, kind="stable")[ks - 1]
        got = np.asarray(
            distributed_radix_select_many(x, ks, mesh=mesh8, cutover=3)
        )
        np.testing.assert_array_equal(got, want)
        # tight budget: the batched ladder's rung-2/full branches
        got = np.asarray(
            distributed_radix_select_many(
                x, ks, mesh=mesh8, cutover=3, cutover_budget=16
            )
        )
        np.testing.assert_array_equal(got, want)


def test_distributed_cutover_float32_ragged(mesh8, rng):
    n = 64_007
    x = rng.standard_normal(n).astype(np.float32)
    got = float(distributed_radix_select(x, n // 2, mesh=mesh8, cutover=2))
    assert got == np.sort(x, kind="stable")[n // 2 - 1]
