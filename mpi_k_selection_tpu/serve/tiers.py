"""Latency tiers of the query server: sketch / exact / auto.

The tier contract (docs/API.md "Serving"):

- ``"sketch"`` answers instantly from the dataset's resident
  :class:`~mpi_k_selection_tpu.streaming.sketch.RadixSketch` — a point
  estimate that ALWAYS carries its exact error bounds (``rank_bounds``
  with true ranks ``lo < k <= hi``, ``value_bounds`` bracketing the true
  order statistic, and ``rank_error_bound = hi - lo``). Requires a
  resident sketch; raises :class:`QueryError` otherwise.
- ``"exact"`` runs the real selection (the batcher's shared-pass walk for
  resident arrays, the sketch-seeded streaming descent for stream
  datasets) — bit-identical to calling ``api.kselect`` yourself.
- ``"auto"`` answers from the sketch when the sketch already PINS the
  answer (its resolved key interval, clamped to the observed extremes,
  is a single key — ``RadixSketch.pin``), and escalates the whole
  request to the exact tier otherwise. Pinned answers are exact by
  construction (the true value lies in a one-key interval), so auto
  answers are ALWAYS bit-identical to exact ones; a multi-rank request
  escalates as a unit if any of its ranks is unpinned, keeping one
  request = one tier = one latency class.

Pure host logic — no device work and no compilation happens here
(KSL010); sketch reads are numpy over the resident pyramid.
"""

from __future__ import annotations

import dataclasses

from mpi_k_selection_tpu.serve.errors import QueryError

TIERS = ("sketch", "exact", "auto")


@dataclasses.dataclass(frozen=True)
class RankAnswer:
    """One rank query's answer. ``tier`` is the tier that ANSWERED
    (``"sketch"`` or ``"exact"``); ``exact`` is True when the value is
    the true order statistic bit-for-bit (always for the exact tier, and
    for sketch answers the sketch pinned). Sketch-tier answers always
    carry the three bound fields; exact-tier answers carry None (the
    value itself is the proof)."""

    k: int
    value: object
    tier: str
    exact: bool
    rank_bounds: tuple | None = None
    value_bounds: tuple | None = None
    rank_error_bound: int | None = None
    escalated: bool = False

    def as_dict(self) -> dict:
        """JSON-ready form (numpy scalars -> Python numbers)."""
        out = {
            "k": int(self.k),
            "value": _jsonable(self.value),
            "tier": self.tier,
            "exact": bool(self.exact),
            "escalated": bool(self.escalated),
        }
        if self.rank_bounds is not None:
            out["rank_bounds"] = [int(b) for b in self.rank_bounds]
        if self.value_bounds is not None:
            out["value_bounds"] = [_jsonable(v) for v in self.value_bounds]
        if self.rank_error_bound is not None:
            out["rank_error_bound"] = int(self.rank_error_bound)
        return out


def _jsonable(v):
    item = getattr(v, "item", None)
    return item() if item is not None else v


def validate_tier(tier: str) -> str:
    if tier not in TIERS:
        raise QueryError(f"unknown tier {tier!r}; choose from {TIERS}")
    return tier


def sketch_answers(ds, ks) -> list[RankAnswer]:
    """Sketch-tier answers for every rank in ``ks`` — point estimates
    with their exact bounds attached (the sketch-tier response contract:
    bounds are never omitted)."""
    sk = require_sketch(ds)
    out = []
    for k in ks:
        k = int(k)
        # one bucket resolution per rank (RadixSketch.describe) — the
        # separate rank_bounds/value_bounds/pin/query calls each redo it
        lo, hi, v_lo, v_hi, pinned = sk.describe(k)
        out.append(
            RankAnswer(
                k=k,
                value=pinned if pinned is not None else v_lo,
                tier="sketch",
                exact=pinned is not None,
                rank_bounds=(lo, hi),
                value_bounds=(v_lo, v_hi),
                rank_error_bound=hi - lo,
            )
        )
    return out


def auto_pins(ds, ks) -> bool:
    """True when the resident sketch pins EVERY rank in ``ks`` — the
    auto tier's stay-on-sketch predicate (no sketch = never pins)."""
    if ds.sketch is None:
        return False
    return all(ds.sketch.pin(int(k)) is not None for k in ks)


def require_sketch(ds):
    if ds.sketch is None:
        raise QueryError(
            f"dataset {ds.dataset_id!r} has no resident sketch; register "
            "with sketch=True or query tier='exact'"
        )
    return ds.sketch
