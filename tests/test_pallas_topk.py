"""Pallas batched top-k kernel (ops/pallas/topk.py) vs numpy, interpret mode.

Covers the three runtime paths: non-suspect fold, bounded rescue (rows with
a lane hiding a 4th top-8 member), and the full lax.top_k fallback (suspect
count over the rescue budget), plus ties, k < 8, and -inf rows.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.ops.pallas.topk import (
    batched_topk_supported,
    pallas_batched_topk_values,
)
from mpi_k_selection_tpu.ops.topk import topk

B, D = 64, 4096


def _want(x, k):
    return np.sort(x, axis=1)[:, ::-1][:, :k].astype(np.float32)


@pytest.mark.parametrize("k", [1, 5, 8])
def test_block_topk_random(rng, k):
    x = rng.standard_normal((B, D)).astype(np.float32)
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(x), k))
    np.testing.assert_array_equal(got, _want(x, k))


def test_block_topk_duplicates(rng):
    x = (rng.integers(0, 13, size=(B, D))).astype(np.float32)
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(x), 8))
    np.testing.assert_array_equal(got, _want(x, 8))


def test_block_topk_rescue_path(rng):
    # top-8 of a few rows clustered in ONE lane (stride-128 positions):
    # those rows MUST flag suspect and be rescued exactly
    x = rng.standard_normal((B, D)).astype(np.float32)
    big = 100.0 + np.arange(8, dtype=np.float32)
    for r in (3, 17, 40):
        x[r, 5 + 128 * np.arange(8)] = big  # same lane (col % 128 == 5)
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(x), 8))
    np.testing.assert_array_equal(got, _want(x, 8))


def test_block_topk_fallback_path(rng):
    # EVERY row clustered => suspects exceed the rescue budget => the cond
    # takes the full lax.top_k fallback; result must still be exact
    x = rng.standard_normal((128, D)).astype(np.float32)
    big = 50.0 + np.arange(8, dtype=np.float32)
    for r in range(128):
        x[r, 7 + 128 * np.arange(8)] = big
    got = np.asarray(
        pallas_batched_topk_values(jnp.asarray(x), 8, rescue_rows=16)
    )
    np.testing.assert_array_equal(got, _want(x, 8))


def test_block_topk_neg_inf_rows(rng):
    x = rng.standard_normal((B, D)).astype(np.float32)
    x[5, :] = -np.inf  # top-8 all -inf: suspect logic degrades to rescue
    x[9, :D - 4] = -np.inf  # fewer finite values than k
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(x), 8))
    np.testing.assert_array_equal(got, _want(x, 8))


def test_block_topk_dispatch_contract():
    assert batched_topk_supported((4096, 32768), np.float32, 8)
    # r5 widened envelope: k <= 16 (depth-4 + fold-16) and bfloat16
    assert batched_topk_supported((4096, 32768), np.float32, 9)
    assert batched_topk_supported((4096, 32768), np.float32, 16)
    assert batched_topk_supported((4096, 32768), jnp.bfloat16, 8)
    assert not batched_topk_supported((4096, 32768), np.float32, 17)
    assert not batched_topk_supported((4096, 32768), np.float64, 8)
    assert not batched_topk_supported((4096, 32768), np.float16, 8)
    assert not batched_topk_supported((100, 32768), np.float32, 8)  # B % 64
    assert not batched_topk_supported((4096, 2048), np.float32, 8)  # D < 4096
    assert not batched_topk_supported((4096,), np.float32, 8)


@pytest.mark.parametrize("k", [16])
def test_block_topk_depth4_band(rng, k):
    """The r5 k <= 16 envelope: depth-4 chain + 16-wide bitonic fold,
    random and tie-heavy data, plus the one-lane-hides-winners rescue.
    (k=9..15 run the identical depth-4/fold-16 path with a final slice —
    one k covers it; k=9 is exercised compiled in tpu_smoke.)"""
    x = rng.standard_normal((B, D)).astype(np.float32)
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(x), k))
    np.testing.assert_array_equal(got, _want(x, k))
    xt = rng.integers(0, 11, size=(B, D)).astype(np.float32)
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(xt), k))
    np.testing.assert_array_equal(got, _want(xt, k))
    xa = rng.standard_normal((B, D)).astype(np.float32)
    big = 100.0 + np.arange(16, dtype=np.float32)
    xa[7, 3 + 128 * np.arange(16)] = big  # one lane holds the whole top-16
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(xa), k))
    np.testing.assert_array_equal(got, _want(xa, k))


def test_block_topk_bfloat16(rng):
    """bf16 inputs (r5): the kernels upcast to f32 in-register (Mosaic on
    v5e rejects bf16 vector compares) and the downcast back is exact.
    Values must be BITWISE the bf16 elements; indices pair through the
    public topk()."""
    import jax

    xb = rng.standard_normal((B, D)).astype(jnp.bfloat16)
    # k=8 only: the bf16 k=16 (depth-4) combination costs another ~15 s of
    # interpret trace and runs compiled in tpu_smoke.py every round
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(xb), 8))
    want = np.asarray(jax.lax.top_k(jnp.asarray(xb), 8)[0])
    np.testing.assert_array_equal(got.view(np.uint16), want.view(np.uint16))
    vals, idx = topk(jnp.asarray(xb), 8, method="block")
    rv, ri = jax.lax.top_k(jnp.asarray(xb), 8)
    np.testing.assert_array_equal(
        np.asarray(vals).view(np.uint16), np.asarray(rv).view(np.uint16)
    )
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ri))


def test_topk_block_method_values_and_indices(rng):
    # the public topk() pairing: kernel values + XLA-path indices agree
    x = rng.standard_normal((B, D)).astype(np.float32)
    vals, idx = topk(jnp.asarray(x), 8, method="block")
    want = _want(x, 8)
    np.testing.assert_array_equal(np.asarray(vals), want)
    np.testing.assert_array_equal(
        np.take_along_axis(x, np.asarray(idx), axis=1), want
    )


def test_block_topk_index_recovery_matches_lax(rng):
    """The streaming index recovery (ops/topk.py:_block_topk_indices, r5)
    must reproduce lax.top_k's indices EXACTLY — values, positions, and
    the (value desc, position asc) tie rule — across the edge cases.
    Decoupled from the kernel (values taken from lax.top_k) so the test
    isolates the recovery and stays fast off-TPU."""
    import jax

    from mpi_k_selection_tpu.ops.topk import (
        _block_topk_indices,
        _block_topk_indices_from_values,
    )

    k = 8
    cases = {}
    cases["random"] = rng.standard_normal((B, D)).astype(np.float32)
    cases["ties"] = rng.integers(0, 16, size=(B, D)).astype(np.float32)
    cases["all-equal"] = np.zeros((B, D), np.float32)
    cases["-inf"] = np.full((B, D), -np.inf, np.float32)
    xinf = rng.standard_normal((B, D)).astype(np.float32)
    xinf[5, 100], xinf[5, 200] = np.inf, -np.inf
    cases["inf-mix"] = xinf
    xdup = rng.integers(0, 4, size=(B, D)).astype(np.float32) * 100
    xdup[:, 5] = 1000.0
    xdup[:, 999] = 1000.0
    cases["dup-strict"] = xdup
    # signed zeros at the k boundary: lax.top_k's total order ranks
    # -0.0 < +0.0; the key-space recovery must match (r5 review finding)
    xz = np.full((B, D), -1.0, np.float32)
    xz[:, 0] = -0.0
    xz[:, 1] = 0.0
    cases["signed-zero"] = xz
    xz2 = np.full((B, D), -1.0, np.float32)
    xz2[:, 100:103] = -0.0
    xz2[:, 200:210] = 0.0
    xz2[:, 50] = 7.0
    cases["zeros+big"] = xz2
    for name, x in cases.items():
        xj = jnp.asarray(x)
        v, refidx = jax.lax.top_k(xj, k)
        idx, ok = _block_topk_indices_from_values(xj, v, k)
        assert bool(np.asarray(ok).all()), name  # no rescue needed
        np.testing.assert_array_equal(np.asarray(idx), np.asarray(refidx), err_msg=name)
        full = np.asarray(_block_topk_indices(xj, v, k))
        np.testing.assert_array_equal(full, np.asarray(refidx), err_msg=name)


def test_block_topk_index_recovery_nan_rescue(rng):
    """NaN rows make tau incomparable: the streaming recovery must flag
    them (ok=False) and the bounded rescue must return lax.top_k's own
    answer; over-budget NaN rows must take the full fallback."""
    import jax

    from mpi_k_selection_tpu.ops.topk import (
        _block_topk_indices,
        _block_topk_indices_from_values,
    )

    k = 8
    # NaN winner with a DUPLICATED finite boundary value (r5 review
    # finding): tau stays matchable, every tie slot "finds" a duplicate,
    # and only the NaN-in-values guard routes the row to the rescue
    xd2 = np.zeros((B, D), np.float32)
    xd2[3, 7] = np.nan
    xd2[3, 100] = 5.0
    xd2[3, 200] = 5.0
    from mpi_k_selection_tpu.ops.topk import (
        _block_topk_indices as _bi,
        _block_topk_indices_from_values as _bv,
    )
    xj2 = jnp.asarray(xd2)
    v2, refidx2 = jax.lax.top_k(xj2, 2)
    _, ok2 = _bv(xj2, v2, 2)
    assert not bool(np.asarray(ok2)[3])
    np.testing.assert_array_equal(
        np.asarray(_bi(xj2, v2, 2)), np.asarray(refidx2)
    )

    x = rng.standard_normal((B, D)).astype(np.float32)
    x[3, 7] = np.nan
    x[10, :] = np.nan
    xj = jnp.asarray(x)
    v, refidx = jax.lax.top_k(xj, k)
    idx, ok = _block_topk_indices_from_values(xj, v, k)
    okn = np.asarray(ok)
    assert not okn[3] and not okn[10] and okn.sum() == B - 2
    full = np.asarray(_block_topk_indices(xj, v, k))
    np.testing.assert_array_equal(full, np.asarray(refidx))
    # every row NaN + tiny rescue budget => the lax.cond full fallback
    xall = rng.standard_normal((B, D)).astype(np.float32)
    xall[:, 0] = np.nan
    xj = jnp.asarray(xall)
    v, refidx = jax.lax.top_k(xj, k)
    full = np.asarray(_block_topk_indices(xj, v, k, rescue_rows=4))
    np.testing.assert_array_equal(full, np.asarray(refidx))


def test_pallas_tau_counts_kernel(rng):
    """The r5 tau-threshold count kernel (interpret mode) vs numpy: per
    tile-row counts of keys strictly beyond / equal to a full-width tau,
    across key_op variants, both directions, and pad masking."""
    from mpi_k_selection_tpu.ops.pallas.histogram import pallas_tau_counts
    from mpi_k_selection_tpu.utils.dtypes import to_sortable_bits

    R = 128  # tile rows (must be a multiple of block_rows)
    n = 128 * R - 37  # ragged: the last row is partly pad
    for name, x, key_op, key_xor in [
        ("float", rng.standard_normal(n).astype(np.float32), "float", 0),
        (
            "xor",
            rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32),
            "xor",
            0x80000000,
        ),
    ]:
        raw = x.view(np.uint32)
        tiles = jnp.asarray(
            np.pad(raw, (0, R * 128 - n)).reshape(R, 128).view(np.int32)
        )
        u = np.asarray(to_sortable_bits(jnp.asarray(x)))
        tauk = np.asarray(u[n // 3])
        for largest in (True, False):
            cgt, ceq = pallas_tau_counts(
                tau_key=jnp.asarray(tauk),
                tiles=tiles,
                orig_n=n,
                key_op=key_op,
                key_xor=key_xor,
                largest=largest,
                block_rows=128,
                interpret=True,
            )
            up = np.pad(u, (0, R * 128 - n)).reshape(R, 128)
            valid = (np.arange(R * 128) < n).reshape(R, 128)
            want_b = (((up > tauk) if largest else (up < tauk)) & valid).sum(1)
            want_e = ((up == tauk) & valid).sum(1)
            np.testing.assert_array_equal(
                np.asarray(cgt), want_b, err_msg=f"{name} largest={largest}"
            )
            np.testing.assert_array_equal(np.asarray(ceq), want_e, err_msg=name)


def test_threshold_indices_via_counts_path(rng):
    """The r5 prepared-tiles winner collect (interpret-mode kernel) must
    reproduce lax.top_k indices exactly, incl. ties and smallest-k; off-TPU
    the public topk() takes the jnp fallback, so this drives the fast path
    directly."""
    import jax

    from mpi_k_selection_tpu.ops.radix import _Descent
    from mpi_k_selection_tpu.ops.topk import _threshold_indices_via_counts

    n, k = 1 << 14, 32
    for name, x in [
        ("random", rng.standard_normal(n).astype(np.float32)),
        ("ties", rng.integers(0, 40, size=n).astype(np.float32)),
    ]:
        xj = jnp.asarray(x)
        # force the pallas raw-tile preparation (interpret mode off-TPU) —
        # "auto" resolves to tile-less jnp methods on the CPU test host.
        # tau comes from the numpy oracle, not _select_key_on_prep: the
        # descent's 8 interpret-mode passes cost ~9 s here and are covered
        # by their own tests; this test isolates the collect
        prep = _Descent(xj, None, "pallas", 32768, block_rows=128)
        assert prep.count_tiles is not None and len(prep.tiles) == 1
        from mpi_k_selection_tpu.utils.dtypes import to_sortable_bits

        s = np.sort(x, kind="stable")
        tauk = jnp.asarray(np.asarray(to_sortable_bits(jnp.asarray(s[n - k]))))
        idx = np.asarray(_threshold_indices_via_counts(prep, tauk, k, True))
        _, ref = jax.lax.top_k(xj, k)
        np.testing.assert_array_equal(idx, np.asarray(ref), err_msg=name)
        # smallest-k: mirror rank + direction
        tauk2 = jnp.asarray(np.asarray(to_sortable_bits(jnp.asarray(s[k - 1]))))
        idx2 = np.asarray(_threshold_indices_via_counts(prep, tauk2, k, False))
        want2 = np.argsort(x, kind="stable")[:k]
        np.testing.assert_array_equal(idx2, want2, err_msg=name)


def test_block_topk_nan_rows(rng):
    # NaN floods a lane's chain registers; isnan(lane3) must flag the row
    # so the lax.top_k rescue handles it instead of returning flood garbage
    x = rng.standard_normal((B, D)).astype(np.float32)
    x[11, 77] = np.nan
    x[30, 3999] = np.nan
    got = np.asarray(pallas_batched_topk_values(jnp.asarray(x), 8))
    want = np.asarray(
        __import__("jax").lax.top_k(jnp.asarray(x), 8)[0]
    )  # rescue contract: same as lax.top_k for NaN rows
    np.testing.assert_array_equal(got[[11, 30]], want[[11, 30]])
    clean = np.setdiff1d(np.arange(B), [11, 30])
    np.testing.assert_array_equal(got[clean], _want(x, 8)[clean])
