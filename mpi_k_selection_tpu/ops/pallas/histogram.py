"""Pallas TPU radix-histogram kernel — the production hot loop.

This is the hand-written replacement for the reference's hot local compute
(the per-shard ``qsort`` at ``TODO-kth-problem-cgm.c:115`` and the linear
L/E/G counting sweep at ``:175-185``): one streaming pass over the shard that
counts radix-digit occurrences among elements matching the current prefix.

Kernel design (per the TPU architecture, not the reference's C loops):

- The input is viewed as ``(M, 128)`` — lanes are the fast axis — and the
  grid walks row-blocks of ``block_rows`` rows. Each step DMAs one block to
  VMEM (Pallas double-buffers automatically) and the VPU computes a
  *per-lane* histogram: ``blockhist[b, lane] = #{rows: digit == b}``.
  Keeping 128 independent lane-histograms avoids any cross-lane reduction
  inside the kernel; the tiny ``(nbuckets, 128)`` accumulator is summed over
  lanes once at the end, outside the kernel.
- Buckets are enumerated statically (``nbuckets`` compares of a
  ``(block_rows, 128)`` tile per step), so everything is dense VPU work with
  no scatter, no gather, no dynamic shapes. With ``radix_bits=4`` the
  compute is ~16 ops/element/pass, comfortably under the HBM-bandwidth
  roofline, so the streaming read dominates — the kernel runs at memory
  speed.
- The active-element predicate (key's high bits == prefix) and the padded
  tail are folded into one mask; the prefix is a traced scalar in SMEM, so
  every radix pass reuses the same compiled kernel.

Only 32-bit-and-narrower keys go through the kernel (TPU vector lanes are
32-bit); 64-bit keys fall back to the XLA one-hot path in ops/histogram.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128


def _hist_kernel(prefix_ref, keys_ref, out_ref, *, shift, radix_bits, has_prefix, n_rows_valid, block_rows):
    """One grid step: per-lane histogram of one (block_rows, 128) key block."""
    i = pl.program_id(0)
    k = keys_ref[:]  # (block_rows, LANES) int32 (bit-pattern of the uint key)
    nb = 1 << radix_bits
    mask_val = nb - 1
    # logical shift on the int32 bit pattern = shift on the uint32 key
    digits = jax.lax.shift_right_logical(k, jnp.int32(shift)) & jnp.int32(mask_val)
    # padded tail rows (the wrapper pads whole rows) are never valid
    row0 = i * block_rows
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (block_rows, LANES), 0)
    active = rows < n_rows_valid
    if has_prefix:
        high = jax.lax.shift_right_logical(k, jnp.int32(shift + radix_bits))
        active = jnp.logical_and(active, high == prefix_ref[0, 0])

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    block = [
        jnp.sum(
            jnp.logical_and(active, digits == jnp.int32(b)),
            axis=0,
            dtype=jnp.int32,
        )
        for b in range(nb)
    ]
    out_ref[:] += jnp.stack(block)


@functools.partial(
    jax.jit,
    static_argnames=("shift", "radix_bits", "block_rows", "interpret", "count_dtype"),
)
def pallas_radix_histogram(
    keys: jax.Array,
    *,
    shift: int,
    radix_bits: int,
    prefix=None,
    count_dtype=jnp.int32,
    block_rows: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Histogram of the ``radix_bits`` digit at ``shift`` over active keys.

    Same contract as ``masked_radix_histogram`` (ops/histogram.py): ``keys``
    unsigned <= 32 bits, active means ``keys >> (shift + radix_bits) ==
    prefix`` (all active when ``prefix`` is None). Returns ``(2**radix_bits,)``
    counts in ``count_dtype``.
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas histogram kernel is not available in this jax build"
        )
    keys = keys.ravel()
    if keys.dtype.itemsize > 4:
        raise ValueError("the pallas histogram kernel supports <=32-bit keys")
    if keys.dtype != jnp.uint32:
        keys = keys.astype(jnp.uint32)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = keys.shape[0]
    nb = 1 << radix_bits

    # view as (rows, 128) lanes; pad to whole blocks of rows
    n_rows = -(-n // LANES)
    n_rows_valid = n // LANES  # full rows; a ragged last row is masked below
    ragged = n - n_rows_valid * LANES
    grid = -(-n_rows // block_rows)
    pad_to = grid * block_rows * LANES
    kp = jnp.pad(keys, (0, pad_to - n))
    # a ragged final row would need per-lane masking; fold it in by counting
    # the ragged elements with the XLA path and adding (rare: n % 128 != 0)
    k2d = jax.lax.bitcast_convert_type(
        kp.reshape(grid * block_rows, LANES), jnp.int32
    )

    has_prefix = prefix is not None
    pref = jnp.asarray(prefix if has_prefix else 0, jnp.uint32)
    pref = jax.lax.bitcast_convert_type(pref, jnp.int32).reshape(1, 1)

    kernel = functools.partial(
        _hist_kernel,
        shift=shift,
        radix_bits=radix_bits,
        has_prefix=has_prefix,
        n_rows_valid=n_rows_valid,
        block_rows=block_rows,
    )
    lane_hist = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((nb, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb, LANES), jnp.int32),
        interpret=interpret,
    )(pref, k2d)
    hist = jnp.sum(lane_hist, axis=1, dtype=count_dtype)

    if ragged:
        tail = keys[n_rows_valid * LANES :]
        tdig = (tail >> jnp.uint32(shift)) & jnp.uint32(nb - 1)
        tact = jnp.ones(tail.shape, bool)
        if has_prefix:
            tact = (tail >> jnp.uint32(shift + radix_bits)) == jnp.asarray(
                prefix, jnp.uint32
            )
        thist = jnp.zeros((nb,), count_dtype).at[tdig.astype(jnp.int32)].add(
            tact.astype(count_dtype)
        )
        hist = hist + thist
    return hist
