"""Typed exceptions shared across the package.

The reference signals every failure as a process exit (``MPI_Abort``,
``TODO-kth-problem-cgm.c:58``); a library needs typed errors so callers can
distinguish "this machine cannot run it" from "the run failed".
"""

from __future__ import annotations


class NativeUnavailableError(RuntimeError):
    """The native (C++) runtime cannot be built/loaded on this machine —
    e.g. no C++ toolchain. Environmental, not a bug: harness code (bench.py)
    treats it as a tolerable skip, while any other exception from the native
    backend is a real failure."""


class SpillError(RuntimeError):
    """Misuse of the streaming spill store (streaming/spill.py): reading an
    empty/closed store, writing after commit, and similar lifecycle errors."""


class SpillRecordError(SpillError):
    """A spill record on disk failed validation — missing file, truncated
    header/payload, or a checksum/metadata mismatch. Raised BEFORE any key
    reaches a histogram: a corrupt spill cache must fail loudly, never feed
    the descent silently wrong survivors."""


class SpillCapacityError(SpillError):
    """The spill store ran out of disk (ENOSPC) in a mode that cannot
    degrade: ``spill="force"`` and caller-owned stores asked for the spill
    explicitly, so a silent fallback to the replay path would hide a real
    capacity problem. ``spill="auto"`` descents degrade to the replay of
    the last good generation instead of raising this (a warning
    FaultEvent marks the downgrade) — see docs/ROBUSTNESS.md."""


class TransientError(RuntimeError):
    """A failure the caller believes is retryable — a chunk-source hiccup,
    a staging transfer blip. The resilience policies
    (faults/policy.py:RetryPolicy) retry exactly this class (plus
    ``ConnectionError``/``TimeoutError``) with bounded backoff; anything
    else propagates immediately, because retrying a logic error just
    repeats it. The fault-injection harness raises this for its
    ``"raise"`` fault kind, so injected transients exercise the same
    recovery path real ones take."""


class RetryExhaustedError(RuntimeError):
    """A :class:`~mpi_k_selection_tpu.faults.RetryPolicy` ran out of
    attempts: the operation kept failing with transient errors past
    ``max_attempts``. Carries ``site`` (which operation) and ``attempts``;
    the last underlying error rides ``__cause__``."""

    def __init__(self, message: str, *, site: str = "", attempts: int = 0):
        super().__init__(message)
        self.site = site
        self.attempts = attempts
