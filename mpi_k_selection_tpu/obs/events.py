"""Typed descent telemetry events and pluggable sinks.

The reference's entire observability story is two wall-clock pairs
(``clock()`` in ``kth-problem-seq.c:30,35``, ``MPI_Wtime()`` in
``TODO-kth-problem-cgm.c:76,279``); the framework-grade replacement needs
to answer *what the descent actually did* — which prefixes survived each
pass, how many keys crossed the host->device boundary, which chip each
chunk landed on, how fast the spill generations shrank — not only how
long it took. This module is the event half of that story:

- every radix pass of the streaming descent (replay, spill, and collect
  paths) emits one :class:`StreamPassEvent`; every consumed chunk emits a
  :class:`ChunkEvent` carrying its round-robin device slot; spill
  generation commits emit :class:`SpillGenerationEvent`; the resident and
  distributed entry shells emit :class:`ResidentSelectEvent` /
  :class:`DistributedSelectEvent` (their pass loops are jit-traced, so
  per-pass granularity is a streaming-only capability — see
  docs/OBSERVABILITY.md).
- events are *frozen dataclasses*: pure observations of host integers the
  descent already computed. Emission can therefore never perturb an
  answer — the bit-identical-with-sinks-on/off contract tests/test_obs.py
  enforces over the devices x pipeline_depth x spill grid.
- sinks are pluggable and OFF by default: with no
  :class:`~mpi_k_selection_tpu.obs.Observability` passed, the descent
  skips every emission behind one ``obs is None`` check.

:func:`check_stream_invariants` encodes the event stream's structural
contract (monotone pass indices, per-rank survivor populations
non-increasing, bytes consistent with a spill store's ``pass_log``) —
shared by the unit tests and ``__graft_entry__``'s gauntlet case 10.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import ClassVar


@dataclasses.dataclass(frozen=True)
class ObsEvent:
    """Base telemetry event. ``kind`` names the event type; ``as_dict``
    is the JSON-ready form every sink/exporter shares."""

    kind: ClassVar[str] = "event"

    def as_dict(self) -> dict:
        d = {"event": self.kind}
        d.update(dataclasses.asdict(self))
        return d


@dataclasses.dataclass(frozen=True)
class StreamPassEvent(ObsEvent):
    """One streamed radix pass of the exact descent (pass 0, every later
    prefix-filtered pass, and the final collect as ``pass_index
    "collect"``).

    ``survivors`` is the per-rank population tuple AFTER this pass's
    bucket walk, aligned with the descent's rank order and covering every
    rank (parked ranks keep their last population) — so consecutive
    events are elementwise non-increasing, the geometric-shrink contract
    :func:`check_stream_invariants` checks.
    """

    kind: ClassVar[str] = "stream.pass"

    pass_index: object  # int radix level, or "collect"
    resolved_bits: int
    prefixes: tuple  # active (being-histogrammed) prefixes this pass
    chunks: int  # chunks consumed
    keys_read: int
    bytes_read: int
    read_from: str  # "source" | "spill"
    bucket_total: int  # total population counted across prefixes
    bucket_max: int  # heaviest single bucket
    bucket_nonzero: int  # buckets holding >= 1 key
    survivors: tuple  # per-rank populations after the walk
    keys_written: int | None = None  # spill survivors written (None = no tee)
    bytes_written: int | None = None
    #: PHYSICAL bytes moved (spill.py's on-disk record payloads, packed
    #: when ``pack_spill`` engaged) vs the LOGICAL ``bytes_read`` /
    #: ``bytes_written`` above (keys x itemsize, the descent-algebra
    #: unit). Written physical <= written logical always — the packer
    #: falls back to the unpacked v1 format per record rather than ever
    #: inflating. Read physical prices what a (possibly PRUNED) replay
    #: actually touches: matching segments plus each record's directory,
    #: so it can exceed the logical column on small heavily-pruned reads
    #: while collapsing far below it on the big early ones. ``None`` on
    #: old event streams only; source-read passes report physical ==
    #: logical (the source hands keys at full width).
    disk_bytes_read: int | None = None
    disk_bytes_written: int | None = None


@dataclasses.dataclass(frozen=True)
class ChunkEvent(ObsEvent):
    """One chunk consumed by a streamed pass: size, staged bytes, and the
    round-robin device slot it landed on (``None`` = host-resident or the
    uncommitted default-device path) — the chunk->device assignment
    record."""

    kind: ClassVar[str] = "stream.chunk"

    pass_index: object
    chunk_index: int
    n: int
    nbytes: int
    device_slot: int | None
    staged: bool


@dataclasses.dataclass(frozen=True)
class SpillGenerationEvent(ObsEvent):
    """One committed spill generation (pass-0 tee or a filtered survivor
    write): its record count, key count and payload bytes. ``nbytes`` is
    the PHYSICAL on-disk payload total; ``logical_nbytes`` (keys x
    itemsize) is what those keys cost unpacked, so ``nbytes /
    logical_nbytes`` is the generation's disk compression ratio when
    ``packed`` (any record in the v2 prefix-packed format) is True —
    and the two are equal when it is False."""

    kind: ClassVar[str] = "spill.generation"

    generation: int
    records: int
    keys: int
    nbytes: int
    logical_nbytes: int | None = None
    packed: bool = False


@dataclasses.dataclass(frozen=True)
class SketchPassEvent(ObsEvent):
    """One ``RadixSketch.update_stream`` accumulation pass."""

    kind: ClassVar[str] = "sketch.pass"

    chunks: int
    keys_read: int
    bytes_read: int
    staged_chunks: int


@dataclasses.dataclass(frozen=True)
class CertificateEvent(ObsEvent):
    """One streamed rank-certificate pass: the (less, leq) counts."""

    kind: ClassVar[str] = "certificate.pass"

    chunks: int
    keys_read: int
    less: int
    leq: int


@dataclasses.dataclass(frozen=True)
class ResidentSelectEvent(ObsEvent):
    """One resident (in-core) selection dispatch at the api shell. The
    pass loop itself is jit-traced — per-pass events are streaming-only."""

    kind: ClassVar[str] = "resident.select"

    n: int
    queries: int
    algorithm: str
    dtype: str


@dataclasses.dataclass(frozen=True)
class DistributedSelectEvent(ObsEvent):
    """One distributed selection dispatch at the parallel/ entry shell."""

    kind: ClassVar[str] = "distributed.select"

    n: int
    queries: int
    n_devices: int
    radix_bits: int
    cutover_passes: int | None
    dtype: str


@dataclasses.dataclass(frozen=True)
class ServeQueryEvent(ObsEvent):
    """One client request answered by the query server (serve/server.py):
    which dataset and op, the tier requested vs the tier that answered
    (``tier_requested`` is None for non-tiered ops), how many rank
    queries the request carried, and whether auto escalated it from
    sketch to exact."""

    kind: ClassVar[str] = "serve.query"

    dataset: str
    op: str  # kselect | quantiles | topk | rank_certificate
    tier_requested: str | None
    tier_answered: str
    queries: int
    escalated: bool
    #: request-correlation id (docs/OBSERVABILITY.md "Trace IDs"): minted
    #: per query by the server (or honored from the client's
    #: ``X-Ksel-Trace-Id``); ``None`` for embedding callers that pass none
    trace_id: str | None = None


@dataclasses.dataclass(frozen=True)
class FaultEvent(ObsEvent):
    """One fault observation: an injected fault firing, or a resilience
    policy acting on a (real or injected) failure. ``action`` is the
    lifecycle step:

    - ``"inject"``  — the harness fired a scheduled fault (site/kind/
      index/attempt name it);
    - ``"retry"``   — a RetryPolicy is retrying after a transient error;
    - ``"reread"``  — the spill recovery ladder is re-reading a
      generation after a record validation failure;
    - ``"rebuild"`` — the ladder gave up on the generation and is
      re-running the pass from its fallback (the replayable source, or a
      one-shot run's gen-0 tee);
    - ``"degrade"`` — ENOSPC downgraded ``spill="auto"`` to the replay
      of the last good generation (spilling disabled for the rest of the
      descent);
    - ``"shed"``    — the query server refused admission (queue depth
      bound);
    - ``"deadline"``— a request's deadline expired (failed fast);
    - ``"restart"`` — the batcher's dispatch loop crashed and was
      restarted (in-flight queries failed, queued ones survive).

    ``error`` is the triggering exception rendered as
    ``"TypeName: message"`` (empty for injections and sheds). Pure host
    observation, like every event here: emitting can never change an
    answer bit."""

    kind: ClassVar[str] = "fault"

    site: str
    action: str
    fault_kind: str | None = None
    index: int | None = None
    attempt: int = 0
    error: str = ""


@dataclasses.dataclass(frozen=True)
class ServeBatchEvent(ObsEvent):
    """One coalesced dispatch of the query server's batcher: how many
    client requests rode the shared-pass walk and the total rank-query
    width they coalesced into. ``trace_ids`` are the request-correlation
    ids of every query in the group (docs/OBSERVABILITY.md "Trace IDs"),
    so one slow walk is joinable back to the client requests that rode
    it."""

    kind: ClassVar[str] = "serve.batch"

    dataset: str
    requests: int
    width: int
    trace_ids: tuple = ()


@dataclasses.dataclass(frozen=True)
class RecompileStormEvent(ObsEvent):
    """The runtime twin of KSC103/KSL010 (obs/ledger.py): one dispatch
    site's distinct-program compile count crossed the ledger's storm
    threshold — the site is serving shape/width churn at compile latency.
    Emitted on the crossing compile and every later one; ``key`` is the
    repr of the compile key that triggered it, ``compiles`` the site's
    distinct-key compile total at emission."""

    kind: ClassVar[str] = "ledger.recompile_storm"

    site: str
    key: str
    compiles: int
    threshold: int


class EventSink:
    """Sink protocol: ``emit`` receives every event. Implementations must
    be thread-safe — the pipelined descent emits from both the producer
    and the consumer thread."""

    def emit(self, event: ObsEvent) -> None:  # pragma: no cover - protocol
        raise NotImplementedError


class ListSink(EventSink):
    """Collects events in arrival order (thread-safe append). The default
    sink for tests, the gauntlet, and post-run analysis."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events: list[ObsEvent] = []  # ksel: guarded-by[_lock]

    def emit(self, event: ObsEvent) -> None:
        with self._lock:
            self.events.append(event)

    def of_kind(self, kind: str) -> list[ObsEvent]:
        with self._lock:
            return [e for e in self.events if e.kind == kind]

    def __len__(self) -> int:
        with self._lock:
            return len(self.events)


class CallbackSink(EventSink):
    """Adapts a plain callable into a sink (the caller owns its thread
    safety — it may be invoked from the producer thread)."""

    def __init__(self, fn):
        self._fn = fn

    def emit(self, event: ObsEvent) -> None:
        self._fn(event)


def check_stream_invariants(events, spill_pass_log=None) -> None:
    """Assert the structural contract of one descent's event stream;
    raises ``AssertionError`` naming the first violation.

    - at least one :class:`StreamPassEvent`, integer pass indices strictly
      increasing, any ``"collect"`` event last;
    - per-rank ``survivors`` tuples elementwise non-increasing pass over
      pass (the descent only ever narrows), each bounded by that pass's
      ``keys_read``;
    - ``bucket_total`` accounting: pass 0 counts the whole stream
      (``bucket_total == keys_read``); later passes count only the
      surviving active-prefix populations, so ``bucket_total`` is bounded
      by ``keys_read`` and non-increasing pass over pass;
    - the terminal collect event carries the honest per-spec accounting
      (the executor knows every spec's survivor count at drain time):
      ``survivors`` aligns with ``prefixes`` one collected population per
      spec, each >= 1 (a collect spec is a walked bucket holding the
      rank), ``bucket_total`` is their sum and ``bucket_max`` their max,
      all bounded by that pass's ``keys_read``;
    - chunk events: per-pass chunk indices 0..chunks-1 in order, sizes
      summing to ``keys_read``, staged slots well-formed;
    - physical vs logical byte accounting on the WRITE side:
      ``disk_bytes_written <= bytes_written`` on every pass that reports
      them — the prefix packer never inflates a record (it falls back to
      the unpacked v1 format per record). The read side carries no such
      bound: a PRUNED replay reads each record's segment directory, bytes
      the logical column (keys streamed x itemsize) does not see, so
      small heavily-pruned reads can price more disk than logical bytes;
    - with ``spill_pass_log`` (a ``SpillStore.pass_log``): the events'
      bytes_read/bytes_written AND disk_bytes_read/disk_bytes_written
      match the store's log entry for entry.
    """
    passes = [e for e in events if isinstance(e, StreamPassEvent)]
    assert passes, "no StreamPassEvent emitted"
    int_idx = [e.pass_index for e in passes if isinstance(e.pass_index, int)]
    assert int_idx == sorted(set(int_idx)), (
        f"pass indices not strictly increasing: {int_idx}"
    )
    for e in passes[:-1]:
        assert e.pass_index != "collect", "collect event is not last"
    prev = None
    for e in passes:
        if e.pass_index == "collect":
            assert len(e.survivors) == len(e.prefixes), (
                f"collect: {len(e.survivors)} survivor populations for "
                f"{len(e.prefixes)} specs"
            )
            assert all(s >= 1 for s in e.survivors), (
                f"collect: empty spec population in {e.survivors} — every "
                "collect spec is a walked bucket holding its rank"
            )
            assert e.bucket_total == sum(e.survivors), (
                f"collect: bucket_total {e.bucket_total} != "
                f"sum(survivors) {sum(e.survivors)}"
            )
            assert e.bucket_max == max(e.survivors, default=0), (
                f"collect: bucket_max {e.bucket_max} != max(survivors)"
            )
            assert e.bucket_total <= e.keys_read, (
                f"collect: collected {e.bucket_total} exceeds keys_read "
                f"{e.keys_read}"
            )
            continue
        assert len(e.survivors) >= 1, f"pass {e.pass_index}: no survivors tuple"
        assert all(0 <= s <= e.keys_read for s in e.survivors), (
            f"pass {e.pass_index}: survivors {e.survivors} exceed "
            f"keys_read {e.keys_read}"
        )
        assert e.bucket_max <= e.bucket_total, f"pass {e.pass_index}: bucket summary"
        assert e.bucket_total <= e.keys_read, (
            f"pass {e.pass_index}: bucket_total {e.bucket_total} exceeds "
            f"keys_read {e.keys_read}"
        )
        if e.pass_index == 0 and not e.prefixes:
            # the unfiltered length-scan pass counts EVERY key it read
            assert e.bucket_total == e.keys_read, (
                f"pass 0: bucket_total {e.bucket_total} != keys_read "
                f"{e.keys_read} on the unfiltered pass"
            )
        if prev is not None:
            assert e.bucket_total <= prev.bucket_total, (
                f"pass {e.pass_index}: counted population {e.bucket_total} "
                f"grew past the previous pass's {prev.bucket_total}"
            )
            assert len(e.survivors) == len(prev.survivors), (
                "rank count changed mid-descent"
            )
            assert all(
                s <= p for s, p in zip(e.survivors, prev.survivors)
            ), (
                f"pass {e.pass_index}: survivors {e.survivors} grew past "
                f"{prev.survivors}"
            )
        prev = e
    by_pass: dict = {}
    for c in events:
        if isinstance(c, ChunkEvent):
            by_pass.setdefault(c.pass_index, []).append(c)
    for e in passes:
        chunks = by_pass.get(e.pass_index, [])
        if not chunks:  # chunk events off, or a zero-chunk pass
            continue
        # a recovered pass (faults/policy.py: pass-level retry, spill
        # rebuild) re-ran its chunk loop, so the pass may carry chunk
        # events from ABORTED attempts before the successful one; only
        # the final attempt — the run from the LAST chunk_index == 0
        # onward — describes the pass the StreamPassEvent accounts.
        # Fault-free streams have exactly one such run, so this is the
        # historical strict check there.
        zeros = [i for i, c in enumerate(chunks) if c.chunk_index == 0]
        if zeros:
            chunks = chunks[zeros[-1]:]
        assert [c.chunk_index for c in chunks] == list(range(e.chunks)), (
            f"pass {e.pass_index}: chunk indices out of order"
        )
        assert sum(c.n for c in chunks) == e.keys_read, (
            f"pass {e.pass_index}: chunk sizes sum to "
            f"{sum(c.n for c in chunks)}, keys_read {e.keys_read}"
        )
        for c in chunks:
            assert c.device_slot is None or c.device_slot >= 0
    for e in passes:
        if e.disk_bytes_written is not None:
            assert e.bytes_written is not None, (
                f"pass {e.pass_index}: disk_bytes_written without a tee"
            )
            assert e.disk_bytes_written <= e.bytes_written, (
                f"pass {e.pass_index}: disk_bytes_written "
                f"{e.disk_bytes_written} exceeds logical bytes_written "
                f"{e.bytes_written} — the packer must never inflate a record"
            )
    if spill_pass_log is not None:
        logged = {entry["pass"]: entry for entry in spill_pass_log}
        for e in passes:
            entry = logged.get(e.pass_index)
            if entry is None:
                continue
            assert e.bytes_read == entry["bytes_read"], (
                f"pass {e.pass_index}: event bytes_read {e.bytes_read} != "
                f"pass_log {entry['bytes_read']}"
            )
            if e.bytes_written is not None:
                assert e.bytes_written == entry.get("bytes_written"), (
                    f"pass {e.pass_index}: event bytes_written "
                    f"{e.bytes_written} != pass_log "
                    f"{entry.get('bytes_written')}"
                )
            if e.disk_bytes_read is not None and "disk_bytes_read" in entry:
                assert e.disk_bytes_read == entry["disk_bytes_read"], (
                    f"pass {e.pass_index}: event disk_bytes_read "
                    f"{e.disk_bytes_read} != pass_log "
                    f"{entry['disk_bytes_read']}"
                )
            if e.disk_bytes_written is not None:
                assert e.disk_bytes_written == entry.get(
                    "disk_bytes_written"
                ), (
                    f"pass {e.pass_index}: event disk_bytes_written "
                    f"{e.disk_bytes_written} != pass_log "
                    f"{entry.get('disk_bytes_written')}"
                )
