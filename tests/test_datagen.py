"""Seeded generators: determinism, dtype, pattern shape."""

import numpy as np
import pytest

from mpi_k_selection_tpu.utils import datagen


def test_deterministic():
    a = datagen.generate(1000, pattern="uniform", seed=42)
    b = datagen.generate(1000, pattern="uniform", seed=42)
    np.testing.assert_array_equal(a, b)
    c = datagen.generate(1000, pattern="uniform", seed=43)
    assert not np.array_equal(a, c)


@pytest.mark.parametrize("pattern", datagen.PATTERNS)
def test_patterns_shape_dtype(pattern):
    dtype = np.float32 if pattern in ("normal", "funiform") else np.int32
    x = datagen.generate(512, pattern=pattern, seed=0, dtype=dtype)
    assert x.shape == (512,)
    assert x.dtype == dtype


def test_uniform_matches_reference_range():
    # rand() % 99999999 + 1 (TODO-kth-problem-cgm.c:15) -> values in [1, 99999999]
    x = datagen.generate(100_000, pattern="uniform", seed=1)
    assert x.min() >= 1 and x.max() <= 99_999_999


def test_descending_sequential_equal():
    d = datagen.generate(10, pattern="descending")
    np.testing.assert_array_equal(d, np.arange(10, 0, -1))
    s = datagen.generate(10, pattern="sequential")
    np.testing.assert_array_equal(s, np.arange(1, 11))
    e = datagen.generate(10, pattern="equal")
    assert len(np.unique(e)) == 1


def test_batched():
    x = datagen.generate(64, pattern="normal", dtype=np.float32, batch=(4, 3))
    assert x.shape == (4, 3, 64)


def test_adversarial_fixtures():
    fx = datagen.adversarial_fixtures(256, dtype=np.int32)
    names = [n for n, _ in fx]
    assert "equal" in names and "extremes" in names
    for _, arr in fx:
        assert arr.shape == (256,)
