"""Native (C++) runtime: sequential selection engine + multi-process CGM
collectives — the compiled layer mirroring the reference's gcc/MPICH
binaries (`seq`, `todo`). See kselect_native.cpp."""

from mpi_k_selection_tpu.native import cgm_driver, loader  # noqa: F401
