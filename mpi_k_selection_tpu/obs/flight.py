"""Flight recorder — a bounded ring of recent telemetry plus the
fault-triggered JSON debug bundle.

"What exactly happened in the 10 seconds before this query failed?" is a
postmortem question, and answering it from live sinks means having had
every channel on and exporting continuously. The flight recorder is the
cheap standing alternative: a fixed-size ring of the most recent typed
events and a ring of the most recent host spans (it implements the
PhaseTimer recorder protocol, so it rides the same KSL004-sanctioned
clock route as the trace recorder), appended O(1) under a lock, off by
default — attach one as the ``flight`` channel of an
:class:`~mpi_k_selection_tpu.obs.Observability` (or the query server's
``flight=`` knob) and every emission/span it sees is retained, oldest
evicted first.

On demand (:meth:`~mpi_k_selection_tpu.serve.server.KSelectServer.
debug_bundle`, HTTP ``GET /debug/bundle``, CLI ``--debug-bundle PATH``)
— or automatically, ONCE per recorder, on a terminal failure
(``RetryExhaustedError`` / unrecoverable spill damage in the descent's
recovery ladder, ``DispatchCrashedError`` in the serve supervisor) — the
ring dumps a single JSON **debug bundle** with five always-present
sections (docs/OBSERVABILITY.md "Flight recorder & debug bundle"):

- ``events``   — the typed-event tail (FaultEvents included), in order;
- ``metrics``  — the live registry snapshot (ledger gauges folded in);
- ``ledger``   — the process ProgramLedger snapshot (compiles, bytes,
  recent recompile storms);
- ``spans``    — the span tail with thread identity (>= 2 tracks on any
  pipelined run) plus the distinct track count;
- ``faults``   — the FaultEvent tail split out, with the armed plan's
  description when the injector is armed;

plus ``lock_order`` (the last LockOrderSanitizer's observed graph, when
one ran) and ``reason``/``trace_ids`` context. Auto-dump paths carry the
``ksel-flight-`` prefix; every dump is registered so the test suite's
conftest fixture validates each bundle and fails leaked ones — the same
discipline as spill temp dirs. Pure host observation throughout:
enabling the recorder never changes an answer bit (tests/test_ledger.py
runs the full devices x depth x spill x fused grid with it on).
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading

from mpi_k_selection_tpu.resource_protocols import FLIGHT_FILE_PREFIX

# FLIGHT_FILE_PREFIX (imported above): auto-dump file prefix (conftest
# leak discipline, like ksel-spill-*). Canonical: resource_protocols.py.

#: Default ring capacities (events / spans kept). Sized for "the last
#: few seconds of a busy run": a streamed pass emits O(chunks) events,
#: so 512 holds several recent passes; tune per recorder via the ctor.
DEFAULT_CAPACITY = 512

#: The five sections every bundle carries (conftest validates them on
#: every dump the suite produces).
BUNDLE_SECTIONS = ("events", "metrics", "ledger", "spans", "faults")

# every bundle path written by this process (auto and on-demand dumps
# alike), drained by the conftest fixture that validates + leak-checks
_DUMPED_LOCK = threading.Lock()
_DUMPED: list[str] = []  # ksel: guarded-by[_DUMPED_LOCK]


def _register_dump(path: str) -> None:
    with _DUMPED_LOCK:
        _DUMPED.append(path)


def drain_dumped() -> list[str]:
    """Return-and-clear the bundle paths written since the last drain
    (the conftest fixture's hook)."""
    with _DUMPED_LOCK:
        out, _DUMPED[:] = list(_DUMPED), []
    return out


class FlightRecorder:
    """The bounded telemetry ring. Thread-safe: events arrive from
    producer/consumer/dispatch threads, spans from whichever thread ran
    the phase (it IS a PhaseTimer recorder). ``dump_dir`` roots the
    auto-dump files (default: the system temp dir)."""

    def __init__(
        self,
        *,
        capacity: int = DEFAULT_CAPACITY,
        span_capacity: int | None = None,
        dump_dir: str | None = None,
    ):
        self._lock = threading.Lock()
        # deques are self-synchronizing for append; the lock makes the
        # snapshot (ordering across both rings + the sequence counter)
        # consistent
        self._events: collections.deque = collections.deque(
            maxlen=max(1, int(capacity))
        )
        self._spans: collections.deque = collections.deque(
            maxlen=max(1, int(span_capacity if span_capacity is not None else capacity))
        )
        self._seq = 0  # ksel: guarded-by[_lock] (events seen, evicted included)
        self._auto_dumped = False  # ksel: guarded-by[_lock]
        self.dump_dir = dump_dir
        self.auto_dumps: list[str] = []  # ksel: guarded-by[_lock]

    # -- appends (O(1)) ----------------------------------------------------

    def record_event(self, event) -> None:
        """Retain one typed obs event (Observability.emit fans in here
        when the flight channel is on)."""
        with self._lock:
            self._seq += 1
            self._events.append((self._seq, event))

    def record(self, name: str, t0: float, t1: float, args=None) -> None:
        """PhaseTimer recorder protocol: retain one finished span with
        its thread identity (no clock is read here — KSL004). ``args``
        carries span context when the phase provides any (the serve
        walk's trace ids)."""
        t = threading.current_thread()
        with self._lock:
            self._spans.append((name, t0, t1, t.ident or 0, t.name, args))

    # -- bundle ------------------------------------------------------------

    def events_tail(self) -> list:
        with self._lock:
            return [e for _, e in self._events]

    def spans_tail(self) -> list:
        """The retained span tuples, oldest first (snapshotted under the
        lock — a producer thread appending mid-copy must not tear it)."""
        with self._lock:
            return list(self._spans)

    def bundle(self, *, obs=None, reason: str = "on-demand", extra=None) -> dict:
        """Assemble the debug-bundle dict (see module docstring for the
        section schema). ``obs`` supplies the live metrics registry;
        ``extra`` merges top-level context keys (server state, trace
        ids)."""
        return build_bundle(obs, reason=reason, flight=self, extra=extra)

    def dump(self, path=None, *, obs=None, reason: str = "on-demand", extra=None) -> str:
        """Write one bundle as JSON. ``path=None`` creates a
        ``ksel-flight-*.json`` file under ``dump_dir`` (or the temp
        dir). Every dump is registered for the conftest validation."""
        payload = self.bundle(obs=obs, reason=reason, extra=extra)
        if path is None:
            fd, path = tempfile.mkstemp(
                prefix=FLIGHT_FILE_PREFIX, suffix=".json", dir=self.dump_dir
            )
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, indent=2, default=str)
        else:
            path = os.fspath(path)
            with open(path, "w") as f:
                json.dump(payload, f, indent=2, default=str)
        _register_dump(path)
        return path

    def maybe_auto_dump(self, reason: str, *, obs=None, exc=None) -> str | None:
        """The fault-triggered dump: at most ONE per recorder (a retry
        storm must not write a bundle per attempt), test-and-set under
        the lock. Returns the path, or None when already dumped."""
        with self._lock:
            if self._auto_dumped:
                return None
            self._auto_dumped = True
        extra = {}
        if exc is not None:
            extra["error"] = f"{type(exc).__name__}: {exc}"
        try:
            path = self.dump(None, obs=obs, reason=reason, extra=extra)
        except BaseException:
            # a failed WRITE must not consume the latch: the trigger is
            # often the very condition (ENOSPC) that fails the dump, and
            # the next terminal failure — after space frees — still
            # deserves its one bundle
            with self._lock:
                self._auto_dumped = False
            raise
        with self._lock:
            self.auto_dumps.append(path)
        return path


def resolve_flight(flight) -> FlightRecorder | None:
    """Normalize a ``flight=`` knob: None/False = off, True = default
    recorder, an int = that ring capacity, a FlightRecorder = itself."""
    if flight is None or flight is False:
        return None
    if flight is True:
        return FlightRecorder()
    if isinstance(flight, FlightRecorder):
        return flight
    if isinstance(flight, int):
        return FlightRecorder(capacity=flight)
    raise ValueError(
        f"flight must be a bool, an int ring capacity, or a "
        f"FlightRecorder, got {flight!r}"
    )


def _lock_order_section():
    """The last LockOrderSanitizer's observed graph, when one ran in
    this process (analysis/lockorder.py records it on exit)."""
    try:
        from mpi_k_selection_tpu.analysis import lockorder
    except Exception:  # pragma: no cover - analysis always importable here
        return None
    return getattr(lockorder, "LAST_OBSERVED", None)


def _faults_section(events) -> dict:
    from mpi_k_selection_tpu.obs.events import FaultEvent

    out = {
        "events": [e.as_dict() for e in events if isinstance(e, FaultEvent)],
        "plan": None,
    }
    try:
        from mpi_k_selection_tpu.faults import inject as _inj

        injector = _inj.active_injector()
        if injector is not None:
            out["plan"] = repr(getattr(injector, "plan", injector))
    except Exception:  # pragma: no cover - faults always importable here
        pass
    return out


def build_bundle(obs, *, reason: str = "on-demand", flight=None, extra=None) -> dict:
    """Assemble one debug bundle from whatever channels exist. Works
    without a flight channel (empty events/spans tails) so the on-demand
    surfaces degrade gracefully; the five BUNDLE_SECTIONS are always
    present."""
    from mpi_k_selection_tpu.obs.ledger import LEDGER

    if flight is None and obs is not None:
        flight = getattr(obs, "flight", None)
    events = flight.events_tail() if flight is not None else []
    spans = flight.spans_tail() if flight is not None else []
    metrics = {}
    if obs is not None and obs.metrics is not None:
        # phase/pool state is folded in by its owners (descent end, the
        # server's collect_metrics); only the ledger mapping is re-run
        # here — idempotent, and bundles built WITHOUT a server in front
        # still get the ledger gauges
        from mpi_k_selection_tpu.obs.ledger import collect_ledger

        collect_ledger(obs.metrics)
        metrics = obs.metrics.as_dict()
    span_rows = [
        {
            "name": name,
            "t0": t0,
            "t1": t1,
            "thread_id": tid,
            "thread": tname,
            "args": args,
        }
        for name, t0, t1, tid, tname, args in spans
    ]
    bundle = {
        "reason": reason,
        "events": [e.as_dict() for e in events],
        "metrics": metrics,
        "ledger": LEDGER.snapshot(),
        "spans": {
            "tail": span_rows,
            "thread_tracks": len({r["thread_id"] for r in span_rows}),
        },
        "faults": _faults_section(events),
        "lock_order": _lock_order_section(),
    }
    if extra:
        bundle.update(extra)
    return bundle


def auto_dump(obs, reason: str, *, exc=None) -> str | None:
    """THE fault-triggered hook the recovery surfaces call (descent
    ladder on RetryExhaustedError / unrecoverable spill damage, serve
    supervisor on DispatchCrashedError): dumps once per recorder; a
    no-op without a flight channel. Never raises — a postmortem artifact
    failing to write must not mask the typed error in flight."""
    flight = getattr(obs, "flight", None) if obs is not None else None
    if flight is None:
        return None
    try:
        return flight.maybe_auto_dump(reason, obs=obs, exc=exc)
    except Exception:  # pragma: no cover - disk-full etc.: the postmortem
        # dump is best-effort by contract — the typed error that triggered
        # it is already propagating, and raising here would replace it
        return None
