"""Order-preserving key transforms: round-trip + ordering vs NumPy sort."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.utils import dtypes as dt
from mpi_k_selection_tpu.utils import x64

from mpi_k_selection_tpu.utils import compat

DTYPES_32 = [np.int32, np.uint32, np.float32, np.int16, np.uint16, np.int8, np.uint8]


def _sample(dtype, n=4097, seed=7):
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    if dtype.kind in "iu":
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, size=n, endpoint=True, dtype=dtype)
        # force extreme values in
        x[:4] = [info.min, info.max, 0, info.max - 1 if dtype.kind == "u" else -1]
        return x
    x = rng.standard_normal(n).astype(dtype) * dtype.type(100)
    x[:5] = [0.0, -0.0, np.finfo(dtype).max, np.finfo(dtype).min, 1.5]
    return x


@pytest.mark.parametrize("dtype", DTYPES_32)
def test_roundtrip(dtype):
    x = _sample(dtype)
    u = dt.to_sortable_bits(jnp.asarray(x))
    back = np.asarray(dt.from_sortable_bits(u, dtype))
    np.testing.assert_array_equal(back, x)


@pytest.mark.parametrize("dtype", DTYPES_32)
def test_order_preserved(dtype):
    x = _sample(dtype)
    u = np.asarray(dt.to_sortable_bits(jnp.asarray(x)))
    order_u = np.argsort(u, kind="stable")
    xs = np.sort(x, kind="stable")
    np.testing.assert_array_equal(x[order_u], xs)


def test_bfloat16_roundtrip_and_order():
    x = jnp.asarray(np.random.default_rng(3).standard_normal(513), dtype=jnp.bfloat16)
    u = dt.to_sortable_bits(x)
    back = dt.from_sortable_bits(u, jnp.bfloat16)
    assert bool(jnp.all(back == x))
    xs = np.asarray(jax.lax.sort(x).astype(jnp.float32))
    xu = np.asarray(x.astype(jnp.float32))[np.argsort(np.asarray(u), kind="stable")]
    np.testing.assert_array_equal(xu, xs)


def test_int64_requires_x64():
    assert not jax.config.jax_enable_x64
    with pytest.raises(ValueError, match="64-bit"):
        dt._require_x64(np.int64)


def test_int64_roundtrip_under_x64():
    with x64.enable_x64():
        x = jnp.asarray(
            np.random.default_rng(5).integers(-(2**62), 2**62, size=257, dtype=np.int64)
        )
        u = dt.to_sortable_bits(x)
        assert u.dtype == jnp.uint64
        back = np.asarray(dt.from_sortable_bits(u, np.int64))
        np.testing.assert_array_equal(back, np.asarray(x))
        order_u = np.argsort(np.asarray(u), kind="stable")
        np.testing.assert_array_equal(np.asarray(x)[order_u], np.sort(np.asarray(x)))


def test_f64_raw_bits_matches_bitcast_exhaustive():
    """The arithmetic IEEE-bit construction (the TPU path — f64-source
    bitcasts crash that compiler) must be bit-exact vs the real bitcast for
    every exponent, both signs, denormals, -0.0, infinities; NaN
    canonicalizes to +0x7FF8000000000000 by contract."""
    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu.utils.dtypes import f64_raw_bits

    with compat.enable_x64(True):
        rng = np.random.default_rng(99)
        # every NORMAL binary exponent (XLA flushes f64 denormals to zero in
        # compiled arithmetic, so the contract maps them to signed zero)
        mant = 1.0 + rng.random(2046)          # [1, 2)
        exps = np.arange(-1022, 1024)
        vals = np.ldexp(mant, exps)
        vals = np.concatenate([
            vals, -vals,
            np.array([0.0, -0.0, np.inf, -np.inf, np.finfo(np.float64).max,
                      np.finfo(np.float64).tiny]),
            rng.standard_normal(4096),
        ])
        got = np.asarray(f64_raw_bits(jnp.asarray(vals)))
        want = vals.view(np.uint64)
        np.testing.assert_array_equal(got, want)
        # denormals -> signed zero (FTZ contract), NaN -> canonical quiet NaN
        spec = np.array([5e-324, -5e-324, 1e-310, np.nan, -np.nan])
        got_s = np.asarray(f64_raw_bits(jnp.asarray(spec)))
        want_s = np.array(
            [0, 1 << 63, 0, 0x7FF8000000000000, 0x7FF8000000000000],
            dtype=np.uint64,
        )
        np.testing.assert_array_equal(got_s, want_s)


def test_sortable_from_raw_bits_matches_to_sortable():
    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu.utils.dtypes import (
        sortable_from_raw_bits,
        to_sortable_bits,
    )

    rng = np.random.default_rng(7)
    with compat.enable_x64(True):
        for dtype in (np.int32, np.uint32, np.float32, np.int64, np.uint64,
                      np.float64):
            dtype = np.dtype(dtype)
            if dtype.kind == "f":
                x = rng.standard_normal(4096).astype(dtype)
                x[:2048] = -np.abs(x[:2048])
            elif dtype.kind == "u":
                x = rng.integers(0, 2**(8*dtype.itemsize) - 1, size=4096, dtype=dtype)
            else:
                x = rng.integers(-(2**(8*dtype.itemsize-1)), 2**(8*dtype.itemsize-1) - 1, size=4096, dtype=dtype)
            kdt = np.dtype(f"uint{8*dtype.itemsize}")
            raw = jnp.asarray(x.view(kdt))
            got = np.asarray(sortable_from_raw_bits(raw, dtype))
            want = np.asarray(to_sortable_bits(jnp.asarray(x)))
            np.testing.assert_array_equal(got, want, err_msg=str(dtype))


def test_f64_tpu_host_keys_and_decode_roundtrip(monkeypatch):
    """The f64-on-TPU exact route's host-side halves, unit-tested off-TPU:
    keys must equal the bitcast to_sortable transform, the decode must
    invert bit-exactly (incl. -0.0 and infinities), and without x64 the
    route must raise instead of silently truncating keys to uint32."""
    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu.ops import radix as radix_mod
    from mpi_k_selection_tpu.utils.dtypes import to_sortable_bits

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    rng = np.random.default_rng(3)
    x = np.concatenate([
        rng.standard_normal(4096),
        np.array([0.0, -0.0, np.inf, -np.inf, np.finfo(np.float64).max]),
    ])
    with compat.enable_x64(True):
        keys = radix_mod._f64_tpu_host_keys(x)
        assert keys is not None and keys.dtype == jnp.uint64
        want = np.asarray(to_sortable_bits(jnp.asarray(x)))
        np.testing.assert_array_equal(np.asarray(keys), want)
        # key order == value order
        order_k = np.argsort(np.asarray(keys), kind="stable")
        order_v = np.argsort(x, kind="stable")
        np.testing.assert_array_equal(x[order_k], x[order_v])
        # decode inverts bit-exactly (host-side, no device round trip)
        back = radix_mod._f64_from_keys_host(keys)
        np.testing.assert_array_equal(back.view(np.uint64), x.view(np.uint64))
        # non-f64 and non-tpu inputs decline the route
        assert radix_mod._f64_tpu_host_keys(x.astype(np.float32)) is None
    # x64 off: must raise the clear error, not truncate
    import pytest as _pytest

    with _pytest.raises(ValueError, match="64-bit"):
        radix_mod._f64_tpu_host_keys(x)


def test_f64_tpu_host_route_declines_under_trace_and_warns(monkeypatch):
    """ADVICE r4 (medium) + VERDICT r4 item 4: a CONCRETE f64 array closed
    over inside a user jit must NOT take the host-key route (the host-side
    decode of a traced select result would raise
    TracerArrayConversionError); it falls through to the traced
    approximation and emits the one-time approximate-f64 warning. The
    eager exact route must stay silent."""
    import warnings

    import jax
    import jax.numpy as jnp
    import pytest

    from mpi_k_selection_tpu.ops import radix as radix_mod

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(radix_mod, "_f64_tpu_approx_warned", set())
    rng = np.random.default_rng(5)
    x = rng.standard_normal(4096)
    want = float(np.sort(x, kind="stable")[499])
    with compat.enable_x64(True):
        # the gate itself: concrete x, active trace -> route declined
        seen = {}

        def probe():
            seen["keys"] = radix_mod._f64_tpu_host_keys(x)
            return jnp.zeros(())

        jax.jit(probe)()
        assert seen["keys"] is None

        # end-to-end: the advisor's reproducer must not crash, and must
        # warn once (scatter: the patched backend name would otherwise pick
        # the compiled pallas path on the CPU test host). On real CPU
        # devices the "approximation" is bit-exact, so the value checks.
        with pytest.warns(UserWarning, match="approximate"):
            got = jax.jit(
                lambda: radix_mod.radix_select(x, 500, hist_method="scatter")
            )()
        assert float(got) == want
        # one-time: a second traced call stays silent
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            jax.jit(
                lambda: radix_mod.radix_select_many(
                    x, jnp.asarray([500]), hist_method="scatter"
                )
            )()
        # the eager exact host route never warns
        monkeypatch.setattr(radix_mod, "_f64_tpu_approx_warned", set())
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            got = radix_mod.radix_select(x, 500, hist_method="scatter")
        assert float(np.asarray(got)) == want
