"""Order-preserving bit transforms for radix selection.

Radix select works on unsigned keys whose numeric order equals the order of
the original values. This module maps every supported dtype to such keys and
back, so one selection kernel serves int8/16/32/64, uint*, bfloat16,
float16/32/64.

The reference operates only on C ``int`` (``vector.h:7-11``); supporting the
wider dtype set is part of the north-star scope (BASELINE.json configs use
int32, int64 and float32).

Transform rules (classic radix-sort tricks):
- signed int  -> flip the sign bit: ``u = bits(x) ^ MSB``
- unsigned    -> identity
- float       -> if sign bit set, flip all bits; else set the sign bit.
  This orders -inf < ... < -0.0 < +0.0 < ... < +inf < +NaN, matching
  ``np.sort`` for NaN-free data (NaNs with the sign bit clear sort last like
  NumPy; negative-NaN bit patterns sort first — documented deviation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# dtype -> (unsigned key dtype, total bits)
_KEY_INFO = {
    np.dtype(np.int8): (np.uint8, 8),
    np.dtype(np.uint8): (np.uint8, 8),
    np.dtype(np.int16): (np.uint16, 16),
    np.dtype(np.uint16): (np.uint16, 16),
    np.dtype(np.int32): (np.uint32, 32),
    np.dtype(np.uint32): (np.uint32, 32),
    np.dtype(np.int64): (np.uint64, 64),
    np.dtype(np.uint64): (np.uint64, 64),
    np.dtype(np.float16): (np.uint16, 16),
    np.dtype(jnp.bfloat16): (np.uint16, 16),
    np.dtype(np.float32): (np.uint32, 32),
    np.dtype(np.float64): (np.uint64, 64),
}


def key_dtype(dtype) -> np.dtype:
    """Unsigned key dtype used for radix passes over `dtype`."""
    dtype = np.dtype(dtype)
    if dtype not in _KEY_INFO:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    return np.dtype(_KEY_INFO[dtype][0])


def key_bits(dtype) -> int:
    """Total number of key bits for `dtype`."""
    dtype = np.dtype(dtype)
    if dtype not in _KEY_INFO:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    return _KEY_INFO[dtype][1]


def key_fold(dtype):
    """In-kernel form of :func:`to_sortable_bits` for raw-bits kernel tiles.

    Returns ``("xor", C)`` when ``key == raw_bits ^ C`` (every integer
    dtype: C is the sign-bit mask for signed, 0 for unsigned) — the fold is
    *free* in the histogram kernels because a logical shift distributes over
    xor (``(raw ^ C) >> s == (raw >> s) ^ (C >> s)``), so C folds into the
    kernel's existing xor constant. Returns ``("float",)`` for
    float32/float64, whose sign-dependent transform costs two VPU ops in
    kernel. Returns None for sub-32-bit dtypes, which are widened on the
    host side anyway (the widening copy subsumes the transform).

    Why this exists: materializing ``to_sortable_bits(x)`` before the Pallas
    kernels is a full extra read+write of the array per select (the kernels
    are opaque custom calls, so XLA cannot fuse the transform into them —
    measured 1.63 ms of a 7.5 ms select at N=2^27 on v5e). Feeding raw bits
    and folding the transform into the kernel removes that pass entirely.
    """
    dtype = np.dtype(dtype)
    if dtype not in _KEY_INFO:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt, bits = _KEY_INFO[dtype]
    if bits < 32:
        return None
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return ("xor", 0)
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return ("xor", 1 << (bits - 1))
    return ("float",)


def f64_raw_bits(x: jax.Array) -> jax.Array:
    """IEEE-754 binary64 bit pattern of ``x`` as uint64, computed WITHOUT a
    float64-source bitcast.

    The TPU toolchain rejects every ``bitcast_convert_type`` whose source is
    f64 (the compile helper crashes — f64 is software-emulated on the VPU and
    its storage has no bitcast lowering), which would make float64 selection
    impossible on the very backend this framework targets. This reconstructs
    the bits arithmetically from primitives that DO lower: f64 compares,
    exact power-of-two multiplies, and value-converts to uint64.

    Method: predicated binary normalization of ``|x|`` into ``v * 2^e`` with
    ``v in [1, 2)`` (descending power-of-two ladder, every multiply exact),
    then mantissa = ``(v - 1) * 2^52``. Exact for every NORMAL value
    including -0.0 (sign recovered via ``1/x`` when ``x == 0``) and for
    infinities; NaNs canonicalize to +0x7FF8000000000000 (payload and NaN
    sign not preserved — the same deviation class the NaN-ordering note
    above documents). Denormals collapse to the matching signed zero: XLA
    flushes f64 denormals to zero in compiled arithmetic (measured on both
    CPU and TPU), so no arithmetic reconstruction can see their bits —
    order degrades only by tying denormals with +-0.0, and a selection
    whose k-th order statistic IS a denormal returns +-0.0 instead. The
    bitcast backends (CPU/seq oracle) remain bit-exact.
    """
    ax = jnp.abs(x)
    neg = jnp.where(x != 0.0, x < 0.0, (1.0 / x) < 0.0)
    v = ax
    e = jnp.zeros(x.shape, jnp.int32)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        big = v >= 2.0**k
        v = jnp.where(big, v * 2.0**-k, v)
        e = jnp.where(big, e + k, e)
    # scale small values up (normals only reach 2^-1022; denormals are
    # already flushed to zero by XLA before this ladder can see them)
    for k in (512, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        small = v < 2.0 ** (1 - k)
        v = jnp.where(small, v * 2.0**k, v)
        e = jnp.where(small, e - k, e)
    normal = e >= -1022
    f_norm = ((v - 1.0) * 2.0**52).astype(jnp.uint64)
    E = jnp.where(normal, e + 1023, 0).astype(jnp.uint64)
    # non-normal finite = zero or a denormal FTZ'd to zero upstream: bits 0
    bits = jnp.where(
        normal,
        jax.lax.shift_left(E, jnp.uint64(52)) | f_norm,
        jnp.uint64(0),
    )
    bits = jnp.where(jnp.isinf(x), jnp.uint64(0x7FF) << jnp.uint64(52), bits)
    bits = jnp.where(neg, bits | jnp.uint64(1) << jnp.uint64(63), bits)
    # NaN last (and unsigned): canonical quiet NaN
    bits = jnp.where(jnp.isnan(x), jnp.uint64(0x7FF8000000000000), bits)
    return bits


def f64_to_u64_bits(x: jax.Array) -> jax.Array:
    """Raw uint64 bits of a float64 array: a plain bitcast everywhere except
    TPU, where bitcasts FROM f64 crash the compiler (see
    :func:`f64_raw_bits`)."""
    if jax.default_backend() == "tpu":
        return f64_raw_bits(x)
    return jax.lax.bitcast_convert_type(x, jnp.uint64)


def _require_x64(dtype):
    if np.dtype(dtype).itemsize == 8 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"{np.dtype(dtype)} selection requires 64-bit mode; enable it via "
            "jax.config.update('jax_enable_x64', True) or the "
            "jax.experimental.enable_x64() context manager"
        )


def to_sortable_bits(x: jax.Array) -> jax.Array:
    """Map `x` to unsigned keys with the same ordering."""
    dtype = np.dtype(x.dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    _require_x64(dtype)
    kdt = np.dtype(kdt)
    msb = np.array(1, dtype=np.uint64) << np.uint64(bits - 1)
    msb = kdt.type(msb)
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return x
    if dtype == np.dtype(np.float64):
        u = f64_to_u64_bits(x)  # f64-source bitcasts crash the TPU compiler
    else:
        u = jax.lax.bitcast_convert_type(x, kdt)
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return u ^ msb
    # floating point
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = (u >> kdt.type(bits - 1)) != kdt.type(0)
    return jnp.where(neg, u ^ all_ones, u | msb)


def sortable_from_raw_bits(raw: jax.Array, dtype) -> jax.Array:
    """:func:`to_sortable_bits` taking the RAW bit pattern (already widened
    to the key dtype) instead of values. Lets the collect paths map raw
    kernel tiles to key space with pure integer ops — no value round trip,
    and (for float64) no f64-source bitcast anywhere near the TPU compiler.
    """
    dtype = np.dtype(dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt = np.dtype(kdt)
    if raw.dtype != kdt:
        raise ValueError(f"raw bits must be {kdt}, got {raw.dtype}")
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return raw
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return raw ^ msb
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = jax.lax.shift_right_logical(raw, kdt.type(bits - 1)) != kdt.type(0)
    return jnp.where(neg, raw ^ all_ones, raw | msb)


def np_to_sortable_bits(x: np.ndarray) -> np.ndarray:
    """Host (NumPy) twin of :func:`to_sortable_bits` — pure view-casts and
    integer ops, no device round trip. The streaming subsystem
    (streaming/chunked.py, streaming/sketch.py) converts host chunks through
    here, which makes out-of-core float64 selection bit-exact even on TPU:
    the f64 bits never touch the device's ~49-bit f64 storage (the same
    trick as ops/radix.py:_f64_tpu_host_keys, generalized to every dtype).
    No x64 requirement — NumPy's uint64 is always real."""
    x = np.ascontiguousarray(x)
    dtype = np.dtype(x.dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt = np.dtype(kdt)
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return x.view(kdt)
    u = x.view(kdt)
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return u ^ msb
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = (u >> kdt.type(bits - 1)) != kdt.type(0)
    return np.where(neg, u ^ all_ones, u | msb)


def np_from_sortable_bits(u: np.ndarray, dtype) -> np.ndarray:
    """Inverse of :func:`np_to_sortable_bits` (host twin of
    :func:`from_sortable_bits`)."""
    dtype = np.dtype(dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt = np.dtype(kdt)
    u = np.ascontiguousarray(np.asarray(u, kdt))
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return u.astype(dtype)
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return (u ^ msb).view(dtype)
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = (u & msb) == kdt.type(0)  # keys below MSB came from negative floats
    raw = np.where(neg, u ^ all_ones, u & ~msb)
    return np.ascontiguousarray(raw).view(dtype)


def from_sortable_bits(u: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`to_sortable_bits`."""
    dtype = np.dtype(dtype)
    kdt, bits = _KEY_INFO.get(dtype, (None, None))
    if kdt is None:
        raise TypeError(f"unsupported dtype for k-selection: {dtype}")
    kdt = np.dtype(kdt)
    u = u.astype(kdt)
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    if jnp.issubdtype(dtype, jnp.unsignedinteger):
        return u
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return jax.lax.bitcast_convert_type(u ^ msb, dtype)
    all_ones = kdt.type(~np.uint64(0) >> np.uint64(64 - bits))
    neg = (u & msb) == kdt.type(0)  # keys below MSB came from negative floats
    raw = jnp.where(neg, u ^ all_ones, u & ~msb)
    return jax.lax.bitcast_convert_type(raw, dtype)
