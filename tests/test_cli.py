"""CLI driver: backends, algorithms, top-k mode, verify, JSON output."""

import json

import numpy as np
import pytest

from mpi_k_selection_tpu.cli import main


def test_seq_backend_verify(capsys):
    rc = main(["--backend", "seq", "--n", "10000", "--k", "250", "--verify"])
    out = capsys.readouterr().out
    assert rc == 0
    # the sequential program's distinct output contract (kth-problem-seq.c:37)
    assert "Solution found solution=" in out and "exact match" in out


def test_tpu_backend_reference_output(capsys):
    rc = main(["--backend", "tpu", "--n", "20000", "--k", "100", "--distribute", "never"])
    out = capsys.readouterr().out
    assert rc == 0
    # the CGM program's output contract (TODO-kth-problem-cgm.c:280)
    assert "kth element=" in out


def test_tpu_backend_json(capsys):
    rc = main(
        ["--backend", "tpu", "--n", "65536", "--verify", "--json", "--distribute", "never"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["n"] == 65536
    assert rec["k"] == 32768  # default: median (N/2)
    assert rec["extra"]["exact_match"] is True


def test_cgm_algorithm(capsys):
    rc = main(
        ["--backend", "tpu", "--algorithm", "cgm", "--n", "32768", "--verify", "--json"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["extra"]["exact_match"] is True


def test_topk_mode(capsys):
    rc = main(
        [
            "--backend", "tpu", "--gen", "normal", "--dtype", "float32",
            "--n", "4096", "--topk", "16", "--verify", "--json",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["algorithm"] == "topk" and rec["extra"]["exact_match"] is True


def test_batched_topk_mode(capsys):
    rc = main(
        [
            "--backend", "tpu", "--gen", "funiform", "--dtype", "float32",
            "--n", "1024", "--batch", "8", "--topk", "4", "--verify", "--json",
        ]
    )
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["extra"]["exact_match"] is True


def test_k_out_of_range():
    with pytest.raises(SystemExit):
        main(["--backend", "seq", "--n", "100", "--k", "0"])


def test_reference_operating_point(capsys):
    # k=250 at small n, seq oracle — the kth-problem-seq.c:24 operating point
    rc = main(["--backend", "seq", "--n", "100000", "--k", "250", "--json"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    x = np.sort(
        __import__("mpi_k_selection_tpu.utils.datagen", fromlist=["generate"]).generate(
            100000, pattern="uniform", seed=0, dtype=np.int32
        )
    )
    assert rec["answer"] == int(x[249])


def test_float16_dtype(capsys):
    rc = main(
        ["--backend", "tpu", "--gen", "funiform", "--dtype", "float16",
         "--n", "20000", "--k", "500", "--verify", "--json", "--distribute", "never"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert json.loads(out.strip().splitlines()[-1])["extra"]["exact_match"] is True


def test_cli_quantiles(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        ["--backend", "tpu", "--n", "100000", "--quantiles", "0.5,0.9,0.99",
         "--seed", "5", "--verify"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "exact match" in out


def test_cli_quantiles_bad_combo():
    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="exclusive"):
        main(["--quantiles", "0.5", "--topk", "8"])
    with pytest.raises(SystemExit, match="tpu backend"):
        main(["--backend", "seq", "--quantiles", "0.5", "--n", "1000"])


def test_cli_quantiles_distributed(monkeypatch):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from mpi_k_selection_tpu.cli import main

    rc = main(
        ["--backend", "tpu", "--n", "100000", "--quantiles", "0.25,0.75",
         "--distribute", "always", "--seed", "6", "--verify", "--json"]
    )
    assert rc == 0


def test_cli_quantiles_devices_cap_auto_falls_back_single(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        ["--backend", "tpu", "--n", "50000", "--quantiles", "0.5",
         "--devices", "1", "--seed", "3", "--verify"]
    )
    assert rc == 0
    assert "exact match" in capsys.readouterr().out


def test_cli_quantiles_devices_cap_always_errors():
    # distribute='always' capped below 2 devices raises (the reference's
    # world_size >= 2 abort), no silent single-chip fallback
    import pytest

    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="needs >= 2 devices"):
        main(
            ["--backend", "tpu", "--n", "50000", "--quantiles", "0.5",
             "--distribute", "always", "--devices", "1", "--seed", "3"]
        )


def test_cli_quantiles_rejects_non_radix_algorithm():
    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="radix"):
        main(["--quantiles", "0.5", "--algorithm", "sort", "--n", "1000"])
