"""Fused single-read ingest (ops/pallas/fused_ingest.py + the ``fused``
knob, ISSUE 11).

The contracts under test:

- **Bit-equality over the full grid**: devices {1, 2, max} x
  pipeline_depth {0, 2} x spill {off, force} x fused {auto, off} return
  identical bits over heterogeneous (host + device + ragged + empty)
  chunk streams — ``fused="off"`` (the unfused consumer bundle) is the
  bit-for-bit oracle, as is ``deferred="off"`` beneath it.
- **Kernel vs numpy oracle**: the fused program's histogram, per-spec
  compactions and tee payload equal the host filters, pads excluded,
  survivor order preserved.
- **Device-resident source chunks take the staged/deferred path**: at
  pipeline_depth >= 1 a device chunk is wrapped in the pow2 staging
  discipline ON its own device (stage_device_keys) — no transfer, no
  eager gather — and a bucket-sized chunk is wrapped WITHOUT a copy
  (``own_data=False``: release() must not delete the caller's array).
- **The read accounting**: ``ingest.bucket_read_bytes`` equals
  ``ingest.staged_bytes`` under fusion (every staged key read once per
  pass) and exceeds it for the unfused bundle; the fused run dispatches
  no separate tee/collect programs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_k_selection_tpu import obs as obs_lib
from mpi_k_selection_tpu.ops.pallas import fused_ingest as fi
from mpi_k_selection_tpu.streaming import (
    SpillStore,
    live_staged_keys,
    resolve_fused,
    stage_device_keys,
    streaming_kselect,
    streaming_kselect_many,
)
from mpi_k_selection_tpu.streaming import executor as ex_mod
from mpi_k_selection_tpu.streaming.pipeline import stage_keys


def _chunks(rng, sizes=(4096, 1, 0, 2777, 4096), device_chunk=1):
    """Heterogeneous stream: host chunks, ragged sizes, an empty chunk,
    and `device_chunk` chunks already resident on a device."""
    out = [
        rng.integers(-(2**31), 2**31 - 1, size=s, dtype=np.int32)
        for s in sizes
    ]
    for i in range(device_chunk):
        out[i * 3] = jnp.asarray(out[i * 3])
    return out


def _oracle(chunks, ks):
    x = np.concatenate([np.asarray(c).ravel() for c in chunks])
    part = np.partition(x, [k - 1 for k in ks])
    return [int(part[k - 1]) for k in ks]


# ---------------------------------------------------------------------------
# the grid


@pytest.mark.parametrize("devices", [None, 2, 8])
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("spill", ["off", "force"])
@pytest.mark.parametrize("fused", ["auto", "off"])
def test_grid_bit_equality(rng, devices, depth, spill, fused):
    chunks = _chunks(rng)
    n = sum(int(np.asarray(c).size) for c in chunks)
    ks = [1, n // 3, n // 2, n]
    want = _oracle(chunks, ks)
    got = streaming_kselect_many(
        chunks, ks, radix_bits=8, collect_budget=256,
        pipeline_depth=depth, devices=devices, spill=spill, fused=fused,
    )
    assert [int(g) for g in got] == want
    assert live_staged_keys() == 0


def test_fused_matches_unfused_and_sync_f32(rng):
    chunks = [
        rng.standard_normal(s).astype(np.float32) for s in (3000, 1500, 700)
    ]
    n = sum(c.size for c in chunks)
    k = n // 2
    kw = dict(radix_bits=8, collect_budget=128, devices=8, pipeline_depth=2,
              spill="force")
    a = streaming_kselect(chunks, k, fused="auto", **kw)
    b = streaming_kselect(chunks, k, fused="off", **kw)
    c = streaming_kselect(chunks, k, fused="off", deferred="off", **kw)
    d = streaming_kselect(chunks, k, pipeline_depth=0, radix_bits=8,
                          collect_budget=128)
    assert (
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        == np.asarray(c).tobytes() == np.asarray(d).tobytes()
    )


def test_spill_generations_identical_across_fused(rng):
    """The fused tee writes the SAME per-pass survivor bytes as the
    unfused tee (the multiset contract, visible in the pass_log)."""
    chunks = _chunks(rng, sizes=(4096, 2048, 4096), device_chunk=0)
    n = sum(c.size for c in chunks)
    logs = {}
    for fused in ("auto", "off"):
        with SpillStore() as store:
            streaming_kselect(
                chunks, n // 2, radix_bits=4, collect_budget=64,
                devices=8, pipeline_depth=2, spill=store, fused=fused,
            )
            logs[fused] = [
                {kk: e[kk] for kk in ("pass", "keys_read", "keys_written")
                 if kk in e}
                for e in store.pass_log
            ]
    assert logs["auto"] == logs["off"]


def test_one_shot_tee_fused(rng):
    """A consumed generator under spill='auto': the fused tee must anchor
    the same gen-0 bytes and the descent the same answer."""
    chunks = [rng.integers(-1000, 1000, size=s, dtype=np.int32)
              for s in (3000, 2000, 1000)]
    n = sum(c.size for c in chunks)
    k = n // 2
    want = _oracle(chunks, [k])[0]
    got = streaming_kselect(
        (c for c in chunks), k, radix_bits=4, collect_budget=128,
        fused="auto",
    )
    got_off = streaming_kselect(
        (c for c in chunks), k, radix_bits=4, collect_budget=128,
        fused="off",
    )
    assert int(got) == int(got_off) == want


# ---------------------------------------------------------------------------
# the fused program vs the numpy oracle


def test_fused_program_matches_numpy_oracle(rng):
    kdt = np.dtype(np.uint32)
    keys = rng.integers(0, 2**32, size=3011, dtype=np.uint32)  # ragged: pads
    staged = stage_keys(keys)
    try:
        prefixes = sorted({int(keys[0] >> 24), int(keys[7] >> 24)})
        collect_specs = [(8, int(keys[0] >> 24)), (16, int(keys[5] >> 16))]
        tee_specs = collect_specs
        hist, collect, tee = fi.dispatch_fused_ingest(
            staged, kdt=kdt, total_bits=32, shift=16, radix_bits=8,
            hist_prefixes=prefixes, method="scatter",
            collect_specs=collect_specs, tee_specs=tee_specs,
        )
        hist = np.asarray(hist)
        parts = [ex_mod.materialize_compacted(p, kdt) for p in collect]
        tee_out = ex_mod.materialize_compacted(tee, kdt)
    finally:
        staged.release()
    # histogram: over the WHOLE padded bucket (pad keys are key-space 0 —
    # the executor's finish subtracts them; here we include them)
    padded = np.zeros(staged.data.shape[0], np.uint32)
    padded[: keys.size] = keys
    assert hist.dtype == np.int32
    for i, p in enumerate(prefixes):
        up = padded >> np.uint32(24)
        dig = (padded >> np.uint32(16)) & np.uint32(0xFF)
        want = np.bincount(
            dig[up == np.uint32(p)].astype(np.int64), minlength=256
        )
        np.testing.assert_array_equal(hist[i], want)
    # per-spec compactions: pad excluded, chunk order preserved
    union = np.zeros(keys.shape, bool)
    for (resolved, prefix), got in zip(collect_specs, parts):
        m = (keys >> np.uint32(32 - resolved)) == np.uint32(prefix)
        union |= m
        assert got.dtype == kdt
        np.testing.assert_array_equal(got, keys[m])
    # tee: the union of specs, compacted once
    np.testing.assert_array_equal(tee_out, keys[union])


def test_fused_collect_only_program(rng):
    """hist_prefixes=None — the collect pass's fused shape (no histogram,
    K spec compactions in one program)."""
    kdt = np.dtype(np.uint32)
    keys = np.full(1000, 0xABCD1234, np.uint32)
    staged = stage_keys(keys)
    try:
        hist, collect, tee = fi.dispatch_fused_ingest(
            staged, kdt=kdt, total_bits=32,
            collect_specs=[(16, 0x1111), (16, 0xABCD)],
        )
        assert hist is None and tee is None
        none_, all_ = (
            ex_mod.materialize_compacted(p, kdt) for p in collect
        )
    finally:
        staged.release()
    assert none_.size == 0
    np.testing.assert_array_equal(all_, keys)  # pads must NOT leak in


# ---------------------------------------------------------------------------
# device-resident source chunks take the staged/deferred path


def test_device_chunks_stage_and_defer(rng):
    host = rng.integers(-(2**31), 2**31 - 1, size=3000, dtype=np.int32)
    chunks = [jnp.asarray(host), host[:1777]]
    n = 3000 + 1777
    k = n // 2
    want = _oracle([np.asarray(c) for c in chunks], [k])[0]
    o = obs_lib.Observability.collecting()
    got = streaming_kselect(chunks, k, pipeline_depth=2, obs=o)
    assert int(got) == want
    ev = o.events.of_kind("stream.chunk")
    # the DEVICE chunk (index 0 of every pass, including the collect) is
    # staged; the host chunk stays host-side on the single-device collect
    dev_ev = [e for e in ev if e.chunk_index == 0]
    assert dev_ev and all(e.staged for e in dev_ev)
    # the synchronous oracle keeps device chunks unstaged
    o0 = obs_lib.Observability.collecting()
    got0 = streaming_kselect(chunks, k, pipeline_depth=0, obs=o0)
    assert int(got0) == want
    assert all(not e.staged for e in o0.events.of_kind("stream.chunk"))


def test_host_exact_routes_still_bypass_staging(rng):
    """64-bit device keys without x64 resolve to the host route: a device
    chunk must NOT be staged (deferral/fusion never see it) and the
    answer stays exact."""
    chunks = [
        rng.integers(-(2**62), 2**62, size=s, dtype=np.int64)
        for s in (2000, 1000)
    ]
    n = sum(c.size for c in chunks)
    k = n // 2
    o = obs_lib.Observability.collecting()
    got = streaming_kselect(
        chunks, k, collect_budget=64, devices=8, pipeline_depth=2,
        fused="auto", obs=o,
    )
    assert np.asarray(got).tobytes() == np.asarray(
        np.sort(np.concatenate(chunks), kind="stable")[k - 1]
    ).tobytes()
    assert all(not e.staged for e in o.events.of_kind("stream.chunk"))


def test_stage_device_keys_padded_and_released():
    base = live_staged_keys()
    keys = jnp.asarray(np.arange(1, 1001, dtype=np.uint32))  # ragged
    staged = stage_device_keys(keys)
    assert live_staged_keys() == base + 1
    assert staged.n_valid == 1000 and staged.pad == 24
    assert staged.data.shape[0] == 1024
    got = np.asarray(staged.data)
    assert (got[1000:] == 0).all()  # key-space zero pad
    np.testing.assert_array_equal(got[:1000], np.arange(1, 1001))
    staged.release()
    assert live_staged_keys() == base


def test_device_staging_shares_the_stage_fault_site(rng):
    """stage_device_keys sits on the SAME chaos 'stage' site (and in-place
    retry discipline) as the host staging transfer — a seeded fault plan
    targeting staging fires for device-resident sources too, and the
    recovered answer is bit-identical."""
    from mpi_k_selection_tpu import faults

    chunks = [
        jnp.asarray(rng.integers(-1000, 1000, size=s, dtype=np.int32))
        for s in (3000, 1777)
    ]
    n = 3000 + 1777
    k = n // 2
    want = _oracle([np.asarray(c) for c in chunks], [k])[0]
    plan = faults.FaultPlan((faults.FaultSpec("stage", 1, "raise"),))
    pol = faults.RetryPolicy(max_attempts=3, sleeper=faults.VirtualSleeper())
    with faults.inject(plan) as inj:
        got = int(streaming_kselect(chunks, k, pipeline_depth=2, retry=pol))
    assert got == want
    assert inj.fired and inj.fired[0]["site"] == "stage"
    assert live_staged_keys() == 0


def test_stage_device_keys_bucket_sized_wraps_without_copy():
    """A pow2-length device chunk is wrapped as-is (own_data=False):
    release() must NOT delete the caller's array."""
    base = live_staged_keys()
    keys = jnp.asarray(np.arange(2048, dtype=np.uint32))
    staged = stage_device_keys(keys)
    assert staged.data is keys and staged.pad == 0
    assert not staged.own_data
    staged.release()
    assert live_staged_keys() == base
    # the caller's array survives the release
    np.testing.assert_array_equal(np.asarray(keys)[:4], [0, 1, 2, 3])


# ---------------------------------------------------------------------------
# the read accounting


def _read_totals(o):
    read = staged = 0
    phases = set()
    for m in o.metrics.metrics():
        if m.name == "ingest.bucket_read_bytes":
            read += m.value
            phases.add(dict(m.labels).get("phase"))
        elif m.name == "ingest.staged_bytes":
            staged += m.value
    return read, staged, phases


def test_bucket_read_bytes_fused_vs_unfused(rng):
    chunks = _chunks(rng, sizes=(4096, 2048, 4096), device_chunk=0)
    n = sum(c.size for c in chunks)
    totals = {}
    for fused in ("auto", "off"):
        o = obs_lib.Observability.collecting()
        streaming_kselect(
            chunks, n // 2, radix_bits=4, collect_budget=64, devices=2,
            pipeline_depth=2, spill="force", fused=fused, obs=o,
        )
        totals[fused] = _read_totals(o)
    read_f, staged_f, phases_f = totals["auto"]
    read_u, staged_u, phases_u = totals["off"]
    assert staged_f == staged_u  # same staging either way
    # fused: every staged key read exactly once per pass (pass 0 has no
    # tee program on device, so its histogram read keeps the total equal)
    assert read_f == staged_f
    assert "tee" not in phases_f and "collect" not in phases_f
    assert "fused" in phases_f
    # unfused: the tee + per-spec collect programs amplify the reads
    assert read_u > staged_u
    assert {"tee", "collect", "histogram"} <= phases_u


def test_eager_mode_disables_fusion(rng):
    """deferred='off' implies the unfused bundle even at fused='auto' —
    fusion is a deferral discipline."""
    chunks = _chunks(rng, sizes=(4096, 2048), device_chunk=0)
    n = sum(c.size for c in chunks)
    o = obs_lib.Observability.collecting()
    streaming_kselect(
        chunks, n // 2, radix_bits=4, collect_budget=64, devices=2,
        pipeline_depth=2, spill="force", deferred="off", fused="auto",
        obs=o,
    )
    _, _, phases = _read_totals(o)
    assert "fused" not in phases


# ---------------------------------------------------------------------------
# knob + surface units


def test_resolve_fused():
    # "auto" resolves to a fusion TIER now (ISSUE 13): the sweep kernel
    # on TPU backends, the XLA fusion elsewhere — truthy either way, so
    # every `if fused:` caller is unchanged; tier-specific assertions
    # live in test_sweep_ingest.py
    assert resolve_fused("auto") in ("kernel", "xla")
    assert resolve_fused("off") is False
    assert resolve_fused(True) == resolve_fused("auto")
    assert resolve_fused(False) is False
    with pytest.raises(ValueError, match="fused"):
        resolve_fused("sometimes")
    with pytest.raises(ValueError, match="fused"):
        streaming_kselect([np.arange(4, dtype=np.int32)], 1, fused=1.5)


def test_fused_consumer_requires_a_part():
    with pytest.raises(ValueError, match="at least one part"):
        ex_mod.FusedIngestConsumer(kdt=np.dtype(np.uint32), total_bits=32)


def test_streaming_quantiles_fused_knob(rng):
    from mpi_k_selection_tpu.api import StreamingQuantiles

    with pytest.raises(ValueError, match="fused"):
        StreamingQuantiles(np.float32, fused="bogus")
    chunks = [rng.standard_normal(4000).astype(np.float32) for _ in range(3)]
    qs = (0.1, 0.5, 0.9)
    got = {}
    for fused in ("auto", "off"):
        sq = StreamingQuantiles(
            np.float32, devices=8, fused=fused
        ).update_stream(chunks)
        got[fused] = [
            np.asarray(v).tobytes() for v in sq.refine_quantiles(qs, chunks)
        ]
    assert got["auto"] == got["off"]


def test_cli_fused_flag(capsys):
    import json

    from mpi_k_selection_tpu.cli import main

    for mode in ("auto", "off"):
        rc = main([
            "--streaming", "--backend", "tpu", "--n", "40000",
            "--chunk-elems", "8192", "--devices", "2", "--verify", "--check",
            "--spill", "force", "--fused", mode, "--json",
        ])
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["extra"]["exact_match"] is True
        assert rec["extra"]["certificate_ok"] is True
        assert rec["extra"]["fused"] == mode
