"""Build the native runtime: ``python -m mpi_k_selection_tpu.native.build``.

One g++ invocation producing ``_build/libkselect_native.so`` next to the
sources. The loader (loader.py) calls :func:`build` lazily on first use, so
an explicit build is only needed to rebuild after editing the C++.
"""

from __future__ import annotations

import hashlib
import pathlib
import shutil
import subprocess
import sys

_DIR = pathlib.Path(__file__).resolve().parent
SOURCES = [_DIR / "kselect_native.cpp"]
LIB_PATH = _DIR / "_build" / "libkselect_native.so"
STAMP_PATH = LIB_PATH.with_suffix(".so.srchash")
COMPILE_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", "-Wall"]


def _source_hash() -> str:
    """Content hash of all sources + the compile flags.

    Used for staleness instead of mtimes: git does not preserve mtimes, so an
    mtime check can declare a stale (or foreign) binary fresh on checkout.
    Hashing the flags too means a flag change also triggers a rebuild.
    """
    h = hashlib.sha256()
    h.update(" ".join(COMPILE_FLAGS).encode())
    for s in SOURCES:
        h.update(s.name.encode())
        h.update(s.read_bytes())
    return h.hexdigest()


def build(force: bool = False, quiet: bool = True) -> pathlib.Path:
    """Compile the shared library if missing/stale; return its path.

    Staleness is judged by source *content hash* (stamp file next to the
    .so), never by mtime, so the library is always rebuilt from the sources
    actually present — a binary that did not come from this exact source is
    never loaded.
    """
    want = _source_hash()
    if (
        not force
        and LIB_PATH.exists()
        and STAMP_PATH.exists()
        and STAMP_PATH.read_text().strip() == want
    ):
        return LIB_PATH
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        raise RuntimeError("no C++ compiler found (need g++ or clang++)")
    LIB_PATH.parent.mkdir(parents=True, exist_ok=True)
    cmd = [gxx, *COMPILE_FLAGS, *[str(s) for s in SOURCES], "-o", str(LIB_PATH)]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"native build failed:\n{res.stderr}")
    STAMP_PATH.write_text(want + "\n")
    if not quiet:
        print(f"built {LIB_PATH}")  # ksel: noqa[KSL009] -- opt-in build-tool progress line (quiet=False only from the __main__ entry), not runtime telemetry
    return LIB_PATH


if __name__ == "__main__":
    build(force="--force" in sys.argv, quiet=False)
