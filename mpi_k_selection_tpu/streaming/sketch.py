"""RadixSketch — a fixed-size, exactly-mergeable digit-histogram sketch for
online quantiles.

The radix histogram that drives selection (ops/histogram.py) is an *exact,
mergeable, constant-size summary*: counts add elementwise, so per-chunk (or
per-shard) histograms combine associatively AND commutatively with plain
``+`` — merge order cannot change a single bit of the accumulator, unlike
compressed quantile sketches (t-digest, q-digest) whose merges are only
approximately order-invariant. That property is what lets per-shard sketches
ride one ``psum`` in parallel/sketch.py and telemetry pipelines merge
partial sketches in any tree shape.

Structure: level ``l`` (1-indexed) is the exact histogram of the top
``l * radix_bits`` key bits, for ``l = 1..levels``. The deepest level
answers queries; the shallower pyramid gives coarse prefixes for seeding
exact refinement at any resolution multiple of ``radix_bits``. Size is fixed
at ``sum(2**(l*rb))`` int64 counters (~70K counters / 0.5 MB at the default
4 bits x 4 levels) — independent of ``n``.

Guarantees (let ``b = resolution_bits = levels * radix_bits``):

- ``rank_bounds(k) -> (lo, hi)`` with ``lo < k <= hi`` is EXACT for any
  stream, adversarial included: lo/hi are true ranks of the resolved
  key-interval boundaries.
- ``value_bounds(k)`` brackets the true k-th smallest value by the interval
  of width ``2**(key_bits - b)`` in key space (clamped to the observed
  min/max) — again exact for any stream.
- ``query(k)`` / ``quantile(q)`` point estimates carry rank error at most
  ``hi - lo`` (the answering bucket's population — query it via
  ``rank_error_bound(k)``). For streams that do not concentrate more than
  ``c * n / 2**b`` elements into any resolved interval (uniform-ish keys;
  c covers sampling fluctuation), that is the advertised ``c * n / 2**b``
  bound. Heavy duplicates keep the bounds above exact but widen the point
  estimate's rank error — that is inherent to ANY fixed-resolution value
  histogram, and exactly what :meth:`refine` exists for.
- ``refine(source, k)`` is bit-exact: it seeds the out-of-core descent
  (streaming/chunked.py) with the sketch's resolved prefix, skipping
  ``levels`` histogram passes over the stream.
"""

from __future__ import annotations

import numpy as np

from mpi_k_selection_tpu.utils import dtypes as _dt

# fixed-size cap: 2^20 int64 counters = 8 MB for the deepest level
_MAX_RESOLUTION_BITS = 20

_staged_extremes_fn = None


def _staged_extremes(data, n_valid):
    """``(min, max)`` over the first ``n_valid`` keys of a padded staged
    buffer, computed over the FULL bucket shape with the pad lanes masked
    to the exact unsigned min/max identities — so the extremes program
    compiles once per (bucket, dtype), like the histogram half, instead of
    once per distinct chunk length (``n_valid`` rides as a traced scalar,
    not a baked constant). Bitwise identical to min/max over the valid
    slice: chunks are non-empty, so at least one unmasked lane wins."""
    global _staged_extremes_fn
    if _staged_extremes_fn is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(d, nv):
            valid = jax.lax.iota(jnp.int32, d.shape[0]) < nv
            return (
                jnp.min(jnp.where(valid, d, ~jnp.zeros((), d.dtype))),
                jnp.max(jnp.where(valid, d, jnp.zeros((), d.dtype))),
            )

        _staged_extremes_fn = fn
    return _staged_extremes_fn(data, n_valid)


class _SketchFoldConsumer:
    """The sketch's :class:`~mpi_k_selection_tpu.streaming.executor.
    StreamExecutor` consumer: staged chunks dispatch their deepest-level
    int32 histogram + key-space extremes on their OWN device and fold in
    FIFO chunk order at finish; host/device-resident chunks fold
    immediately at dispatch (the historical inline path). Buffer release
    rides the executor.

    ``fused="kernel"`` (the resolved tier) closes the last
    2-programs-per-staged-bucket consumer: a supported bucket dispatches
    the single-sweep kernel's sketch part
    (:meth:`RadixSketch._dispatch_staged_sweep` — deep histogram AND
    extremes in ONE program, one guaranteed read,
    ``ingest.bucket_reads{phase="sketch"}`` = 1 per bucket); the
    ``"xla"``/off tiers keep the historical deep-fold + extremes pair
    (2 programs). The folded pyramid is bit-identical either way."""

    def __init__(self, sketch: "RadixSketch", obs=None, fused=False):
        self._sketch = sketch
        self._obs = obs
        self._kernel = fused == "kernel"
        self.staged_chunks = 0

    def dispatch(self, keys, kv):
        import numpy as _np

        from mpi_k_selection_tpu.obs import wiring as _wr
        from mpi_k_selection_tpu.ops.pallas import sweep_ingest as _si
        from mpi_k_selection_tpu.streaming import pipeline as _pl

        if isinstance(keys, _pl.StagedKeys):
            self.staged_chunks += 1
            if self._kernel and _si.sweep_supported(
                keys, self._sketch.kdt,
                sketch_bits=self._sketch.resolution_bits,
            ):
                # ONE sweep program per staged bucket (deep histogram +
                # extremes together — the single-read ingest)
                _wr.bucket_read(self._obs, "sketch", keys, 1)
                return self._sketch._dispatch_staged_sweep(keys)
            # two device programs per staged bucket (deep histogram +
            # extremes) — honest reads-per-pass accounting
            _wr.bucket_read(self._obs, "sketch", keys, 2)
            return self._sketch._dispatch_staged(keys)
        # device chunks arrive as device keys (bitwise twins of the host
        # transform; the f64-on-TPU route already resolved to host-exact
        # keys inside the iterator) — land them host-side for the bincount
        # accumulator
        if not isinstance(kv, _np.ndarray):
            kv = _np.asarray(kv)
        self._sketch._update_keys(kv)
        return None

    def finish(self, handle) -> None:
        self._sketch._fold_staged(handle)


class RadixSketch:
    """Mergeable multi-level radix-digit histogram over one dtype's streams."""

    def __init__(self, dtype, *, radix_bits: int = 4, levels: int = 4):
        self.dtype = np.dtype(dtype)
        self.kdt = np.dtype(_dt.key_dtype(self.dtype))  # validates dtype
        self.total_bits = _dt.key_bits(self.dtype)
        if radix_bits < 1 or levels < 1:
            raise ValueError("radix_bits and levels must be >= 1")
        if levels * radix_bits > min(self.total_bits, _MAX_RESOLUTION_BITS):
            raise ValueError(
                f"levels*radix_bits={levels * radix_bits} exceeds "
                f"{min(self.total_bits, _MAX_RESOLUTION_BITS)} "
                f"(key bits capped at {_MAX_RESOLUTION_BITS} to keep the "
                "sketch fixed-size; refine() provides exactness beyond it)"
            )
        self.radix_bits = radix_bits
        self.levels = levels
        self.n = 0
        self.hists = [
            np.zeros((1 << (l * radix_bits),), np.int64)
            for l in range(1, levels + 1)
        ]
        # exact observed extremes, in key space (None until first update)
        self._min_key = None
        self._max_key = None
        # memoized per-level CDFs for the query path: {level: (n, cumsum)}.
        # ``n`` is the validity stamp — every accumulation (update,
        # update_value, fold_scaled past its no-op guards) grows ``n``, so
        # a stale entry can never answer. Benign under concurrent readers
        # (the serve fast path queries a frozen sketch from many request
        # threads): racing rebuilds store the identical array.
        self._cdf_cache: dict = {}

    # -- accumulation ------------------------------------------------------

    @property
    def resolution_bits(self) -> int:
        """Key bits the deepest level resolves (= levels * radix_bits)."""
        return self.levels * self.radix_bits

    def update(self, chunk) -> "RadixSketch":
        """Fold one chunk in (host-side — a sketch is a host accumulator;
        for device-sharded arrays use parallel/sketch.py, which computes the
        same histograms on device and merges them with one psum). Returns
        ``self``. Empty chunks are no-ops."""
        c = np.ravel(np.asarray(chunk))
        if c.size == 0:
            return self
        if np.dtype(c.dtype) != self.dtype:
            raise TypeError(
                f"chunk dtype {np.dtype(c.dtype)} != sketch dtype {self.dtype}"
            )
        return self._update_keys(_dt.np_to_sortable_bits(c))

    def _update_keys(self, keys: np.ndarray) -> "RadixSketch":
        """Fold one chunk's (host, key-space) unsigned view in — the
        accumulation core shared by :meth:`update` and the pipelined
        :meth:`update_stream`."""
        # one full-chunk pass builds the DEEPEST level; each shallower level
        # is that histogram with its lower digits summed out (a reshape-sum
        # over <= 2^resolution_bits counters, bitwise identical to counting
        # the chunk again at the coarser width and ~levels x cheaper)
        shift = self.kdt.type(self.total_bits - self.resolution_bits)
        deep = np.bincount(
            (keys >> shift).astype(np.int64),
            minlength=1 << self.resolution_bits,
        ).astype(np.int64)
        self._fold_deep_histogram(deep)
        kmin, kmax = keys.min(), keys.max()
        if self._min_key is None or kmin < self._min_key:
            self._min_key = self.kdt.type(kmin)
        if self._max_key is None or kmax > self._max_key:
            self._max_key = self.kdt.type(kmax)
        self.n += int(keys.size)
        return self

    def update_stream(
        self, source, *, pipeline_depth=None, timer=None, devices=None,
        spill=None, fused=None, pack_spill=None, ingest_workers=None,
        obs=None,
    ) -> "RadixSketch":
        """Fold EVERY chunk of a replayable/listed ``source`` in (one
        stream pass), drawing from the pipelined iterator: a background
        thread produces and key-encodes chunk *i+1* while chunk *i*'s
        deepest-level bincount folds in — the same overlap discipline as
        the chunked descent (streaming/pipeline.py). ``pipeline_depth``
        ``None`` takes the pipeline default; 0 is the synchronous path.

        ``devices`` > 1 stages chunks round-robin across that many chips
        and counts each chunk's DEEPEST-level histogram (plus key-space
        extremes) on its own device, folding the per-device int32 partials
        into the host int64 pyramid in chunk order — exactly how
        ``parallel/sketch.py:distributed_sketch`` merges its psum lanes,
        minus the collective (the partials ride the host accumulator
        instead). The host-exact 64-bit-no-x64 and f64-on-TPU routes keep
        counting on host regardless.

        ``spill`` is an optional
        :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore`: the ONE
        stream pass also tees every chunk's encoded keys into a new
        generation of it (the sketch-then-refine flow for one-shot
        sources — a bare iterator/generator is accepted when teeing).
        Afterwards the STORE is a first-class chunk source:
        ``sketch.refine(store, k)`` runs the exact descent entirely from
        disk, never re-reading the original stream.

        ``fused`` (``None`` resolves to the package default,
        streaming/executor.py:DEFAULT_FUSED) picks the staged fold's
        fusion tier: at ``"kernel"`` a supported staged bucket's deep
        histogram and extremes ride ONE single-sweep program (one
        guaranteed read; ``ingest.bucket_reads{phase="sketch"}`` = 1
        per bucket) instead of the historical 2-program pair, which the
        ``"xla"``/``"off"`` tiers keep. Bit-identical either way.

        ``pack_spill="auto"`` tees the generation in spill.py's format
        v2, segmented by each key's top digit
        (:data:`~mpi_k_selection_tpu.streaming.spill.GEN0_SEGMENT_BITS`)
        — exactly like the descent's own pass-0 tee. A later
        :meth:`refine`/:meth:`refine_many` over the store then PRUNES its
        sketch-seeded first pass to the segments under the surviving
        sketch buckets instead of re-reading the whole generation, and
        each record sheds its stored top bits on disk. ``"off"`` (the
        ``None`` default) keeps the full-width v1 records. Bit-identical
        answers either way.

        ``ingest_workers`` widens the host data plane exactly as in
        ``streaming_kselect``: ``"auto"``/an int > 1 runs the stream
        pass's encode + spill-tee pack + staging on a pool of
        ``ksel-ingest-*`` workers behind a reorder sequencer, 1 (the
        ``None`` default) is the byte-for-byte single-producer path.
        The fold itself (and its chunk order) is unchanged either way.

        ``obs`` (an :class:`~mpi_k_selection_tpu.obs.Observability`) emits
        per-chunk ingest events, a ``sketch.pass`` summary event, window
        occupancy samples and the StagingPool counters — off by default,
        never changes a count bit.

        Bit-identical to sequential :meth:`update` calls over the same
        chunks, for every ``pipeline_depth`` x ``devices`` combination.
        Returns ``self``."""
        from mpi_k_selection_tpu.obs import events as _ev
        from mpi_k_selection_tpu.obs import wiring as _wr
        from mpi_k_selection_tpu.streaming import executor as _exec
        from mpi_k_selection_tpu.streaming import pipeline as _pl
        from mpi_k_selection_tpu.streaming import spill as _sp
        from mpi_k_selection_tpu.streaming.chunked import (
            _key_chunk_stream,
            as_chunk_source,
        )

        pipeline_depth = _pl.validate_pipeline_depth(pipeline_depth)
        pack_spill = _sp.validate_pack_spill(pack_spill)
        pool_n = _pl.resolve_ingest_workers(ingest_workers)
        devs = _pl.resolve_stream_devices(devices)
        # the staged fold is deferred by construction (it rides the FIFO
        # window), so the tier resolves unconditionally
        fuse = _exec.resolve_fused(
            _exec.DEFAULT_FUSED if fused is None else fused
        )
        timer, _restore_recorder = _wr.attach_timer(obs, timer)
        # staging is gated on the RAW knobs (depth, the devices argument)
        # — never on the resolved tuple, so an explicitly requested
        # single device stages committed instead of host-folding (KSL022)
        staged = pipeline_depth > 0 and devices is not None
        if spill is not None and not isinstance(spill, _sp.SpillStore):
            raise TypeError(
                "update_stream's spill must be a SpillStore (the caller "
                f"owns its lifecycle), got {type(spill).__name__!r}"
            )
        src = as_chunk_source(
            source, one_shot_ok=spill is not None, workers=pool_n
        )
        _wr.ingest_workers_gauge(obs, pool_n)
        writer = (
            spill.new_generation(
                pack_digit_bits=(
                    _sp.GEN0_SEGMENT_BITS if pack_spill == "auto" else None
                )
            )
            if spill is not None else None
        )
        chunk_i = keys_read = 0
        ex = keys = None
        try:
            # consumer/executor built INSIDE the try: a constructor
            # raising must still abort the generation, or its records
            # strand on disk (KSL020)
            consumer = _SketchFoldConsumer(self, obs=obs, fused=fuse)
            ex = _exec.StreamExecutor(
                [consumer], window=len(devs),
                occupancy=_wr.window_occupancy(obs, phase="sketch"),
            )
            with _pl._phase(timer, "sketch.pass"), _key_chunk_stream(
                src, self.dtype, pipeline_depth=pipeline_depth, timer=timer,
                # "scatter" handles the deepest level's 2**resolution_bits
                # buckets (the same method distributed_sketch defaults to);
                # resolve_stream_hist downgrades it to host counting exactly
                # where the device would not be bit-exact
                hist_method="scatter" if staged else None,
                devices=devs if staged else None,
                spill=writer,
                workers=pool_n,
            ) as kc:
                for keys, _ in kc:
                    if obs is not None:
                        _wr.chunk_event(
                            obs, "sketch", chunk_i, keys, self.kdt, devs
                        )
                    chunk_i += 1
                    keys_read += int(keys.size)
                    ex.push(keys)
                ex.drain()
            # commit INSIDE the try: anything raising between the drain
            # and the commit (the recorder detach below included) must
            # abort the generation, not strand it uncommitted
            if writer is not None:
                writer.commit()
        except BaseException:
            # writer.abort() rides a finally: an executor abort (or the
            # staged-chunk release) raising must not strand the
            # generation's ksel-spill records
            try:
                if ex is not None:
                    ex.abort()
                _exec.release_staged(keys)  # the chunk in hand (idempotent)
            finally:
                if writer is not None:
                    writer.abort()
            raise
        finally:
            # detach a recorder this call attached to a caller-owned timer
            # (no phase records outside the stream context above)
            _restore_recorder()
        if obs is not None:
            obs.emit(
                _ev.SketchPassEvent(
                    chunks=chunk_i,
                    keys_read=keys_read,
                    bytes_read=keys_read * self.kdt.itemsize,
                    staged_chunks=consumer.staged_chunks,
                )
            )
            if obs.metrics is not None:
                from mpi_k_selection_tpu.obs.metrics import collect_runtime

                collect_runtime(
                    obs.metrics, staging_pool=_pl.STAGING_POOL,
                    spill_store=spill, timer=timer,
                )
        return self

    def _dispatch_staged(self, staged) -> tuple:
        """Dispatch one staged chunk's deepest-level int32 histogram and
        key-space extremes on ITS device (async); finished by
        :meth:`_fold_staged` in chunk order."""
        import jax.numpy as jnp

        from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram

        deep = masked_radix_histogram(
            staged.data,  # the whole padded bucket: fixed shape, one compile
            shift=self.total_bits - self.resolution_bits,
            radix_bits=self.resolution_bits,
            prefix=None,
            method="scatter",
            count_dtype=jnp.int32,  # exact per chunk (chunk size < 2^31)
        )
        # extremes must not see the pad zeros — computed over the FULL
        # bucket with the pad masked to the identities, so this half stays
        # bucket-shaped (one compile per bucket) like the histogram half
        dmin, dmax = _staged_extremes(staged.data, np.int32(staged.n_valid))
        return staged, deep, dmin, dmax

    def _dispatch_staged_sweep(self, staged) -> tuple:
        """The kernel-tier twin of :meth:`_dispatch_staged`: deep
        histogram AND extremes from ONE single-sweep program
        (ops/pallas/sweep_ingest.py) — same handle shape, so
        :meth:`_fold_staged` (and its exact pad subtraction) serves both
        tiers unchanged."""
        from mpi_k_selection_tpu.ops.pallas import sweep_ingest as _si

        _, _, _, _, (deep, dmin, dmax) = _si.dispatch_sweep_ingest(
            staged, kdt=self.kdt, sketch_bits=self.resolution_bits
        )
        return staged, deep, dmin, dmax

    def _fold_staged(self, handle) -> None:
        """Materialize one :meth:`_dispatch_staged` handle into the host
        int64 pyramid — the same int32-partial -> int64-accumulator merge
        discipline as ``parallel/sketch.py:distributed_sketch`` (pad keys
        are key-space 0: an exact subtraction from deep bucket 0). Buffer
        release belongs to the executor, which frees the staged slot once
        the whole bundle has finished."""
        staged, deep, dmin, dmax = handle
        h = np.asarray(deep).astype(np.int64)
        if staged.pad:
            h[0] -= staged.pad
        self._fold_deep_histogram(h)
        kmin = self.kdt.type(np.asarray(dmin))
        kmax = self.kdt.type(np.asarray(dmax))
        if self._min_key is None or kmin < self._min_key:
            self._min_key = kmin
        if self._max_key is None or kmax > self._max_key:
            self._max_key = kmax
        self.n += staged.n_valid

    def _fold_deep_histogram(self, deep: np.ndarray) -> None:
        """Accumulate one deepest-level int64 histogram into every level
        (shallow levels by reshape-sum — see :meth:`update`). Shared with
        parallel/sketch.py, whose device pass also produces only the
        deepest level."""
        self.hists[-1] += deep
        for l in range(1, self.levels):
            self.hists[l - 1] += deep.reshape(1 << (l * self.radix_bits), -1).sum(
                axis=1
            )

    def _check_compatible(self, other: "RadixSketch") -> None:
        if not isinstance(other, RadixSketch):
            raise TypeError(f"cannot merge RadixSketch with {type(other).__name__}")
        if (
            self.dtype != other.dtype
            or self.radix_bits != other.radix_bits
            or self.levels != other.levels
        ):
            raise ValueError(
                f"incompatible sketches: ({self.dtype}, rb={self.radix_bits}, "
                f"L={self.levels}) vs ({other.dtype}, rb={other.radix_bits}, "
                f"L={other.levels})"
            )

    def merge(self, other: "RadixSketch") -> "RadixSketch":
        """Pure elementwise-sum merge — associative and commutative, so any
        merge tree over the same update set yields a bitwise-identical
        sketch. Neither operand is mutated."""
        self._check_compatible(other)
        out = RadixSketch(self.dtype, radix_bits=self.radix_bits, levels=self.levels)
        out.n = self.n + other.n
        out.hists = [a + b for a, b in zip(self.hists, other.hists)]
        mins = [s._min_key for s in (self, other) if s._min_key is not None]
        maxs = [s._max_key for s in (self, other) if s._max_key is not None]
        out._min_key = self.kdt.type(min(mins)) if mins else None
        out._max_key = self.kdt.type(max(maxs)) if maxs else None
        return out

    __add__ = merge

    def copy(self) -> "RadixSketch":
        """Independent deep copy (counts and extremes) — the suffix-merge
        seed of the sliding-window ring (monitor/windows.py)."""
        out = RadixSketch(self.dtype, radix_bits=self.radix_bits, levels=self.levels)
        out.n = self.n
        out.hists = [h.copy() for h in self.hists]
        out._min_key = self._min_key
        out._max_key = self._max_key
        return out

    def fold_scaled(self, other: "RadixSketch", weight: int) -> "RadixSketch":
        """In-place count-scaled fold: every count of ``other`` enters
        ``self`` multiplied by the non-negative integer ``weight``
        (``weight=1`` is a plain in-place merge — the windowed ring's
        subtract-free suffix aggregation; larger weights are the
        fixed-point exponential decay of monitor/decay.py, where a bucket
        of age ``a`` folds at ``round(decay**a * 2**DECAY_SHIFT)``).

        Because each term is an exact ``int64`` product summed
        elementwise, scaled folds stay associative AND commutative: any
        grouping of buckets (each carrying its own fixed weight) yields a
        bitwise-identical accumulator. The int64 accumulator discipline
        (KSC102) bounds the width: this refuses loudly when
        ``other.n * weight`` could push the total count past ``2**63 - 1``
        instead of silently wrapping. ``weight=0`` folds nothing (a fully
        decayed bucket) but is still a valid no-op. Returns ``self``."""
        self._check_compatible(other)
        weight = int(weight)
        if weight < 0:
            raise ValueError(f"fold weight must be >= 0, got {weight}")
        if weight == 0 or other.n == 0:
            return self
        if other.n > ((1 << 63) - 1 - self.n) // weight:
            raise OverflowError(
                f"count-scaled fold of n={other.n} at weight={weight} would "
                f"overflow the int64 accumulator (current n={self.n}); lower "
                "DECAY_SHIFT or shorten the window (docs/OBSERVABILITY.md "
                "'Continuous monitoring')"
            )
        for mine, theirs in zip(self.hists, other.hists):
            if weight == 1:
                mine += theirs
            else:
                mine += theirs * weight
        self.n += other.n * weight
        if other._min_key is not None and (
            self._min_key is None or other._min_key < self._min_key
        ):
            self._min_key = self.kdt.type(other._min_key)
        if other._max_key is not None and (
            self._max_key is None or other._max_key > self._max_key
        ):
            self._max_key = self.kdt.type(other._max_key)
        return self

    def update_value(self, value) -> "RadixSketch":
        """Fold ONE observation in — O(levels) counter increments, no
        ``2**resolution_bits`` bincount allocation — the per-observe path
        of the windowed-histogram bridge (obs/windows.py), where a sketch
        sees one latency sample at a time. Bit-identical to
        ``update([value])``."""
        key = _dt.np_to_sortable_bits(
            np.asarray([value], self.dtype)
        )[0]
        deep = int(key >> self.kdt.type(self.total_bits - self.resolution_bits))
        for l in range(1, self.levels + 1):
            self.hists[l - 1][deep >> ((self.levels - l) * self.radix_bits)] += 1
        if self._min_key is None or key < self._min_key:
            self._min_key = self.kdt.type(key)
        if self._max_key is None or key > self._max_key:
            self._max_key = self.kdt.type(key)
        self.n += 1
        return self

    def __eq__(self, other) -> bool:
        if not isinstance(other, RadixSketch):
            return NotImplemented
        return (
            self.dtype == other.dtype
            and self.radix_bits == other.radix_bits
            and self.levels == other.levels
            and self.n == other.n
            and self._min_key == other._min_key
            and self._max_key == other._max_key
            and all(np.array_equal(a, b) for a, b in zip(self.hists, other.hists))
        )

    __hash__ = None  # mutable accumulator

    # -- queries -----------------------------------------------------------

    def _bucket(self, k: int, level: int | None = None):
        """(bucket, rank_lo, rank_hi) at ``level`` (deepest by default):
        the resolved-prefix bucket whose exact rank interval contains k.
        The level's CDF is memoized until the next accumulation — on the
        serve fast path a pinned sketch answers thousands of queries
        between updates, and the cumsum was ~3/4 of per-query cost."""
        if self.n == 0:
            raise ValueError("empty sketch")
        k = int(k)
        if not 1 <= k <= self.n:
            raise ValueError(f"k={k} out of range [1, {self.n}]")
        lvl = self.levels if level is None else level
        cached = self._cdf_cache.get(lvl)
        if cached is not None and cached[0] == self.n:
            cum = cached[1]
        else:
            cum = np.cumsum(self.hists[lvl - 1])
            self._cdf_cache[lvl] = (self.n, cum)
        b = int(np.searchsorted(cum, k, side="left"))
        lo = int(cum[b - 1]) if b else 0
        return b, lo, int(cum[b])

    def rank_bounds(self, k: int) -> tuple[int, int]:
        """Exact ``(lo, hi)`` with ``lo < k <= hi``: the true ranks
        bracketing the resolved key interval that contains the k-th
        smallest element. Holds for ANY stream (adversarial included)."""
        _, lo, hi = self._bucket(k)
        return lo, hi

    def rank_error_bound(self, k: int) -> int:
        """Worst-case rank error of :meth:`query`'s point estimate for this
        k: the answering bucket's population (``hi - lo``). For streams with
        no resolved interval heavier than ``c * n / 2**resolution_bits``
        this is the advertised ``c * n / 2**bits`` bound."""
        lo, hi = self.rank_bounds(k)
        return hi - lo

    def max_bucket_population(self) -> int:
        """Heaviest deepest-level bucket — the sketch-wide rank-error bound
        (``max_k rank_error_bound(k)``)."""
        return int(self.hists[-1].max()) if self.n else 0

    def _interval_keys(self, bucket: int):
        shift = self.total_bits - self.resolution_bits
        lo_key = self.kdt.type(np.uint64(bucket) << np.uint64(shift))
        span = (np.uint64(1) << np.uint64(shift)) - np.uint64(1)
        hi_key = self.kdt.type((np.uint64(bucket) << np.uint64(shift)) | span)
        lo_key = max(lo_key, self._min_key)
        hi_key = min(hi_key, self._max_key)
        return lo_key, hi_key

    def value_bounds(self, k: int):
        """``(v_lo, v_hi)`` values of the stream's dtype with the true k-th
        smallest guaranteed inside ``[v_lo, v_hi]`` — the resolved key
        interval clamped to the observed extremes. Exact for any stream."""
        b, _, _ = self._bucket(k)
        lo_key, hi_key = self._interval_keys(b)
        pair = _dt.np_from_sortable_bits(np.asarray([lo_key, hi_key], self.kdt), self.dtype)
        return pair[0], pair[1]

    def query(self, k: int):
        """Point estimate for the k-th smallest: the answering interval's
        lower boundary (clamped to the observed extremes). Rank error
        bounded by :meth:`rank_error_bound`; use :meth:`refine` for exact."""
        return self.value_bounds(k)[0]

    def describe(self, k: int):
        """Everything the serve sketch tier reports about one rank in a
        SINGLE bucket resolution: ``(rank_lo, rank_hi, v_lo, v_hi,
        pinned)``, field-for-field equal to :meth:`rank_bounds`,
        :meth:`value_bounds` and :meth:`pin` called separately. Those
        three each re-resolve the same bucket and re-decode the same key
        interval; on the serve fast path (serve/tiers.py) that redundancy
        was the bulk of per-query cost, so the hot path asks once."""
        b, lo, hi = self._bucket(k)
        lo_key, hi_key = self._interval_keys(b)
        pair = _dt.np_from_sortable_bits(
            np.asarray([lo_key, hi_key], self.kdt), self.dtype
        )
        pinned = pair[0] if lo_key == hi_key else None
        return lo, hi, pair[0], pair[1], pinned

    def pin(self, k: int):
        """The EXACT k-th smallest when the sketch already pins it — the
        answering key interval, clamped to the observed extremes, is a
        single key, so the true order statistic can only be that value —
        else ``None``. The query server's auto tier answers from the
        sketch exactly when every requested rank pins
        (serve/tiers.py); a pinned value is bit-identical to the exact
        descent's answer by construction. Pinning happens when the
        resolution covers the full key width (e.g. 16-bit dtypes at
        4x4), when the data concentrates (min == max inside the
        answering bucket), or at the clamped extremes."""
        b, _, _ = self._bucket(k)
        lo_key, hi_key = self._interval_keys(b)
        if lo_key != hi_key:
            return None
        return _dt.np_from_sortable_bits(
            np.asarray([lo_key], self.kdt), self.dtype
        )[0]

    def quantile(self, q: float):
        """Approximate quantile (nearest-rank convention, matching
        api.quantile_ranks)."""
        return self.quantiles([q])[0]

    def quantiles(self, qs):
        from mpi_k_selection_tpu.api import quantile_ranks

        return [self.query(k) for k in quantile_ranks(qs, self.n)]

    # -- exact refinement --------------------------------------------------

    def walk(self, k: int):
        """``(prefix, rebased_k, resolved_bits, population)`` of the deepest
        exact level — the seed for a chunked descent, identical in meaning
        to ``resolution_bits / radix_bits`` streamed histogram passes."""
        b, lo, hi = self._bucket(k)
        return b, int(k) - lo, self.resolution_bits, hi - lo

    def check_stream(self, dtype, radix_bits: int, width_schedule="off") -> None:
        """Validate that a chunked descent with ``radix_bits`` can continue
        from this sketch's resolved prefix (streaming/chunked.py calls this
        before seeding). With a non-``"off"`` ``width_schedule`` the
        divisibility constraint moves to the schedule itself
        (chunked.py:resolve_width_schedule validates that the widths sum
        to the remaining bits, whatever ``radix_bits`` is) — only the
        dtype agreement is checked here."""
        if np.dtype(dtype) != self.dtype:
            raise TypeError(
                f"stream dtype {np.dtype(dtype)} != sketch dtype {self.dtype}"
            )
        if width_schedule != "off":
            return
        remaining = self.total_bits - self.resolution_bits
        if remaining % radix_bits:
            raise ValueError(
                f"radix_bits={radix_bits} must divide the {remaining} key "
                f"bits left below the sketch's {self.resolution_bits} "
                "resolved bits"
            )

    def refine(self, source, k: int, **kwargs):
        """Exact k-th smallest over ``source`` (which must replay the very
        stream this sketch accumulated), reusing the sketch's resolved
        prefix to skip its ``levels`` passes. ``source`` may be the
        :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore` a
        one-shot :meth:`update_stream` teed into — the refinement then
        runs entirely from the spilled generation, shrinking it
        geometrically pass over pass, and the original stream is never
        read again. Keyword options are those of
        streaming/chunked.py:streaming_kselect."""
        from mpi_k_selection_tpu.streaming.chunked import streaming_kselect

        kwargs.setdefault("radix_bits", self.radix_bits)
        return streaming_kselect(source, k, sketch=self, **kwargs)

    def refine_many(self, source, ks, **kwargs):
        """Exact k-th smallest for EVERY rank in ``ks`` over ``source``
        (which must replay the very stream this sketch accumulated) —
        the multi-rank twin of :meth:`refine`, and the resident-sketch
        exact entry the query server's stream datasets dispatch through
        (serve/registry.py): one sketch-seeded descent shares every
        streamed pass across all requested ranks, so a coalesced batch
        costs roughly the stream replays of one rank. ``source`` may be
        a committed :class:`~mpi_k_selection_tpu.streaming.spill.
        SpillStore`. Returns answers in ``ks`` order."""
        from mpi_k_selection_tpu.streaming.chunked import streaming_kselect_many

        kwargs.setdefault("radix_bits", self.radix_bits)
        return streaming_kselect_many(source, ks, sketch=self, **kwargs)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"RadixSketch(dtype={self.dtype}, radix_bits={self.radix_bits}, "
            f"levels={self.levels}, n={self.n}, "
            f"resolution_bits={self.resolution_bits})"
        )
