"""Continuous telemetry quantiles — sliding-window & decayed sketch
monitoring over unbounded streams.

The reference's second operating point already ran exact medians
(``k = N/2`` in the ``.c~`` backups) — quantiles, not just top-k, were
always the workload. This package turns the repo's mergeable
:class:`~mpi_k_selection_tpu.streaming.sketch.RadixSketch` (exact
rank/value bounds, associative+commutative merge) into a *continuous*
monitoring surface: p50/p90/p99 — any rank set — over a stream that
never ends, with every answer still carrying the sketch's exact bounds.

- :mod:`windows` — :class:`WindowedSketch`: a ring of per-time-bucket
  sketches whose two-stack (subtract-free) suffix aggregation gives O(1)
  amortized sketch merges per window advance and bit-identical
  re-aggregation over any suffix of live buckets.
- :mod:`decay` — :class:`DecayedWindowedSketch` /
  :class:`DecayedSketch`: the exponential-decay variant. Counts scale by
  integer fixed-point weights BEFORE the fold, so decayed merges stay
  associative/commutative and ``decay=1.0`` degenerates bit-identically
  to the undecayed ring.
- :mod:`monitor` — :class:`Monitor`: drives any replayable-or-one-shot
  chunk source through the existing ingest pipeline + async executor
  (unchanged underneath) and yields a continuous
  ``multirank_p50_p90_p99`` sample stream.

Surfaced as the CLI ``monitor`` subcommand (``kselect monitor``), the
windowed-histogram metrics bridge (obs/windows.py — backs
``serve.latency_seconds{tier}`` with exactly-bounded windowed quantiles
via ``KSelectServer(latency_windows=...)``), and ``bench.py:
bench_monitor`` (the O(1)-advance proof). See docs/OBSERVABILITY.md
"Continuous monitoring".
"""

from __future__ import annotations

from mpi_k_selection_tpu.monitor.decay import (
    DECAY_SHIFT,
    DecayedSketch,
    DecayedWindowedSketch,
    decay_weight,
)
from mpi_k_selection_tpu.monitor.monitor import (
    Monitor,
    MonitorSample,
    start_metrics_server,
)
from mpi_k_selection_tpu.monitor.windows import WindowedSketch

__all__ = [
    "DECAY_SHIFT",
    "DecayedSketch",
    "DecayedWindowedSketch",
    "Monitor",
    "MonitorSample",
    "WindowedSketch",
    "decay_weight",
    "start_metrics_server",
]
