"""Experiment: Pallas block top-k kernel for the batched config (r4 item 3).

Measures, on the real chip, at B=4096 x D=32768 f32 k=8:
  1. max-only streaming kernel  -> the achievable data-touch floor
  2. insert-chain top-8 kernel  -> per-(row,lane) running sorted-8, final
     XLA top_k merge over 8*128 candidates/row
vs the current production path (ops/topk.py chunked) and lax.top_k.

Scratch harness — findings land in ops/topk.py + docs; file kept as the
measurement record for the accept/reject decision.

r5 addendum (envelope widening, measured via bench._timed_chain on v5e at
4096x32768 — the decisions shipped in ops/pallas/topk.py):
  - depth-4 chain + 16-wide bitonic fold for 8 < k <= 16: ACCEPTED —
    values-only 1.25-1.5 ms (vs lax f32 top_k 6.3 ms), full tuple 5.1 ms;
    suspect rate C(16,5)/128^4 keeps the rescue bounded.
  - bfloat16 input: ACCEPTED via in-register f32 upcast (Mosaic v5e
    rejects bf16 vector compares: "Target does not support this
    comparison" on vector<...xbf16> cmpf) — values-only ~1.1 ms vs
    lax-bf16 9.0 ms (XLA's bf16 TopK is SLOWER than its f32 TopK),
    tuple 3.8 ms; compute-bound, so halved HBM traffic does not speed
    the chain.
  - index-carrying chain (value+slab register pairs): REJECTED — 5 VPU
    ops per insert vs 2 (cmp + 4 selects), ~2.4 ms projected at depth 3;
    the streaming post-hoc recovery (ops/topk.py:_block_topk_indices)
    costs ~3 ms total-tuple instead and is DCE-free for values-only
    callers. The r4 target "tuple <= 1.5 ms" was set against XLA TopK's
    2.4 ms VALUES-only figure; with indices actually consumed every XLA
    variant lowers to a ~135-142 ms variadic sort, so 3.7-4.5 ms is
    ~31-37x the only real alternative (recorded negative on the 1.5 ms
    number itself: the kernel + one unavoidable second read of x already
    costs ~1.7 ms).
"""
# ksel: noqa-file[KSL004] -- research script using the same inline perturb-chain clock discipline as bench.py

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, D, K = 4096, 32768, 8


def timeit(fn, *args, reps=5):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else fn(*args).block_until_ready()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        (r[0] if isinstance(r, tuple) else r).block_until_ready()
        ts.append(time.perf_counter() - t0)
    return min(ts)


# --- 1. max-only kernel: the floor ---------------------------------------


def _max_kernel(x_ref, o_ref, *, nd):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.full_like(o_ref, -jnp.inf)

    bb, bd = x_ref.shape
    x = x_ref[:].reshape(bb, bd // 128, 128)
    o_ref[:] = jnp.maximum(o_ref[:], jnp.max(x, axis=1))


@functools.partial(jax.jit, static_argnames=("bb", "bd"))
def pallas_row_max(x, bb=256, bd=4096):
    nb, nd = B // bb, D // bd
    out = pl.pallas_call(
        functools.partial(_max_kernel, nd=nd),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.float32),
        interpret=False,
    )(x)
    return jnp.max(out, axis=1)


# --- 2. insert-chain top-8: per-(row,lane) sorted-8 registers ------------
# tile (bb, bd) viewed as (bb, bd//128, 128): stream sublane slabs through
# an 8-deep compare-insert chain kept in the output block (bb, 8, 128),
# accumulated across the d-grid (index_map pins the out block per row).


def _top8_kernel(x_ref, o_ref, *, bd):
    j = pl.program_id(1)
    slabs = bd // 128

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.full_like(o_ref, -jnp.inf)

    bb = x_ref.shape[0]
    x = x_ref[:].reshape(bb, slabs, 128)
    regs = [o_ref[i * bb:(i + 1) * bb, :] for i in range(8)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(8):
            ri = regs[i]
            new_ri = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
            regs[i] = new_ri
    o_ref[:] = jnp.concatenate(regs, axis=0)


@functools.partial(jax.jit, static_argnames=("bb", "bd"))
def pallas_batched_top8(x, bb=256, bd=2048):
    nb, nd = B // bb, D // bd
    cand = pl.pallas_call(
        functools.partial(_top8_kernel, bd=bd),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8 * bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8 * B, 128), jnp.float32),
        interpret=False,
    )(x)
    # block i rows [8*bb*i, 8*bb*(i+1)): reg r at [r*bb, (r+1)*bb) within
    cand = cand.reshape(nb, 8, bb, 128).transpose(0, 2, 1, 3).reshape(B, 8 * 128)
    vals, _ = jax.lax.top_k(cand, K)
    return vals


def measure(fn, xd, reps=(2, 8)):
    """bench.py's differential perturb-chain timing (defeats the tunnel's
    repeat-elision that made naive block_until_ready timing report 17 TB/s)."""
    from bench import _perturb_chain, _timed_chain

    return _timed_chain(
        lambda r: _perturb_chain(fn, r), xd, lambda i: jnp.uint32(i + 1), reps
    )


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    t = measure(lambda a: pallas_row_max(a), x)
    print(f"max-only floor: {t*1e3:.3f} ms  ({B*D*4/t/1e9:.0f} GB/s)")

    from mpi_k_selection_tpu.ops.topk import topk

    t_prod = measure(lambda a: topk(a, K)[0], x)
    print(f"current production topk: {t_prod*1e3:.3f} ms")

    want = np.sort(np.asarray(x), axis=1)[:, ::-1][:, :K]

    for bb, bd in ((256, 2048), (512, 2048), (256, 4096), (128, 8192)):
        try:
            got = np.asarray(pallas_batched_top8(x, bb=bb, bd=bd))
            ok = np.array_equal(got, want)
            t = measure(lambda a, bb=bb, bd=bd: pallas_batched_top8(a, bb=bb, bd=bd), x)
            print(f"insert-chain top8 bb={bb} bd={bd}: {t*1e3:.3f} ms exact={ok}")
        except Exception as e:
            print(f"insert-chain top8 bb={bb} bd={bd}: FAIL {str(e)[:120]}")


if __name__ == "__main__":
    main()


# --- 3. depth-t chain (model calibration) + sort8-group variant ----------


def _topt_kernel(x_ref, o_ref, *, bd, depth):
    j = pl.program_id(1)
    slabs = bd // 128

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.full_like(o_ref, -jnp.inf)

    bb = x_ref.shape[0]
    x = x_ref[:].reshape(bb, slabs, 128)
    regs = [o_ref[i * bb:(i + 1) * bb, :] for i in range(depth)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(depth):
            ri = regs[i]
            new_ri = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
            regs[i] = new_ri
    o_ref[:] = jnp.concatenate(regs, axis=0)


@functools.partial(jax.jit, static_argnames=("bb", "bd", "depth"))
def pallas_topt(x, bb=512, bd=2048, depth=4):
    nb, nd = B // bb, D // bd
    cand = pl.pallas_call(
        functools.partial(_topt_kernel, bd=bd, depth=depth),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((depth * bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((depth * B, 128), jnp.float32),
        interpret=False,
    )(x)
    return cand  # candidates only; merge cost measured separately


def _sort8_group_kernel(x_ref, o_ref, *, bd):
    """Per 8-slab group: bitonic-sort the 8 slabs per (row,lane) descending,
    then merge with the running sorted-8 (compare r_i vs g_{7-i} + bitonic
    clean). ~(19 + 8 + 9) CE per 8 slabs ≈ 9 ops/elem vs the chain's 16."""
    j = pl.program_id(1)
    slabs = bd // 128

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.full_like(o_ref, -jnp.inf)

    bb = x_ref.shape[0]
    x = x_ref[:].reshape(bb, slabs, 128)
    regs = [o_ref[i * bb:(i + 1) * bb, :] for i in range(8)]

    def ce(a, b):  # descending compare-exchange
        return jnp.maximum(a, b), jnp.minimum(a, b)

    for g in range(slabs // 8):
        v = [x[:, g * 8 + i, :] for i in range(8)]
        # bitonic sort8 descending (19 CEs)
        for (a, b) in ((0,1),(2,3),(4,5),(6,7)):
            v[a], v[b] = ce(v[a], v[b])
        for (a, b) in ((0,2),(1,3),(4,6),(5,7)):
            v[a], v[b] = ce(v[a], v[b])
        for (a, b) in ((1,2),(5,6)):
            v[a], v[b] = ce(v[a], v[b])
        for (a, b) in ((0,4),(1,5),(2,6),(3,7)):
            v[a], v[b] = ce(v[a], v[b])
        for (a, b) in ((2,4),(3,5)):
            v[a], v[b] = ce(v[a], v[b])
        for (a, b) in ((1,2),(3,4),(5,6)):
            v[a], v[b] = ce(v[a], v[b])
        # merge with running top-8: winners of (r_i, v_{7-i}) form a bitonic
        # sequence; clean with a log network (12 CEs)
        w = [jnp.maximum(regs[i], v[7 - i]) for i in range(8)]
        for (a, b) in ((0,4),(1,5),(2,6),(3,7)):
            w[a], w[b] = ce(w[a], w[b])
        for (a, b) in ((0,2),(1,3),(4,6),(5,7)):
            w[a], w[b] = ce(w[a], w[b])
        for (a, b) in ((0,1),(2,3),(4,5),(6,7)):
            w[a], w[b] = ce(w[a], w[b])
        regs = w
    o_ref[:] = jnp.concatenate(regs, axis=0)


@functools.partial(jax.jit, static_argnames=("bb", "bd"))
def pallas_sort8_group(x, bb=512, bd=2048):
    nb, nd = B // bb, D // bd
    cand = pl.pallas_call(
        functools.partial(_sort8_group_kernel, bd=bd),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8 * bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8 * B, 128), jnp.float32),
        interpret=False,
    )(x)
    cand = cand.reshape(nb, 8, bb, 128).transpose(0, 2, 1, 3).reshape(B, 8 * 128)
    vals, _ = jax.lax.top_k(cand, K)
    return vals


# --- 4. depth-8 chain + IN-KERNEL bitonic lane fold (no XLA merge) -------


def _ce(a, b):
    return jnp.maximum(a, b), jnp.minimum(a, b)


def _lane_fold_top8(regs, bb):
    """Merge the per-lane sorted-8 columns across lanes: at each fold the
    left/right lane halves hold independent sorted-8 runs; winners of
    (a_i, b_{7-i}) form a bitonic sequence, cleaned with a 3-stage network.
    Returns 8 (bb, 1) arrays: the row's true top-8, sorted."""
    w = regs[0].shape[1] // 2
    while w >= 1:
        a = [r[:, :w] for r in regs]
        b = [r[:, w:2 * w] for r in regs]
        m = [jnp.maximum(a[i], b[7 - i]) for i in range(8)]
        for (i, j) in ((0, 4), (1, 5), (2, 6), (3, 7)):
            m[i], m[j] = _ce(m[i], m[j])
        for (i, j) in ((0, 2), (1, 3), (4, 6), (5, 7)):
            m[i], m[j] = _ce(m[i], m[j])
        for (i, j) in ((0, 1), (2, 3), (4, 5), (6, 7)):
            m[i], m[j] = _ce(m[i], m[j])
        regs = m
        w //= 2
    return regs


def _top8_fold_kernel(x_ref, o_ref, acc, *, bd, nd):
    j = pl.program_id(1)
    slabs = bd // 128
    bb = x_ref.shape[0]

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.full_like(acc, -jnp.inf)

    x = x_ref[:].reshape(bb, slabs, 128)
    regs = [acc[i * bb:(i + 1) * bb, :] for i in range(8)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(8):
            ri = regs[i]
            new_ri = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
            regs[i] = new_ri
    acc[:] = jnp.concatenate(regs, axis=0)

    @pl.when(j == nd - 1)
    def _():
        top = _lane_fold_top8(regs, bb)
        o_ref[:] = jnp.concatenate(top, axis=1)  # (bb, 8), sorted desc


@functools.partial(jax.jit, static_argnames=("bb", "bd"))
def pallas_top8_fold(x, bb=512, bd=2048):
    nb, nd = B // bb, D // bd
    out = pl.pallas_call(
        functools.partial(_top8_fold_kernel, bd=bd, nd=nd),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bb, 8), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 8), jnp.float32),
        scratch_shapes=[pltpu.VMEM((8 * bb, 128), jnp.float32)],
        interpret=False,
    )(x)
    return out


# --- 5. two-kernel variant: chain (no scratch) + tiny fold kernel --------


def _fold_only_kernel(c_ref, o_ref, *, bb):
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(8)]
    top = _lane_fold_top8(regs, bb)
    o_ref[:] = jnp.concatenate(top, axis=1)


@functools.partial(jax.jit, static_argnames=("bb", "bd"))
def pallas_top8_twokernel(x, bb=512, bd=2048):
    nb, nd = B // bb, D // bd
    cand = pl.pallas_call(
        functools.partial(_top8_kernel, bd=bd),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8 * bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8 * B, 128), jnp.float32),
        interpret=False,
    )(x)
    out = pl.pallas_call(
        functools.partial(_fold_only_kernel, bb=bb),
        grid=(nb,),
        in_specs=[pl.BlockSpec((8 * bb, 128), lambda i: (i, 0), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bb, 8), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 8), jnp.float32),
        interpret=False,
    )(cand)
    return out


# --- 6. depth-3 chain + fold + suspect-row rescue (target <= 1.2 ms) -----
# Exactness: if no lane's 3rd-kept value is > t8_hat (the 8th of the folded
# candidate top-8), every row value > t8_hat is among the candidates, which
# forces fold(candidates) == true top-8 BY VALUE. Suspect rows (a lane
# holding >= 4 of the row's top 8 — P ~ 3e-3 per batch row for random
# data) are re-solved exactly by lax.top_k on a gathered bounded subset,
# with a cond full-fallback if the budget overflows.


def _chain_kernel_t(x_ref, o_ref, *, bd, depth):
    j = pl.program_id(1)
    slabs = bd // 128
    bb = x_ref.shape[0]

    @pl.when(j == 0)
    def _():
        o_ref[:] = jnp.full_like(o_ref, -jnp.inf)

    x = x_ref[:].reshape(bb, slabs, 128)
    regs = [o_ref[i * bb:(i + 1) * bb, :] for i in range(depth)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(depth):
            ri = regs[i]
            regs[i] = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
    o_ref[:] = jnp.concatenate(regs, axis=0)


def _fold3_kernel(c_ref, o_ref, s_ref, *, bb):
    neg = jnp.full((bb, 128), -jnp.inf, jnp.float32)
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(3)] + [neg] * 5
    lane3 = regs[2]
    top = _lane_fold_top8(regs, bb)
    o_ref[:] = jnp.concatenate(top, axis=1)
    t8 = top[7]  # (bb, 1)
    s = jnp.where(lane3 > t8, jnp.float32(1), jnp.float32(0))
    w = 64
    while w >= 1:
        s = jnp.maximum(s[:, :w], s[:, w:2 * w])
        w //= 2
    s_ref[:] = s


@functools.partial(jax.jit, static_argnames=("bb", "bd", "rescue_rows"))
def pallas_top8_rescue(x, bb=512, bd=2048, rescue_rows=128):
    nb, nd = B // bb, D // bd
    cand = pl.pallas_call(
        functools.partial(_chain_kernel_t, bd=bd, depth=3),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((3 * bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((3 * B, 128), jnp.float32),
        interpret=False,
    )(x)
    top, susp = pl.pallas_call(
        functools.partial(_fold3_kernel, bb=bb),
        grid=(nb,),
        in_specs=[pl.BlockSpec((3 * bb, 128), lambda i: (i, 0), memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((bb, 8), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 8), jnp.float32),
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
        ],
        interpret=False,
    )(cand)
    sflag = susp[:, 0] > 0
    nsusp = jnp.sum(sflag.astype(jnp.int32))

    # bounded rescue: re-solve the suspect rows exactly
    sval, sidx = jax.lax.top_k(sflag.astype(jnp.int32), rescue_rows)
    rows = x[sidx]  # (rescue_rows, D) gather
    rtop, _ = jax.lax.top_k(rows, 8)
    fixed = jnp.where(sval[:, None] > 0, rtop, top[sidx])
    top = top.at[sidx].set(fixed)

    def full_fallback(_):
        v, _ = jax.lax.top_k(x, 8)
        return v

    return jax.lax.cond(nsusp <= rescue_rows, lambda _: top, full_fallback, 0)


# --- 7. fused single-kernel: chain + fold/suspect at last grid step ------


def _fused3_kernel(x_ref, c_ref, o_ref, s_ref, *, bd, nd):
    j = pl.program_id(1)
    slabs = bd // 128
    bb = x_ref.shape[0]

    @pl.when(j == 0)
    def _():
        c_ref[:] = jnp.full_like(c_ref, -jnp.inf)

    x = x_ref[:].reshape(bb, slabs, 128)
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(3)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(3):
            ri = regs[i]
            regs[i] = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
    c_ref[:] = jnp.concatenate(regs, axis=0)

    @pl.when(j == nd - 1)
    def _():
        neg = jnp.full((bb, 128), -jnp.inf, jnp.float32)
        lane3 = regs[2]
        top = _lane_fold_top8(list(regs) + [neg] * 5, bb)
        o_ref[:] = jnp.concatenate(top, axis=1)
        t8 = top[7]
        s = jnp.where(lane3 > t8, jnp.float32(1), jnp.float32(0))
        w = 64
        while w >= 1:
            s = jnp.maximum(s[:, :w], s[:, w:2 * w])
            w //= 2
        s_ref[:] = s


@functools.partial(jax.jit, static_argnames=("bb", "bd", "rescue_rows"))
def pallas_top8_fused(x, bb=512, bd=2048, rescue_rows=64):
    nb, nd = B // bb, D // bd
    _cand, top, susp = pl.pallas_call(
        functools.partial(_fused3_kernel, bd=bd, nd=nd),
        grid=(nb, nd),
        in_specs=[pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((3 * bb, 128), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 8), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((3 * B, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, 8), jnp.float32),
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
        ],
        interpret=False,
    )(x)
    sflag = susp[:, 0] > 0
    nsusp = jnp.sum(sflag.astype(jnp.int32))
    sval, sidx = jax.lax.top_k(sflag.astype(jnp.int32), rescue_rows)
    rows = x[sidx]
    rtop, _ = jax.lax.top_k(rows, 8)
    fixed = jnp.where(sval[:, None] > 0, rtop, top[sidx])
    top = top.at[sidx].set(fixed)

    def full_fallback(_):
        v, _ = jax.lax.top_k(x, 8)
        return v

    return jax.lax.cond(nsusp <= rescue_rows, lambda _: top, full_fallback, 0)
