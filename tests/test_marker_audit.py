"""Tier-1 membership audit — thin wrapper over the KSL005 lint rule.

The audit logic (every ``tests/test_*.py`` must either contribute at
least one collected test to the ``-m 'not slow'`` selection or contain an
explicit ``pytest.mark.slow`` opt-out) now lives in
``analysis/ast_rules.py:Tier1Membership`` so the ``kselect-lint`` gate
enforces it too; this test keeps the historical entry point and the
direct failure message.
"""

import pathlib

from mpi_k_selection_tpu.analysis.ast_rules import Tier1Membership

TESTS_DIR = pathlib.Path(__file__).resolve().parent


def test_every_test_file_is_tier1_or_explicitly_slow():
    offenders = [f.name for f in Tier1Membership().collect_offenders(TESTS_DIR)]
    assert not offenders, (
        "test files neither collected under tier-1 (-m 'not slow') nor "
        f"explicitly slow-marked: {offenders}"
    )
