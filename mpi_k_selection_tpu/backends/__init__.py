"""Backend registry: ``seq`` (CPU oracle), ``tpu`` (JAX), ``mpi`` (native
multi-process CGM) — the ``--backend={seq,mpi,tpu}`` surface mandated by the
north star (BASELINE.json)."""

BACKENDS = ("seq", "tpu", "mpi")


def get_backend(name: str):
    if name == "seq":
        from mpi_k_selection_tpu.backends import seq

        return seq
    if name == "tpu":
        from mpi_k_selection_tpu.backends import tpu

        return tpu
    if name == "mpi":
        from mpi_k_selection_tpu.backends import mpi

        return mpi
    raise ValueError(f"unknown backend {name!r}; choose from {BACKENDS}")
