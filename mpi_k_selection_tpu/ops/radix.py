"""Exact k-selection by radix descent — the TPU-native core algorithm.

This is the TPU replacement for the reference's selection engines: the
sequential sort-then-index (``kth-problem-seq.c:32-33``), the hand-rolled
quicksort partition (``vector.c:23-50``), and the CGM pivot-count-discard loop
(``TODO-kth-problem-cgm.c:122-232``). Instead of physically discarding
elements (``VecErase`` swap-deletes, ``TODO-…:204-225``) — impossible under
XLA's static shapes — radix descent never moves data at all: each pass counts
digit occurrences among the elements that still match the current bit prefix,
narrows the prefix by ``radix_bits`` bits, and rescales k. After
``key_bits / radix_bits`` passes the answer's bits are fully determined.

Properties that make this the right TPU design (SURVEY.md §7):

- fixed trip count (4 passes for 32-bit at radix 256) — no data-dependent
  control flow, everything jits into one XLA program;
- static shapes throughout — the "discard" is implicit in the prefix mask;
- the only cross-pass state is (prefix, k): two scalars, so the distributed
  version needs just one psum of the histogram per pass
  (parallel/radix.py), mirroring how the reference's per-round traffic is
  O(p) scalars (SURVEY.md §3.2) but with even fewer rounds.

Exactness: counts are integer and exact, so the returned value is always the
true k-th smallest (1-indexed, duplicates included) — the same guarantee the
reference's ``L < k <= L+E`` test provides (``TODO-…:194``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.ops.histogram import (
    masked_radix_histogram,
    multi_masked_radix_histogram,
)
from mpi_k_selection_tpu.utils import dtypes as _dt


def default_radix_bits(dtype, hist_method: str = "auto") -> int:
    """4 on the TPU Pallas path (8 memory-bound passes beat 4 compute-bound
    ones on the VPU — see ops/pallas/histogram.py), 8 elsewhere (fewer
    passes; the scatter/onehot paths scale fine to 256 buckets)."""
    from mpi_k_selection_tpu.ops.histogram import resolve_hist_method

    method = resolve_hist_method(hist_method, _dt.key_dtype(dtype))
    return 4 if method in ("pallas", "pallas64") else 8


def select_count_dtype(n: int):
    """int32 counts are exact for n < 2^31; beyond that int64 (requires x64)."""
    if n < 2**31:
        return jnp.int32
    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"n={n} needs int64 counters; enable jax_enable_x64 "
            "(SURVEY.md §7: int overflow hygiene)"
        )
    return jnp.int64


def cutover_passes(n: int, total_bits: int, radix_bits: int, budget: int) -> int | None:
    """Number of full histogram passes to run before the first
    collect-and-sort cutover attempt, or None when the fixed schedule is
    better.

    Chosen so the *expected* surviving population (``n >> resolved_bits``
    for full-range uniform keys) is <= budget/16 — a 16x margin because
    real data rarely spans the full key range (the reference generator's
    values sit in [1, 1e8], 43x denser than full-range int32 —
    ``TODO-kth-problem-cgm.c:15``), which inflates survivors by
    range_fraction^-1 over the model. Data denser still falls to the next
    rung of the runtime ladder (see radix_select: one more pass, then a
    second collect attempt), and only after both rungs overflow does the
    remainder of the fixed schedule run — so the worst case costs the
    fixed schedule plus two conds, never more. This is the reference CGM's
    ``< n/(c*p)`` sequential-finish cutover (``TODO-kth-problem-cgm.c:122,
    236-280``) rebuilt without data movement until the final collect.

    The cutover only pays when the skipped passes outweigh the collect
    (one extra count scan + a rank-slot gather + a small sort); below
    that, None.
    """
    if n < (1 << 20):  # small inputs: pass cost is trivial, skip the cond
        return None
    npasses = total_bits // radix_bits
    r = radix_bits
    while r < total_bits and (n >> r) > max(budget >> 4, 64):
        r += radix_bits
    ncut = r // radix_bits
    if ncut >= npasses:
        return None
    if (npasses - ncut - 1) * n <= 100_000_000:  # collect ~ 1 pass + 0.5ms
        return None
    return ncut


def resolve_cutover(cutover, n, total_bits, radix_bits, budget):
    """Static cutover pass count shared by every select entry point
    (single-chip and distributed): ``"auto"`` -> :func:`cutover_passes`,
    ``None`` -> disabled, int -> forced (validated against the pass count)."""
    npasses = total_bits // radix_bits
    if cutover == "auto":
        return cutover_passes(n, total_bits, radix_bits, budget)
    if cutover is None:
        return None
    ncut = int(cutover)  # ksel: noqa[KSL001] -- cutover is a static jit arg (static_argnames); int() runs at trace time, never on a tracer
    if not 1 <= ncut < npasses:
        raise ValueError(f"cutover={ncut} out of range [1, {npasses - 1}]")
    return ncut


def run_cutover_ladder(ncut, npasses, pop0, pred, step, finish_small, finish_full_from, state):
    """The 2-rung runtime cutover ladder, shared by all four select paths
    (radix_select, radix_select_many, and their distributed counterparts in
    parallel/radix.py): try the collect after ``ncut`` passes; if the
    surviving population still overflows the budget (dense/skewed data —
    the static ncut models full-range uniform keys), run ONE more pass and
    try again; only then fall back to the remaining fixed passes.

    ``pred(pop)`` is the fits-the-budget test; ``step(p, state) -> (state,
    pop)`` runs pass p; ``finish_small(resolved_passes)`` / ``finish_full_from(p0)``
    build the cond branch functions over ``state``.
    """
    if ncut + 1 < npasses:
        def rung2(state):
            state, pop = step(ncut, state)
            return jax.lax.cond(
                pred(pop), finish_small(ncut + 1), finish_full_from(ncut + 1),
                state,
            )

        return jax.lax.cond(pred(pop0), finish_small(ncut), rung2, state)
    return jax.lax.cond(
        pred(pop0), finish_small(ncut), finish_full_from(ncut), state
    )


def _rank_block_search(off, target):
    """First index b with ``off[b] >= target`` for each target — the
    slot->block mapping of the collect. Semantically
    ``jnp.searchsorted(off, target, side='left')`` clipped to the table,
    but computed as a two-level compare-and-sum: ``jnp.searchsorted`` on
    TPU lowers to a ~20-step while loop whose per-step gathers dominated
    the whole select (measured 32 ms of a 64 ms multi-select at a 1M-entry
    table). Here level A counts superblock sums (one dense compare sweep),
    level B gathers one superblock row per target — no loop, no scatter.

    ``off`` is (m,) nondecreasing with ``target`` (T,), or batched:
    (K, m) tables with ``target`` (K, T). Returns indices in [0, m-1] of
    ``target``'s shape.

    Recursive with small (128-entry) leaves: each level gathers one
    128-entry row per target and counts with a dense compare — a sqrt(m)
    leaf at m=1M made the level-B gather (T, 1024) the single biggest op
    of the whole select (600 MB of random gather traffic at T=147K).
    """
    S = 128
    m = off.shape[-1]
    if m <= S:
        b = jnp.sum(off[..., None, :] < target[..., :, None], axis=-1)
        return jnp.minimum(b, m - 1)
    nsuper = -(-m // S)
    pad = nsuper * S - m
    if pad:
        widths = [(0, 0)] * (off.ndim - 1) + [(0, pad)]
        off = jnp.pad(off, widths, mode="edge")
    sup = off.reshape(*off.shape[:-1], nsuper, S)
    sup_last = sup[..., -1]  # (..., nsuper)
    sb = _rank_block_search(sup_last, target)  # superblock containing target
    if off.ndim == 1:
        rows = sup[sb]  # (T, S)
    else:
        rows = jnp.take_along_axis(sup, sb[..., None], axis=-2)  # (K, T, S)
    b = sb * S + jnp.sum(rows < target[..., None], axis=-1)
    return jnp.minimum(b, m - 1)


def _collect_prefix_matches(
    u,
    resolved_bits,
    prefix,
    budget: int,
    block: int = 1024,
    n_valid: int | None = None,
    key_of=None,
):
    """Values (in key space) of up to ``budget`` elements whose top
    ``resolved_bits`` bits equal ``prefix`` (both traced), in position order,
    padded with the order-maximum. Streaming per-block counts + per-slot
    block gather — no full-length cumsum. Returns (values, population).

    ``n_valid``: elements at positions >= n_valid are pad, never collected
    (used when ``u`` is the prepared-tiles view, whose zero pad would
    otherwise match a zero prefix).

    ``u`` may be 2-D — the prepared ``(rows, 128)`` tiles are consumed AS
    IS (``block`` is then the tile width): routing the very tensor the
    histogram passes read into this branch lets XLA share one buffer across
    the cutover ``cond``; a ravel+reshape round-trip here made XLA
    rematerialize a second full-size copy inside the branch (OOM at the 1B
    int32 config). With the raw-tiles fast path, ``u`` holds raw bit
    patterns (or a (hi, lo) tuple of raw planes for 64-bit keys) and
    ``key_of`` maps them to key space on the fly — elementwise, so XLA
    fuses it into the compares and never materializes the keys.
    """
    if key_of is None:
        key_of = lambda v: v
    planes = isinstance(u, tuple)
    if planes:
        hi2, lo2 = u
        nb_, block = hi2.shape
        n = hi2.size
        nv = n if n_valid is None else n_valid
        kdt = key_of((hi2[:1, :1], lo2[:1, :1])).dtype
        total_bits = np.dtype(kdt).itemsize * 8
        cdt = jnp.int32 if n < 2**31 else jnp.int64
        padded = nv != n
        ku2 = key_of((hi2, lo2))
    elif u.ndim == 2:
        nb_, block = u.shape
        n = u.size
        nv = n if n_valid is None else n_valid
        kdt = key_of(u[:1, :1]).dtype
        total_bits = np.dtype(kdt).itemsize * 8
        cdt = jnp.int32 if n < 2**31 else jnp.int64
        padded = nv != n
        ku2 = key_of(u)
    else:
        n = u.shape[0]
        nv = n if n_valid is None else n_valid
        kdt = u.dtype
        total_bits = np.dtype(kdt).itemsize * 8
        cdt = jnp.int32 if n < 2**31 else jnp.int64
        nb_ = -(-n // block)
        padded = nb_ * block != n or nv != n
        up = jnp.pad(u, (0, nb_ * block - n)) if nb_ * block != n else u
        u = up.reshape(nb_, block)
        ku2 = u
    mshift = jnp.asarray(total_bits - resolved_bits, jnp.int32).astype(kdt)  # >= 1 pass ran; values <= 64, int32 never narrows
    match2 = jax.lax.shift_right_logical(ku2, mshift) == prefix
    if padded:
        valid = (
            jax.lax.broadcasted_iota(cdt, (nb_, block), 0) * block
            + jax.lax.broadcasted_iota(cdt, (nb_, block), 1)
            < nv
        )
        match2 = jnp.logical_and(match2, valid)
    cnt = jnp.sum(match2, axis=1, dtype=cdt)
    off = jnp.cumsum(cnt)
    pop = off[-1]
    jj = jnp.arange(budget, dtype=cdt)
    target = jj + 1
    b = _rank_block_search(off, target).astype(cdt)
    prev = jnp.where(b > 0, off[jnp.maximum(b - 1, 0)], jnp.zeros_like(target))
    r = target - prev  # 1-based rank within block b
    if planes:
        rows = key_of((hi2[b], lo2[b]))  # (budget, block), key space
    else:
        rows = key_of(u[b]) if u.ndim == 2 else u[b]
    rmatch = jax.lax.shift_right_logical(rows, mshift) == prefix
    if padded:
        cols = jax.lax.broadcasted_iota(cdt, (budget, block), 1)
        rmatch = jnp.logical_and(rmatch, cols < (nv - b[:, None] * block))
    within = jnp.cumsum(rmatch.astype(cdt), axis=1)
    local = jnp.argmax(jnp.logical_and(within == r[:, None], rmatch), axis=1)
    vals = rows[jnp.arange(budget), local]
    maxkey = np.array(~np.uint64(0)).astype(np.dtype(kdt))
    return jnp.where(jj < pop, vals, maxkey), pop


def collect_view(dtype, u, tiles, tiles_n, key_op):
    """``(u_collect, n_collect, key_of)`` — the view `_collect_prefix_matches`
    should scan for a prepared selection state, shared by the single-chip
    descent (`_Descent`) and the distributed shard functions
    (parallel/radix.py).

    Raw tiles (``key_op != "none"``) are consumed as-is with an on-the-fly
    bits->key transform (elementwise, so XLA fuses it into the compares and
    never materializes the keys); key-space uint32 tiles are consumed
    directly (sharing the kernels' buffer across the cutover ``cond``);
    anything else (sub-32-bit keys, non-pallas methods) scans the 1-D key
    array ``u``.
    """
    if key_op != "none":
        u_collect = tiles[0] if len(tiles) == 1 else (tiles[0], tiles[1])

        def key_of(raw_bits):
            if isinstance(raw_bits, tuple):
                hi, lo = raw_bits
                raw64 = jax.lax.shift_left(
                    hi.astype(jnp.uint64), jnp.uint64(32)
                ) | lo.astype(jnp.uint64)
                # pure integer transform on the recombined bits — no value
                # round trip (a bitcast to f64 and back would also hit the
                # TPU compiler's broken f64-source bitcast, utils/dtypes.py)
                return _dt.sortable_from_raw_bits(raw64, dtype)
            # 32-bit raw tiles keep x's own dtype — transform directly
            return _dt.to_sortable_bits(raw_bits)

        return u_collect, tiles_n, key_of
    kdt = jnp.dtype(_dt.key_dtype(dtype))
    if tiles is not None and len(tiles) == 1 and kdt == jnp.uint32:
        # 32-bit: the collect scans the 2-D tiles tensor itself (the same
        # uint32 buffer the kernels read) so `u` fuses away. Sub-32-bit
        # keys keep the native-width `u`: the tiles are widened uint32, so
        # collecting from them would shift by the wrong key width and
        # return the wrong dtype.
        return tiles[0], tiles_n, None
    return u, None, None


def bucket_walk_step(hist, kk, prefix, kdt, radix_bits):
    """One descent step on a (global) bucket histogram: pick the bucket
    containing the k-th element, rebase k within it, extend the prefix.
    ``prefix=None`` on the first (prefix-free) step. The single shared
    implementation of the walk — local and distributed, single- and
    multi-rank paths all call this. Returns (prefix, kk, bucket_count)."""
    cum = jnp.cumsum(hist)
    bucket = jnp.argmax(cum >= kk)
    kk = kk - (cum[bucket] - hist[bucket])
    bkey = bucket.astype(kdt)
    if prefix is not None:
        bkey = jax.lax.shift_left(prefix, kdt.type(radix_bits)) | bkey
    return bkey, kk, hist[bucket]


def bucket_walk_step_multi(hist2d, kk, prefixes, kdt, radix_bits):
    """Vectorized :func:`bucket_walk_step` for K queries at once:
    ``hist2d`` is (K, nbuckets) — each query's masked histogram from one
    shared data sweep — and ``kk``/``prefixes`` are (K,). ``prefixes=None``
    on the shared prefix-free first step (``hist2d`` may then be (nbuckets,)
    — one global histogram serves every query's first walk).
    Returns (prefixes, kk, bucket_counts), each (K,)."""
    if hist2d.ndim == 1:
        hist2d = jnp.broadcast_to(hist2d, (kk.shape[0],) + hist2d.shape)
    cum = jnp.cumsum(hist2d, axis=1)
    hit = cum >= kk[:, None]
    bucket = jnp.argmax(hit, axis=1)
    take = lambda a: jnp.take_along_axis(a, bucket[:, None], axis=1)[:, 0]
    kk = kk - (take(cum) - take(hist2d))
    bkey = bucket.astype(kdt)
    if prefixes is not None:
        bkey = jax.lax.shift_left(prefixes, kdt.type(radix_bits)) | bkey
    return bkey, kk, take(hist2d)


class _Descent:
    """Shared per-select state: prepared kernel tiles (raw-bits with the
    in-kernel key fold when available, key-space otherwise) and the
    one_pass bucket-walk closure both select entry points drive."""

    def __init__(self, x, radix_bits, hist_method, chunk, block_rows=4096):
        n = x.shape[0]
        if radix_bits is None:
            radix_bits = default_radix_bits(x.dtype, hist_method)
        total_bits = _dt.key_bits(x.dtype)
        if total_bits % radix_bits:
            raise ValueError(
                f"radix_bits={radix_bits} must divide key bits {total_bits}"
            )
        self.radix_bits = radix_bits
        self.total_bits = total_bits
        self.npasses = total_bits // radix_bits
        self.cdt = select_count_dtype(n)
        self.kdt = jnp.dtype(_dt.key_dtype(x.dtype))
        from mpi_k_selection_tpu.ops.histogram import check_block_rows

        check_block_rows(block_rows)  # the kernels' shared geometry contract
        self.block_rows = block_rows

        from mpi_k_selection_tpu.ops.histogram import prepare_keys, prepare_raw

        # raw fast path (pallas methods, 32/64-bit dtypes): tiles hold the
        # input's raw bits, the key transform runs in kernel — removes the
        # full-array to_sortable pass (1.63 ms at N=2^27 on v5e). Either
        # way the tiled view is built ONCE for all passes (and the cutover
        # collect): per-pass views make XLA hold/remat extra full-size
        # temporaries, OOMing 16 GB HBM at the 1B-element config.
        _dt._require_x64(x.dtype)  # 64-bit key math needs x64 in every mode
        raw = prepare_raw(hist_method, x, block_rows)
        if raw is not None:
            self.tiles, self.tiles_n, self.key_op, self.key_xor = raw
            self.u = None
        else:
            self.key_op, self.key_xor = "none", 0
            self.u = _dt.to_sortable_bits(x)
            self.tiles, self.tiles_n = prepare_keys(hist_method, self.u, block_rows)
        # the collect consumes the very buffers the kernels read (see
        # collect_view) so the cutover cond's branches share one full-size
        # tensor; a separate view made XLA rematerialize a second full-size
        # copy inside the branch (OOM at the 1B int32 config)
        self.u_collect, self.n_collect, self.key_of = collect_view(
            x.dtype, self.u, self.tiles, self.tiles_n, self.key_op
        )

        # count-kernel collect (pallas): per-subblock match counts in one
        # streaming read for all queries — XLA's jnp formulation of the
        # same count refuses to fuse (measured ~20 ms for K=9 at 2^27 vs
        # this kernel's ~1 ms). The 64-bit prefix lives entirely in the hi
        # plane while resolved_bits <= 32, so the 32-bit kernel serves it.
        self.count_tiles = None
        self.count_key = ("none", 0)
        # the match-count kernel's row regrouping needs whole 128-row groups
        if self.tiles is not None and block_rows % 128 == 0:
            if len(self.tiles) == 2:
                self.count_tiles = self.tiles[0]  # hi plane
                if self.key_op == "xor":
                    self.count_key = ("xor", self.key_xor >> 32)
                elif self.key_op == "float":
                    self.count_key = ("float", 0)
            elif self.kdt == jnp.uint32 or self.key_op != "none":
                self.count_tiles = self.tiles[0]
                self.count_key = (self.key_op, self.key_xor)

        cdt, kdt = self.cdt, self.kdt

        def one_pass(p, prefix, kk):
            shift = total_bits - (p + 1) * radix_bits
            hist = masked_radix_histogram(
                self.u,
                shift=shift,
                radix_bits=radix_bits,
                prefix=prefix if p else None,
                method=hist_method,
                count_dtype=cdt,
                chunk=chunk,
                tiles=self.tiles,
                orig_n=self.tiles_n,
                key_op=self.key_op,
                key_xor=self.key_xor,
                block_rows=block_rows,
            )
            return bucket_walk_step(hist, kk, prefix if p else None, kdt, radix_bits)

        self.one_pass = one_pass


def _collect_via_counts(prep, resolved_passes: int, prefixes, budget: int):
    """Collect up to ``budget`` candidates per query via the pallas
    match-count kernel: one streaming read counts every query's matches per
    128-element subblock, then each candidate slot gathers just its
    subblock. ``prefixes`` is (K,) in key space; ``resolved_passes`` is
    static. Returns ``(values (K, budget) in key space, pops (K,))``."""
    res = resolved_passes * prep.radix_bits
    planes = prep.tiles is not None and len(prep.tiles) == 2
    from mpi_k_selection_tpu.ops.pallas.histogram import pallas_match_counts

    key_op, key_xor = prep.count_key
    # for 64-bit keys the resolved prefix lives entirely in the hi plane
    # (res <= 32 guarded by the caller), so the 32-bit kernel serves both
    pref32 = prefixes.astype(jnp.uint32)
    cnt = pallas_match_counts(
        resolved_bits=res,
        prefixes=pref32,
        tiles=prep.count_tiles,
        orig_n=prep.tiles_n,
        key_op=key_op,
        key_xor=key_xor,
        count_dtype=prep.cdt,
        # cap like the histogram kernels do: 8192-row tiles (valid geometry)
        # would blow the scoped-VMEM budget at full height; 4096 divides any
        # larger power-of-two tiling
        block_rows=min(prep.block_rows, 4096),
    )  # (K, R)
    cdt = prep.cdt
    nq = prefixes.shape[0]
    off = jnp.cumsum(cnt, axis=1)
    pops = off[:, -1]
    jj = jnp.arange(budget, dtype=cdt)
    target = jj + 1
    b = _rank_block_search(off, jnp.broadcast_to(target, (nq, budget))).astype(cdt)
    prev = jnp.where(
        b > 0,
        jnp.take_along_axis(off, jnp.maximum(b - 1, 0), axis=1),
        jnp.zeros((), cdt),
    )
    r = target[None, :] - prev  # 1-based rank within subblock, (K, budget)
    # subblock index == tile row index: gather whole rows (the one gather
    # shape XLA lowers efficiently; per-element coordinates were ~60x worse)
    if planes:
        gathered = (prep.tiles[0][b], prep.tiles[1][b])
    else:
        gathered = prep.tiles[0][b]  # (K, budget, 128)
    keys = prep.key_of(gathered) if prep.key_of is not None else gathered
    kdt = keys.dtype
    mshift = kdt.type(np.dtype(kdt).itemsize * 8 - res)
    rmatch = jax.lax.shift_right_logical(keys, mshift) == prefixes.astype(kdt)[:, None, None]
    pos = (b[..., None] * 128 + jnp.arange(128, dtype=cdt)).astype(cdt)
    rmatch = jnp.logical_and(rmatch, pos < prep.tiles_n)
    within = jnp.cumsum(rmatch.astype(cdt), axis=2)
    local = jnp.argmax(jnp.logical_and(within == r[..., None], rmatch), axis=2)
    vals = jnp.take_along_axis(keys, local[..., None], axis=2)[..., 0]
    maxkey = np.array(~np.uint64(0)).astype(np.dtype(kdt))
    return jnp.where(jj[None, :] < pops[:, None], vals, maxkey), pops


def _trace_state_clean() -> bool:
    """True when no jax trace is active (we are in eager context). Private
    jax API; if it moves, the True fallback alone would reinstate the
    concrete-f64-in-jit crash, so the host route's callers ALSO wrap the
    host decode in a TracerArrayConversionError rescue (belt and braces —
    see radix_select)."""
    try:
        from jax._src.core import trace_state_clean

        return trace_state_clean()
    except Exception:  # pragma: no cover - jax internals moved
        return True


# advice strings already emitted (None = the default kselect advice):
# one-time PER ADVICE, not per process — the kselect and threshold-top-k
# paths carry contradictory guidance (an eager-exact escape exists for one
# and not the other), so whichever fires first must not suppress the other
_f64_tpu_approx_warned: set = set()


def _warn_f64_tpu_approx(x, advice=None):
    """One-time (per distinct ``advice``) warning when an f64-on-TPU
    selection takes the traced ~49-bit key approximation
    (utils/dtypes.py:f64_raw_bits) instead of the exact host-key route —
    the one dtype/backend pair where a jit silently changes the answer's
    guarantee. Fires for traced f64 inputs and for concrete f64 closed
    over inside a user jit; never on the exact host route
    (``_f64_tpu_host_keys`` succeeded) and never off-TPU."""
    if advice in _f64_tpu_approx_warned:
        return
    try:
        is_f64 = np.dtype(x.dtype) == np.float64
    except Exception:
        return
    if is_f64 and jax.default_backend() == "tpu":
        _f64_tpu_approx_warned.add(advice)
        import inspect
        import warnings

        # attribute the warning to the first frame OUTSIDE this package so
        # a user with several f64 selection sites sees which one fired
        # (the shells are reached at varying depth: directly, via api.*,
        # via backends/CLI)
        level, pkg = 2, __name__.split(".")[0]
        for level, frame in enumerate(inspect.stack()[1:], start=2):
            if pkg not in frame.frame.f_globals.get("__name__", ""):
                break
        if advice is None:
            advice = (
                "For bit-exact f64 results call the selection "
                "eagerly with a host (numpy) array — see docs/API.md. "
            )
        warnings.warn(
            "float64 selection on TPU here uses an approximate ~49-bit "
            "key (TPU f64 is double-double; exact f64 bitcasts crash its "
            "compiler). " + advice +
            "This warning is emitted once per process per selection path.",
            stacklevel=level,
        )


def _f64_exact_shell(traced_fn, x, *args, **kwargs):
    """The eager f64-on-TPU shell shared by :func:`radix_select` and
    :func:`radix_select_many`: exact host-derived uint64 keys when the host
    route applies, otherwise the traced-path approximation with the
    one-time warning. The TracerArrayConversionError rescue wraps ONLY the
    host decode (not the select itself), so a genuine conversion bug inside
    the traced select still surfaces from its real path."""
    keys = _f64_tpu_host_keys(x)
    if keys is not None:
        res = traced_fn(keys, *args, **kwargs)
        try:
            return _f64_from_keys_host(res)
        except jax.errors.TracerArrayConversionError:
            pass  # trace active but undetected (jax internals moved)
    _warn_f64_tpu_approx(x)
    return traced_fn(x, *args, **kwargs)


def _f64_tpu_host_keys(x):
    """Exact uint64 sortable keys for a CONCRETE float64 array on the TPU
    backend, or None when the trick does not apply.

    TPU f64 is double-double emulation (~49-bit effective mantissa): every
    f64-source bitcast crashes its compiler, computed f64 truncates, and
    even ``device_put`` of an f64 array loses the low mantissa bits in
    device storage (all measured on v5e). So the exact route never lets
    f64 touch the device: a zero-copy numpy view-cast on host, then the
    order-preserving transform as pure integer ops; the select runs
    entirely in uint64 key space on device and the answer key converts
    back on host (:func:`_f64_from_keys_host`).

    Exactness contract: bit-exact for HOST-resident inputs (numpy arrays —
    the CLI/datagen path). A device-resident f64 input was already
    truncated by device storage before this function can see it; selection
    is then exact with respect to the array's actual device contents.
    """
    if jax.default_backend() != "tpu":
        return None
    if isinstance(x, jax.core.Tracer):
        return None
    if np.dtype(x.dtype) != np.float64:
        return None
    # Inside a user trace the host route cannot work even for a CONCRETE x
    # (a closure constant): the select result is a tracer, and the host-side
    # decode (np.asarray in _f64_from_keys_host) would raise
    # TracerArrayConversionError. Fall through to the traced approximation.
    if not _trace_state_clean():
        return None
    # same x64 requirement (and error) as the traced path: without it,
    # jnp.asarray would silently truncate the uint64 keys to uint32
    _dt._require_x64(np.float64)
    raw = np.asarray(x).reshape(-1).view(np.uint64)
    neg = (raw >> np.uint64(63)) != 0
    keys = np.where(neg, ~raw, raw | np.uint64(1 << 63))
    return jnp.asarray(keys)


def _f64_from_keys_host(ans):
    """Inverse of :func:`_f64_tpu_host_keys` for the answer key(s), computed
    on host, returned as a HOST (numpy) array: putting the result back on
    the TPU would truncate it again — f64 device storage itself is ~49-bit
    (measured), so the exact value can only live host-side. Callers treat
    it like any array result (float()/np.asarray() both work)."""
    k = np.asarray(ans)
    shape = k.shape
    k = k.reshape(-1)
    msb = np.uint64(1) << np.uint64(63)
    neg = (k & msb) == 0  # keys below MSB came from negative floats
    raw = np.where(neg, ~k, k & ~msb)
    return np.ascontiguousarray(raw).view(np.float64).reshape(shape)


@functools.partial(
    jax.jit,
    static_argnames=(
        "radix_bits",
        "hist_method",
        "chunk",
        "early_exit_budget",
        "cutover",
        "cutover_budget",
        "block_rows",
    ),
)
def _radix_select_traced(
    x: jax.Array,
    k,
    *,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
    early_exit_budget: int | None = None,
    cutover: int | str | None = "auto",
    cutover_budget: int = 8192,
    block_rows: int = 4096,
) -> jax.Array:
    """Exact k-th smallest element of ``x`` (k is 1-indexed, reference semantics).

    ``x`` may have any shape (flattened); ``k`` may be a traced scalar.

    ``cutover`` (the production fast path): after a *static* number of
    histogram passes, one ``lax.cond`` on the surviving population (free —
    it is the chosen bucket's count from the pass just run) picks between
    (a) collecting the <= ``cutover_budget`` survivors and sort-indexing
    them directly, skipping every remaining pass, or (b) the remaining
    fixed passes. The radix analogue of the reference CGM's ``< n/(c*p)``
    sequential-finish cutover (``TODO-kth-problem-cgm.c:122, 236-280``).
    Unlike the per-pass ``early_exit_budget`` scheme below, the schedule is
    static and there is exactly one cond, so skewed/duplicate-heavy data
    that overflows the budget pays only that cond on top of the fixed
    schedule. ``cutover='auto'`` resolves via :func:`cutover_passes`;
    an int forces that pass count; None disables.

    ``early_exit_budget`` (kept for research/comparison): per-pass conds
    skip remaining passes as soon as the population fits. Measured on v5e:
    the 7 cond wrappers cost more than the skipped passes save (26.8ms vs
    11.4ms at N=134M) — use ``cutover`` instead.
    """
    x = x.ravel()
    prep = _Descent(x, radix_bits, hist_method, chunk, block_rows)
    ans = _select_key_on_prep(
        prep,
        x.shape[0],
        k,
        early_exit_budget=early_exit_budget,
        cutover=cutover,
        cutover_budget=cutover_budget,
    )
    return _dt.from_sortable_bits(ans, x.dtype)


def _select_key_on_prep(
    prep: "_Descent",
    n: int,
    k,
    *,
    early_exit_budget: int | None = None,
    cutover: int | str | None = "auto",
    cutover_budget: int = 8192,
):
    """The radix descent on a prebuilt :class:`_Descent`, returning the
    answer in KEY space. Split out of :func:`_radix_select_traced` (r5) so
    the top-k threshold path can run the select AND the winner collect on
    ONE prepared tile set — building a second `_Descent` (or re-deriving
    ``to_sortable_bits(x)``) costs a full read+write pass of x."""
    radix_bits, total_bits, npasses = prep.radix_bits, prep.total_bits, prep.npasses
    cdt, kdt, one_pass = prep.cdt, prep.kdt, prep.one_pass
    u_collect, n_collect, key_of = prep.u_collect, prep.n_collect, prep.key_of

    kk = jnp.clip(jnp.asarray(k, cdt), 1, n)
    early = early_exit_budget is not None and n > early_exit_budget
    if early:
        ncut = None  # research path below
    else:
        ncut = resolve_cutover(cutover, n, total_bits, radix_bits, cutover_budget)

    if ncut is not None:
        prefix = jnp.zeros((), kdt)
        pop = jnp.asarray(n, cdt)
        for p in range(ncut):
            prefix, kk, pop = one_pass(p, prefix, kk)

        use_counts = (
            prep.count_tiles is not None and (ncut + 1) * radix_bits <= 32
        )

        def finish_small(resolved_passes):
            if use_counts:
                def fn(args):
                    prefix, kk = args
                    cand, _pops = _collect_via_counts(
                        prep, resolved_passes, prefix[None], cutover_budget
                    )
                    return jax.lax.sort(cand[0])[
                        jnp.clip(kk - 1, 0, cutover_budget - 1)
                    ]

                return fn
            resolved = jnp.asarray(resolved_passes * radix_bits, jnp.int32)

            def fn(args):
                prefix, kk = args
                cand, _pop = _collect_prefix_matches(
                    u_collect, resolved, prefix, cutover_budget, block=128,
                    n_valid=n_collect, key_of=key_of,
                )
                return jax.lax.sort(cand)[jnp.clip(kk - 1, 0, cutover_budget - 1)]

            return fn

        def finish_full_from(p0):
            def fn(args):
                prefix, kk = args
                for p in range(p0, npasses):
                    prefix, kk, _ = one_pass(p, prefix, kk)
                return prefix

            return fn

        def step(p, args):
            prefix, kk = args
            prefix, kk, pop = one_pass(p, prefix, kk)
            return (prefix, kk), pop

        ans = run_cutover_ladder(
            ncut, npasses, pop, lambda q: q <= cutover_budget, step,
            finish_small, finish_full_from, (prefix, kk),
        )
        return ans

    if not early:
        prefix = jnp.zeros((), kdt)
        for p in range(npasses):
            prefix, kk, _ = one_pass(p, prefix, kk)
        return prefix

    # pass 0 always runs (n > budget); later passes are cond-skipped once the
    # matching population fits the budget
    prefix, kk, pop = one_pass(0, jnp.zeros((), kdt), kk)
    resolved = jnp.asarray(radix_bits, jnp.int32)
    state = (prefix, kk, pop, resolved)
    for p in range(1, npasses):
        def run(state, p=p):
            prefix, kk, _, resolved = state
            prefix, kk, pop = one_pass(p, prefix, kk)
            return prefix, kk, pop, resolved + radix_bits

        state = jax.lax.cond(state[2] > early_exit_budget, run, lambda s: s, state)
    prefix, kk, pop, resolved = state

    def finish_small(_):
        cand, _pop = _collect_prefix_matches(
            u_collect, resolved, prefix, early_exit_budget, n_valid=n_collect,
            key_of=key_of,
        )
        return jax.lax.sort(cand)[jnp.clip(kk - 1, 0, early_exit_budget - 1)]

    # population never fit the budget => every key bit is resolved and all
    # matching elements equal the prefix itself; the collection only runs
    # (cond) when the early exit actually fired
    return jax.lax.cond(
        pop > early_exit_budget, lambda _: prefix, finish_small, operand=None
    )


def radix_select(x, k, **kwargs):
    """Exact k-th smallest element of ``x`` (1-indexed). Thin eager shell
    over the jitted descent (:func:`_radix_select_traced` — see it for all
    keyword options): concrete float64 inputs on TPU are routed through
    exact host-derived uint64 keys (:func:`_f64_tpu_host_keys`); everything
    else goes straight through. Inside a user ``jit`` the shell is traced
    away and f64-on-TPU falls back to the documented ~49-bit key
    approximation (utils/dtypes.py:f64_raw_bits)."""
    return _f64_exact_shell(_radix_select_traced, x, k, **kwargs)


def _collect_prefix_matches_multi(
    u, resolved_bits, prefixes, budget: int, n_valid: int | None = None, key_of=None
):
    """K-query :func:`_collect_prefix_matches`: values (in key space, shape
    ``(K, budget)``) of up to ``budget`` elements per prefix in ``prefixes``
    (shape (K,)), padded with the order-maximum, plus populations (K,).
    The streaming count reads the data ONCE for all K prefixes (the K
    compares fuse into the per-block reduction)."""
    if key_of is None:
        key_of = lambda v: v
    planes = isinstance(u, tuple)
    if planes:
        hi2, lo2 = u
        nb_, block = hi2.shape
        n = hi2.size
        kdt = key_of((hi2[:1, :1], lo2[:1, :1])).dtype
        ku2 = key_of((hi2, lo2))
    else:
        if u.ndim != 2:
            nb_ = -(-u.shape[0] // 128)
            n_valid = u.shape[0] if n_valid is None else n_valid
            u = jnp.pad(u, (0, nb_ * 128 - u.shape[0])).reshape(nb_, 128)
        nb_, block = u.shape
        n = u.size
        kdt = key_of(u[:1, :1]).dtype
        ku2 = key_of(u)
    nv = n if n_valid is None else n_valid
    total_bits = np.dtype(kdt).itemsize * 8
    cdt = jnp.int32 if n < 2**31 else jnp.int64
    padded = nv != n
    nq = prefixes.shape[0]
    mshift = jnp.asarray(total_bits - resolved_bits, jnp.int32).astype(kdt)  # values <= 64
    shifted = jax.lax.shift_right_logical(ku2, mshift)  # (nb_, block)
    match3 = shifted[None] == prefixes.astype(kdt)[:, None, None]
    if padded:
        valid = (
            jax.lax.broadcasted_iota(cdt, (nb_, block), 0) * block
            + jax.lax.broadcasted_iota(cdt, (nb_, block), 1)
            < nv
        )
        match3 = jnp.logical_and(match3, valid[None])
    cnt = jnp.sum(match3, axis=2, dtype=cdt)  # (K, nb_)
    off = jnp.cumsum(cnt, axis=1)
    pops = off[:, -1]
    jj = jnp.arange(budget, dtype=cdt)
    target = jj + 1
    b = _rank_block_search(off, jnp.broadcast_to(target, (nq, budget))).astype(cdt)
    prev = jnp.where(
        b > 0,
        jnp.take_along_axis(off, jnp.maximum(b - 1, 0), axis=1),
        jnp.zeros((), cdt),
    )
    r = target[None, :] - prev  # 1-based rank within block, (K, budget)
    if planes:
        rows = key_of((hi2[b], lo2[b]))  # (K, budget, block)
    else:
        rows = key_of(u[b])
    rmatch = jax.lax.shift_right_logical(rows, mshift) == prefixes.astype(kdt)[:, None, None]
    if padded:
        cols = jax.lax.broadcasted_iota(cdt, (nq, budget, block), 2)
        rmatch = jnp.logical_and(rmatch, cols < (nv - b[..., None] * block))
    within = jnp.cumsum(rmatch.astype(cdt), axis=2)
    local = jnp.argmax(jnp.logical_and(within == r[..., None], rmatch), axis=2)
    vals = jnp.take_along_axis(rows, local[..., None], axis=2)[..., 0]
    maxkey = np.array(~np.uint64(0)).astype(np.dtype(kdt))
    return jnp.where(jj[None, :] < pops[:, None], vals, maxkey), pops


@functools.partial(
    jax.jit,
    static_argnames=(
        "radix_bits", "hist_method", "chunk", "cutover", "cutover_budget",
        "block_rows",
    ),
)
def _radix_select_many_traced(
    x: jax.Array,
    ks,
    *,
    radix_bits: int | None = None,
    hist_method: str = "auto",
    chunk: int = 32768,
    cutover: int | str | None = "auto",
    cutover_budget: int = 8192,
    block_rows: int = 4096,
) -> jax.Array:
    """Exact k-th smallest for EVERY k in ``ks`` over the same array.

    The amortized multi-rank form (the telemetry shape: p50/p90/p99 of one
    giant array): the tiled key view and the prefix-free first pass are
    computed ONCE and shared by all queries, and every later pass runs ALL
    K queries through one shared data sweep (the multi-prefix kernels,
    ops/pallas/histogram.py) — the data is read ``npasses`` times total
    instead of ``1 + K * (npasses - 1)``. The cutover applies to the whole
    batch: one cond on the LARGEST query population, then a batched
    collect + sort finishes every query at once. Returns answers in ``ks``
    order (shape ``ks.shape``; K is static from it).

    Out-of-range concrete ks raise in the API layer (api.kselect_many);
    traced ks are clamped to [1, n] like radix_select.
    """
    x = x.ravel()
    n = x.shape[0]
    ks_arr = jnp.atleast_1d(jnp.asarray(ks))
    prep = _Descent(x, radix_bits, hist_method, chunk, block_rows)
    radix_bits, total_bits, npasses = prep.radix_bits, prep.total_bits, prep.npasses
    cdt, kdt = prep.cdt, prep.kdt
    kk = jnp.clip(ks_arr.astype(cdt), 1, n).ravel()

    # shared prefix-free pass: one histogram serves every query's first step
    hist0 = masked_radix_histogram(
        prep.u,
        shift=total_bits - radix_bits,
        radix_bits=radix_bits,
        prefix=None,
        method=hist_method,
        count_dtype=cdt,
        chunk=chunk,
        tiles=prep.tiles,
        orig_n=prep.tiles_n,
        key_op=prep.key_op,
        key_xor=prep.key_xor,
        block_rows=block_rows,
    )
    prefixes, kk, pops = bucket_walk_step_multi(hist0, kk, None, kdt, radix_bits)

    def multi_pass(p, prefixes, kk):
        shift = total_bits - (p + 1) * radix_bits
        hist = multi_masked_radix_histogram(
            prep.u,
            shift=shift,
            radix_bits=radix_bits,
            prefixes=prefixes,
            method=hist_method,
            count_dtype=cdt,
            chunk=chunk,
            tiles=prep.tiles,
            orig_n=prep.tiles_n,
            key_op=prep.key_op,
            key_xor=prep.key_xor,
            block_rows=block_rows,
        )
        return bucket_walk_step_multi(hist, kk, prefixes, kdt, radix_bits)

    ncut = resolve_cutover(cutover, n, total_bits, radix_bits, cutover_budget)

    if ncut is None:
        for p in range(1, npasses):
            prefixes, kk, pops = multi_pass(p, prefixes, kk)
        ans = prefixes
    else:
        for p in range(1, ncut):
            prefixes, kk, pops = multi_pass(p, prefixes, kk)

        use_counts = (
            prep.count_tiles is not None and (ncut + 1) * radix_bits <= 32
        )

        def finish_small(resolved_passes):
            def fn(args):
                prefixes, kk = args
                if use_counts:
                    cand, _pops = _collect_via_counts(
                        prep, resolved_passes, prefixes, cutover_budget
                    )
                else:
                    resolved = jnp.asarray(resolved_passes * radix_bits, jnp.int32)
                    cand, _pops = _collect_prefix_matches_multi(
                        prep.u_collect, resolved, prefixes, cutover_budget,
                        n_valid=prep.n_collect, key_of=prep.key_of,
                    )
                s = jnp.sort(cand, axis=1)
                idx = jnp.clip(kk - 1, 0, cutover_budget - 1)
                return jnp.take_along_axis(s, idx[:, None], axis=1)[:, 0]

            return fn

        def finish_full_from(p0):
            def fn(args):
                prefixes, kk = args
                for p in range(p0, npasses):
                    prefixes, kk, _ = multi_pass(p, prefixes, kk)
                return prefixes

            return fn

        def step(p, args):
            prefixes, kk = args
            prefixes, kk, pops = multi_pass(p, prefixes, kk)
            return (prefixes, kk), pops

        ans = run_cutover_ladder(
            ncut, npasses, pops, lambda q: jnp.max(q) <= cutover_budget,
            step, finish_small, finish_full_from, (prefixes, kk),
        )
    ans = _dt.from_sortable_bits(ans, x.dtype)
    return ans.reshape(ks_arr.shape)


def radix_select_many(x, ks, **kwargs):
    """Exact k-th smallest for every k in ``ks``. Same eager shell as
    :func:`radix_select` (exact f64-on-TPU via host-derived keys); see
    :func:`_radix_select_many_traced` for the descent and options."""
    return _f64_exact_shell(_radix_select_many_traced, x, ks, **kwargs)
