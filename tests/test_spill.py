"""Survivor spill store (ISSUE 5): geometric pass shrinking for the
out-of-core streaming descent.

The acceptance contract: ``spill="off"`` is bit-identical to the pre-spill
replay path, ``spill="force"`` is bit-identical to ``spill="off"`` for
every devices x pipeline_depth combination (heterogeneous/ragged/empty
chunks included), a one-shot generator completes exactly via the spill
path (and still gets the actionable error under ``spill="off"``), a
corrupt/truncated spill record raises a typed error before any key is
counted, per-pass streamed bytes shrink geometrically, and no spill temp
dir outlives its call on ANY exit path (the autouse conftest fixture
backstops every test here).
"""

import glob
import os
import tempfile

import numpy as np
import pytest

from mpi_k_selection_tpu.backends import seq
from mpi_k_selection_tpu.errors import SpillError, SpillRecordError
from mpi_k_selection_tpu.streaming import (
    RadixSketch,
    SpillStore,
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming.spill import SPILL_DIR_PREFIX, validate_spill_mode


def _chunks(x, nchunks):
    return [np.ascontiguousarray(c) for c in np.array_split(x, nchunks)]


def _ints(rng, n, dtype=np.int32):
    return rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(dtype)


def _device_grid():
    import jax

    return sorted({1, 2, len(jax.devices())})


def _spill_dirs():
    return set(glob.glob(os.path.join(tempfile.gettempdir(), SPILL_DIR_PREFIX + "*")))


# -- the determinism grid ----------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("spill", ["off", "force"])
def test_grid_bit_identical(depth, spill, rng):
    """devices {1,2,max} x depth {0,2} x spill {off,force} over
    heterogeneous chunk sizes with an empty chunk mixed in, multiple
    ranks, and a tiny collect budget (several prefix-filtered passes ->
    several spill generations) — all bit-identical to the devices=1
    depth=0 spill=off oracle."""
    x = _ints(rng, (1 << 14) + 311)
    chunks = _chunks(x, 7)
    chunks.insert(3, np.empty(0, np.int32))  # empty chunk: a no-op
    ks = [1, 137, x.size // 2, x.size]
    oracle = streaming_kselect_many(
        chunks, ks, pipeline_depth=0, devices=1, spill="off", collect_budget=64
    )
    assert oracle == [seq.kselect_sort(x, k) for k in ks]
    for devices in _device_grid():
        got = streaming_kselect_many(
            chunks, ks, pipeline_depth=depth, devices=devices, spill=spill,
            collect_budget=64,
        )
        assert got == oracle, (devices, depth, spill)


def test_grid_ragged_staged_buckets(rng):
    """A short final chunk lands in a different pow2 staging bucket; the
    spill replay must re-stage every record into ITS bucket and keep the
    answer bit-identical (hist_method='scatter' forces staging on CPU)."""
    x = _ints(rng, 5 * 1000 + 537)
    chunks = [x[i * 1000:(i + 1) * 1000] for i in range(5)] + [x[5000:]]
    k = x.size // 2
    want = seq.kselect_sort(x, k)
    for devices in _device_grid():
        got = streaming_kselect(
            chunks, k, hist_method="scatter", pipeline_depth=2,
            devices=devices, spill="force", collect_budget=64,
        )
        assert got == want, devices


def test_spill_host_exact_64bit_route(rng):
    """64-bit keys without x64 resolve to host counting: the spill filter
    must run host-side there too, and stay bit-identical."""
    import jax

    assert not jax.config.jax_enable_x64
    x = rng.integers(-(2**62), 2**62, size=1 << 13, dtype=np.int64)
    k = x.size // 2
    want = seq.kselect_sort(x, k)
    got = streaming_kselect(
        _chunks(x, 8), k, pipeline_depth=2, spill="force", collect_budget=64
    )
    assert got == want


def test_spill_float32_and_quantile_ranks(rng):
    """float32 keys (sign-flip encode/decode round-trips through the spill
    records) across spill modes, multi-rank."""
    x = rng.standard_normal(1 << 13).astype(np.float32)
    ks = [3, x.size // 3, x.size - 5]
    want = streaming_kselect_many(_chunks(x, 6), ks, spill="off")
    got = streaming_kselect_many(
        _chunks(x, 6), ks, spill="force", collect_budget=128
    )
    assert [g.tobytes() for g in got] == [w.tobytes() for w in want]


# -- one-shot sources --------------------------------------------------------


def test_one_shot_generator_end_to_end(rng):
    """A consumed-once generator completes the exact descent via the spill
    path (spill='auto' default) — passes >= 1 never touch the source."""
    x = _ints(rng, 1 << 14)
    chunks = _chunks(x, 9)
    k = x.size // 2
    want = seq.kselect_sort(x, k)
    got = streaming_kselect(
        (c for c in chunks), k, collect_budget=64, radix_bits=4
    )
    assert got == want
    # multi-rank, pipelined, multi-device
    ks = [5, k, x.size - 1]
    want_many = streaming_kselect_many(chunks, ks, spill="off")
    for devices in _device_grid():
        got_many = streaming_kselect_many(
            iter(chunks), ks, pipeline_depth=2, devices=devices,
            collect_budget=64,
        )
        assert got_many == want_many, devices


def test_one_shot_rejected_when_spill_off(rng):
    x = _ints(rng, 4096)
    with pytest.raises(TypeError, match="spill"):
        streaming_kselect(iter(_chunks(x, 4)), 7, spill="off")


def test_one_shot_source_never_reinvoked(rng):
    """The source callable of a spill descent is consumed exactly once —
    a drifting source cannot drift, because it is never replayed: the
    answer is exact w.r.t. the pass-0 snapshot."""
    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 5)
    calls = {"n": 0}

    def src():
        calls["n"] += 1
        return iter(chunks)

    k = x.size // 2
    got = streaming_kselect(src, k, spill="force", collect_budget=64)
    assert got == seq.kselect_sort(x, k)
    assert calls["n"] == 1  # replay path would have called it per pass
    # the replay path on the same budget reads it more than once
    calls["n"] = 0
    streaming_kselect(src, k, spill="off", collect_budget=64)
    assert calls["n"] > 1


def test_drifting_source_off_raises_force_answers_snapshot(rng):
    """spill='off' keeps the replay-stability raise for drifting sources;
    spill='force' reads the source once, so the same source answers
    exactly for its FIRST materialization."""
    calls = {"n": 0}

    def drifting():
        calls["n"] += 1
        r = np.random.default_rng(calls["n"])
        return iter([r.integers(-(2**31), 2**31, size=4096, dtype=np.int64)
                     .astype(np.int32)])

    with pytest.raises(RuntimeError, match="replay-stable"):
        streaming_kselect(drifting, 2048, spill="off", collect_budget=64)
    calls["n"] = 0
    got = streaming_kselect(drifting, 2048, spill="force", collect_budget=64)
    first = np.random.default_rng(1).integers(
        -(2**31), 2**31, size=4096, dtype=np.int64
    ).astype(np.int32)
    assert calls["n"] == 1
    assert got == seq.kselect_sort(first, 2048)


# -- caller-owned stores: pass log, reuse, sketch flows ----------------------


def test_pass_log_shrinks_geometrically(rng):
    """The issue's acceptance bound: after pass 1 (which reads gen 0
    whole), every spill-read histogram pass streams <= ~1/2^(radix_bits-1)
    of its predecessor's bytes."""
    rb = 4
    x = _ints(rng, 1 << 15)
    k = x.size // 2
    with SpillStore() as store:
        got = streaming_kselect(
            _chunks(x, 7), k, radix_bits=rb, collect_budget=16, spill=store
        )
        assert got == seq.kselect_sort(x, k)
        log = store.pass_log
    assert log[0]["pass"] == 0 and log[0]["read"] == "source"
    assert log[0]["keys_written"] == x.size  # the full tee
    assert log[-1]["pass"] == "collect"
    reads = [
        e["bytes_read"] for e in log
        if isinstance(e["pass"], int) and e["pass"] >= 1
    ]
    assert len(reads) >= 2
    assert reads[0] == x.size * 4  # pass 1 reads gen 0 whole
    for a, b in zip(reads, reads[1:]):
        assert b <= a / (1 << (rb - 1)), (a, b)


def test_spill_metrics_and_events_mirror_pass_log(rng):
    """ISSUE 6 satellite: the obs registry's spill.* counters are sums
    over the store's OWN pass_log (collected from the same dicts, so
    exactly equal), and the per-pass events carry identical byte
    accounting entry for entry."""
    from mpi_k_selection_tpu.obs import (
        Observability,
        check_stream_invariants,
    )

    x = _ints(rng, 1 << 14)
    k = x.size // 2
    o = Observability.collecting()
    with SpillStore() as store:
        got = streaming_kselect(
            _chunks(x, 5), k, radix_bits=4, collect_budget=16, spill=store,
            obs=o,
        )
        assert got == seq.kselect_sort(x, k)
        log = [dict(e) for e in store.pass_log]
    reg = o.metrics
    assert reg.counter("spill.passes").value == len(log)
    assert reg.counter("spill.bytes_read").value == sum(
        e["bytes_read"] for e in log
    )
    assert reg.counter("spill.keys_read").value == sum(
        e["keys_read"] for e in log
    )
    assert reg.counter("spill.bytes_written").value == sum(
        e.get("bytes_written", 0) for e in log
    )
    assert reg.counter("spill.keys_written").value == sum(
        e.get("keys_written", 0) for e in log
    )
    # entry-for-entry: the event stream's bytes match the pass_log
    check_stream_invariants(o.events.events, spill_pass_log=log)
    by_pass = {e["pass"]: e for e in log}
    for ev in o.events.of_kind("stream.pass"):
        entry = by_pass[ev.pass_index]
        assert ev.bytes_read == entry["bytes_read"]
        assert ev.keys_read == entry["keys_read"]
        if "bytes_written" in entry:
            assert ev.bytes_written == entry["bytes_written"]
            assert ev.keys_written == entry["keys_written"]


def test_caller_store_keeps_gen0_for_reuse(rng):
    """A caller-owned store keeps its pass-0 generation: it serves the
    rank certificate, a second descent, and store-as-source — without
    re-reading the original stream — and descent-internal generations are
    dropped (disk holds exactly one generation afterwards)."""
    x = _ints(rng, 1 << 13)
    k = x.size // 2
    want = seq.kselect_sort(x, k)
    with SpillStore() as store:
        got = streaming_kselect(
            _chunks(x, 6), k, spill=store, collect_budget=64
        )
        assert got == want
        assert len(store.generations) == 1  # gen 0 only
        gen0 = store.latest_generation()
        assert gen0.keys == x.size
        # certificate straight from the spilled keys
        less, leq = streaming_rank_certificate(store, want)
        assert less < k <= leq
        # the store IS a source: a second, different-rank descent
        got2 = streaming_kselect(store, 17, collect_budget=64)
        assert got2 == seq.kselect_sort(x, 17)
        # and gen 0 is still the only generation left on disk
        assert len(store.generations) == 1
        assert store.latest_generation() is gen0


def test_sketch_update_stream_tee_then_refine(rng):
    """The sketch-then-refine flow for one-shot streams: update_stream
    tees the single pass, refine answers exactly from the store."""
    x = _ints(rng, 1 << 13)
    chunks = _chunks(x, 6)
    k = x.size // 2
    with SpillStore() as store:
        sk = RadixSketch(np.int32).update_stream(iter(chunks), spill=store)
        assert sk.n == x.size
        got = sk.refine(store, k, collect_budget=64)
        assert got == seq.kselect_sort(x, k)
        # refine is repeatable: gen 0 survived the first refinement
        assert sk.refine(store, 11, collect_budget=64) == seq.kselect_sort(x, 11)
    with pytest.raises(TypeError, match="SpillStore"):
        RadixSketch(np.int32).update_stream(chunks, spill="force")


def test_streaming_quantiles_spill_flow(rng):
    from mpi_k_selection_tpu.api import StreamingQuantiles, quantile_ranks

    x = rng.standard_normal(1 << 13).astype(np.float32)
    chunks = _chunks(x, 5)
    qs = [0.1, 0.5, 0.99]
    with SpillStore() as store:
        t = StreamingQuantiles(np.float32).update_stream(
            iter(chunks), spill=store
        )
        got = t.refine_quantiles(qs, store)
    s = np.sort(x, kind="stable")
    want = [s[k - 1] for k in quantile_ranks(qs, x.size)]
    assert [g.tobytes() for g in got] == [w.tobytes() for w in want]


def test_spill_records_device_slots(rng):
    """With committed multi-device staging, gen-0 records carry the
    round-robin slot each chunk was staged to — the (chunk_index, bucket,
    dtype, device) key the replay re-stages by."""
    x = _ints(rng, 6 * 2048)
    chunks = _chunks(x, 6)
    k = x.size // 2
    with SpillStore() as store:
        got = streaming_kselect(
            chunks, k, spill=store, pipeline_depth=2, devices=2,
            hist_method="scatter",
        )
        assert got == seq.kselect_sort(x, k)
        slots = [r.device_slot for r in store.latest_generation().records]
    assert slots == [0, 1, 0, 1, 0, 1]


# -- corruption: typed errors, never wrong answers ---------------------------


def _spilled_store(rng, tmp_path):
    x = _ints(rng, 1 << 12)
    store = SpillStore(str(tmp_path))
    streaming_kselect(_chunks(x, 4), 7, spill=store, collect_budget=64)
    return x, store


def test_corrupt_record_raises_typed_error(rng, tmp_path):
    x, store = _spilled_store(rng, tmp_path)
    rec = store.latest_generation().records[2]
    data = bytearray(open(rec.path, "rb").read())
    data[-3] ^= 0xFF  # flip one payload byte
    with open(rec.path, "wb") as f:
        f.write(data)
    with pytest.raises(SpillRecordError, match="checksum"):
        streaming_kselect(store, 7, collect_budget=64)
    store.close()


def test_truncated_record_raises_typed_error(rng, tmp_path):
    x, store = _spilled_store(rng, tmp_path)
    rec = store.latest_generation().records[0]
    data = open(rec.path, "rb").read()
    with open(rec.path, "wb") as f:
        f.write(data[:-7])
    with pytest.raises(SpillRecordError, match="truncated"):
        streaming_kselect(store, 7, collect_budget=64)
    store.close()


def test_missing_record_raises_typed_error(rng, tmp_path):
    x, store = _spilled_store(rng, tmp_path)
    os.unlink(store.latest_generation().records[1].path)
    with pytest.raises(SpillRecordError, match="unreadable"):
        streaming_rank_certificate(store, 0)
    store.close()


def test_corruption_error_types_are_distinguishable():
    assert issubclass(SpillRecordError, SpillError)
    assert issubclass(SpillError, RuntimeError)


# -- cleanup on every exit path ----------------------------------------------


def test_internal_store_cleanup_on_success(rng):
    before = _spill_dirs()
    x = _ints(rng, 1 << 13)
    streaming_kselect(iter(_chunks(x, 5)), 9, collect_budget=64)
    streaming_kselect(_chunks(x, 5), 9, spill="force", collect_budget=64)
    assert _spill_dirs() == before


def test_internal_store_cleanup_on_consumer_raise(rng):
    """A mid-stream raise (dtype drift, with producer threads in flight)
    must both propagate AND remove the internal store — plus leave no
    pipeline thread behind (conftest fixtures backstop both)."""
    before = _spill_dirs()
    x = _ints(rng, 1 << 13)
    bad = _chunks(x, 4) + [x[:64].astype(np.float32)]
    with pytest.raises(TypeError, match="stream dtype"):
        streaming_kselect(bad, 9, spill="force", pipeline_depth=2)
    with pytest.raises(TypeError, match="stream dtype"):
        streaming_kselect(iter(bad), 9, pipeline_depth=2)
    assert _spill_dirs() == before


def test_internal_store_cleanup_on_bad_k(rng):
    before = _spill_dirs()
    x = _ints(rng, 4096)
    with pytest.raises(ValueError, match="out of range"):
        streaming_kselect(iter(_chunks(x, 4)), x.size + 1)
    assert _spill_dirs() == before


def test_spill_dir_knob_roots_the_store(rng, tmp_path):
    x = _ints(rng, 4096)
    root = tmp_path / "spillroot"
    streaming_kselect(
        _chunks(x, 4), 7, spill="force", spill_dir=str(root), collect_budget=64
    )
    assert root.exists()  # created on demand...
    assert list(root.iterdir()) == []  # ...and the store inside was removed


# -- knob validation + store API ---------------------------------------------


def test_validate_spill_mode():
    with pytest.raises(ValueError, match="spill"):
        validate_spill_mode("always")
    with pytest.raises(ValueError, match="spill"):
        streaming_kselect([np.arange(4, dtype=np.int32)], 1, spill=True)
    s = SpillStore()
    s.close()
    with pytest.raises(SpillError, match="closed"):
        validate_spill_mode(s)


def test_store_api_lifecycle(tmp_path):
    store = SpillStore(str(tmp_path))
    with pytest.raises(SpillError, match="no committed generation"):
        store.latest_generation()
    w = store.new_generation()
    w.append(np.arange(8, dtype=np.uint32), np.int32, device_slot=None)
    gen = w.commit()
    with pytest.raises(SpillError, match="committed/aborted"):
        w.append(np.arange(8, dtype=np.uint32), np.int32)
    assert store.latest_generation() is gen
    assert gen.keys == 8 and gen.nbytes == 32
    [chunk] = list(gen.iter_chunks())
    assert chunk.device_slot is None and chunk.orig_dtype == np.dtype(np.int32)
    np.testing.assert_array_equal(chunk.keys, np.arange(8, dtype=np.uint32))
    store.drop_generation(gen)
    with pytest.raises(SpillError, match="dropped"):
        list(gen.iter_chunks())
    store.close()
    store.close()  # idempotent
    with pytest.raises(SpillError, match="closed"):
        store.new_generation()


def test_writer_abort_removes_records(tmp_path):
    store = SpillStore(str(tmp_path))
    w = store.new_generation()
    w.append(np.arange(8, dtype=np.uint32), np.int32)
    path = w.path
    assert os.listdir(path)
    w.abort()
    assert not os.path.exists(path)
    w.abort()  # idempotent
    store.close()


# -- CLI ---------------------------------------------------------------------


def test_cli_spill_flags(tmp_path, capsys):
    from mpi_k_selection_tpu import cli

    rc = cli.main([
        "--streaming", "--backend", "seq", "--n", "40000",
        "--chunk-elems", "8192", "--spill", "force",
        "--spill-dir", str(tmp_path), "--check", "--verify", "--json",
    ])
    assert rc == 0
    import json

    rec = json.loads(capsys.readouterr().out)
    assert rec["extra"]["spill"] == "force"
    assert rec["extra"]["exact_match"] is True
    assert rec["extra"]["certificate_ok"] is True
    passes = rec["extra"]["spill_passes"]
    assert passes[0]["pass"] == 0 and passes[0]["keys_written"] == 40000
    # the store is gone afterwards (only the empty root dir may remain)
    assert not glob.glob(os.path.join(str(tmp_path), SPILL_DIR_PREFIX + "*"))

# -- the packed (format v2) record surface ------------------------------------


def _random_packed_population(rng, total_bits, n_specs, max_per_spec):
    """Random ``(keys, specs)`` with ragged (possibly EMPTY) segments:
    each spec is a random ``(resolved, prefix)`` at a random depth, and
    each key is drawn under one spec's prefix — the shape of a filtered
    survivor write (mixed depths = parked ranks among the active set)."""
    specs, parts = [], []
    seen = set()
    for _ in range(n_specs):
        resolved = int(rng.integers(0, total_bits))  # 0..total_bits-1
        prefix = int(rng.integers(0, 1 << resolved)) if resolved else 0
        if (resolved, prefix) in seen:
            continue
        seen.add((resolved, prefix))
        specs.append((resolved, prefix))
        count = int(rng.integers(0, max_per_spec + 1))  # ragged incl. empty
        width = total_bits - resolved
        low = rng.integers(0, 1 << min(width, 63), size=count).astype(np.uint64)
        if width == 64:
            low |= rng.integers(0, 2, size=count).astype(np.uint64) << np.uint64(63)
        parts.append(low | np.uint64(prefix << width) if resolved else low)
    keys = np.concatenate(parts) if parts else np.empty(0, np.uint64)
    # shuffle across segments: the writer must group them itself
    keys = keys[rng.permutation(keys.shape[0])]
    return keys, tuple(specs)


@pytest.mark.parametrize("key_dtype", [np.uint32, np.uint64])
@pytest.mark.parametrize("mmap", [False, True])
def test_packed_roundtrip_fuzz(key_dtype, mmap, tmp_path, rng):
    """pack -> CRC -> replay is key-exact for random spec unions (uint64
    included, resolved depths 0..total_bits-1, ragged/empty segments) on
    both the read and the mmap routes, and the physical record never
    exceeds the logical one (the per-record v1 fallback)."""
    total_bits = np.dtype(key_dtype).itemsize * 8
    for trial in range(8):
        keys, specs = _random_packed_population(
            rng, total_bits, n_specs=int(rng.integers(1, 7)), max_per_spec=800
        )
        keys = keys.astype(key_dtype)
        store = SpillStore(str(tmp_path / f"t{total_bits}-{trial}-{mmap}"))
        w = store.new_generation(pack_specs=specs, total_bits=total_bits)
        w.append(keys, np.float64 if total_bits == 64 else np.int32)
        gen = w.commit()
        [rec] = gen.records
        assert rec.nbytes <= keys.nbytes  # physical <= logical, always
        [chunk] = list(gen.iter_chunks(mmap=mmap)) or [None]
        got = chunk.keys if chunk is not None else np.empty(0, key_dtype)
        np.testing.assert_array_equal(np.sort(got), np.sort(keys))
        # a filtered read is SEGMENT-granular: it returns exactly the
        # keys of every segment matching a kept spec (the writer assigns
        # deepest-first), which is a superset of the keys matching the
        # filter directly — the pruning contract the descent leans on
        if specs:
            from mpi_k_selection_tpu.streaming.spill import _segment_matches

            keep = specs[: max(1, len(specs) // 2)]
            u = keys.astype(np.uint64)
            assigned = np.zeros(u.shape[0], dtype=bool)
            expect = np.zeros(u.shape[0], dtype=bool)
            direct = np.zeros(u.shape[0], dtype=bool)
            for r, p in sorted(specs, key=lambda s: (-s[0], s[1])):
                seg = ~assigned
                if r:
                    seg &= (u >> np.uint64(total_bits - r)) == np.uint64(p)
                assigned |= seg
                if _segment_matches(r, p, keep):
                    expect |= seg
            for r, p in keep:
                direct |= (
                    (u >> np.uint64(total_bits - r)) == np.uint64(p)
                    if r else np.ones_like(direct)
                )
            got_f = np.concatenate(
                [c.keys for c in gen.iter_chunks(mmap=mmap, filter_specs=keep)]
                or [np.empty(0, key_dtype)]
            )
            np.testing.assert_array_equal(np.sort(got_f), np.sort(keys[expect]))
            assert not np.any(direct & ~expect)  # never drops a match
        store.close()


def test_packed_digit_tee_prunes_and_prices(tmp_path, rng):
    """The digit-segmented tee (pack_digit_bits): filtered replay returns
    exactly the keys under the filter, and ``read_nbytes``/``read_keys``
    price the pruned read from the static layout — strictly below the
    full generation for a narrow filter."""
    keys = rng.integers(0, 1 << 63, size=20_000, dtype=np.int64).astype(np.uint64)
    store = SpillStore(str(tmp_path))
    w = store.new_generation(pack_digit_bits=8)
    for part in np.array_split(keys, 4):
        w.append(part, np.uint64)
    gen = w.commit()
    assert gen.packed and gen.nbytes < gen.logical_nbytes
    specs = ((4, 0x7),)  # every key whose top 4 bits are 0b0111
    mask = (keys >> np.uint64(60)) == np.uint64(0x7)
    got = np.concatenate(
        [c.keys for c in gen.iter_chunks(filter_specs=specs)]
        or [np.empty(0, np.uint64)]
    )
    np.testing.assert_array_equal(np.sort(got), np.sort(keys[mask]))
    assert gen.read_keys(specs) == int(mask.sum())
    assert gen.read_nbytes(specs) < gen.nbytes
    assert gen.read_nbytes(None) == gen.nbytes
    assert gen.read_keys(None) == keys.shape[0]
    store.close()


def test_packed_tiny_record_falls_back_to_v1(tmp_path):
    """Records the directory would dominate (and full-width resolved=0
    packs) stay format v1 — a packed generation is never physically
    larger than its logical bytes, and mixed v1/v2 generations replay."""
    store = SpillStore(str(tmp_path))
    w = store.new_generation(pack_digit_bits=8)
    big = np.arange(4096, dtype=np.uint64) * np.uint64(1 << 50)
    tiny = np.asarray([1, 2], np.uint64)
    w.append(big, np.uint64)
    w.append(tiny, np.uint64)
    gen = w.commit()
    versions = [rec.version for rec in gen.records]
    assert versions == [2, 1]
    assert all(r.nbytes <= r.logical_nbytes for r in gen.records)
    got = np.concatenate([c.keys for c in gen.iter_chunks()])
    np.testing.assert_array_equal(
        np.sort(got), np.sort(np.concatenate([big, tiny]))
    )
    # resolved=0 pack (width == total_bits) can never shrink: stays v1
    w2 = store.new_generation(pack_specs=((0, 0),), total_bits=64)
    w2.append(big, np.uint64)
    assert w2.commit().records[0].version == 1
    store.close()


def _packed_store(tmp_path, name, rng):
    keys = rng.integers(0, 1 << 63, size=4096, dtype=np.int64).astype(np.uint64)
    store = SpillStore(str(tmp_path / name))
    w = store.new_generation(pack_digit_bits=8)
    w.append(keys, np.uint64)
    gen = w.commit()
    assert gen.records[0].version == 2
    return keys, store, gen


@pytest.mark.parametrize("mmap", [False, True])
def test_packed_corrupt_directory_raises_typed(mmap, tmp_path, rng):
    _, store, gen = _packed_store(tmp_path, f"dir{mmap}", rng)
    rec = gen.records[0]
    data = bytearray(open(rec.path, "rb").read())
    data[128 + 12] ^= 0xFF  # a directory entry byte (header is 64B)
    with open(rec.path, "wb") as f:
        f.write(data)
    with pytest.raises(SpillRecordError, match="corrupt segment directory"):
        list(gen.iter_chunks(mmap=mmap))
    store.close()


@pytest.mark.parametrize("mmap", [False, True])
def test_packed_corrupt_segment_raises_typed(mmap, tmp_path, rng):
    keys, store, gen = _packed_store(tmp_path, f"seg{mmap}", rng)
    rec = gen.records[0]
    data = bytearray(open(rec.path, "rb").read())
    data[-2] ^= 0xFF  # a byte inside the LAST segment's payload
    with open(rec.path, "wb") as f:
        f.write(data)
    with pytest.raises(SpillRecordError, match="corrupt segment resolved="):
        list(gen.iter_chunks(mmap=mmap))
    # a pruned read that skips the damaged segment still serves — per-
    # segment CRCs checksum exactly what a filtered replay touches —
    # and one that includes it still raises
    tops = np.sort(np.unique(keys >> np.uint64(56)))
    good, bad = int(tops[0]), int(tops[-1])
    got = np.concatenate(
        [c.keys for c in gen.iter_chunks(mmap=mmap, filter_specs=((8, good),))]
    )
    np.testing.assert_array_equal(
        np.sort(got), np.sort(keys[(keys >> np.uint64(56)) == np.uint64(good)])
    )
    with pytest.raises(SpillRecordError, match="checksum"):
        list(gen.iter_chunks(mmap=mmap, filter_specs=((8, bad),)))
    store.close()


@pytest.mark.parametrize("mmap", [False, True])
def test_packed_truncated_raises_typed(mmap, tmp_path, rng):
    _, store, gen = _packed_store(tmp_path, f"trunc{mmap}", rng)
    rec = gen.records[0]
    data = open(rec.path, "rb").read()
    with open(rec.path, "wb") as f:
        f.write(data[:-9])
    with pytest.raises(SpillRecordError, match="truncated|implies|short read"):
        list(gen.iter_chunks(mmap=mmap))
    store.close()


def test_packed_descent_reads_v1_generations(tmp_path, rng):
    """v1 compatibility: a store teed WITHOUT packing serves a descent
    that asks for pack_spill='auto' — the reader keys on each record's
    header version, so old generations stay readable (chosen over a
    versioned refusal)."""
    x = _ints(rng, 1 << 12)
    store = SpillStore(str(tmp_path))
    want = seq.kselect(x, 77)
    got = streaming_kselect(
        iter(_chunks(x, 4)), 77, spill=store, collect_budget=64,
        pack_spill="off",
    )
    assert got == want
    assert not store.latest_generation().packed
    got2 = streaming_kselect(store, 77, collect_budget=64, pack_spill="auto")
    assert got2 == want
    store.close()
