"""Top-k selection (values + indices), single and batched.

The reference only ever returns the single k-th order statistic; top-k (the
full set of k extreme elements) is the north-star extension covering the
BASELINE.md configs "Single-chip top-k: N=64M float32, k=128 (MoE router
logits)" and "Batched top-k: B=4096 x D=32768 float32, k=8 (beam-search /
vocab top-k)".

Implementation notes:

- ``lax.top_k`` is the XLA baseline (operates on the last axis; leading axes
  batch for free, so batched_topk is the same code path).
- ``smallest``-k and unsigned dtypes are handled via the order-preserving
  key transforms in utils/dtypes.py: build signed keys whose descending order
  equals the requested order, top_k the keys, gather the original values.
- ``method="chunked"`` is the two-stage large-D variant: split the last axis
  into C chunks, take top-k per chunk (parallel, small sorts), then top-k of
  the C*k candidates. For D >> k this does ~D + C*k work per row instead of
  a single large-D top_k, and it is how the Pallas block kernel decomposes.
- ``method="tournament"`` is the multi-round variant for huge 1-D inputs:
  ``lax.top_k`` gets its speed from batch parallelism across rows, so a
  single giant row is its worst case. Each round reshapes the candidate
  pool into (rows, sub) and keeps the per-row top-k, shrinking the pool by
  ~sub/k until one cheap flat top-k finishes (~3x faster than flat at
  N=64M on a v5e).
- ``method="threshold"`` is the production 1-D path: the k-th largest value
  is found by radix descent (the Pallas histogram kernel, ops/radix.py),
  then the k winners are collected by a cumsum-rank gather — all streaming,
  no giant sort anywhere. ~10x faster than flat at N=64M, k=128 on a v5e.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.utils import dtypes as _dt


def _signed_keys(x: jax.Array, largest: bool):
    """``(keys, native)``: keys whose *descending* signed order equals the
    requested value order, and whether they are ``x`` itself (native)."""
    dtype = np.dtype(x.dtype)
    if largest and (jnp.issubdtype(dtype, jnp.signedinteger) or dtype.kind == "f"):
        # lax.top_k compares these natively — but on TPU the float TopK
        # path is ~3.5x slower than integer TopK (measured 5.8 vs 3.0 ms at
        # 4096x32768 f32 k=8), so floats take the order-preserving integer
        # bitcast below there; one elementwise pass buys a faster sort
        if not (dtype.kind == "f" and jax.default_backend() == "tpu"):
            return x, True
    u = _dt.to_sortable_bits(x)
    kdt = u.dtype
    bits = _dt.key_bits(dtype)
    if not largest:
        u = ~u
    msb = kdt.type(np.uint64(1) << np.uint64(bits - 1))
    signed = np.dtype(f"int{bits}")
    return jax.lax.bitcast_convert_type(u ^ msb, signed), False


def _decode_keys(kv: jax.Array, dtype, largest: bool) -> jax.Array:
    """Inverse of the non-native :func:`_signed_keys` transform: signed keys
    back to values of ``dtype``. Lets the flat/chunked paths return values
    straight from ``lax.top_k``'s own output instead of a
    ``take_along_axis`` gather — the batched (B, k)-from-(B, d) gather
    lowers catastrophically on TPU (measured 135 ms for 32K indices at
    4096x32768, ~25x the whole top-k)."""
    dtype = np.dtype(dtype)
    bits = _dt.key_bits(dtype)
    kdt = np.dtype(f"uint{bits}")
    u = jax.lax.bitcast_convert_type(kv, kdt)
    msb = u.dtype.type(np.uint64(1) << np.uint64(bits - 1))
    u = u ^ msb
    if not largest:
        u = ~u
    return _dt.from_sortable_bits(u, dtype)


@functools.partial(jax.jit, static_argnames=("k", "largest", "method", "num_chunks"))
def topk(
    x: jax.Array,
    k: int,
    *,
    largest: bool = True,
    method: str = "auto",
    num_chunks: int | None = None,
):
    """Top-k along the last axis. Returns ``(values, indices)`` sorted by rank.

    ``largest=False`` returns the k smallest (ascending). Leading axes batch.
    """
    d = x.shape[-1]
    if not 1 <= k <= d:
        raise ValueError(f"k={k} out of range for last axis of size {d}")
    keys, native = _signed_keys(x, largest)
    from mpi_k_selection_tpu.ops.pallas.topk import (
        batched_topk_supported,
        pallas_batched_topk_values,
    )

    if method == "auto":
        if (
            x.ndim == 2
            and largest
            and jax.default_backend() == "tpu"
            and batched_topk_supported(x.shape, x.dtype, k)
        ):
            # the Pallas depth-3-chain + lane-fold + rescue kernel
            # (ops/pallas/topk.py): ~2x XLA TopK at the BASELINE batched
            # config. Values come from the kernel; indices from the XLA key
            # path below. Callers that use only the values (vocab pruning,
            # beam-score thresholds — the BASELINE metric) never pay for
            # indices (XLA DCEs them); callers that materialize the indices
            # pay kernel + XLA TopK (~1.5x the flat path) — pass
            # method="flat" there if latency matters more than values speed.
            method = "block"
        elif x.ndim == 1 and d >= 1 << 18 and d >= 64 * k and d < 2**31:
            method = "threshold"
        elif d >= 1 << 16 and d >= 64 * k and jax.default_backend() != "tpu":
            # chunked wins ~90x over lax.top_k on CPU; on TPU the XLA TopK
            # custom call is already strong and chunked LOSES 3-9x at every
            # measured batched shape (see bench history) — use flat there
            method = "chunked"
        else:
            method = "flat"
    # the flat/chunked paths take values straight from lax.top_k's output
    # (key-decoded when the keys are transformed) — the batched (B, k)
    # take_along_axis gather lowers catastrophically on TPU (see
    # _decode_keys); the 1-D threshold/tournament paths produce indices
    # only, and a 1-D gather of k elements is cheap
    if method == "block":
        if x.ndim != 2 or not largest:
            raise ValueError("block method applies to 2-D inputs, largest=True")
        values = pallas_batched_topk_values(x, k)
        # tie order matches lax.top_k: both produce the exact sorted top-k
        # value sequence for NaN-free rows, so values[i] == x[row, idx[i]].
        # NaN-containing rows take the kernel's lax.top_k rescue (NaNs rank
        # first on both paths; payload-level order carries the same caveat
        # as utils/dtypes.py's NaN note)
        _, idx = jax.lax.top_k(keys, k)
        return values, idx
    if method == "threshold":
        if x.ndim != 1:
            raise ValueError("threshold method applies to 1-D inputs")
        idx = _threshold_topk_indices(x, k, largest)
        return jnp.take_along_axis(x, idx, axis=-1), idx
    if method == "tournament":
        if x.ndim != 1:
            raise ValueError("tournament method applies to 1-D inputs")
        idx = _tournament_topk_indices(keys, k)
        return jnp.take_along_axis(x, idx, axis=-1), idx
    if method == "flat":
        kv, idx = jax.lax.top_k(keys, k)
    elif method == "chunked":
        c = num_chunks or _pick_num_chunks(d, k)
        if c <= 1 or d % c:
            kv, idx = jax.lax.top_k(keys, k)
        else:
            sub = d // c
            kk = keys.reshape(*keys.shape[:-1], c, sub)
            subvals, subidx = jax.lax.top_k(kk, min(k, sub))
            base = jnp.arange(c, dtype=subidx.dtype)[:, None] * sub
            cand_idx = (subidx + base).reshape(*keys.shape[:-1], -1)
            cand_vals = subvals.reshape(*keys.shape[:-1], -1)
            kv, pos = jax.lax.top_k(cand_vals, k)
            idx = jnp.take_along_axis(cand_idx, pos, axis=-1)
    else:
        raise ValueError(f"unknown topk method {method!r}")
    values = kv if native else _decode_keys(kv, x.dtype, largest)
    return values, idx


def _threshold_topk_indices(x: jax.Array, k: int, largest: bool) -> jax.Array:
    """Indices of the k extreme elements of 1-D ``x`` via radix threshold +
    cumsum-rank gather. Exact under duplicates: all strict winners are taken,
    then earliest-position ties of the threshold value fill the rest."""
    from mpi_k_selection_tpu.ops.radix import radix_select

    n = x.shape[0]
    u = _dt.to_sortable_bits(x)
    if not largest:
        u = ~u  # mirror the order so "largest key" means "requested extreme"
    # threshold = k-th largest key == (n-k+1)-th smallest original value for
    # largest=True; radix_select works in the same key space so ties agree
    tau_rank = (n - k + 1) if largest else k
    tau = _dt.to_sortable_bits(radix_select(x, tau_rank))
    if not largest:
        tau = ~tau
    # Collect winners without a full-length cumsum (26 ms at 64M on a v5e —
    # slower than the whole radix descent). Instead: one streaming pass of
    # per-block (gt, eq) counts, tiny cumsums over the blocks, then for each
    # of the k output slots gather just its block and rank within it.
    cdt = jnp.int32  # n < 2^31 enforced by the auto dispatch / caller
    block = 32768
    nb = -(-n // block)
    up = jnp.pad(u, (0, nb * block - n)).reshape(nb, block)
    valid = jax.lax.broadcasted_iota(cdt, (nb, block), 0) * block + jax.lax.broadcasted_iota(cdt, (nb, block), 1) < n
    bgt = jnp.sum((up > tau) & valid, axis=1, dtype=cdt)
    beq = jnp.sum((up == tau) & valid, axis=1, dtype=cdt)
    ogt = jnp.cumsum(bgt)
    oeq = jnp.cumsum(beq)
    g = ogt[-1]
    jj = jnp.arange(k, dtype=cdt)
    strict = jj < g
    target = jnp.where(strict, jj + 1, jj - g + 1)  # 1-based rank sought
    b = jnp.where(strict, jnp.searchsorted(ogt, target), jnp.searchsorted(oeq, target))
    b = jnp.clip(b, 0, nb - 1).astype(cdt)
    prev = jnp.where(
        b > 0, jnp.where(strict, ogt[b - 1], oeq[b - 1]), jnp.zeros_like(target)
    )
    r = target - prev  # 1-based rank within the block
    rows = up[b]  # (k, block) — only k blocks are ever touched
    cols = jax.lax.broadcasted_iota(cdt, (k, block), 1)
    rvalid = cols < (n - b[:, None] * block)
    m = jnp.where(strict[:, None], rows > tau, rows == tau) & rvalid
    within = jnp.cumsum(m.astype(cdt), axis=1)
    local = jnp.argmax((within == r[:, None]) & m, axis=1).astype(cdt)
    idx = b * block + local
    # order the k winners by rank (tiny top_k over k elements)
    _, pos = jax.lax.top_k(u[idx], k)
    return idx[pos]


def _tournament_topk_indices(keys: jax.Array, k: int) -> jax.Array:
    """Indices of the k largest signed keys of 1-D ``keys`` via batched
    reduction rounds. Exact: every round keeps each row's full top-k, and the
    global top-k survives per-row top-k by the subset property."""
    d = keys.shape[0]
    sub = 1024
    while sub < 4 * k:  # rows must be enough larger than k to shrink the pool
        sub *= 2
    idx = None
    finish = max(1 << 16, sub)
    while d > finish:
        rows = d // sub
        main = rows * sub
        vals, sidx = jax.lax.top_k(keys[:main].reshape(rows, sub), k)
        base = jnp.arange(rows, dtype=sidx.dtype)[:, None] * sub
        cand = (sidx + base).reshape(-1)
        if main < d:  # ragged tail rides along as extra candidates
            cand = jnp.concatenate([cand, jnp.arange(main, d, dtype=cand.dtype)])
        idx = cand if idx is None else idx[cand]
        keys = jnp.concatenate([vals.reshape(-1), keys[main:]]) if main < d else vals.reshape(-1)
        d = keys.shape[0]
    _, pos = jax.lax.top_k(keys, k)
    return pos if idx is None else idx[pos]


def _pick_num_chunks(d: int, k: int) -> int:
    """Largest power-of-two chunk count with chunk size >= max(256, 2k)."""
    c = 1
    while d % (c * 2) == 0 and d // (c * 2) >= max(256, 2 * k):
        c *= 2
    return c


def batched_topk(x: jax.Array, k: int, **kwargs):
    """Alias for :func:`topk` on ``(..., D)`` arrays (BASELINE batched config)."""
    return topk(x, k, **kwargs)
