"""Fused single-read ingest program — one device program per staged
bucket per streamed pass.

Before this module, a staged chunk (streaming/pipeline.py:StagedKeys) was
read by up to THREE separate device programs per radix pass: the digit
histogram (ops/histogram.py via streaming/executor.py:
dispatch_chunk_histograms), the deferred survivor compaction
(``compact_core`` below, one dispatch per collect spec), and the spill
tee's union-mask compaction. Each program is its own XLA dispatch over the
same pow2-padded buffer — on the out-of-core hot path that multiplies the
per-pass HBM traffic of every staged key by the consumer count, exactly
the bandwidth the reference CGM protocol's one-scan-per-round discipline
exists to avoid (PAPER.md; the ROADMAP's "fused single-read ingest"
item).

:func:`fused_ingest_core` computes every per-chunk device product the
streamed descent needs in ONE program: the (multi-prefix) radix
histogram, a fixed-shape ``(compacted survivors, int32 count)`` pair per
survivor-collect spec, and — when a spill tee is armed — the compacted
union-of-specs payload the ``SpillWriter`` appends. Everything
data-dependent (``n_valid``, the histogram prefixes, the ``(shift,
prefix)`` spec scalars) rides as traced values, so the program compiles
once per (bucket, dtype, #hist-prefixes, #collect-specs, #tee-specs) —
the same KSC103 trail-stability discipline as the unfused programs, which
the contract grid (analysis/jaxpr_checks.py:_streaming_fused_ingest_cases)
pins at both staging buckets.

Bit-equality with the unfused bundle is by construction: the histogram
half calls the very same ``masked_radix_histogram`` /
``multi_masked_radix_histogram`` primitives over the same padded buffer
(integer counts; the host pad correction is applied at finish exactly as
for the unfused dispatch), and the compaction halves are
:func:`compact_core` — the single program the unfused deferred executor
already dispatches per spec — evaluated on the same traced scalars.
``fused="off"`` (streaming/executor.py) keeps the unfused bundle as the
bit-for-bit oracle.

Like the histogram kernels, this module runs identically on CPU (the
pallas kernels interpret; the jit program is plain XLA elsewhere) — the
fusion is a dispatch/read-count contract, observable through the
``ingest.bucket_reads{phase}`` counter (obs/wiring.py:bucket_read) and
the KSL014 lint rule, not a TPU-only code path.

Since ISSUE 13 this program is the ``fused="xla"`` TIER: one dispatch
with shared subexpressions, but no guarantee XLA walks the bucket only
once inside it. The hand-written single-sweep kernel
(ops/pallas/sweep_ingest.py, the ``"kernel"`` tier and the ``"auto"``
default on TPU backends) makes the one-HBM-read contract structural;
this module remains the fallback for buckets outside the kernel's
support matrix and the cheap-compile default off-TPU — and
:func:`compact_core` remains the compaction oracle the kernel's buffers
are bit-identical to.
"""

from __future__ import annotations

import numpy as np


def compact_core(data, n_valid, shifts, prefixes):
    """mask -> count -> fixed-shape compaction over one padded staging
    bucket: survivors (keys matching ANY ``(shift, prefix)`` spec, pad
    lanes masked out) are scattered to the FRONT of a bucket-shaped
    output, in chunk order, alongside their int32 count. Everything
    data-dependent (``n_valid``, the spec scalars) rides as traced
    values, so the program compiles once per (bucket, dtype, #specs) —
    the same discipline as the staged histogram — and its primitive
    trail is size-stable (KSC103). Only ``#specs`` is baked into the
    trace (the union loop unrolls over it), and a pass's spec count is
    fixed for every chunk of that pass."""
    import jax
    import jax.numpy as jnp

    m = None
    for j in range(shifts.shape[0]):
        mj = jax.lax.shift_right_logical(data, shifts[j]) == prefixes[j]
        m = mj if m is None else (m | mj)
    m = m & (jax.lax.iota(jnp.int32, data.shape[0]) < n_valid)
    mi = m.astype(jnp.int32)
    pos = jnp.cumsum(mi) - 1  # survivor j's target slot (int32: bucket < 2^31)
    tgt = jnp.where(m, pos, jnp.int32(data.shape[0]))  # non-survivors drop OOB
    out = jnp.zeros(data.shape, data.dtype).at[tgt].set(data, mode="drop")
    return out, jnp.sum(mi)


def fused_ingest_core(
    data,
    n_valid,
    hist_prefixes,
    c_shifts,
    c_prefixes,
    t_shifts,
    t_prefixes,
    *,
    shift,
    radix_bits,
    method,
    hist_mode,
    n_collect,
    n_tee,
):
    """ONE sweep of a padded staging bucket producing every per-chunk
    device product of a streamed pass:

    - ``hist``: the int32 digit histogram(s) at ``shift`` — ``(K, 2**rb)``
      for ``hist_mode="multi"`` (one row per traced prefix in
      ``hist_prefixes``), ``None`` for ``hist_mode="none"`` (the collect
      pass carries no histogram). The exact per-chunk accumulator the
      unfused staged dispatch produces (ops/histogram.py over the whole
      padded buffer; pad corrected host-side at finish).
    - ``collect``: a tuple of ``n_collect`` ``(compacted, count)`` pairs,
      one :func:`compact_core` per single collect spec — byte-identical
      to the unfused per-spec dispatches (``c_shifts``/``c_prefixes``
      hold the spec scalars, traced).
    - ``tee``: the union-of-``n_tee``-specs :func:`compact_core` pair the
      spill tee appends (``None`` when no tee is armed).

    ``hist_mode``, ``n_collect`` and ``n_tee`` are the only structural
    (static) parameters besides the kernel geometry — a pass's spec
    counts are fixed across its chunks, so the program compiles once per
    (bucket, dtype, #hist-prefixes, #collect, #tee) and its primitive
    trail is bucket-size-stable (KSC102/KSC103 grid coverage)."""
    import jax.numpy as jnp

    from mpi_k_selection_tpu.ops.histogram import multi_masked_radix_histogram

    if hist_mode not in ("none", "multi"):
        raise ValueError(f"unknown hist_mode {hist_mode!r}")
    hist = None
    if hist_mode == "multi":
        # the very call the unfused staged dispatch makes
        # (streaming/executor.py:dispatch_chunk_histograms): shared-sweep
        # multi-prefix counts over the WHOLE padded buffer, int32
        hist = multi_masked_radix_histogram(
            data,
            shift=shift,
            radix_bits=radix_bits,
            prefixes=hist_prefixes,
            method=method,
            count_dtype=jnp.int32,
        )
    collect = tuple(
        compact_core(data, n_valid, c_shifts[j : j + 1], c_prefixes[j : j + 1])
        for j in range(n_collect)
    )
    tee = compact_core(data, n_valid, t_shifts, t_prefixes) if n_tee else None
    return hist, collect, tee


_FUSED_FN = None


def _fused_fn():
    global _FUSED_FN
    if _FUSED_FN is None:
        import jax

        _FUSED_FN = jax.jit(
            fused_ingest_core,
            static_argnames=(
                "shift", "radix_bits", "method", "hist_mode",
                "n_collect", "n_tee",
            ),
        )
    return _FUSED_FN


def _spec_arrays(specs, kdt, total_bits):
    """``(shifts, prefixes)`` concrete arrays for a ``(resolved_bits,
    prefix)`` spec list — the traced scalars :func:`compact_core`
    consumes (empty pair for no specs)."""
    if not specs:
        return (np.empty((0,), kdt), np.empty((0,), kdt))
    shifts = np.asarray([total_bits - r for r, _ in specs], kdt)
    prefixes = np.asarray([p for _, p in specs], kdt)
    return shifts, prefixes


def dispatch_fused_ingest(
    staged,
    *,
    kdt,
    total_bits,
    shift=None,
    radix_bits=None,
    hist_prefixes=None,
    method=None,
    collect_specs=(),
    tee_specs=(),
):
    """Launch the fused program for one staged chunk on its OWN device
    (async dispatch — ``staged.data`` is committed, so the program runs
    where the chunk lives). ``hist_prefixes`` is the pass's surviving
    prefix list (``None`` = no histogram: the collect pass);
    ``collect_specs``/``tee_specs`` are ``(resolved_bits, prefix)``
    lists. Returns the in-flight ``(hist, collect, tee)`` handle whose
    parts the :class:`~mpi_k_selection_tpu.streaming.executor.
    FusedIngestConsumer` materializes at FIFO-finish time."""
    if hist_prefixes is not None:
        hist_mode = "multi"
        hp = np.asarray(list(hist_prefixes), kdt)
        hshift, hrb, hmethod = shift, radix_bits, method
    else:
        hist_mode = "none"
        hp = np.empty((0,), kdt)
        # structural placeholders: unused by the "none" trace, but static
        # jit keys — pin them so collect-only passes share one cache line
        hshift, hrb, hmethod = 0, 1, "scatter"
    c_shifts, c_prefixes = _spec_arrays(list(collect_specs), kdt, total_bits)
    t_shifts, t_prefixes = _spec_arrays(list(tee_specs), kdt, total_bits)
    return _fused_fn()(
        staged.data,
        np.int32(staged.n_valid),
        hp,
        c_shifts,
        c_prefixes,
        t_shifts,
        t_prefixes,
        shift=hshift,
        radix_bits=hrb,
        method=hmethod,
        hist_mode=hist_mode,
        n_collect=len(collect_specs),
        n_tee=len(tee_specs),
    )
