"""ctypes bindings for the native runtime (kselect_native.cpp).

Exposes a thin typed wrapper object; builds the library on first use. All
failures degrade gracefully — callers (backends/seq.py) fall back to NumPy.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

_lock = threading.Lock()
_lib = None  # ksel: guarded-by[_lock]
_failed = False  # ksel: guarded-by[_lock]

_NTH = {
    np.dtype(np.int32): ("nth_element_i32", ctypes.c_int32),
    np.dtype(np.int64): ("nth_element_i64", ctypes.c_int64),
    np.dtype(np.float32): ("nth_element_f32", ctypes.c_float),
    np.dtype(np.float64): ("nth_element_f64", ctypes.c_double),
}


class NativeLib:
    def __init__(self, cdll: ctypes.CDLL):
        self._cdll = cdll
        for name, ctyp in _NTH.values():
            fn = getattr(cdll, name)
            fn.argtypes = [
                ctypes.POINTER(ctyp),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctyp),
            ]
            fn.restype = ctypes.c_int
        cg = cdll.cgm_kselect_i32
        cg.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32),
        ]
        cg.restype = ctypes.c_int

    def nth_element(self, x: np.ndarray, k: int):
        """k-th smallest (1-indexed) via std::nth_element; None if unsupported."""
        x = np.ascontiguousarray(x).ravel()
        entry = _NTH.get(x.dtype)
        if entry is None:
            return None
        name, ctyp = entry
        out = ctyp(0)
        rc = getattr(self._cdll, name)(
            x.ctypes.data_as(ctypes.POINTER(ctyp)), x.size, int(k), ctypes.byref(out)
        )
        if rc != 0:
            raise ValueError(f"native nth_element failed (rc={rc}, k={k}, n={x.size})")
        return x.dtype.type(out.value)

    def cgm_kselect(self, x: np.ndarray, k: int, *, num_procs: int, c: int):
        """Distributed CGM selection over forked ranks. int32 only (reference
        operates on C int). Returns (answer, rounds, elapsed_s, found_early)."""
        x = np.ascontiguousarray(x, dtype=np.int32).ravel()
        ans = ctypes.c_int32(0)
        rounds = ctypes.c_int64(0)
        elapsed = ctypes.c_double(0.0)
        found = ctypes.c_int32(0)
        rc = self._cdll.cgm_kselect_i32(
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            x.size,
            int(k),
            int(num_procs),
            int(c),
            ctypes.byref(ans),
            ctypes.byref(rounds),
            ctypes.byref(elapsed),
            ctypes.byref(found),
        )
        if rc == 1:
            raise ValueError(
                f"invalid CGM arguments (n={x.size}, k={k}, num_procs={num_procs}, "
                f"c={c}); num_procs must be in [2, 64] — the reference aborts the "
                "same way (TODO-kth-problem-cgm.c:56-59)"
            )
        if rc != 0:
            raise RuntimeError(f"native CGM runtime failed (rc={rc})")
        return int(ans.value), int(rounds.value), float(elapsed.value), bool(found.value)


def get_lib() -> NativeLib | None:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _failed
    with _lock:
        if _lib is not None:
            return _lib
        if _failed:
            return None
        try:
            from mpi_k_selection_tpu.native.build import build

            _lib = NativeLib(ctypes.CDLL(str(build())))
        except Exception:
            _failed = True
            return None
        return _lib
