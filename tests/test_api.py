"""Public API dispatch + reference-semantics checks."""

import jax.numpy as jnp
import numpy as np
import pytest

import mpi_k_selection_tpu as ks
from mpi_k_selection_tpu.backends import get_backend, seq
from mpi_k_selection_tpu.utils import datagen

from mpi_k_selection_tpu.utils import compat


def test_kselect_dispatch():
    x = datagen.generate(3000, pattern="uniform", seed=1, dtype=np.int32)
    k = 1500
    want = int(seq.kselect(x, k))
    assert int(ks.kselect(jnp.asarray(x), k)) == want
    assert int(ks.kselect(jnp.asarray(x), k, algorithm="sort")) == want
    assert int(ks.kselect(jnp.asarray(x), k, algorithm="radix")) == want


def test_median_matches_reference_operating_point():
    # k = N/2, 1-indexed (kth-problem-seq.c~:24)
    x = datagen.generate(1000, pattern="uniform", seed=2, dtype=np.int32)
    want = int(np.sort(x)[1000 // 2 - 1])
    assert int(ks.median(jnp.asarray(x))) == want
    assert int(seq.median(x)) == want


def test_backend_registry():
    assert get_backend("seq") is seq
    with pytest.raises(ValueError):
        get_backend("cuda")


def test_reference_defaults_config():
    # the reference constants survive as defaults: N=1e8, k=250/150, c=500
    from mpi_k_selection_tpu import config

    assert config.REFERENCE_N == 100_000_000
    assert config.REFERENCE_K_SEQ == 250
    assert config.REFERENCE_K_CGM == 150
    assert config.REFERENCE_C == 500


@pytest.mark.parametrize("n", [5000, 100_001])  # sort path, radix path
def test_kselect_many_matches_oracle(rng, n):
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int32)
    ks_q = np.array([1, 7, n // 2, n - 1, n], dtype=np.int64)
    got = np.asarray(ks.kselect_many(jnp.asarray(x), ks_q))
    want = np.sort(x)[ks_q - 1]
    np.testing.assert_array_equal(got, want)


def test_kselect_many_duplicates_and_float(rng):
    xd = (rng.integers(0, 9, size=60_000)).astype(np.int32)
    ks_q = np.array([1, 30_000, 60_000])
    np.testing.assert_array_equal(
        np.asarray(ks.kselect_many(jnp.asarray(xd), ks_q)), np.sort(xd)[ks_q - 1]
    )
    xf = rng.standard_normal(70_001).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(ks.kselect_many(jnp.asarray(xf), ks_q)), np.sort(xf)[ks_q - 1]
    )


def test_kselect_many_rejects_bad_k(rng):
    x = jnp.asarray(rng.integers(0, 100, size=1000, dtype=np.int32))
    with pytest.raises(ValueError):
        ks.kselect_many(x, [1, 0])
    with pytest.raises(ValueError):
        ks.kselect_many(x, [1, 1001])


def test_quantiles_nearest_rank(rng):
    x = rng.integers(-(10**6), 10**6, size=99_999, dtype=np.int32)
    qs = [0.0, 0.5, 0.9, 0.99, 1.0]
    got = np.asarray(ks.quantiles(jnp.asarray(x), qs))
    s = np.sort(x)
    import math
    want = np.array([s[max(1, min(x.size, math.ceil(q * x.size))) - 1] for q in qs])
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError):
        ks.quantiles(jnp.asarray(x), [0.5, 1.5])


def test_kselect_many_large_k_sort_dispatch(rng):
    # >= 112 queries take the one-sort-K-gathers path (measured crossover
    # ~K=110 at n=2^27 on v5e; see api.kselect_many) — exactness unchanged
    import mpi_k_selection_tpu as pkg

    n = 50_000
    x = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    ks = np.linspace(1, n, 128).astype(np.int64)
    got = np.asarray(pkg.kselect_many(x, ks))
    np.testing.assert_array_equal(got, np.sort(x, kind="stable")[ks - 1])


def test_f64_host_route_reachable_from_api(monkeypatch, rng):
    """api.kselect/kselect_many must NOT device-commit host float64 on the
    TPU backend (device f64 storage truncates, measured on v5e): the host
    array flows through as_selection_array to the exact host-key route.
    Emulated off-TPU by patching the backend name — the route itself is
    pure host numpy + uint64 device select, so it runs anywhere."""
    import jax

    import mpi_k_selection_tpu as pkg
    from mpi_k_selection_tpu import api

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with compat.enable_x64(True):
        # large-n radix route
        x = rng.standard_normal(70_001)
        kept = api.as_selection_array(x)
        assert isinstance(kept, np.ndarray) and kept.dtype == np.float64
        # scatter method: the patched backend name would otherwise make
        # the pallas wrappers pick compiled (non-interpret) mode on CPU
        got = float(pkg.kselect(x, 35_000, hist_method="scatter"))
        assert got == float(np.sort(x, kind="stable")[34_999])
        # small-n sort route stays host-side too
        xs = rng.standard_normal(1_000)
        got = float(pkg.kselect(xs, 500))
        assert got == float(np.sort(xs, kind="stable")[499])
        # multi-rank: radix route and the large-K sort route
        ks = np.array([1, 35_000, 70_001])
        gm = np.asarray(pkg.kselect_many(x, ks, hist_method="scatter"))
        np.testing.assert_array_equal(gm, np.sort(x, kind="stable")[ks - 1])
        ks_big = np.linspace(1, 70_001, 128).astype(np.int64)
        gm = np.asarray(pkg.kselect_many(x, ks_big))
        np.testing.assert_array_equal(gm, np.sort(x, kind="stable")[ks_big - 1])


def test_kselect_many_traced_scalar_ks_host_f64(monkeypatch, rng):
    """ADVICE r4 (low): a scalar TRACED ks on the host-f64 sort path must be
    detected by the isinstance check BEFORE np.atleast_1d can observe it
    (atleast_1d on a scalar tracer raises TracerArrayConversionError); it
    then routes through the radix shell's traced path, which on the CPU
    test host is bit-exact."""
    import warnings

    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu import api
    from mpi_k_selection_tpu.ops import radix as radix_mod

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # the traced calls below trip the one-time f64-approx warning; keep the
    # process-global flag's state out of other tests
    monkeypatch.setattr(radix_mod, "_f64_tpu_approx_warned", set())
    with compat.enable_x64(True):
        x = rng.standard_normal(1_000)  # size <= 2^14 -> the sort path
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            out = jax.jit(
                lambda k: api.kselect_many(x, k, hist_method="scatter")
            )(jnp.asarray(500, jnp.int64))
        assert float(out) == float(np.sort(x, kind="stable")[499])
        # this branch honors kwargs (routes to radix) — the kwargs-ignored
        # warning must NOT fire here
        assert not any("ignored" in str(w.message) for w in caught)
        # a Python LIST of traced ks must also be detected before any
        # numpy conversion can observe the tracers
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out2 = jax.jit(
                lambda k1, k2: api.kselect_many(x, [k1, k2], hist_method="scatter")
            )(jnp.asarray(1, jnp.int64), jnp.asarray(1_000, jnp.int64))
        s = np.sort(x, kind="stable")
        np.testing.assert_allclose(np.asarray(out2), s[[0, 999]])


def test_many_sort_dispatch_warning_matches_constant(rng):
    """VERDICT r4 weak 5: the kwargs-ignored warning must quote the actual
    dispatch threshold, interpolated so the two cannot drift; r5 makes the
    threshold n-aware (fit through measured crossovers 82 at n=2^24 and
    134 at 2^28; 121 predicted at 2^27, within noise of r4's ~110)."""
    import pytest

    from mpi_k_selection_tpu import api

    x = rng.integers(0, 100, size=100, dtype=np.int32)  # small -> sort path
    with pytest.warns(
        UserWarning, match=str(api.many_sort_dispatch_queries(x.size))
    ):
        got = api.kselect_many(x, [5, 10], chunk=1024)
    np.testing.assert_array_equal(
        np.asarray(got), np.sort(x, kind="stable")[[4, 9]]
    )
    # the n-aware rule reproduces the measured crossovers and clamps
    assert api.many_sort_dispatch_queries(1 << 24) == 82
    assert api.many_sort_dispatch_queries(1 << 27) == 121
    assert api.many_sort_dispatch_queries(1 << 28) == 134
    assert api.many_sort_dispatch_queries(100) == 64
    assert api.many_sort_dispatch_queries(1 << 40) == 192
