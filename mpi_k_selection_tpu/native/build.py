"""Build the native runtime: ``python -m mpi_k_selection_tpu.native.build``.

One g++ invocation producing ``_build/libkselect_native.so`` next to the
sources. The loader (loader.py) calls :func:`build` lazily on first use, so
an explicit build is only needed to rebuild after editing the C++.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess
import sys

_DIR = pathlib.Path(__file__).resolve().parent
SOURCES = [_DIR / "kselect_native.cpp"]
LIB_PATH = _DIR / "_build" / "libkselect_native.so"


def build(force: bool = False, quiet: bool = True) -> pathlib.Path:
    """Compile the shared library if missing/stale; return its path."""
    if (
        not force
        and LIB_PATH.exists()
        and all(LIB_PATH.stat().st_mtime >= s.stat().st_mtime for s in SOURCES)
    ):
        return LIB_PATH
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        raise RuntimeError("no C++ compiler found (need g++ or clang++)")
    LIB_PATH.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        gxx,
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        "-Wall",
        *[str(s) for s in SOURCES],
        "-o",
        str(LIB_PATH),
    ]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"native build failed:\n{res.stderr}")
    if not quiet:
        print(f"built {LIB_PATH}")
    return LIB_PATH


if __name__ == "__main__":
    build(force="--force" in sys.argv, quiet=False)
