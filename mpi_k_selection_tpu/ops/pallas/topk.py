"""Pallas batched top-k (values) kernel — BASELINE config 4's hot path.

Replaces XLA's TopK for the batched shape (B x D float32, k <= 8, the
beam-search / vocab top-k config: B=4096, D=32768, k=8). XLA's integer-key
TopK runs ~2.4 ms there; this pipeline measures ~1.1-1.3 ms on v5e
(exp_btopk.py records the full design-space measurements: streaming floor
0.51 ms, full insert-chain 3.5 ms, depth-8 + in-kernel fold 1.7 ms,
depth-3 + rescue ~1.2 ms — the variant below).

Design (VPU-shaped, not a port of any CPU/GPU heap scheme):

1. **Depth-d insert chain** (`_chain_kernel`; d=3 for k <= 8, d=4 for
   k <= 16 — r5 widened the envelope): the (bb, bd) tile is viewed
   as (bb, bd/128, 128) sublane slabs; each slab streams through a d-deep
   compare-insert chain kept per (row, lane) in the output block, which the
   d-grid revisits as an accumulator. 2d VPU ops/element — the whole reason
   this beats both XLA TopK and a full 8-deep chain (16 ops/element,
   measured 2x slower end-to-end).
2. **Bitonic lane fold** (`_fold_kernel`): the per-lane sorted-d columns
   (padded to sorted-m with -inf; m = 8 or 16 per the k band) are merged
   across lanes by halving: winners of (a_i, b_{m-1-i}) form a bitonic
   sequence, cleaned by a log2(m)-stage half-cleaner network — 7 fold
   levels turn (depth, 128) candidates/row into the row's top-m IF no
   lane hid a (depth+1)-th member of the true top-m. The same kernel
   emits a per-row suspect flag: some lane's depth-th kept value > the
   folded m-th value.
3. **Bounded rescue**: suspect rows (a lane holding >= depth+1 of the
   row's top-m — P ~ C(m, depth+1)/128^depth per row: ~1e-3 per 4096-row
   batch at (3, 8), ~6e-2 at (4, 16), for random data; adversarial
   stride-128 layouts can force it) are re-solved exactly by
   ``lax.top_k`` on a gathered <= ``rescue_rows`` subset; if even that
   budget overflows, one ``lax.cond`` falls back to full ``lax.top_k``.
   Exactness therefore never depends on the data distribution.

Exactness proof of the non-suspect case (by value, duplicates included):
with no suspect lane, every hidden element is <= its lane's depth-th kept
<= tm_hat (the folded m-th value), so all row values > tm_hat are among
the candidates; if the true m-th value were > tm_hat, the >= m values
above tm_hat would all be candidates and the folded m-th would exceed
tm_hat — contradiction. Hence the candidate top-m equals the true top-m
by value.

Values only: the chain carries no positions (an index-carrying chain
measured ~2.5x the ops). ops/topk.py recovers indices post-hoc with the
streaming threshold pass (`_block_topk_indices`, r5); when the caller
uses only values (vocab pruning, thresholds, beam scores against a
bound), XLA dead-code-eliminates the recovery and the kernel's speed is
the call's speed. bfloat16 inputs are upcast to f32 in-register (Mosaic
on v5e rejects bf16 vector compares); the final downcast is exact.
Measured (r5, 4096x32768): f32 k=16 values 1.25-1.5 ms / lax 6.3 ms;
bf16 k=8 values ~1.1 ms / lax-bf16 9.0 ms; tuples 5.1 / 3.8 ms vs the
~138 ms index-carrying XLA class.

Reference anchor: the reference has no batched dimension at all (one
IntVector, ``vector.h:7-11``); this is north-star scope (BASELINE.md
config 4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from mpi_k_selection_tpu.utils import compat

try:  # pragma: no cover
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128
# candidates kept per (row, lane) / fold width by k band (see the
# suspect-rate analysis in the module docstring): k <= 8 uses the
# measured depth-3 + fold-8 design; 8 < k <= 16 uses depth-4 + fold-16
# (P(lane hides a 5th top-16 member) ~ C(16,5)/128^4 ~ 1.6e-5 per row)
def _depth_fold(k: int):
    return (3, 8) if k <= 8 else (4, 16)


def _ce(a, b):
    """Descending compare-exchange."""
    return jnp.maximum(a, b), jnp.minimum(a, b)


def _chain_kernel(x_ref, c_ref, *, bd, depth):
    j = pl.program_id(1)
    slabs = bd // LANES
    bb = x_ref.shape[0]

    @pl.when(j == 0)
    def _():
        c_ref[:] = jnp.full_like(c_ref, -jnp.inf)

    # compute in f32: Mosaic on v5e rejects bf16 vector compares ("Target
    # does not support this comparison"); the in-register upcast is exact
    # for bf16 values and free for f32
    x = x_ref[:].astype(jnp.float32).reshape(bb, slabs, LANES)
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(depth)]
    for s in range(slabs):
        t = x[:, s, :]
        for i in range(depth):
            ri = regs[i]
            regs[i] = jnp.maximum(ri, t)
            t = jnp.minimum(ri, t)
    c_ref[:] = jnp.concatenate(regs, axis=0)


def _lane_fold_topm(regs, bb, m_out):
    """Merge ``m_out`` per-lane sorted-descending columns across the lane
    axis (m_out a power of two).

    At each fold the left/right lane halves hold independent sorted-m runs
    per lane; ``max(a_i, b_{m-1-i})`` yields a bitonic sequence containing
    the merged top-m, cleaned by a bitonic half-cleaner network (strides
    m/2, m/4, ..., 1). Returns m_out ``(bb, 1)`` arrays — the fold
    target's top-m, sorted.
    """
    w = regs[0].shape[1] // 2
    while w >= 1:
        a = [r[:, :w] for r in regs]
        b = [r[:, w:2 * w] for r in regs]
        m = [jnp.maximum(a[i], b[m_out - 1 - i]) for i in range(m_out)]
        s = m_out // 2
        while s >= 1:
            for i in range(m_out):
                if (i // s) % 2 == 0:
                    m[i], m[i + s] = _ce(m[i], m[i + s])
            s //= 2
        regs = m
        w //= 2
    return regs


def _fold_kernel(c_ref, o_ref, s_ref, *, bb, depth, m_out):
    dt = jnp.float32  # candidates are carried in f32 (see _chain_kernel)
    neg = jnp.full((bb, LANES), -jnp.inf, dt)
    regs = [c_ref[i * bb:(i + 1) * bb, :] for i in range(depth)]
    lane_last = regs[-1]
    top = _lane_fold_topm(regs + [neg] * (m_out - depth), bb, m_out)
    o_ref[:] = jnp.concatenate(top, axis=1)
    tm = top[m_out - 1]  # (bb, 1): the folded m-th value
    # NaN anywhere in a lane floods that lane's registers (max/min both
    # propagate NaN), so isnan(lane_last) catches every contaminated row
    # and routes it to the exact lax.top_k rescue — without this,
    # `lane_last > tm` is False for NaN and the flood would return
    # silently wrong values
    suspect = jnp.logical_or(lane_last > tm, jnp.isnan(lane_last))
    s = jnp.where(suspect, jnp.float32(1), jnp.float32(0))
    w = LANES // 2
    while w >= 1:  # lane-axis max: any suspect lane flags the row
        s = jnp.maximum(s[:, :w], s[:, w:2 * w])
        w //= 2
    s_ref[:] = s


def _pick_block(size, options):
    for o in options:
        if size % o == 0:
            return o
    return None


def batched_topk_supported(shape, dtype, k) -> bool:
    """Static dispatch test for :func:`pallas_batched_topk_values`."""
    if pltpu is None or len(shape) != 2:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return False
    b, d = shape
    if not 1 <= k <= 16:  # k <= 8: depth-3/fold-8; k <= 16: depth-4/fold-16
        return False
    if _pick_block(b, (512, 256, 128, 64)) is None:
        return False
    # d must split into whole (>= 1024)-wide column blocks of whole slabs,
    # and give each lane enough depth for the suspect analysis to pay
    return d % 1024 == 0 and d >= 4096


@functools.partial(jax.jit, static_argnames=("k", "rescue_rows", "interpret"))
def pallas_batched_topk_values(
    x: jax.Array,
    k: int,
    *,
    rescue_rows: int = 64,
    interpret: bool | None = None,
) -> jax.Array:
    """Exact top-k VALUES (sorted descending) per row of 2-D float32 or
    bfloat16 ``x``, k <= 16 (bf16 computes in f32 in-register and the
    returned values are bitwise the original bf16 elements).

    Use :func:`batched_topk_supported` to gate dispatch; out-of-envelope
    shapes should take the XLA paths in ops/topk.py.
    """
    if pltpu is None:
        raise NotImplementedError(
            "the pallas batched top-k kernel is not available in this build"
        )
    if not batched_topk_supported(x.shape, x.dtype, k):
        raise ValueError(
            f"unsupported batched-topk shape {x.shape} dtype {x.dtype} k={k}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, D = x.shape
    depth, m_out = _depth_fold(k)
    bb = _pick_block(B, (512, 256, 128, 64))
    bd = _pick_block(D, (2048, 1024))
    nb, nd = B // bb, D // bd
    rescue_rows = min(rescue_rows, B)
    dt = x.dtype

    with compat.enable_x64(False):
        cand = pl.pallas_call(
            functools.partial(_chain_kernel, bd=bd, depth=depth),
            grid=(nb, nd),
            in_specs=[
                pl.BlockSpec((bb, bd), lambda i, j: (i, j), memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec(
                (depth * bb, LANES), lambda i, j: (i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=compat.shape_dtype_struct((depth * B, LANES), jnp.float32, vma=compat.vma_of(x)),
            interpret=interpret,
        )(x)
        top, susp = pl.pallas_call(
            functools.partial(_fold_kernel, bb=bb, depth=depth, m_out=m_out),
            grid=(nb,),
            in_specs=[
                pl.BlockSpec(
                    (depth * bb, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
                )
            ],
            out_specs=[
                pl.BlockSpec((bb, m_out), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((bb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                compat.shape_dtype_struct((B, m_out), jnp.float32, vma=compat.vma_of(x)),
                compat.shape_dtype_struct((B, 1), jnp.float32, vma=compat.vma_of(x)),
            ],
            interpret=interpret,
        )(cand)

    sflag = susp[:, 0] > 0
    nsusp = jnp.sum(sflag.astype(jnp.int32))
    # bounded exact rescue: lax.top_k over the <= rescue_rows gathered rows
    # (rescue values upcast to the candidates' f32 carrier — exact for bf16)
    sval, sidx = jax.lax.top_k(sflag.astype(jnp.int32), rescue_rows)
    rtop, _ = jax.lax.top_k(x[sidx], m_out)
    fixed = jnp.where(sval[:, None] > 0, rtop.astype(jnp.float32), top[sidx])
    top = top.at[sidx].set(fixed)

    def full_fallback(_):
        v, _ = jax.lax.top_k(x, m_out)
        return v.astype(jnp.float32)

    top = jax.lax.cond(nsusp <= rescue_rows, lambda _: top, full_fallback, 0)
    # the f32 -> bf16 downcast is exact: every candidate is (an upcast of)
    # an original bf16 element
    return top[:, :k].astype(dt)
