"""Version-compatibility shims for jax APIs that moved between releases.

The package targets current jax (``jax.shard_map``, ``jax.typeof``,
``jax.enable_x64``, ``jax.lax.pcast``), but the distributed and Pallas
paths must still import — and where possible run — on the 0.4.x line,
where the same capabilities live under ``jax.experimental`` (or do not
exist at all, like varying-manual-axes types). Every version-sensitive
attribute is resolved HERE and nowhere else; the static analyzer enforces
that (analysis/ast_rules.py:KSL006), so a new jax API drift shows up as
one shim edit instead of a scattered AttributeError hunt.

Resolution map:

===================  ============================  =========================
shim                 current jax                   0.4.x fallback
===================  ============================  =========================
``shard_map``        ``jax.shard_map``             ``jax.experimental.
                     (``check_vma=``)              shard_map.shard_map``
                                                   (``check_rep=False`` —
                                                   no vma types to check)
``enable_x64``       ``jax.enable_x64(flag)``      ``jax.experimental.
                                                   {enable,disable}_x64()``
``typeof``           ``jax.typeof``                ``jax.core.get_aval``
``vma_of``           ``jax.typeof(x).vma``         ``frozenset()`` (the
                                                   type system predates vma)
``shape_dtype_       ``jax.ShapeDtypeStruct(...,   drops the ``vma``
struct``             vma=...)``                    keyword (always empty)
``pvary``            ``jax.lax.pcast(..,           identity (replication
                     to="varying")``               is check_rep's job)
``process_           ``jax.experimental.           same location on 0.4.x;
allgather``          multihost_utils.              resolved here so a future
                     process_allgather``           move is one shim edit
===================  ============================  =========================
"""

from __future__ import annotations

import jax

# (KSL006 exempts utils/compat.py by path — this module IS the shim; the
# redundant noqa-file here was retired by the staleness audit)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    ``check_vma`` is honored on current jax; the 0.4.x fallback runs with
    ``check_rep=False`` — legacy replication inference predates the vma
    type system these shard bodies are written against (explicit
    ``pvary``/``pmax`` re-establishment), and letting it guess produces
    spurious mismatches the new checker would not raise.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def enable_x64(enable: bool = True):
    """Context manager forcing 64-bit types on (or off), across versions."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enable)
    if enable:
        from jax.experimental import enable_x64 as _ctx
    else:
        from jax.experimental import disable_x64 as _ctx
    return _ctx()


def typeof(x):
    """``jax.typeof`` across versions (falls back to the abstract value)."""
    if hasattr(jax, "typeof"):
        return jax.typeof(x)
    return jax.core.get_aval(x)


def vma_of(x) -> frozenset:
    """``x``'s varying-manual-axes set; empty where the type system
    predates vma (every manual-axes value is then untyped — the legacy
    ``check_rep`` regime)."""
    if hasattr(jax, "typeof"):
        return getattr(jax.typeof(x), "vma", frozenset())
    return frozenset()


def shape_dtype_struct(shape, dtype, *, vma: frozenset = frozenset()):
    """``jax.ShapeDtypeStruct`` carrying ``vma`` where supported. An empty
    ``vma`` is omitted (equivalent on current jax, required on 0.4.x whose
    constructor rejects the keyword)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def process_allgather(x, *, tiled: bool = False):
    """``jax.experimental.multihost_utils.process_allgather`` — one DCN
    gather of a host-local value across every process in the job; the
    result (leading axis = process count when untiled) is identical on all
    hosts. Single-process jobs get a length-1 leading axis. Resolved here
    (not at call sites) so a future relocation of multihost_utils is one
    shim edit, per the KSL006 discipline."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=tiled)


def pvary(value, axes):
    """Mark ``value`` varying over mesh ``axes`` inside shard_map bodies.

    ``pcast`` on current jax, ``pvary`` on the releases that shipped it
    under that name, identity on 0.4.x (no vma types; the legacy
    ``check_rep=False`` regime the :func:`shard_map` shim selects needs no
    value-level marking).
    """
    if isinstance(axes, str):
        axes = (axes,)
    else:
        axes = tuple(axes)
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(value, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(value, axes)
    return value
