"""Distributed selection over a jax.sharding.Mesh (ICI/DCN collectives)."""

from mpi_k_selection_tpu.parallel.cgm import distributed_cgm_select
from mpi_k_selection_tpu.parallel.mesh import make_mesh, require_distributed, shard_1d
from mpi_k_selection_tpu.parallel.radix import (
    distributed_radix_select,
    distributed_radix_select_many,
)
from mpi_k_selection_tpu.parallel.sketch import dcn_merge_sketch, distributed_sketch
from mpi_k_selection_tpu.parallel.topk import distributed_topk

DISTRIBUTED_ALGORITHMS = ("radix", "cgm")


def distributed_kselect(x, k, *, algorithm: str = "radix", mesh=None, **kwargs):
    """Exact k-th smallest of ``x`` sharded over ``mesh`` (all devices by
    default). ``algorithm='radix'`` is the flagship fixed-round path;
    ``'cgm'`` is the reference-parity weighted-median iteration."""
    if algorithm == "radix":
        return distributed_radix_select(x, k, mesh=mesh, **kwargs)
    if algorithm == "cgm":
        return distributed_cgm_select(x, k, mesh=mesh, **kwargs)
    raise ValueError(
        f"unknown distributed algorithm {algorithm!r}; choose from {DISTRIBUTED_ALGORITHMS}"
    )


__all__ = [
    "distributed_kselect",
    "distributed_radix_select",
    "distributed_radix_select_many",
    "distributed_cgm_select",
    "dcn_merge_sketch",
    "distributed_sketch",
    "distributed_topk",
    "make_mesh",
    "require_distributed",
    "shard_1d",
    "DISTRIBUTED_ALGORITHMS",
]
