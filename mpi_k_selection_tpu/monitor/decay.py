"""Exponential-decay windowed quantiles — fixed-point count scaling so
decayed merges stay exact, associative and commutative.

Floating-point decayed counters (``acc = acc * d + x``) are neither
mergeable nor order-invariant. This variant keeps every guarantee of the
undecayed ring by scaling counts with INTEGER weights before the fold:

- the weight of a bucket of age ``a`` (buckets before the current one)
  is ``decay_weight(decay, a) = round(decay**a * 2**DECAY_SHIFT)`` — a
  fixed-point integer on a ``2**DECAY_SHIFT`` scale, computed ONCE per
  (decay, age) pair;
- a decayed aggregate is ``sum_a bucket_a.counts * weight(a)`` — every
  term an exact int64 product, so ANY grouping or ordering of the folds
  yields a bitwise-identical accumulator (``RadixSketch.fold_scaled``;
  associativity/commutativity test-enforced across split points in
  tests/test_monitor.py);
- ``decay=1.0`` gives ``weight(a) == 2**DECAY_SHIFT`` exactly for every
  age, so the decayed aggregate is the undecayed one with every count
  shifted left by ``DECAY_SHIFT`` — rank queries resolve the SAME bucket
  (``ceil(ceil(q*n*S)/S) == ceil(q*n)`` for any integer scale ``S``),
  i.e. the degenerate case is bit-identical to the undecayed sketch's
  answers (test-enforced).

Width contract (the host int64 accumulator discipline, KSC102): scaled
counts live in the same int64 pyramid, so the window's total UNWEIGHTED
count must stay below ``2**(63 - DECAY_SHIFT)`` (~2^43 at the default
shift of 20) — ``fold_scaled`` refuses loudly past it. Buckets whose
weight rounds to 0 (age beyond ~``log(2**-DECAY_SHIFT)/log(decay)``)
contribute nothing and are skipped — exponential decay's natural
horizon.
"""

from __future__ import annotations

from mpi_k_selection_tpu.monitor.windows import WindowedSketch
from mpi_k_selection_tpu.streaming.sketch import RadixSketch

#: Fixed-point scale of the decay weights: weight(age) is an integer on
#: a 2**DECAY_SHIFT scale. 20 bits leaves 2**43 unweighted counts of
#: int64 headroom per window — far beyond any telemetry window.
DECAY_SHIFT = 20


def decay_weight(decay: float, age: int, *, shift: int = DECAY_SHIFT) -> int:
    """Fixed-point weight of a bucket ``age`` advances old:
    ``round(decay**age * 2**shift)``. ``decay=1.0`` returns exactly
    ``2**shift`` for every age; weights reaching 0 mean the bucket has
    fully decayed out."""
    decay = float(decay)
    if not 0.0 < decay <= 1.0:
        raise ValueError(f"decay must be in (0, 1], got {decay}")
    age = int(age)
    if age < 0:
        raise ValueError(f"bucket age must be >= 0, got {age}")
    return int(round(decay**age * (1 << shift)))


class DecayedSketch(RadixSketch):
    """A decay-weighted RadixSketch: the same pyramid, extremes and query
    machinery, with every count on the ``2**shift`` fixed-point scale
    (``n`` is the total WEIGHTED count). Rank arguments to ``query`` /
    ``rank_bounds`` / ``value_bounds`` / ``pin`` are weighted ranks in
    ``[1, n]``; ``quantile``/``quantiles`` already convert through
    nearest-rank on ``n``, so they need no caller-side scaling. Exactness
    is preserved: ``rank_bounds`` are true WEIGHTED ranks of the resolved
    interval boundaries, and ``value_bounds`` brackets the true weighted
    order statistic."""

    def __init__(self, dtype, *, radix_bits: int = 4, levels: int = 4,
                 decay: float = 1.0, shift: int = DECAY_SHIFT):
        super().__init__(dtype, radix_bits=radix_bits, levels=levels)
        self.decay = float(decay)
        self.shift = int(shift)
        #: the fixed-point scale every count is multiplied by at age 0
        self.scale = 1 << self.shift

    @property
    def weighted_n(self) -> int:
        """Alias for ``n`` making the scale explicit at call sites."""
        return self.n

    def fold_bucket(self, bucket: RadixSketch, age: int) -> "DecayedSketch":
        """Count-scaled fold of one time bucket at ``age`` advances old
        (weight ``decay_weight(self.decay, age, shift=self.shift)``;
        zero-weight buckets are skipped). Returns ``self``."""
        self.fold_scaled(
            bucket, decay_weight(self.decay, age, shift=self.shift)
        )
        return self


class DecayedWindowedSketch(WindowedSketch):
    """The exponential-decay sliding window: the same bucket ring and
    O(1) advance as :class:`WindowedSketch` (advance never touches
    weights — ages are assigned at QUERY time, newest bucket age 0), with
    ``query`` returning a :class:`DecayedSketch` whose counts are the
    live buckets' scaled by their age weights. Cached suffix aggregates
    cannot serve decayed queries (weights change every advance), so the
    ring skips aggregate maintenance entirely
    (``_maintain_aggregates``) and a decayed query folds its O(window)
    raw buckets — the window advance itself stays O(1): a ring append
    and at most one eviction."""

    _maintain_aggregates = False

    def __init__(self, dtype, *, window: int, decay: float,
                 radix_bits: int = 4, levels: int = 4,
                 shift: int = DECAY_SHIFT):
        super().__init__(dtype, window=window, radix_bits=radix_bits,
                         levels=levels)
        self.decay = float(decay)
        self.shift = int(shift)
        decay_weight(self.decay, 0, shift=self.shift)  # validates decay

    def query(self, window: int | None = None) -> DecayedSketch:
        """Decay-weighted merge of the newest ``window`` live buckets:
        ``sum_a bucket_a * weight(age a)``, the current bucket at age 0.
        Bit-identical to folding the same (bucket, age) pairs in any
        order or grouping (each weight depends only on the bucket's own
        age)."""
        w = self._resolve_window(window)
        out = DecayedSketch(
            self.dtype, radix_bits=self.radix_bits, levels=self.levels,
            decay=self.decay, shift=self.shift,
        )
        newest_first = list(reversed(self.live_buckets()))[:w]
        for age, bucket in enumerate(newest_first):
            out.fold_bucket(bucket, age)
        return out
