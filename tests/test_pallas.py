"""Pallas histogram kernel vs the scatter oracle (interpret mode on CPU).

SURVEY.md §4 test plan: "unit tests for ... each Pallas kernel vs NumPy".

Most cases pass a small explicit ``block_rows``: interpret mode evaluates the
kernel per grid step in Python, so the production default (4096 rows — tuned
for v5e HBM streaming) would make each case walk a mostly-padded half-million
element block; small blocks are faster AND cover multi-step grids + ragged
tails. The production-default geometry is covered once by the adversarial
skew test below (which is also the regression test for the SWAR byte-field
overflow: all elements in one bucket at block_rows > 1920 overflowed the
8-bit fields before the periodic drain in ``_packed_count``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram
from mpi_k_selection_tpu.ops.pallas.histogram import pallas_radix_histogram
from mpi_k_selection_tpu.ops.radix import radix_select

from mpi_k_selection_tpu.utils import compat


def _oracle(keys, shift, radix_bits, prefix):
    keys = np.asarray(keys, np.uint64)
    nb = 1 << radix_bits
    digits = (keys >> np.uint64(shift)) & np.uint64(nb - 1)
    active = np.ones(keys.shape, bool)
    if prefix is not None:
        active = (keys >> np.uint64(shift + radix_bits)) == np.uint64(prefix)
    return np.bincount(digits[active].astype(np.int64), minlength=nb)


@pytest.mark.parametrize(
    "n,shift,radix_bits,prefix",
    # rb=4 at every size (128 / ragged / two-grid-steps); rb=8 once — its
    # nreg=32 SWAR kernel costs ~19s of TRACE time per distinct shape in
    # interpret mode, so one representative n covers it (the rb=8 drain
    # logic is unit-tested shape-independently by test_packed_count_drain)
    [(n, s, rb, p)
     for n in (128, 1000, 12345, 1 << 17)
     for (s, rb, p) in ((28, 4, None), (24, 4, 7), (0, 4, 2**27 - 5))]
    # ONE prefixed rb=8 case (r5: the unprefixed twin cost another ~16 s of
    # interpret trace for strictly less logic — masking supersets it — and
    # the unprefixed compiled kernel runs on hardware in tpu_smoke.py)
    + [(12345, 16, 8, 129)],
)
def test_pallas_histogram_matches_oracle(rng, n, shift, radix_bits, prefix):
    keys = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    # rb=8 kernels trace nreg=32 SWAR groups — 64-row blocks cut the unroll
    # (and the ~19 s/case trace time) 4x while still spanning whole grids
    br = 256 if radix_bits <= 4 else 64
    got = np.asarray(
        pallas_radix_histogram(
            keys, shift=shift, radix_bits=radix_bits, prefix=prefix, block_rows=br
        )
    )
    want = _oracle(keys, shift, radix_bits, prefix)
    np.testing.assert_array_equal(got, want)


def test_pallas_histogram_small_block_multigrid(rng):
    # force several grid steps + a ragged tail in one shot
    n = 4 * 256 * 128 + 77
    keys = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    got = np.asarray(
        pallas_radix_histogram(keys, shift=8, radix_bits=4, prefix=3, block_rows=256)
    )
    np.testing.assert_array_equal(got, _oracle(keys, 8, 4, 3))


@pytest.mark.parametrize(
    "radix_bits,block_rows",
    [(4, 4096), (8, 2048)],
    # rb=4 at 4096 rows: the production geometry, where the flushes==17
    # drain actually fires (needs > 2040 rows). rb=8 is capped to 1024 rows
    # by _cap_block_rows (scoped VMEM), so it covers the multi-register
    # (nreg=32) end-of-block extract under skew, NOT the mid-block drain —
    # that is covered at nreg=32 by test_packed_count_drain_nreg32 below.
)
def test_pallas_histogram_default_block_adversarial_skew(rng, radix_bits, block_rows):
    # every element in ONE bucket: the SWAR byte-field overflow case
    # (counts per field >> 255 without the periodic drain at flushes==17).
    # NOTE (r5): do not shrink n for the rb=8 case — n=66_000 measured 3x
    # SLOWER than 300_000 standalone (interpret-mode cost is not monotone
    # in n at this geometry)
    n = 300_000
    keys = jnp.asarray(np.full(n, 0x12345678, dtype=np.uint32))
    got = np.asarray(
        pallas_radix_histogram(
            keys,
            shift=24 - radix_bits + 4,
            radix_bits=radix_bits,
            prefix=jnp.uint32(1),
            block_rows=block_rows,
        )
    )
    nb = 1 << radix_bits
    key = 0x12345678 >> (24 - radix_bits + 4)
    want = np.zeros(nb, np.int64)
    assert (key >> radix_bits) == 1  # prefix matches
    want[key & (nb - 1)] = n
    np.testing.assert_array_equal(got, want)


class _FakeRef:
    """Minimal out_ref stand-in so _packed_count runs outside a kernel."""

    def __init__(self, a):
        self.a = a

    def __getitem__(self, idx):
        return self.a[idx]

    def __setitem__(self, idx, v):
        self.a = v


@pytest.mark.parametrize("radix_bits", [4, 8])
def test_packed_count_drain(rng, radix_bits):
    # direct unit test of the SWAR accumulator at a drain-triggering height
    # (> 2040 rows => flushes==17 fires mid-block), including the
    # multi-register nreg=32 case the kernel-level tests cannot reach
    # (_cap_block_rows caps rb=8 kernels to 1024 rows for scoped VMEM)
    import jax.numpy as jnp

    from mpi_k_selection_tpu.ops.pallas.histogram import LANES, _packed_count

    rows = 4096
    nb = 1 << radix_bits
    # adversarial: every element in one bucket, plus a random tail
    z_np = np.full((rows, LANES), nb - 1, dtype=np.int32)
    z_np[3000:] = rng.integers(0, nb, size=(rows - 3000, LANES), dtype=np.int32)
    out = _FakeRef(jnp.zeros((nb, LANES), jnp.int32))
    _packed_count(jnp.asarray(z_np), out, radix_bits)
    got = np.asarray(out.a)
    want = np.stack(
        [(z_np == b).sum(axis=0, dtype=np.int64) for b in range(nb)]
    )
    np.testing.assert_array_equal(got, want)


def test_pallas_histogram_rejects_64bit():
    from mpi_k_selection_tpu.utils.x64 import maybe_x64

    with maybe_x64(True):
        keys = jnp.arange(8, dtype=jnp.uint64)
        with pytest.raises(ValueError, match="32-bit"):
            pallas_radix_histogram(keys, shift=0, radix_bits=4)


def test_masked_histogram_pallas_method_dispatch(rng):
    keys = jnp.asarray(rng.integers(0, 2**32, size=4096, dtype=np.uint32))
    got = np.asarray(
        masked_radix_histogram(keys, shift=16, radix_bits=4, prefix=jnp.uint32(3), method="pallas")
    )
    np.testing.assert_array_equal(got, _oracle(keys, 16, 4, 3))


@pytest.mark.parametrize("radix_bits", [4, 8, 16])
def test_radix_select_explicit_radix_bits(rng, radix_bits):
    x = jnp.asarray(rng.integers(-(2**31), 2**31, size=20001, dtype=np.int32))
    k = 777
    got = int(radix_select(x, k, radix_bits=radix_bits))
    assert got == int(np.sort(np.asarray(x))[k - 1])


@pytest.mark.parametrize(
    "shift,radix_bits,prefix",
    [(60, 4, None), (56, 4, 9), (32, 4, 3**10), (28, 4, 11), (0, 4, 2**50 + 17),
     (24, 8, 77), (48, 8, 5)],
)
def test_pallas64_matches_oracle(rng, shift, radix_bits, prefix):
    from mpi_k_selection_tpu.ops.pallas.histogram import pallas_radix_histogram64
    from mpi_k_selection_tpu.utils.x64 import enable_x64

    with enable_x64():
        keys = jnp.asarray(rng.integers(0, 2**64, size=54321, dtype=np.uint64))
        got = np.asarray(
            pallas_radix_histogram64(
                keys, shift=shift, radix_bits=radix_bits, prefix=prefix, block_rows=256
            )
        )
        np.testing.assert_array_equal(got, _oracle(keys, shift, radix_bits, prefix))


@pytest.mark.parametrize(
    "shift,radix_bits,prefix", [(60, 4, None), (56, 4, 9), (28, 4, 11), (0, 4, 17)]
)
def test_pallas64_tiles_path_matches_keys_path(rng, shift, radix_bits, prefix):
    # prepare-once tiles (the pass-loop fast path) == per-call prepare
    from mpi_k_selection_tpu.ops.pallas.histogram import (
        pallas_radix_histogram64,
        prepare_tiles64,
    )
    from mpi_k_selection_tpu.utils.x64 import enable_x64

    with enable_x64():
        keys = jnp.asarray(rng.integers(0, 2**64, size=12345, dtype=np.uint64))
        hi2, lo2, n = prepare_tiles64(keys, block_rows=256)
        got = np.asarray(
            pallas_radix_histogram64(
                None,
                shift=shift,
                radix_bits=radix_bits,
                prefix=prefix,
                tiles=(hi2, lo2),
                orig_n=n,
                block_rows=256,
            )
        )
        np.testing.assert_array_equal(got, _oracle(keys, shift, radix_bits, prefix))


def test_pallas64_prefix_free_midkey_rejected(rng):
    from mpi_k_selection_tpu.ops.pallas.histogram import pallas_radix_histogram64
    from mpi_k_selection_tpu.utils.x64 import enable_x64

    with enable_x64():
        keys = jnp.asarray(rng.integers(0, 2**64, size=128, dtype=np.uint64))
        with pytest.raises(ValueError, match="prefix=None"):
            pallas_radix_histogram64(keys, shift=16, radix_bits=4)


# ---------------------------------------------------------------------------
# Raw-bits tiles + in-kernel key fold (key_op/key_xor): the production TPU
# fast path that removes the full-array to_sortable pass. Verified against
# the key-space kernels AND numpy, including the ragged pad correction
# (padded raw zeros carry the key to_sortable(0), not key 0).
# ---------------------------------------------------------------------------


def _raw_fold_case(rng, dtype, n):
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        x = rng.standard_normal(n).astype(dtype)
        # exercise the sign-dependent branch with exact halves
        x[: n // 2] = -np.abs(x[: n // 2])
    elif dtype.kind == "u":
        x = rng.integers(0, 2 ** (dtype.itemsize * 8) - 1, size=n, dtype=dtype)
    else:
        b = dtype.itemsize * 8
        x = rng.integers(-(2 ** (b - 2)), 2 ** (b - 2), size=n, dtype=dtype)
    return x


@pytest.mark.parametrize("dtype", [np.int32, np.uint32, np.float32])
@pytest.mark.parametrize(
    "shift,radix_bits,prefix_from_median", [(28, 4, False), (20, 4, True), (0, 4, True)]
)
def test_pallas_raw_fold_matches_keyspace(rng, dtype, shift, radix_bits, prefix_from_median):
    from mpi_k_selection_tpu.ops.pallas.histogram import (
        prepare_raw_tiles32,
        prepare_tiles32,
    )
    from mpi_k_selection_tpu.utils import dtypes as _dt

    n = 2 * 256 * 128 + 77  # ragged: pad-correction path
    x = _raw_fold_case(rng, dtype, n)
    xd = jnp.asarray(x)
    u = _dt.to_sortable_bits(xd)
    un = np.asarray(u).astype(np.uint64)
    prefix = None
    if prefix_from_median:
        # a live prefix (the median element's bits): nonzero counts
        prefix = jnp.uint32(int(np.sort(un)[n // 2]) >> (shift + radix_bits))
    kt, kn = prepare_tiles32(u, 256)
    rt, rn = prepare_raw_tiles32(xd, 256)
    key_op, *rest = _dt.key_fold(dtype)
    key_xor = rest[0] if key_op == "xor" else 0
    h_ref = pallas_radix_histogram(
        None, shift=shift, radix_bits=radix_bits, prefix=prefix,
        tiles=kt, orig_n=kn, block_rows=256,
    )
    h_raw = pallas_radix_histogram(
        None, shift=shift, radix_bits=radix_bits, prefix=prefix,
        tiles=rt, orig_n=rn, block_rows=256, key_op=key_op, key_xor=key_xor,
    )
    np.testing.assert_array_equal(np.asarray(h_raw), np.asarray(h_ref))
    np.testing.assert_array_equal(
        np.asarray(h_raw),
        _oracle(un, shift, radix_bits, None if prefix is None else int(prefix)),
    )


@pytest.mark.parametrize("dtype", [np.int64, np.uint64, np.float64])
@pytest.mark.parametrize("shift,radix_bits", [(60, 4), (36, 4), (28, 4), (0, 4)])
def test_pallas64_raw_fold_matches_keyspace(rng, dtype, shift, radix_bits):
    import jax

    from mpi_k_selection_tpu.ops.pallas.histogram import (
        pallas_radix_histogram64,
        prepare_raw_tiles64,
        prepare_tiles64,
    )
    from mpi_k_selection_tpu.utils import dtypes as _dt

    with compat.enable_x64(True):
        n = 2 * 256 * 128 + 77
        x = _raw_fold_case(rng, dtype, n)
        xd = jnp.asarray(x)
        u = _dt.to_sortable_bits(xd)
        un = np.asarray(u).astype(np.uint64)
        prefix = None
        if shift + radix_bits != 64:
            prefix = jnp.uint64(int(np.sort(un)[n // 2]) >> (shift + radix_bits))
        hi_k, lo_k, kn = prepare_tiles64(u, 256)
        hi_r, lo_r, rn = prepare_raw_tiles64(xd, 256)
        key_op, *rest = _dt.key_fold(dtype)
        key_xor = rest[0] if key_op == "xor" else 0
        h_ref = pallas_radix_histogram64(
            None, shift=shift, radix_bits=radix_bits, prefix=prefix,
            tiles=(hi_k, lo_k), orig_n=kn, block_rows=256,
        )
        h_raw = pallas_radix_histogram64(
            None, shift=shift, radix_bits=radix_bits, prefix=prefix,
            tiles=(hi_r, lo_r), orig_n=rn, block_rows=256,
            key_op=key_op, key_xor=key_xor,
        )
        np.testing.assert_array_equal(np.asarray(h_raw), np.asarray(h_ref))
        np.testing.assert_array_equal(
            np.asarray(h_raw),
            _oracle(un, shift, radix_bits, None if prefix is None else int(prefix)),
        )


@pytest.mark.parametrize("dtype", [np.int32, np.float32])
def test_radix_select_raw_fold_end_to_end(rng, dtype):
    """Forced-pallas select on a 32-bit foldable dtype: the whole descent
    (passes + cutover collect via key_of) runs on raw tiles."""
    n = 40_000
    x = _raw_fold_case(rng, dtype, n)
    for k in (1, n // 2, n):
        got = np.asarray(
            radix_select(jnp.asarray(x), k, hist_method="pallas", block_rows=256)
        )[()]
        want = np.sort(x, kind="stable")[k - 1]
        assert got == want, (dtype, k, got, want)


def test_masked_histogram_raw_tiles_reject_non_pallas(rng):
    x = jnp.asarray(rng.integers(0, 2**31, size=1024, dtype=np.int32))
    from mpi_k_selection_tpu.ops.pallas.histogram import prepare_raw_tiles32

    tiles, n = prepare_raw_tiles32(x, 256)
    with pytest.raises(ValueError, match="pallas"):
        masked_radix_histogram(
            None, shift=28, radix_bits=4, method="scatter",
            tiles=(tiles,), orig_n=n, key_op="xor", key_xor=1 << 31,
        )


# ---------------------------------------------------------------------------
# Multi-prefix kernels + match-count kernel (the multi-rank fast path) and
# the cutover ladder (forced small-n cutovers so the collect branches run
# in CI, where auto disables the cutover below 2^20 elements).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.int32, np.float32])
def test_pallas_multi_histogram_matches_singles(rng, dtype):
    from mpi_k_selection_tpu.ops.pallas.histogram import (
        pallas_radix_histogram_multi,
        prepare_raw_tiles32,
    )
    from mpi_k_selection_tpu.utils import dtypes as _dt

    n = 256 * 128 + 55
    x = _raw_fold_case(rng, dtype, n)
    xd = jnp.asarray(x)
    un = np.asarray(_dt.to_sortable_bits(xd)).astype(np.uint64)
    rt, rn = prepare_raw_tiles32(xd, 256)
    key_op, *rest = _dt.key_fold(dtype)
    key_xor = rest[0] if key_op == "xor" else 0
    shift, rb = 20, 4
    prefs = np.sort(un)[[n // 4, n // 2, 3 * n // 4]] >> (shift + rb)
    prefs = jnp.asarray(prefs.astype(np.uint32))
    hm = pallas_radix_histogram_multi(
        shift=shift, radix_bits=rb, prefixes=prefs, tiles=rt, orig_n=rn,
        block_rows=256, key_op=key_op, key_xor=key_xor,
    )
    for q in range(3):
        want = _oracle(un, shift, rb, int(prefs[q]))
        np.testing.assert_array_equal(np.asarray(hm[q]), want, err_msg=str(q))


def test_pallas_match_counts_vs_numpy(rng):
    from mpi_k_selection_tpu.ops.pallas.histogram import (
        pallas_match_counts,
        prepare_raw_tiles32,
    )
    from mpi_k_selection_tpu.utils import dtypes as _dt

    n = 2 * 256 * 128 + 99
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int32)
    xd = jnp.asarray(x)
    un = np.asarray(_dt.to_sortable_bits(xd)).astype(np.uint64)
    rt, rn = prepare_raw_tiles32(xd, 256)
    res = 12
    prefs_np = (np.sort(un)[[n // 3, n // 2]] >> (32 - res)).astype(np.uint32)
    cnt = pallas_match_counts(
        resolved_bits=res, prefixes=jnp.asarray(prefs_np), tiles=rt,
        orig_n=rn, key_op="xor", key_xor=1 << 31, block_rows=256,
    )
    R = rt.shape[0]
    up = np.zeros(R * 128, np.uint64)
    up[:n] = un
    valid = np.arange(R * 128) < n
    for q, p in enumerate(prefs_np):
        m = ((up >> np.uint64(32 - res)) == np.uint64(p)) & valid
        want = m.reshape(R, 128).sum(axis=1)
        np.testing.assert_array_equal(np.asarray(cnt[q]), want, err_msg=str(q))


@pytest.mark.parametrize("dtype", [np.int32, np.float32])
def test_radix_select_forced_cutover_ladder(rng, dtype):
    """Forced cutover on small input: rung-1 collect, rung-2 collect (via a
    tight budget), and the full-branch fallback (dense data) all exact.
    block_rows=256 (plumbed through radix_select) keeps interpret-mode cost
    small while still running multi-step grids + the ragged-tail correction."""
    n = 2 * 256 * 128 + 17  # two grid blocks + ragged tail
    x = _raw_fold_case(rng, dtype, n)
    want = np.sort(x, kind="stable")
    for k in (1, n // 2, n):
        got = np.asarray(
            radix_select(
                jnp.asarray(x), k, hist_method="pallas", cutover=2, block_rows=256
            )
        )[()]
        assert got == want[k - 1], (dtype, k, "rung1")
    # tight budget: rung 1 overflows (pop after 2 passes ~ n/256 > 64), rung
    # 2 (pop after 3 passes ~ n/4096 <= 64 for uniform data) must be exact
    got = np.asarray(
        radix_select(
            jnp.asarray(x), n // 2, hist_method="pallas", cutover=2,
            cutover_budget=64, block_rows=256,
        )
    )[()]
    assert got == want[n // 2 - 1], (dtype, "rung2")


def test_radix_select_forced_cutover_full_branch(rng):
    # dense data (values in [0, 200)): the surviving population stays ~n/16
    # after every early pass, so BOTH rungs overflow a tight budget and the
    # remaining fixed passes must finish the descent exactly
    n = 256 * 128 + 9
    x = rng.integers(0, 200, size=n, dtype=np.int32)
    want = np.sort(x, kind="stable")
    for k in (1, n // 2, n):
        got = np.asarray(
            radix_select(
                jnp.asarray(x), k, hist_method="pallas", cutover=2,
                cutover_budget=64, block_rows=256,
            )
        )[()]
        assert got == want[k - 1], (k, "full-branch")


def test_radix_select_many_forced_cutover(rng):
    from mpi_k_selection_tpu.ops.radix import radix_select_many

    n = 2 * 256 * 128 + 17
    x = rng.integers(0, 1 << 24, size=n, dtype=np.int32)  # dense-ish range
    # K=2 (the boundary ranks): the multi-pass trace cost is linear in K
    ks = np.array([1, n])
    got = np.asarray(
        radix_select_many(
            jnp.asarray(x), ks, hist_method="pallas", cutover=3, block_rows=256
        )
    )
    np.testing.assert_array_equal(got, np.sort(x, kind="stable")[ks - 1])


# ---------------------------------------------------------------------------
# 64-bit fast paths: the lo-plane multi-prefix kernel, the planes branch of
# the counts-collect, and float64/uint64 end-to-end (VERDICT r3 item 2 —
# these variants previously had zero in-repo executions).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.int64, np.uint64, np.float64])
@pytest.mark.parametrize("shift", [36, 20, 0])
def test_pallas64_multi_histogram_matches_singles(rng, dtype, shift):
    """shift>=32 routes through the hi-plane 32-bit multi kernel; shift<32
    runs _hist_kernel64_multi_packed (the lo-plane variant)."""
    import jax

    from mpi_k_selection_tpu.ops.pallas.histogram import (
        pallas_radix_histogram64_multi,
        prepare_raw_tiles64,
    )
    from mpi_k_selection_tpu.utils import dtypes as _dt

    with compat.enable_x64(True):
        n = 256 * 128 + 55
        x = _raw_fold_case(rng, dtype, n)
        xd = jnp.asarray(x)
        un = np.asarray(_dt.to_sortable_bits(xd)).astype(np.uint64)
        hi_r, lo_r, rn = prepare_raw_tiles64(xd, 256)
        key_op, *rest = _dt.key_fold(dtype)
        key_xor = rest[0] if key_op == "xor" else 0
        rb = 4
        prefs_np = np.sort(un)[[n // 4, n // 2, 3 * n // 4]] >> np.uint64(shift + rb)
        prefs = jnp.asarray(prefs_np)
        hm = pallas_radix_histogram64_multi(
            shift=shift, radix_bits=rb, prefixes=prefs, tiles=(hi_r, lo_r),
            orig_n=rn, block_rows=256, key_op=key_op, key_xor=key_xor,
        )
        for q in range(3):
            want = _oracle(un, shift, rb, int(prefs_np[q]))
            np.testing.assert_array_equal(np.asarray(hm[q]), want, err_msg=str(q))


@pytest.mark.parametrize("dtype", [np.int64, np.float64, np.uint64])
def test_radix_select_pallas64_forced_cutover(rng, dtype):
    """int64/float64/uint64 end-to-end through the pallas64 kernels with a
    forced cutover: exercises the PLANES branch of the collect — and, for
    the counts path, pallas_match_counts over the hi plane ((ncut+1)*rb <=
    32 holds at ncut=2, rb=4, so _collect_via_counts serves rung 1)."""
    import jax

    with compat.enable_x64(True):
        n = 2 * 256 * 128 + 17
        x = _raw_fold_case(rng, dtype, n)
        want = np.sort(x, kind="stable")
        for k in (1, n // 2, n):
            got = np.asarray(
                radix_select(
                    jnp.asarray(x), k, hist_method="pallas64", cutover=2,
                    block_rows=256,
                )
            )[()]
            assert got == want[k - 1], (dtype, k)


def test_radix_select_many_pallas64_forced_cutover(rng):
    import jax

    from mpi_k_selection_tpu.ops.radix import radix_select_many

    with compat.enable_x64(True):
        n = 2 * 256 * 128 + 17
        x = _raw_fold_case(rng, np.int64, n)
        # K=2: the full-branch trace unrolls ~28 multi passes whose kernel
        # trace cost is linear in K — K=2 halves the 41 s this test took
        ks = np.array([n // 3, n])
        got = np.asarray(
            radix_select_many(
                jnp.asarray(x), ks, hist_method="pallas64", cutover=2,
                block_rows=256,
            )
        )
        np.testing.assert_array_equal(got, np.sort(x, kind="stable")[ks - 1])


@pytest.mark.parametrize("dtype", [np.float64, np.uint64])
def test_radix_select_e2e_float64_uint64_auto(rng, dtype):
    """Plain end-to-end selection for the two dtypes that previously had no
    e2e test anywhere (auto method; scatter on CPU)."""
    import jax

    from mpi_k_selection_tpu.ops.radix import radix_select_many

    with compat.enable_x64(True):
        n = 54_321
        x = _raw_fold_case(rng, dtype, n)
        want = np.sort(x, kind="stable")
        for k in (1, n // 2, n):
            got = np.asarray(radix_select(jnp.asarray(x), k))[()]
            assert got == want[k - 1], (dtype, k)
        ks = np.array([n // 4, n // 2, 3 * n // 4])
        got_m = np.asarray(radix_select_many(jnp.asarray(x), ks))
        np.testing.assert_array_equal(got_m, want[ks - 1])


def test_radix_select_pallas64_deep_cutover_planes_collect(rng):
    """cutover=9 resolves 36 bits > 32, so use_counts is off and the collect
    runs _collect_prefix_matches' PLANES branch (hi/lo tuple + key_of) —
    unreachable from the counts path."""
    import jax

    with compat.enable_x64(True):
        n = 256 * 128 + 13
        x = _raw_fold_case(rng, np.int64, n)
        want = np.sort(x, kind="stable")
        for k in (1, n // 2, n):
            got = np.asarray(
                radix_select(
                    jnp.asarray(x), k, hist_method="pallas64", cutover=9,
                    block_rows=256,
                )
            )[()]
            assert got == want[k - 1], k


@pytest.mark.parametrize(
    "shift,radix_bits,prefix", [(28, 4, None), (16, 8, 129)]
)
def test_pallas_compare_variant_matches_oracle(rng, shift, radix_bits, prefix):
    # packed=False: the compare-per-bucket kernel (the SWAR kernel's
    # reference implementation) — previously exercised only by tpu_smoke
    keys = jnp.asarray(rng.integers(0, 2**32, size=12345, dtype=np.uint32))
    got = np.asarray(
        pallas_radix_histogram(
            keys, shift=shift, radix_bits=radix_bits, prefix=prefix,
            block_rows=64, packed=False,
        )
    )
    np.testing.assert_array_equal(got, _oracle(keys, shift, radix_bits, prefix))


def test_pallas64_compare_variant_matches_oracle(rng):
    from mpi_k_selection_tpu.ops.pallas.histogram import pallas_radix_histogram64
    from mpi_k_selection_tpu.utils.x64 import enable_x64

    with enable_x64():
        kn = rng.integers(0, 2**64, size=12345, dtype=np.uint64)
        keys = jnp.asarray(kn)
        # LIVE prefix (the median key's high bits): a fixed 52-bit prefix
        # over random keys matches nothing and the test would be vacuous.
        # shift=8 < 32 keeps the two-plane compare kernel the thing tested.
        prefix = int(np.sort(kn)[len(kn) // 2] >> np.uint64(12))
        got = np.asarray(
            pallas_radix_histogram64(
                keys, shift=8, radix_bits=4, prefix=prefix, block_rows=256,
                packed=False,
            )
        )
        want = _oracle(kn, 8, 4, prefix)
        assert want.sum() >= 1  # the prefix is live by construction
        np.testing.assert_array_equal(got, want)


def test_radix_select_pallas_compare_method_dispatch(rng):
    # the "pallas_compare" hist_method string through the masked-histogram
    # dispatcher (r5: the former full-select e2e cost 35-48 s of interpret
    # traces — one trace per descent pass — for coverage the per-variant
    # oracle tests already give; the compiled full select through this
    # string runs on hardware in tpu_smoke.py every round)
    from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram

    # keys < 2^20: every key matches prefix 0 above shift+rb=20, so all 16
    # buckets hold ~256 elements — a full-range draw left <= 1 match per
    # bucket and the count would be vacuous (any broken accumulate passes)
    keys = jnp.asarray(rng.integers(0, 2**20, size=4096, dtype=np.uint32))
    got = np.asarray(
        masked_radix_histogram(
            keys, shift=16, radix_bits=4, prefix=jnp.uint32(0),
            method="pallas_compare",
        )
    )
    assert int(got.sum()) == 4096  # non-vacuous: every element counted
    np.testing.assert_array_equal(got, _oracle(keys, 16, 4, 0))
