"""Streaming selection — exact k-select and quantile sketches over data the
device never holds all at once.

Two cooperating pieces (see docs/API.md "Streaming / out-of-core"):

- :mod:`chunked` — out-of-core exact k-selection: stream host- (or
  generator-) resident chunks through the device one radix pass at a time,
  merge the per-chunk digit histograms host-side, narrow the candidate
  prefix, re-stream only for the passes that still need the data. Exact at
  ``n`` far beyond HBM.
- :mod:`sketch` — :class:`RadixSketch`, a fixed-size mergeable multi-level
  digit-histogram accumulator for online quantiles: ``update``/``merge``
  (associative AND commutative — bitwise merge-order invariant), exact
  ``rank_bounds``/``value_bounds``, approximate ``quantile``, and a
  ``refine`` hook that reuses the chunked path for exact answers.
- :mod:`pipeline` — double-buffered ingest for both: a background producer
  thread overlaps chunk *i+1*'s production / host key-encode / host->device
  staging with chunk *i*'s compute (``pipeline_depth`` knob, 0 =
  synchronous oracle, bit-identical answers either way). With the
  ``devices`` knob > 1 the staging goes round-robin across chips and up to
  p chunks histogram concurrently (one in-flight dispatch per device),
  still bit-identical — the host int64 merge drains in chunk order.
- :mod:`spill` — the survivor spill store (``spill`` knob): pass 0 tees
  each chunk's encoded keys to per-device disk records, later passes read
  the previous generation, filter to the surviving prefixes on the owning
  device, and write only the compacted survivors — passes shrink
  geometrically (~N·(2 + 1/2^radix_bits + ...) total bytes instead of
  ~passes·N) and one-shot generators become first-class sources.
"""

from mpi_k_selection_tpu.streaming.chunked import (
    DEFAULT_SPILL,
    as_chunk_source,
    streaming_kselect,
    streaming_kselect_many,
    streaming_rank_certificate,
)
from mpi_k_selection_tpu.streaming.executor import (
    DEFAULT_DEFERRED,
    DEFAULT_FUSED,
    FUSED_MODES,
    FUSED_TIERS,
    FusedIngestConsumer,
    StreamExecutor,
    collect_hidden_frac,
    kernel_tier_available,
    resolve_deferred,
    resolve_fused,
    validate_fused,
)
from mpi_k_selection_tpu.streaming.pipeline import (
    DEFAULT_PIPELINE_DEPTH,
    ChunkPipeline,
    StagedKeys,
    StagingPool,
    ingest_hidden_frac,
    live_staged_keys,
    resolve_stream_devices,
    stage_device_keys,
)
from mpi_k_selection_tpu.streaming.sketch import RadixSketch
from mpi_k_selection_tpu.streaming.spill import (
    SPILL_DIR_PREFIX,
    SPILL_MODES,
    SpillGeneration,
    SpillStore,
)

__all__ = [
    "ChunkPipeline",
    "DEFAULT_DEFERRED",
    "DEFAULT_FUSED",
    "DEFAULT_PIPELINE_DEPTH",
    "DEFAULT_SPILL",
    "FUSED_MODES",
    "FUSED_TIERS",
    "FusedIngestConsumer",
    "RadixSketch",
    "SPILL_DIR_PREFIX",
    "SPILL_MODES",
    "SpillGeneration",
    "SpillStore",
    "StagedKeys",
    "StagingPool",
    "StreamExecutor",
    "as_chunk_source",
    "collect_hidden_frac",
    "ingest_hidden_frac",
    "kernel_tier_available",
    "live_staged_keys",
    "resolve_deferred",
    "resolve_fused",
    "resolve_stream_devices",
    "stage_device_keys",
    "streaming_kselect",
    "streaming_kselect_many",
    "streaming_rank_certificate",
    "validate_fused",
]
