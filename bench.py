"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): k-select throughput in elems/sec/chip with
exact-match verification against the sequential oracle. The baseline is the
reference's own algorithm — sort-then-index (``kth-problem-seq.c:32-33``) —
measured on this host via NumPy over the identical seeded input, so
``vs_baseline`` is the speedup of the TPU radix path over the reference
approach at the reference's operating point (N=1e8-class int32, k=N/2
median; ``kth-problem-seq.c~:24``).

Timing method: the TPU is reached through a tunnel with ~100 ms round-trip
latency, and identical repeated calls can be served from a result cache, so
single-call wall times measure the tunnel, not the chip. Instead we time two
jitted chains of R1 and R2 *data-dependent* selections (iteration i's k
depends on iteration i-1's answer, so no iteration can be elided) and report
the differential (t2 - t1) / (R2 - R1): pure device-side solve time.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_k_selection_tpu.backends import seq
    from mpi_k_selection_tpu.ops.radix import radix_select
    from mpi_k_selection_tpu.utils import datagen

    on_tpu = jax.default_backend() not in ("cpu",)
    # TPU: reference-class N (2^27 = 134M ≈ the reference's 1e8). CPU CI: small.
    n = 1 << 27 if on_tpu else 1 << 22
    k = n // 2
    x = datagen.generate(n, pattern="uniform", seed=0, dtype=np.int32)

    # --- baseline: the reference algorithm (sort-then-index) on the host,
    # via the same oracle implementation the test suite verifies against ---
    t0 = time.perf_counter()
    want = int(seq.kselect_sort(x, k))
    baseline_s = time.perf_counter() - t0

    xd = jax.device_put(jnp.asarray(x))
    kd = jnp.asarray(k, jnp.int32)
    got = int(np.asarray(radix_select(xd, kd)))  # compile + correctness check
    exact = got == want

    def chain(reps: int):
        @jax.jit
        def run(xs, k0):
            def body(_, kk):
                ans = radix_select(xs, kk)
                # serialize: next k depends on this answer (defeats caching/CSE)
                return k0 + jnp.abs(ans).astype(jnp.int32) % 7

            return jax.lax.fori_loop(0, reps, body, k0)

        return run

    def timed(run):
        _ = np.asarray(run(xd, kd))  # compile
        best = float("inf")
        for i in range(1, 4):
            # distinct k0 per repeat: identical repeated calls can be served
            # from a result cache by the remote-execution layer
            k0 = jnp.asarray(k - i, jnp.int32)
            t0 = time.perf_counter()
            _ = np.asarray(run(xd, k0))
            best = min(best, time.perf_counter() - t0)
        return best

    r1, r2 = (1, 9) if on_tpu else (1, 3)
    t1, t2 = timed(chain(r1)), timed(chain(r2))
    per = max((t2 - t1) / (r2 - r1), 1e-9)

    throughput = n / per if exact else 0.0
    print(
        json.dumps(
            {
                "metric": "kselect_throughput_1chip",
                "value": round(throughput, 1),
                "unit": "elems/sec/chip",
                "vs_baseline": round(baseline_s / per, 3) if exact else 0.0,
                "n": n,
                "k": k,
                "seconds": round(per, 6),
                "baseline_seconds": round(baseline_s, 6),
                "exact_match": exact,
                "backend": jax.default_backend(),
            }
        )
    )
    return 0 if exact else 1


if __name__ == "__main__":
    sys.exit(main())
