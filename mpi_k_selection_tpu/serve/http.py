"""HTTP front for the query server — stdlib only, JSON request/response.

A thin shell over :class:`~mpi_k_selection_tpu.serve.server.
KSelectServer`: the HTTP layer parses/serializes and maps typed errors
to status codes; every answer comes from the same in-process API, so
the determinism and bound contracts are identical over the wire.

Endpoints:

- ``POST /v1/query`` — body ``{"dataset": id, "op":
  "kselect"|"quantiles"|"topk"|"rank_certificate", ...}`` with
  ``k``/``ks`` (kselect), ``qs`` (quantiles), ``k``+``largest`` (topk),
  ``value`` (rank_certificate), and optional ``tier``
  (sketch|exact|auto, default auto). Response: ``{"answers": [...]}``
  for rank ops (each answer per ``RankAnswer.as_dict`` — sketch-tier
  entries always carry ``rank_bounds``/``value_bounds``/
  ``rank_error_bound``), ``{"values": [...], "indices": [...]}`` for
  topk, ``{"less": L, "leq": E}`` for certificates.
- ``GET /v1/datasets`` — registered-dataset listing.
- ``GET /metrics`` — Prometheus text exposition of the server metric
  namespace (the ``--metrics-json`` registry, rendered live). With the
  server's ``latency_windows`` knob on, the per-tier
  ``serve.latency_seconds`` histograms additionally expose
  sliding-window quantile gauges with exact bounds
  (``ksel_serve_latency_seconds_windowed{tier=,quantile=}`` — see
  obs/windows.py and docs/OBSERVABILITY.md "Continuous monitoring").
- ``GET /healthz`` — liveness + dataset count + hot-path shape (the
  ``fast_path`` setting and the live dispatch-lane count).

Threading: ``ThreadingHTTPServer`` with NAMED request threads
(``ksel-serve-req-*``) tracked and joined on ``server_close()`` — the
same no-thread-outlives-its-owner discipline as the pipeline producers
(conftest-enforced). ``start_http_server`` runs the accept loop on a
``ksel-serve-http-*`` thread and returns a handle whose ``close()``
shuts down, closes, and joins everything; the CLI ``serve`` mode runs
the loop on the main thread instead.

Error mapping: :class:`DatasetNotFoundError` -> 404,
:class:`QueryError`/``ValueError`` -> 400, :class:`ServerClosedError`
-> 503, :class:`ServerOverloadedError` -> 503 with a ``Retry-After``
header (admission control shed the query — back off and retry),
:class:`DeadlineExceededError` -> 504 (the request's ``deadline_ms``
expired), anything else -> 500 (message included — this is an internal
service, not a hardened edge).

Deadlines over the wire: a ``/v1/query`` body may carry ``deadline_ms``
(milliseconds, this request only); the server's ``default_deadline``
applies otherwise. See docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import itertools
import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mpi_k_selection_tpu.serve.batcher import SERVE_THREAD_PREFIX
from mpi_k_selection_tpu.serve.errors import (
    DatasetNotFoundError,
    DeadlineExceededError,
    QueryError,
    ServerClosedError,
    ServerOverloadedError,
)

#: Request-body ceiling: queries are tiny JSON; a megabyte is a client bug.
MAX_BODY_BYTES = 1 << 20


def _jsonable(v):
    item = getattr(v, "item", None)
    return item() if item is not None else v


class _Handler(BaseHTTPRequestHandler):
    server_version = "ksel-serve"
    protocol_version = "HTTP/1.1"

    # silence the default stderr access log: the obs registry (queue
    # depth, per-tier counters/latency) is this subsystem's telemetry
    # channel, and stray writes would interleave with CLI output
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    @property
    def kserver(self):
        return self.server.kserver

    # -- plumbing ----------------------------------------------------------

    def _request_trace_id(self) -> str:
        """The request-correlation id (docs/OBSERVABILITY.md "Trace
        IDs"): an inbound ``X-Ksel-Trace-Id`` is honored verbatim (so a
        caller's id follows the query across services), else one is
        minted — either way every response echoes it, success and error
        alike, and the serve events/spans of the work it triggered carry
        the same id."""
        tid = getattr(self, "_trace_id", None)
        if tid is None:
            from mpi_k_selection_tpu.serve.server import KSelectServer

            inbound = self.headers.get("X-Ksel-Trace-Id")
            tid = self._trace_id = KSelectServer._trace_id(inbound)
        return tid

    def _send(
        self, code: int, payload, *, content_type="application/json",
        headers=None,
    ):
        body = (
            payload
            if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload).encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("X-Ksel-Trace-Id", self._request_trace_id())
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, code: int, message: str, headers=None):
        # the trace id rides error BODIES too: a 504/503 postmortem
        # starts from the id the client logged
        self._send(
            code,
            {"error": message, "trace_id": self._request_trace_id()},
            headers=headers,
        )

    def _read_json(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length > MAX_BODY_BYTES:
            # the unread body would desync this HTTP/1.1 keep-alive
            # connection (the next parse would read body bytes as a
            # request line) — drop the connection after the error
            self.close_connection = True
            raise QueryError(f"request body exceeds {MAX_BODY_BYTES} bytes")
        raw = self.rfile.read(length) if length else b""
        if not raw:
            raise QueryError("empty request body; send a JSON query")
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise QueryError(f"bad JSON body: {e}") from e

    def _guarded(self, fn):
        try:
            fn()
        except DatasetNotFoundError as e:
            self._send_error_json(404, str(e))
        except (QueryError, ValueError, TypeError) as e:
            self._send_error_json(400, str(e))
        except DeadlineExceededError as e:
            self._send_error_json(504, str(e))
        except ServerOverloadedError as e:
            # shed by admission control: tell the client how long to back
            # off (integer ceiling — Retry-After is delta-seconds)
            self._send_error_json(
                503, str(e),
                headers={"Retry-After": str(max(1, int(-(-e.retry_after // 1))))},
            )
        except ServerClosedError as e:
            self._send_error_json(503, str(e))
        except Exception as e:  # internal service: surface, don't hide
            self._send_error_json(500, f"{type(e).__name__}: {e}")

    # -- routes ------------------------------------------------------------

    def do_GET(self):
        # keep-alive reuses one handler across requests: re-resolve the
        # trace id per request, never per connection
        self._trace_id = None
        self._guarded(self._get)

    def _get(self):
        if self.path == "/healthz":
            self._send(
                200,
                {
                    "status": "ok",
                    "datasets": len(self.kserver.registry),
                    "fast_path": self.kserver.fast_path,
                    "lanes": self.kserver.batcher.lane_count,
                },
            )
        elif self.path == "/v1/datasets":
            self._send(200, {"datasets": self.kserver.list_datasets()})
        elif self.path == "/metrics":
            self._send(
                200,
                self.kserver.render_prometheus().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        elif self.path == "/debug/bundle":
            # the postmortem debug bundle (obs/flight.py; sections are
            # empty-but-present without a flight= channel) — default=str
            # absorbs any non-JSON leaf a span arg or plan repr carries
            self._send(
                200,
                json.dumps(
                    self.kserver.debug_bundle(reason="http"), default=str
                ).encode(),
            )
        else:
            self._send_error_json(404, f"unknown path {self.path!r}")

    def do_POST(self):
        self._trace_id = None
        self._guarded(self._post)

    def _post(self):
        if self.path != "/v1/query":
            self._send_error_json(404, f"unknown path {self.path!r}")
            return
        req = self._read_json()
        dataset = req.get("dataset")
        if not isinstance(dataset, str):
            raise QueryError("query needs a string 'dataset' id")
        op = req.get("op", "kselect")
        tier = req.get("tier", "auto")
        deadline = None
        if "deadline_ms" in req:
            raw_dl = req["deadline_ms"]
            try:
                if isinstance(raw_dl, bool):  # json true/false float()s to 1/0
                    raise TypeError("bool is not a duration")
                deadline = float(raw_dl) / 1000.0
            except (TypeError, ValueError) as e:
                raise QueryError(
                    f"deadline_ms must be a number of milliseconds, got "
                    f"{req['deadline_ms']!r}"
                ) from e
            # stdlib json parses NaN/Infinity: NaN would dodge the <= 0
            # guard and expire instantly, Infinity would never expire —
            # both are malformed requests, not deadlines
            if not math.isfinite(deadline) or deadline <= 0:
                raise QueryError("deadline_ms must be a finite number > 0")
        srv = self.kserver
        tid = self._request_trace_id()
        if op == "kselect":
            ks = req["ks"] if "ks" in req else [req["k"]] if "k" in req else None
            if ks is None:
                raise QueryError("kselect needs 'k' or 'ks'")
            answers = srv.kselect_many(
                dataset, ks, tier=tier, deadline=deadline, trace_id=tid
            )
            self._send(
                200,
                {
                    "dataset": dataset,
                    "op": op,
                    "trace_id": tid,
                    "answers": [a.as_dict() for a in answers],
                },
            )
        elif op == "quantiles":
            if "qs" not in req:
                raise QueryError("quantiles needs 'qs'")
            answers = srv.quantiles(
                dataset, req["qs"], tier=tier, deadline=deadline, trace_id=tid
            )
            self._send(
                200,
                {
                    "dataset": dataset,
                    "op": op,
                    "trace_id": tid,
                    "answers": [a.as_dict() for a in answers],
                },
            )
        elif op == "topk":
            if "k" not in req:
                raise QueryError("topk needs 'k'")
            values, indices = srv.topk(
                dataset, int(req["k"]), largest=bool(req.get("largest", True)),
                deadline=deadline, trace_id=tid,
            )
            self._send(
                200,
                {
                    "dataset": dataset,
                    "op": op,
                    "trace_id": tid,
                    "values": [_jsonable(v) for v in values],
                    "indices": [int(i) for i in indices],
                },
            )
        elif op == "rank_certificate":
            if "value" not in req:
                raise QueryError("rank_certificate needs 'value'")
            less, leq = srv.rank_certificate(
                dataset, req["value"], deadline=deadline, trace_id=tid
            )
            self._send(
                200,
                {
                    "dataset": dataset, "op": op, "trace_id": tid,
                    "less": int(less), "leq": int(leq),
                },
            )
        else:
            raise QueryError(
                f"unknown op {op!r}; choose from "
                "('kselect', 'quantiles', 'topk', 'rank_certificate')"
            )


class KSelectHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with named, tracked, joined request threads."""

    daemon_threads = False
    allow_reuse_address = True

    _ids = itertools.count()

    def __init__(self, address, kserver):
        super().__init__(address, _Handler)
        self.kserver = kserver
        self._req_lock = threading.Lock()
        self._req_threads: list[threading.Thread] = []  # ksel: guarded-by[_req_lock]
        self._serve_thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def process_request(self, request, client_address):
        """Per-request thread with the serve prefix, tracked for the
        join in :meth:`server_close` (the stdlib mixin's anonymous
        ``Thread-N`` workers would dodge the leaked-thread fixture)."""
        t = threading.Thread(
            target=self.process_request_thread,
            args=(request, client_address),
            name=f"{SERVE_THREAD_PREFIX}-req-{next(self._ids)}",
            daemon=False,
        )
        with self._req_lock:
            self._req_threads = [x for x in self._req_threads if x.is_alive()]
            self._req_threads.append(t)
        t.start()

    def server_close(self):
        super().server_close()
        with self._req_lock:
            threads, self._req_threads = self._req_threads, []
        for t in threads:
            t.join(timeout=10.0)

    def close(self):
        """Full shutdown: stop the accept loop, close the socket, join
        request threads and the serve-loop thread (when
        :func:`start_http_server` started one). Does NOT close the
        underlying KSelectServer — the caller owns it."""
        self.shutdown()
        self.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10.0)
            self._serve_thread = None

    def __enter__(self) -> "KSelectHTTPServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def start_http_server(
    kserver, *, host: str = "127.0.0.1", port: int = 0
) -> KSelectHTTPServer:
    """Bind and serve in the background (accept loop on a
    ``ksel-serve-http-*`` thread). ``port=0`` binds an ephemeral port —
    read it off ``handle.port``. ``handle.close()`` tears everything
    down; the caller still owns ``kserver.close()``."""
    httpd = KSelectHTTPServer((host, port), kserver)
    t = threading.Thread(
        target=httpd.serve_forever,
        kwargs={"poll_interval": 0.05},
        name=f"{SERVE_THREAD_PREFIX}-http-{next(KSelectHTTPServer._ids)}",
        daemon=True,
    )
    httpd._serve_thread = t
    t.start()
    return httpd
