from mpi_k_selection_tpu.ops.sort import sort_select
from mpi_k_selection_tpu.ops.radix import radix_select
from mpi_k_selection_tpu.ops.topk import topk, batched_topk
from mpi_k_selection_tpu.ops.histogram import masked_radix_histogram

__all__ = [
    "sort_select",
    "radix_select",
    "topk",
    "batched_topk",
    "masked_radix_histogram",
]
