"""Resource-lifecycle dataflow pass (KSL019-KSL021) + the KSC104
host-transfer census.

Five layers of coverage:

- **rule fixtures** — positive/negative/escape/owner-annotation/
  stale-annotation/noqa sources per rule (staged buffers KSL019, spill
  stores/writers/temp dirs KSL020, ksel- worker threads KSL021);
- **CFG-engine units** — try/finally, the except-release-reraise unwind
  (with isinstance narrowing), loop-carried acquires, conditional
  releases, del/rebind overwrites, the retry_call immediate wrapper and
  the one-hop interprocedural acquire;
- **planted pre-fix leak shapes** — the exact code shapes the first
  whole-repo run found live (the producer's chunk-in-hand on the raise
  edge, the CLI's store-built-before-its-try) each demonstrably caught,
  next to their fixed forms proving clean;
- **runtime regressions** — the fixed paths exercised for real: a hard
  pass-0 tee fault leaves no staged buffer behind, a mid-stream source
  raise aborts the sketch tee's generation (no stranded records);
- **the gate** — zero KSL019-021 findings repo-wide, the ownership
  graph exported to kselect_lifecycle.json (package-relative,
  cwd-independent), the conftest leak-fixture vocabulary proven to BE
  the static pass's registry (resource_protocols.py), and the KSC104
  census clean over every streaming surface program.
"""

import glob
import json
import os
import pathlib
import tempfile
import textwrap

import numpy as np
import pytest

from mpi_k_selection_tpu import resource_protocols as rp
from mpi_k_selection_tpu.analysis import run_analysis, shared_modules
from mpi_k_selection_tpu.analysis.__main__ import main as lint_main
from mpi_k_selection_tpu.analysis.lifecycle import build_lifecycle_report

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = "mpi_k_selection_tpu"


def _lint_source(tmp_path, source, name="mod.py", **kwargs):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    kwargs.setdefault("contracts", False)
    return run_analysis([f], **kwargs)


def _rules_hit(report):
    return {f.rule for f in report.unsuppressed}


def _hits(report, rule):
    return [f for f in report.unsuppressed if f.rule == rule]


# ---------------------------------------------------------------------------
# KSL019 — staged key buffers


KSL019_POSITIVE = """
    def ingest(chunk, bucket, dtype, device):
        keys = stage_keys(chunk, bucket, dtype, device)
        if chunk.size:
            histogram(keys)
            keys.release()
        # the empty-chunk branch falls through with the slot live
"""

KSL019_NEGATIVE = """
    def ingest(chunk, bucket, dtype, device):
        keys = stage_keys(chunk, bucket, dtype, device)
        try:
            histogram(keys)
        finally:
            keys.release()
"""

KSL019_ESCAPES = """
    def produce(chunk, window, q, bucket, dtype, device):
        a = stage_keys(chunk, bucket, dtype, device)
        window.push(a)      # executor FIFO: releases at bundle finish
        b = stage_device_keys(chunk, bucket, dtype, device)
        q.put(b)            # pipeline queue: close() drains and releases
        c = stage_keys(chunk, bucket, dtype, device)
        return c            # the caller owns it
"""

KSL019_OWNER_ANNOTATION = """
    def produce(chunk, sink, bucket, dtype, device):
        keys = stage_keys(chunk, bucket, dtype, device)
        sink.offer(keys)  # ksel: owner[StreamExecutor]
"""

KSL019_STALE_NO_RESOURCE = """
    def produce(sink):
        sink.offer(1)  # ksel: owner[StreamExecutor]
"""

KSL019_UNKNOWN_SITE = """
    def produce(chunk, sink, bucket, dtype, device):
        keys = stage_keys(chunk, bucket, dtype, device)
        sink.offer(keys)  # ksel: owner[NotARegisteredOwner]
"""


def test_ksl019_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL019_POSITIVE, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL019")
    assert len(hits) == 1
    assert "staged key buffer" in hits[0].message
    assert "fall-through" in hits[0].message


def test_ksl019_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL019_NEGATIVE, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL019" not in _rules_hit(report)


def test_ksl019_sanctioned_escapes(tmp_path):
    report = _lint_source(
        tmp_path, KSL019_ESCAPES, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL019" not in _rules_hit(report)


def test_ksl019_owner_annotation(tmp_path):
    report = _lint_source(
        tmp_path, KSL019_OWNER_ANNOTATION, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL019" not in _rules_hit(report)


def test_ksl019_stale_annotation_no_resource(tmp_path):
    report = _lint_source(
        tmp_path, KSL019_STALE_NO_RESOURCE, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL019")
    assert len(hits) == 1
    assert "stale" in hits[0].message
    assert "no tracked resource moves" in hits[0].message


def test_ksl019_unknown_owner_site(tmp_path):
    report = _lint_source(
        tmp_path, KSL019_UNKNOWN_SITE, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL019")
    assert len(hits) == 1
    assert "unregistered owner" in hits[0].message
    assert "NotARegisteredOwner" in hits[0].message


def test_ksl019_unknown_owner_site_on_attribute_transfer(tmp_path):
    # the attribute-assignment transfer path validates the site too
    # (review regression: it used to accept any name silently)
    src = """
    class Holder:
        def take(self, chunk, bucket, dtype, device):
            keys = stage_keys(chunk, bucket, dtype, device)
            self._w = keys  # ksel: owner[BogusSite]
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL019")
    assert len(hits) == 1
    assert "unregistered owner" in hits[0].message
    assert "BogusSite" in hits[0].message


def test_ksl019_scope_and_noqa(tmp_path):
    # outside the package: quiet
    report = _lint_source(tmp_path, KSL019_POSITIVE, name="scripts/mod.py")
    assert "KSL019" not in _rules_hit(report)
    # test files poke lifecycles freely
    report = _lint_source(
        tmp_path, KSL019_POSITIVE, name=f"{PKG}/streaming/test_mod.py"
    )
    assert "KSL019" not in _rules_hit(report)
    # suppression lands on the ACQUIRE line (where the leak is reported)
    src = KSL019_POSITIVE.replace(
        "keys = stage_keys(chunk, bucket, dtype, device)",
        "keys = stage_keys(chunk, bucket, dtype, device)"
        "  # ksel: noqa[KSL019] -- fixture justification",
    )
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    assert "KSL019" not in _rules_hit(report)
    sup = [f for f in report.findings if f.rule == "KSL019" and f.suppressed]
    assert sup and sup[0].justification == "fixture justification"


# ---------------------------------------------------------------------------
# KSL020 — spill stores / writers / temp dirs


KSL020_POSITIVE = """
    def build(chunks):
        store = SpillStore()
        for c in chunks:
            store.append(c)   # a raise here strands the ksel-spill dir
        store.close()
"""

KSL020_NEGATIVE = """
    def build(chunks):
        store = SpillStore()
        try:
            for c in chunks:
                store.append(c)
        finally:
            store.close()
"""

KSL020_WITH_BLOCK = """
    def build(chunks):
        with SpillStore() as store:
            for c in chunks:
                store.append(c)
"""

KSL020_WRITER_POSITIVE = """
    def tee(store, chunks):
        w = store.new_generation()
        for c in chunks:
            w.append(c)       # a raise strands the uncommitted records
        return w.commit()
"""

KSL020_WRITER_NEGATIVE = """
    def tee(store, chunks):
        w = store.new_generation()
        try:
            for c in chunks:
                w.append(c)
        except BaseException:
            w.abort()
            raise
        return w.commit()
"""

KSL020_OWNER_ATTR = """
    import tempfile

    class Store:
        def __init__(self):
            self.root = tempfile.mkdtemp(prefix="ksel-spill-")
"""

KSL020_UNSANCTIONED_ATTR = """
    import tempfile

    class Store:
        def __init__(self):
            self.workdir = tempfile.mkdtemp(prefix="ksel-spill-")
"""


def test_ksl020_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL020_POSITIVE, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL020")
    assert len(hits) == 1
    assert "spill store/writer/temp dir" in hits[0].message
    assert "exception" in hits[0].message


def test_ksl020_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL020_NEGATIVE, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL020" not in _rules_hit(report)


def test_ksl020_with_block_is_sanctioned(tmp_path):
    report = _lint_source(
        tmp_path, KSL020_WITH_BLOCK, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL020" not in _rules_hit(report)


def test_engine_with_constructor_raise_edge_keeps_other_resources(tmp_path):
    # a with-acquired constructor raising still carries OTHER live
    # resources out on the exception edge (review regression: the
    # managed acquire used to suppress the whole raise edge)
    src = """
    def f(c, x, bucket, dtype, device):
        keys = stage_keys(c, bucket, dtype, device)
        with SpillStore(x) as s:
            fill(s, keys)
        keys.release()
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL019")
    assert len(hits) == 1 and "exception" in hits[0].message
    assert "KSL020" not in _rules_hit(report)  # the with stays sanctioned


def test_ksl020_writer_raise_edge(tmp_path):
    report = _lint_source(
        tmp_path, KSL020_WRITER_POSITIVE, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL020")
    assert len(hits) == 1
    report = _lint_source(
        tmp_path, KSL020_WRITER_NEGATIVE, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL020" not in _rules_hit(report)


def test_ksl020_owner_attr(tmp_path):
    # `self.root = mkdtemp(...)`: the store owns its directory
    report = _lint_source(
        tmp_path, KSL020_OWNER_ATTR, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL020" not in _rules_hit(report)
    report = _lint_source(
        tmp_path, KSL020_UNSANCTIONED_ATTR, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL020")
    assert len(hits) == 1
    assert "not a sanctioned owner slot" in hits[0].message


# ---------------------------------------------------------------------------
# KSL021 — ksel- worker threads


KSL021_POSITIVE = """
    import threading

    def serve(handler):
        t = threading.Thread(target=handler, name="ksel-serve-dispatch")
        t.start()
        handler.wait()
        # never joined, never registered with a supervisor
"""

KSL021_NEGATIVE = """
    import threading

    def serve(handler):
        t = threading.Thread(target=handler, name="ksel-serve-req")
        t.start()
        try:
            handler.wait()
        finally:
            t.join()
"""

KSL021_SUPERVISOR = """
    import threading

    class Pipeline:
        def start(self, target):
            t = threading.Thread(target=target, name="ksel-pipeline-0")
            t.start()
            self._thread = t        # the tracked supervisor slot

    class Server:
        def handle(self, target):
            t = threading.Thread(target=target, name="ksel-serve-req")
            t.start()
            self._req_threads.append(t)   # the tracked thread list
"""

KSL021_UNSTARTED = """
    import threading

    def build(target, maybe):
        t = threading.Thread(target=target, name="ksel-pipeline-0")
        maybe(t)
        # unstarted: no OS thread exists, nothing to leak
"""

KSL021_NOT_KSEL = """
    import threading

    def helper(target):
        t = threading.Thread(target=target)
        t.start()
"""

KSL021_UNSANCTIONED_ATTR = """
    import threading

    class Pipeline:
        def start(self, target):
            t = threading.Thread(target=target, name="ksel-pipeline-0")
            t.start()
            self.worker = t   # not a registered supervisor slot
"""


def test_ksl021_positive(tmp_path):
    report = _lint_source(
        tmp_path, KSL021_POSITIVE, name=f"{PKG}/serve/mod.py"
    )
    hits = _hits(report, "KSL021")
    assert len(hits) == 1
    assert "ksel- worker thread" in hits[0].message


def test_ksl021_negative(tmp_path):
    report = _lint_source(
        tmp_path, KSL021_NEGATIVE, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL021" not in _rules_hit(report)


def test_ksl021_supervisor_slots(tmp_path):
    report = _lint_source(
        tmp_path, KSL021_SUPERVISOR, name=f"{PKG}/serve/mod.py"
    )
    assert "KSL021" not in _rules_hit(report)


def test_ksl021_obligation_arms_at_start(tmp_path):
    # an unstarted Thread object holds no OS resources
    report = _lint_source(
        tmp_path, KSL021_UNSTARTED, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL021" not in _rules_hit(report)


def test_ksl021_only_ksel_named_threads_tracked(tmp_path):
    report = _lint_source(
        tmp_path, KSL021_NOT_KSEL, name=f"{PKG}/streaming/mod.py"
    )
    assert "KSL021" not in _rules_hit(report)


def test_ksl021_unsanctioned_attr(tmp_path):
    report = _lint_source(
        tmp_path, KSL021_UNSANCTIONED_ATTR, name=f"{PKG}/streaming/mod.py"
    )
    hits = _hits(report, "KSL021")
    assert len(hits) == 1
    assert "not a sanctioned owner slot" in hits[0].message


# ---------------------------------------------------------------------------
# CFG-engine units


def test_engine_conditional_release(tmp_path):
    src = """
    def f(c, bucket, dtype, device, ok):
        keys = stage_keys(c, bucket, dtype, device)
        if ok:
            keys.release()
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL019")
    assert len(hits) == 1 and "fall-through" in hits[0].message


def test_engine_loop_carried_acquire(tmp_path):
    src = """
    def f(chunks, bucket, dtype, device):
        for c in chunks:
            keys = stage_keys(c, bucket, dtype, device)
            consume(keys)
        keys.release()   # only the LAST iteration's slot
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL019")
    assert hits and any("rebound" in h.message for h in hits)
    # releasing inside the body proves clean
    src_ok = """
    def f(chunks, bucket, dtype, device):
        for c in chunks:
            keys = stage_keys(c, bucket, dtype, device)
            try:
                consume(keys)
            finally:
                keys.release()
    """
    report = _lint_source(tmp_path, src_ok, name=f"{PKG}/streaming/mod.py")
    assert "KSL019" not in _rules_hit(report)


def test_engine_narrow_unwind_idiom(tmp_path):
    # the pipeline.py producer shape AFTER the fix: isinstance-narrowed
    # release in the broad handler proves clean on the re-raise path
    src = """
    def producer(src, q, bucket, dtype, device):
        keys = None
        try:
            for c in src:
                keys = stage_keys(c, bucket, dtype, device)
                tee(keys)
                q.put(keys)
                keys = None
        except BaseException as e:
            if isinstance(keys, StagedKeys):
                keys.release()
            q.put(e)
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    assert "KSL019" not in _rules_hit(report)


def test_engine_del_while_live(tmp_path):
    src = """
    def f(c, bucket, dtype, device):
        keys = stage_keys(c, bucket, dtype, device)
        del keys
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL019")
    assert hits and "del" in hits[0].message


def test_engine_retry_call_wrapper(tmp_path):
    # the staging-retry idiom: the acquire is recognized THROUGH the
    # immediately-invoked retry_call lambda
    src = """
    def produce(c, bucket, dtype, device, policy):
        keys = retry_call(lambda: stage_keys(c, bucket, dtype, device), policy)
        consume(keys)
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    assert _hits(report, "KSL019")
    src_ok = """
    def produce(c, bucket, dtype, device, policy):
        keys = retry_call(lambda: stage_keys(c, bucket, dtype, device), policy)
        try:
            consume(keys)
        finally:
            keys.release()
    """
    report = _lint_source(tmp_path, src_ok, name=f"{PKG}/streaming/mod.py")
    assert "KSL019" not in _rules_hit(report)


def test_engine_interprocedural_one_hop(tmp_path):
    # a module-local function that returns a live resource is an
    # acquire site for its callers
    src = """
    def make(chunk, bucket, dtype, device):
        keys = stage_keys(chunk, bucket, dtype, device)
        return keys

    def use(chunk, bucket, dtype, device):
        k = make(chunk, bucket, dtype, device)
        consume(k)
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL019")
    assert len(hits) == 1 and "`use`" in hits[0].message
    src_ok = src.replace(
        "        consume(k)",
        "        try:\n"
        "            consume(k)\n"
        "        finally:\n"
        "            k.release()",
    )
    report = _lint_source(tmp_path, src_ok, name=f"{PKG}/streaming/mod.py")
    assert "KSL019" not in _rules_hit(report)


def test_engine_typed_handler_propagates(tmp_path):
    # a TYPED handler may not match: the raise edge still carries the
    # live resource past it — only release-then-reraise (or a finally)
    # proves the exception path
    src = """
    def build(chunks):
        store = SpillStore()
        try:
            fill(store, chunks)
        except ValueError:
            store.close()
            raise
        store.close()
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL020")
    assert len(hits) == 1 and "exception" in hits[0].message


def test_engine_return_inside_try_finally(tmp_path):
    src = """
    def build(chunks):
        store = SpillStore()
        try:
            if not chunks:
                return None
            return fill(store, chunks)
        finally:
            store.close()
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    assert "KSL020" not in _rules_hit(report)


# ---------------------------------------------------------------------------
# the planted pre-fix leak shapes (each rule demonstrably catches the
# class it was built for)


def test_planted_prefix_producer_shape_caught(tmp_path):
    # the pipeline.py producer BEFORE the fix: a raise between staging
    # and the queue put (the spill tee) dropped the chunk in hand — the
    # broad handler reported the error but never released the slot
    src = """
    def producer(src, q, bucket, dtype, device):
        try:
            for c in src:
                keys = stage_keys(c, bucket, dtype, device)
                tee(keys)
                q.put(keys)
        except BaseException as e:
            q.put(e)
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    assert _hits(report, "KSL019")


def test_planted_prefix_cli_store_shape_caught(tmp_path):
    # cli.py BEFORE the fix: the --spill=force store was built before
    # the try whose finally closes it, so a failure while ARMING the
    # solve (chaos plan seeding) stranded the fresh ksel-spill-* dir
    src = """
    def run(args):
        store = SpillStore()
        injector = arm(args)
        try:
            solve(store, injector)
        finally:
            store.close()
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    hits = _hits(report, "KSL020")
    assert len(hits) == 1 and "exception" in hits[0].message
    # the fixed shape — the try owns the store from the moment it exists
    src_ok = """
    def run(args):
        store = SpillStore()
        try:
            injector = arm(args)
            solve(store, injector)
        finally:
            store.close()
    """
    report = _lint_source(tmp_path, src_ok, name=f"{PKG}/streaming/mod.py")
    assert "KSL020" not in _rules_hit(report)


def test_planted_thread_leak_caught(tmp_path):
    # a started ksel- thread with NO close path at all — the structural
    # leak class KSL021 exists for
    src = """
    import threading

    def spawn(work):
        t = threading.Thread(target=work, name="ksel-pipeline-extra")
        t.start()
        return None
    """
    report = _lint_source(tmp_path, src, name=f"{PKG}/streaming/mod.py")
    assert _hits(report, "KSL021")


# ---------------------------------------------------------------------------
# runtime regressions for the first whole-repo run's fixed leak paths


def _spill_dirs():
    return set(
        glob.glob(os.path.join(tempfile.gettempdir(), rp.SPILL_DIR_PREFIX + "*"))
    )


def test_runtime_sketch_abort_on_source_raise():
    # sketch.py fix: a mid-stream source raise aborts the tee writer —
    # no committed generation, no stranded gen dir (pre-fix, commit ran
    # outside the try and an abort-path raise could strand records)
    from mpi_k_selection_tpu.streaming import RadixSketch, SpillStore

    before = _spill_dirs()

    def chunks():
        yield np.arange(64, dtype=np.int32)
        raise RuntimeError("stream died mid-pass")

    store = SpillStore()
    try:
        with pytest.raises(RuntimeError, match="stream died"):
            RadixSketch(np.int32).update_stream(
                chunks(), spill=store, pipeline_depth=0
            )
        assert store.generations == {}
        assert not glob.glob(os.path.join(store.root, "gen-*"))
    finally:
        store.close()
    assert _spill_dirs() == before


def test_runtime_producer_releases_chunk_on_hard_tee_fault():
    # pipeline.py fix: a hard pass-0 spill-tee fault raises on the
    # PRODUCER thread between staging and the queue put — the handler
    # now releases the chunk in hand (pre-fix: a leaked staged buffer)
    from mpi_k_selection_tpu import faults
    from mpi_k_selection_tpu.streaming import streaming_kselect
    from mpi_k_selection_tpu.streaming.pipeline import live_staged_keys

    before = _spill_dirs()
    data = np.arange(512, dtype=np.int32)
    chunks = [data[:256], data[256:]]
    plan = faults.FaultPlan(
        (faults.FaultSpec("spill.write", 0, "raise",
                          attempts=tuple(range(99))),)
    )
    with faults.inject(faults.FaultInjector(plan)):
        with pytest.raises(Exception):
            streaming_kselect(
                lambda: iter(chunks), 17, spill="force",
                pipeline_depth=2, retry="off",
            )
    assert live_staged_keys() == 0
    assert _spill_dirs() == before


# ---------------------------------------------------------------------------
# the whole-repo gate + the exported ownership graph


def test_lifecycle_rules_clean_repo_wide():
    report = run_analysis(
        [REPO / PKG], root=REPO, contracts=False,
        select=["KSL019", "KSL020", "KSL021"],
        mods=shared_modules([REPO / PKG], root=REPO),
    )
    assert report.unsuppressed == [], [
        f.render() for f in report.unsuppressed
    ]


def test_lifecycle_gate_whole_repo(tmp_path):
    report = build_lifecycle_report(
        [REPO / PKG], root=REPO,
        mods=shared_modules([REPO / PKG], root=REPO),
    )
    art = json.dumps(report, indent=2, sort_keys=True)
    (tmp_path / "kselect_lifecycle.json").write_text(art)
    try:  # best-effort /tmp mirror (shared-host permission hazard)
        pathlib.Path("/tmp/kselect_lifecycle.json").write_text(art)
    except OSError:
        pass
    res = report["resources"]
    # the graph is populated: every protocol family is visible
    kinds = {a["kind"] for m in res.values() for a in m["acquires"]}
    assert kinds >= {"staged", "spill", "thread"}
    assert f"{PKG}/streaming/pipeline.py" in res
    assert f"{PKG}/streaming/spill.py" in res
    # releases and ownership-transfer edges are recorded, not just
    # acquires (an all-acquire graph would mean the pass is blind to
    # the package's actual release discipline)
    assert any(m["releases"] for m in res.values())
    assert any(m["escapes"] for m in res.values())
    # paths are package-relative (cwd-independent joins)
    assert all(p.startswith(PKG + "/") for p in res)
    # every shipped `# ksel: owner[...]` annotation is LIVE (the
    # staleness audit holds the tree at zero dead entries)
    for mod, anns in report["annotations"].items():
        for a in anns:
            assert a["used"], (mod, a)
    # the exported vocabulary IS the registry
    assert report["prefixes"]["threads"] == list(rp.THREAD_PREFIXES)
    assert report["prefixes"]["spill_dirs"] == rp.SPILL_DIR_PREFIX
    assert report["owners"]["sites"] == dict(rp.OWNER_SITES)


def test_lifecycle_report_cli_cwd_independent(tmp_path, monkeypatch):
    out = tmp_path / "lc.json"
    monkeypatch.chdir(tmp_path)
    rc = lint_main(
        [
            str(REPO / PKG / "streaming" / "pipeline.py"),
            "--no-contracts",
            "--lifecycle-report", str(out),
        ]
    )
    assert rc == 0
    data = json.loads(out.read_text())
    assert f"{PKG}/streaming/pipeline.py" in data["resources"]
    assert data["owners"]["sites"] == dict(rp.OWNER_SITES)


def test_leak_fixture_vocabulary_is_the_registry():
    # satellite: ONE importable source of truth — the owning modules'
    # public prefix constants ARE the registry objects the conftest
    # fixtures and the static pass both read
    from mpi_k_selection_tpu.monitor.monitor import MONITOR_THREAD_PREFIX
    from mpi_k_selection_tpu.obs.flight import FLIGHT_FILE_PREFIX
    from mpi_k_selection_tpu.serve.batcher import SERVE_THREAD_PREFIX
    from mpi_k_selection_tpu.streaming.pipeline import (
        INGEST_THREAD_PREFIX,
        THREAD_NAME_PREFIX,
    )
    from mpi_k_selection_tpu.streaming.spill import SPILL_DIR_PREFIX

    assert THREAD_NAME_PREFIX is rp.PIPELINE_THREAD_PREFIX
    assert INGEST_THREAD_PREFIX is rp.INGEST_THREAD_PREFIX
    assert SERVE_THREAD_PREFIX is rp.SERVE_THREAD_PREFIX
    assert MONITOR_THREAD_PREFIX is rp.MONITOR_THREAD_PREFIX
    assert SPILL_DIR_PREFIX is rp.SPILL_DIR_PREFIX
    assert FLIGHT_FILE_PREFIX is rp.FLIGHT_FILE_PREFIX
    assert set(rp.THREAD_PREFIXES) == {
        THREAD_NAME_PREFIX, INGEST_THREAD_PREFIX, SERVE_THREAD_PREFIX,
        MONITOR_THREAD_PREFIX,
    }
    for prefix in rp.RESOURCE_PREFIXES:
        assert prefix.startswith(rp.KSEL_PREFIX)
    # the KSL021 supervisor vocabulary is non-empty and registry-owned
    assert rp.THREAD_OWNER_ATTRS
    assert rp.OWNER_SITES


# ---------------------------------------------------------------------------
# KSC104 — the host-transfer census


def test_ksc104_registered():
    from mpi_k_selection_tpu.analysis.jaxpr_checks import CONTRACT_CHECKS

    assert "KSC104" in {c.id for c in CONTRACT_CHECKS}


def test_ksc104_census_clean_over_all_surfaces():
    from mpi_k_selection_tpu.analysis.jaxpr_checks import CONTRACT_CHECKS

    check = next(c for c in CONTRACT_CHECKS if c.id == "KSC104")
    findings = check.run()
    assert findings == [], [f.render() for f in findings]


def test_ksc104_budget_table_is_exhaustive():
    # every case-grid label has a declared budget and vice versa — the
    # doc-drift posture applied to the transfer ledger
    from mpi_k_selection_tpu.analysis.jaxpr_checks import (
        _POP_MATERIALIZATION_BUDGET,
        _census_cases,
    )

    labels = {label for _, label, _, _, _ in _census_cases()}
    assert labels == set(_POP_MATERIALIZATION_BUDGET)


def test_ksc104_detects_planted_crossing():
    import jax

    from mpi_k_selection_tpu.analysis.jaxpr_checks import (
        _census_findings,
        _spec,
        _transfer_census,
    )

    def bad(x):
        return jax.pure_callback(
            lambda v: np.asarray(v).sum(),
            jax.ShapeDtypeStruct((), x.dtype),
            x,
        )

    assert _transfer_census(jax.make_jaxpr(bad)(_spec(8, "float32")))
    case = [("pkg/mod.py", "planted[crossing]", bad, "float32", (8, 16))]
    findings = _census_findings(case, {"planted[crossing]": 1})
    assert findings and all(
        "mid-pass host<->device crossing" in f.message for f in findings
    )


def test_ksc104_constant_placement_not_a_crossing():
    # jnp.asarray of a closed-over numpy scalar inserts a literal
    # device_put: constant placement, baked once per compile — NOT a
    # mid-pass crossing (the sweep kernel's certificate-key idiom)
    import jax
    import jax.numpy as jnp

    from mpi_k_selection_tpu.analysis.jaxpr_checks import (
        _spec,
        _transfer_census,
    )

    probe = np.asarray(5, np.uint32)

    def f(x):
        return x + jnp.asarray(probe)

    assert _transfer_census(jax.make_jaxpr(f)(_spec(8, "uint32"))) == []


def test_ksc104_budget_violations():
    import jax.numpy as jnp

    from mpi_k_selection_tpu.analysis.jaxpr_checks import _census_findings

    def two_leaves(x):
        return x, jnp.sum(x)

    # over budget: an undeclared host-facing output
    case = [("pkg/mod.py", "planted[wide]", two_leaves, "float32", (8, 16))]
    findings = _census_findings(case, {"planted[wide]": 1})
    assert findings and all(
        "exceed the declared pop-time budget" in f.message for f in findings
    )
    # missing budget row: the surface must declare itself
    findings = _census_findings(case, {})
    assert len(findings) == 1
    assert "no declared pop-time materialization budget" in findings[0].message
    # stale budget row: a label no grid carries
    findings = _census_findings([], {"planted[gone]": 1})
    assert len(findings) == 1
    assert "stale budget row" in findings[0].message
