"""High-level selection API — algorithm dispatch on device arrays.

The reference exposes its capability only as two ``main()`` programs
(SURVEY.md §1: "the driver *is* the algorithm"). Here selection is a library
function; the CLI (cli.py) and the backends are thin wrappers over this.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpi_k_selection_tpu.ops.radix import radix_select, radix_select_many
from mpi_k_selection_tpu.ops.sort import sort_select
from mpi_k_selection_tpu.utils.debug import check_concrete_k, check_concrete_ks

ALGORITHMS = ("auto", "radix", "sort")

def many_sort_dispatch_queries(n: int) -> int:
    """Query count above which :func:`kselect_many` takes the one-sort-
    K-gathers path instead of the shared radix walk, as a function of n.

    Measured crossovers (v5e, int32, differential chains, r5): K* ~= 82
    at n=2^24 (sort 36.8 ms / walk 0.44 ms/query) and ~134 at 2^28 (sort
    914 ms / walk 6.83 ms/query). The per-query walk costs ~c1*n (the
    masked multi-prefix accumulate is linear in K and n) while the
    one-shot sort costs ~c2*n*log n, so K* = c2/c1 * log n — linear in
    log2(n). Fit through those two points: ``K* = 13*log2(n) - 230``,
    clamped to [64, 192] outside the measured range. At n=2^27 the rule
    gives 121, consistent with the r4 component measurements there (sort
    409 ms / walk ~3.4 ms/query ~= 120; r4's rounder "~110, constant
    112" estimate sat inside the same ±15% noise band)."""
    import math

    return int(min(192, max(64, round(13 * math.log2(max(n, 2)) - 230))))


def as_selection_array(x):
    """``jnp.asarray`` for selection inputs, EXCEPT host float64 on the TPU
    backend, which stays host-side (a numpy array): committing f64 to the
    device truncates it to the TPU's ~49-bit f64 storage (measured — see
    ops/radix.py:_f64_tpu_host_keys), and the exact selection route needs
    the untruncated host bits. Every selection entry layer (api, backends,
    CLI) converts through here so the exact route is reachable from all of
    them, not only from a direct radix_select call. jax arrays and tracers
    pass through untouched (a device-resident f64 array was already
    truncated; selection is then exact w.r.t. its actual contents)."""
    import jax

    from mpi_k_selection_tpu.utils.dtypes import _require_x64

    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return x
    # plain Python lists/scalars widen to int64/float64 under np.asarray;
    # that widening is NumPy's default, not a caller-declared width, so it
    # keeps the historical weak-typed conversion below
    was_typed = hasattr(x, "dtype")
    x = np.asarray(x)
    if x.dtype == np.float64 and jax.default_backend() == "tpu":
        return x
    # CALLER-TYPED 64-bit INTEGER host data must not cross jnp.asarray
    # with x64 off: the conversion silently truncates the bit patterns and
    # the selection answers wrong with no error (kselect over host int64
    # returned the truncated array's k-th element — the KSL002 truncation
    # class, caught by the analyzer's first run). float64 keeps the
    # documented downcast (value ROUNDING, not bit corruption — the
    # docstring's "exact w.r.t. its actual contents" contract), so the
    # default NumPy float dtype keeps working with default jax config.
    if was_typed and x.dtype.kind in "iu":
        _require_x64(x.dtype)
    return jnp.asarray(x)


def _host_f64(x) -> bool:
    return isinstance(x, np.ndarray) and x.dtype == np.float64


def _contains_tracer(ks) -> bool:
    """True when ``ks`` is, or contains, a jax Tracer — WITHOUT converting
    to numpy: ``np.atleast_1d`` on a traced scalar (or on a Python list
    holding one) raises TracerArrayConversionError before any isinstance
    check downstream could route around it."""
    import jax

    if isinstance(ks, jax.core.Tracer):
        return True
    if isinstance(ks, (np.ndarray, jax.Array)):
        return False  # concrete arrays cannot hold tracers
    if isinstance(ks, (list, tuple)):
        return any(_contains_tracer(kv) for kv in ks)
    return False


def _count_query_leaves(ks) -> int:
    """Query count of a (possibly nested) container of ks without numpy
    conversion — tracers expose ``.shape``, containers recurse."""
    if isinstance(ks, (list, tuple)):
        return sum(_count_query_leaves(kv) for kv in ks)
    return int(np.prod(np.shape(ks), dtype=np.int64)) if np.shape(ks) else 1


def kselect(x, k, *, algorithm: str = "auto", obs=None, **kwargs):
    """Exact k-th smallest element (1-indexed k, reference semantics:
    ``kth-problem-seq.c:32-33``).

    ``obs`` (an :class:`~mpi_k_selection_tpu.obs.Observability`) records
    the resolved dispatch as a ``resident.select`` event. The resident
    pass loop is jit-traced, so per-pass events are a streaming-only
    capability (:func:`kselect_streaming`); see docs/OBSERVABILITY.md.
    """
    x = as_selection_array(x)
    if x.size == 0:
        raise ValueError("kselect requires a non-empty input")
    # concrete k raises here; traced k is clamped inside the ops
    check_concrete_k(k, x.size)
    if algorithm == "auto":
        # sort is competitive only for small inputs; radix is O(n) passes.
        algorithm = "sort" if x.size <= 1 << 14 else "radix"
    if obs is not None:
        from mpi_k_selection_tpu.obs.events import ResidentSelectEvent

        obs.emit(
            ResidentSelectEvent(
                n=int(x.size),
                queries=1,
                algorithm=algorithm,
                dtype=str(np.dtype(x.dtype)),
            )
        )
    if algorithm not in ALGORITHMS:
        raise ValueError(
            f"unknown algorithm {algorithm!r}; choose from {ALGORITHMS}"
        )
    # the resident dispatch shell reports into the process ProgramLedger
    # (obs/ledger.py): first (n, dtype, algorithm) here is the compile
    # dispatch, repeats are jit-cache hits — the runtime book behind the
    # steady-state recompile gates. Pure host bookkeeping.
    from mpi_k_selection_tpu.obs import ledger as _ldg

    key = (int(x.size), str(np.dtype(x.dtype)), algorithm, 1)
    with _ldg.ledger_dispatch("api.select", key, obs):
        if algorithm == "radix":
            return radix_select(x, k, **kwargs)
        if _host_f64(x):
            # stay host-side end-to-end (device sort would truncate);
            # traced k can't index numpy — the radix route handles it
            import jax

            if isinstance(k, jax.core.Tracer):
                return radix_select(x, k, **kwargs)
            return np.sort(x.ravel(), kind="stable")[int(k) - 1]
        return sort_select(x, k)


def kselect_many(x, ks, *, obs=None, **kwargs):
    """Exact k-th smallest for every (1-indexed) k in ``ks`` over one array.

    Amortized multi-rank selection (the p50/p90/p99 telemetry shape): the
    radix path shares the prepared key view and the first histogram pass
    across all queries (ops/radix.py:radix_select_many); small inputs sort
    once and gather. Returns answers in ``ks`` order, with ``ks``'s shape
    (a scalar k returns a scalar, matching :func:`kselect`).

    ``obs`` records the resolved dispatch (sort vs shared radix walk,
    query count) as one ``resident.select`` event, exactly like
    :func:`kselect`'s — the query server's batcher coalesces many client
    requests into one call here, and the event stream is how a coalesced
    walk stays attributable.
    """
    x = as_selection_array(x)
    if x.size == 0:
        raise ValueError("kselect_many requires a non-empty input")
    check_concrete_ks(ks, x.size)
    if isinstance(ks, (list, tuple)) and _contains_tracer(ks):
        # np.shape on a container of tracers would convert (and crash);
        # count leaves recursively so nested containers dispatch the same
        # as their concrete twins
        n_queries = _count_query_leaves(ks)
    else:
        n_queries = int(np.prod(np.shape(ks), dtype=np.int64)) if np.shape(ks) else 1
    # n-aware dispatch (r5): the multi-prefix walk costs ~c1*n per query
    # (the per-query masked SWAR accumulate is linear in K) while one
    # lax.sort of the whole array costs ~c2*n*log n, so the crossover
    # grows with log2(n) — 82/110/134 queries measured at n=2^24/27/28.
    sort_at = many_sort_dispatch_queries(x.size)
    use_sort = x.size <= 1 << 14 or n_queries >= sort_at
    if obs is not None:
        from mpi_k_selection_tpu.obs.events import ResidentSelectEvent

        obs.emit(
            ResidentSelectEvent(
                n=int(x.size),
                queries=n_queries,
                algorithm="sort-many" if use_sort else "radix-many",
                dtype=str(np.dtype(x.dtype)),
            )
        )
    # the resident dispatch shell's ProgramLedger report (obs/ledger.py):
    # queries count is part of the compile identity — the shared walk and
    # the sort gather both compile per batch width
    from mpi_k_selection_tpu.obs import ledger as _ldg

    _lkey = (
        int(x.size), str(np.dtype(x.dtype)),
        "sort-many" if use_sort else "radix-many", n_queries,
    )
    with _ldg.ledger_dispatch("api.select", _lkey, obs):
        if use_sort:
            def warn_kwargs_ignored():
                # only the sort branches drop kwargs; the host-f64 traced-ks
                # branch below routes back to radix where they are honored
                if kwargs:
                    import warnings

                    warnings.warn(
                        f"kselect_many: this shape takes the sort path (small "
                        f"input or >= {sort_at} queries at this n); "
                        f"radix options {sorted(kwargs)} are ignored",
                        stacklevel=3,
                    )

            from mpi_k_selection_tpu.ops.radix import select_count_dtype

            if _host_f64(x):
                if _contains_tracer(ks):
                    # radix shell: exact host route eagerly, documented
                    # approximation under an active trace; kwargs honored
                    out = radix_select_many(x, ks, **kwargs)
                else:
                    warn_kwargs_ignored()
                    ks_np = np.atleast_1d(np.asarray(ks, dtype=np.int64))
                    s_np = np.sort(x.ravel(), kind="stable")
                    out = s_np[np.clip(ks_np - 1, 0, x.size - 1)].reshape(
                        ks_np.shape
                    )
                return restore_k_shape(out, ks)
            warn_kwargs_ignored()
            # rank dtype sized to n IN the conversion: an implicit int32
            # asarray would silently wrap int64 ranks for n >= 2^31 (this
            # path is reachable at any n via K >= 192, the dispatch clamp's
            # ceiling), and select_count_dtype raises loudly when that
            # width needs x64
            ks_arr = jnp.atleast_1d(
                jnp.asarray(ks, select_count_dtype(x.size))
            )
            s = jnp.sort(x.ravel())
            idx = jnp.clip(ks_arr - 1, 0, x.size - 1)
            out = s[idx.ravel()].reshape(ks_arr.shape)
        else:
            out = radix_select_many(x, ks, **kwargs)
    return restore_k_shape(out, ks)


def quantile_ranks(qs, n: int) -> list[int]:
    """Nearest-rank 1-indexed ks for quantiles ``qs`` over ``n`` elements:
    ``k = max(1, ceil(q * n))``, computed in float64 on the host (a float32
    round-trip perturbs q — 0.99 -> 0.99000001 — enough to shift
    ``ceil(q * n)`` by one rank)."""
    import math

    qs_list = [float(q) for q in np.atleast_1d(np.asarray(qs, dtype=np.float64))]
    for q in qs_list:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
    return [max(1, min(n, math.ceil(q * n))) for q in qs_list]


def quantile_ks(qs, n: int) -> jnp.ndarray:
    """:func:`quantile_ranks` as a device array in the selection's count
    dtype — int64 for n >= 2^31, where an int32 rank would overflow at the
    multi-chip 64-bit scales PARITY.md targets. The one conversion shared by
    every quantiles entry point (here and backends/tpu.py)."""
    from mpi_k_selection_tpu.ops.radix import select_count_dtype

    return jnp.asarray(quantile_ranks(qs, n), select_count_dtype(n))


def restore_k_shape(out, ks):
    """Shape contract of the *_many entry points: answers carry ``ks``'s
    shape, so a scalar k returns a scalar (matching :func:`kselect`)."""
    if isinstance(ks, (list, tuple)):
        return out  # containers are 1-D query lists (np.ndim would convert
        # and crash on a list holding tracers)
    return out.reshape(()) if np.ndim(ks) == 0 else out


def quantiles(x, qs, **kwargs):
    """Exact order statistics at quantiles ``qs`` (nearest-rank — every
    returned value is an actual array element, the same guarantee the
    reference's selection gives)."""
    # as_selection_array, not jnp.asarray: a bare conversion would both
    # truncate 64-bit host data with x64 off AND commit host float64 to
    # the TPU (losing the exact host-key route) before kselect_many could
    # route around it
    x = as_selection_array(x)
    if x.size == 0:
        raise ValueError("quantiles requires a non-empty input")
    return kselect_many(x, quantile_ks(qs, x.size), **kwargs)


def median(x, **kwargs):
    """Lower median: k = max(1, n//2), matching the reference's median
    operating point ``k = N/2`` (``kth-problem-seq.c~:24``,
    ``TODO-kth-problem-cgm.c~:48``)."""
    x = as_selection_array(x)  # see quantiles: truncation + f64 routing
    return kselect(x, max(1, x.size // 2), **kwargs)


def kselect_streaming(source, k, **kwargs):
    """Exact k-th smallest over data that is only ever materialized in
    chunks — never as one device (or host) array. ``source`` is a
    list/tuple of chunks or a zero-arg callable returning a fresh chunk
    iterator (replayed once per radix pass); chunks may be numpy or device
    arrays. Serves ``n`` far beyond HBM, and is bit-exact for float64 on
    TPU with host chunks (keys never touch the device's ~49-bit f64
    storage). Ingest is double-buffered by default (``pipeline_depth=2``):
    chunk *i+1* is produced, key-encoded and staged to the device on a
    background thread while chunk *i* histograms — pass
    ``pipeline_depth=0`` for the fully synchronous oracle (bit-identical
    answers). ``devices=p`` spreads the pipelined ingest round-robin
    across p chips so p chunks histogram concurrently — answers stay
    bit-identical for every device count (the host int64 merge drains in
    chunk order). ``spill`` engages the survivor spill store
    (streaming/spill.py): pass 0 tees encoded keys to disk and later
    passes read back only the geometrically-shrinking survivors, so a
    ONE-SHOT iterator/generator is a first-class source (``"auto"``, the
    default, spills exactly for those; ``"force"`` always; ``"off"``
    keeps today's replay path and rejects one-shot sources;
    ``spill_dir`` roots the temp store). Answers are bit-identical to
    ``spill="off"`` in every mode. ``deferred`` (default ``"auto"`` = on)
    runs the per-chunk consumers — histogram merge, survivor collect,
    rank-certificate folds, spill tee — under the async streaming
    executor (streaming/executor.py): staged chunks dispatch fixed-shape
    device-side compactions whose host materialization happens when the
    p-wide FIFO window pops, so multi-device collect/spill passes scale
    like the histogram passes instead of serializing on per-chunk eager
    gathers; ``"off"`` is the historical eager path, bit-identical.
    ``fused`` (default ``"auto"``) collapses each deferred pass's
    per-chunk device programs — histogram, survivor compactions,
    spill-tee payload — into ONE program per staged bucket:
    ``"kernel"`` is the hand-written single-sweep pallas kernel
    (ops/pallas/sweep_ingest.py — one GUARANTEED HBM read; the auto
    default on TPU), ``"xla"`` the one-XLA-program fusion
    (ops/pallas/fused_ingest.py; the auto default elsewhere), and
    ``"off"`` keeps the unfused bundle as the bit-for-bit oracle.
    ``width_schedule`` (default ``"off"``) picks how many key bits each
    descent pass resolves: ``"auto"`` spends a WIDE first digit (up to 16
    bits, int32-partial-safe) so the first spill generation shrinks to
    ~n/2^16 survivors and later passes fall back to ``radix_bits``-wide
    digits; an explicit tuple of per-pass widths (summing to the key
    width) pins the schedule. ``pack_spill`` (default ``"off"``) makes
    the spill store's records prefix-packed: each generation stores only
    the still-unresolved low bits per survivor (bit-packed,
    per-segment CRC'd, format-versioned) and replays reconstruct keys
    exactly — generation-0 tees are digit-segmented so later passes read
    ONLY the surviving segments instead of the whole teed stream.
    Both knobs are bit-identical to their ``"off"`` oracles on every
    source/dtype, and ``"off"``/``"off"`` is byte-for-byte the legacy
    path. ``retry`` arms the resilience policies (docs/ROBUSTNESS.md; default
    on): transient source errors re-pull mid-pass, staging transfers
    retry in place, failed passes re-run from the previous spill
    generation, corrupt spill records re-read then rebuild, and ENOSPC
    degrades ``spill="auto"`` with a warning (teeing generation 0 itself
    has nothing to degrade to and raises typed) — recovered answers are
    bit-identical to fault-free runs, and exhausted policies raise
    typed errors; ``"off"`` restores fail-on-first-fault.

    ``ingest_workers`` (default 1) widens the HOST side of every streamed
    pass: ``"auto"`` (= min(4, cores)) or an int > 1 runs chunk encode,
    spill-tee packing and device staging on a pool of ``ksel-ingest-*``
    workers behind a reorder sequencer that releases chunks strictly in
    stream order — so answers, pass logs, spill records and the
    chunk->device round-robin are bit-identical at every worker count,
    and ``1`` is byte-for-byte the legacy single-producer plane. The pool
    pays off when the host work (key encode, ``pack_spill`` bit-packing,
    CRC) is the bottleneck rather than the device programs.

    ``obs`` (an :class:`~mpi_k_selection_tpu.obs.Observability`) turns on
    the descent telemetry — typed per-pass/per-chunk events, a metrics
    registry (occupancy per executor phase, stall seconds, bytes per
    device), and producer/consumer trace spans — with a
    bit-identical-answers guarantee (docs/OBSERVABILITY.md). See
    streaming/chunked.py:streaming_kselect for the full option set
    (``radix_bits``, ``hist_method``, ``collect_budget``, ``sketch``,
    ``pipeline_depth``, ``timer``, ``devices``, ``spill``, ``spill_dir``,
    ``deferred``, ``fused``, ``width_schedule``, ``pack_spill``,
    ``ingest_workers``, ``retry``, ``obs``)."""
    from mpi_k_selection_tpu.streaming.chunked import streaming_kselect

    return streaming_kselect(source, k, **kwargs)


class StreamingQuantiles:
    """Online quantile tracker over a chunked stream: a mergeable
    :class:`~mpi_k_selection_tpu.streaming.sketch.RadixSketch` plus the
    exact-refinement hook. The telemetry shape: feed chunks as they arrive
    (``update``), combine trackers from different shards/processes in any
    order (``merge`` — bitwise order-invariant), read approximate quantiles
    any time (``quantiles`` — rank error per the sketch's documented
    bound), and spend extra passes over a replayable source only when an
    exact answer is worth it (``refine_quantiles``).

    ``pipeline_depth`` governs how chunked ingest (``update_stream``) and
    the exact refinement passes overlap production/encode/transfer with
    compute (streaming/pipeline.py; 0 = synchronous, bit-identical).
    ``devices`` spreads that ingest round-robin across chips (None/1 =
    single device; answers and sketches stay bit-identical for every
    device count — see streaming/chunked.py). ``deferred`` picks the
    executor discipline for the exact refinement passes
    (streaming/executor.py; default auto = deferred device-side
    compaction, ``"off"`` the historical eager gathers — bit-identical
    either way) and ``fused`` the single-read ingest tier for those
    passes AND the staged sketch folds: ``"kernel"`` = ONE single-sweep
    pallas program per staged bucket (ops/pallas/sweep_ingest.py, one
    guaranteed read), ``"xla"`` = the one-XLA-program fusion
    (ops/pallas/fused_ingest.py), ``"off"`` = the unfused oracle;
    default auto = kernel on TPU, xla elsewhere — bit-identical at
    every tier."""

    def __init__(
        self,
        dtype,
        *,
        radix_bits: int = 4,
        levels: int = 4,
        pipeline_depth: int | None = None,
        devices=None,
        deferred=None,
        fused=None,
        width_schedule=None,
        pack_spill=None,
        ingest_workers=None,
        obs=None,
    ):
        from mpi_k_selection_tpu.streaming.chunked import (
            DEFAULT_PACK_SPILL,
            DEFAULT_WIDTH_SCHEDULE,
            validate_width_schedule,
        )
        from mpi_k_selection_tpu.streaming.executor import (
            DEFAULT_DEFERRED,
            DEFAULT_FUSED,
            resolve_deferred,
            validate_fused,
        )
        from mpi_k_selection_tpu.streaming.spill import validate_pack_spill
        from mpi_k_selection_tpu.streaming.pipeline import (
            resolve_ingest_workers,
            resolve_stream_devices,
            validate_pipeline_depth,
        )
        from mpi_k_selection_tpu.streaming.sketch import RadixSketch

        self.pipeline_depth = validate_pipeline_depth(pipeline_depth)
        resolve_stream_devices(devices)  # validate eagerly, like depth
        self.devices = devices
        #: executor discipline for the exact refinement passes
        #: (streaming/executor.py; None resolves to the package default)
        self.deferred = DEFAULT_DEFERRED if deferred is None else deferred
        resolve_deferred(self.deferred)  # validate eagerly, like depth
        #: single-read fused ingest for the refinement passes
        #: (ops/pallas/fused_ingest.py; None resolves to the default)
        self.fused = DEFAULT_FUSED if fused is None else fused
        # validate eagerly, like depth — but WITHOUT resolving "auto"'s
        # tier: resolve_fused probes jax.default_backend(), a full
        # platform init this sketch-only constructor must not trigger
        validate_fused(self.fused)
        #: per-pass digit-width schedule for the exact refinement passes
        #: ("off" = radix_bits every pass, "auto" = wide first digit, or
        #: an explicit per-pass tuple — streaming/chunked.py)
        self.width_schedule = (
            DEFAULT_WIDTH_SCHEDULE if width_schedule is None else width_schedule
        )
        validate_width_schedule(self.width_schedule)  # eagerly, like depth
        #: prefix-packed spill records for update_stream tees and the
        #: refinement passes ("off" = unpacked v1 oracle — spill.py)
        self.pack_spill = validate_pack_spill(
            DEFAULT_PACK_SPILL if pack_spill is None else pack_spill
        )
        #: host ingest-pool width for update_stream and the refinement
        #: passes ("auto", or an int; None = 1 = the single-producer
        #: plane — streaming/pipeline.py). Stored RAW ("auto" resolves
        #: per call, so a tracker pickled on one host adapts to another).
        resolve_ingest_workers(ingest_workers)  # validate eagerly, like depth
        self.ingest_workers = ingest_workers
        #: optional Observability bundle threaded through update_stream
        #: and refine_quantiles (off = None, the default)
        self.obs = obs
        self.sketch = RadixSketch(dtype, radix_bits=radix_bits, levels=levels)

    @property
    def n(self) -> int:
        return self.sketch.n

    def update(self, chunk) -> "StreamingQuantiles":
        self.sketch.update(chunk)
        return self

    def update_stream(self, source, *, spill=None) -> "StreamingQuantiles":
        """Fold every chunk of a replayable/listed ``source`` in via the
        pipelined iterator (chunk *i+1* encoded in the background while
        chunk *i* folds; with ``devices`` > 1, each chunk's deepest-level
        histogram counted on its round-robin device) — bit-identical to
        sequential ``update`` calls. ``spill`` (a
        :class:`~mpi_k_selection_tpu.streaming.spill.SpillStore`) tees the
        stream's encoded keys to disk during this ONE pass, making
        one-shot sources refinable: pass the store to
        :meth:`refine_quantiles` afterwards and the exact descent runs
        entirely from the spilled generation. The tracker's ``fused``
        tier rides along: at ``"kernel"`` each supported staged bucket's
        deep fold + extremes run as ONE single-sweep program
        (ops/pallas/sweep_ingest.py) instead of the 2-program pair. The
        tracker's ``pack_spill`` mode governs the tee: ``"auto"`` writes
        digit-segmented packed records so the later refinement descent
        reads ONLY the segments its sketch-seeded first pass keeps."""
        self.sketch.update_stream(
            source, pipeline_depth=self.pipeline_depth, devices=self.devices,
            spill=spill, fused=self.fused, pack_spill=self.pack_spill,
            ingest_workers=self.ingest_workers, obs=self.obs,
        )
        return self

    def merge(self, other: "StreamingQuantiles") -> "StreamingQuantiles":
        out = StreamingQuantiles(
            self.sketch.dtype,
            radix_bits=self.sketch.radix_bits,
            levels=self.sketch.levels,
            pipeline_depth=self.pipeline_depth,
            devices=self.devices,
            deferred=self.deferred,
            fused=self.fused,
            width_schedule=self.width_schedule,
            pack_spill=self.pack_spill,
            ingest_workers=self.ingest_workers,
            obs=self.obs,
        )
        out.sketch = self.sketch.merge(
            other.sketch if isinstance(other, StreamingQuantiles) else other
        )
        return out

    def quantiles(self, qs):
        """Approximate nearest-rank quantile values (see RadixSketch.query
        for the error contract; exact rank/value brackets via the sketch)."""
        return self.sketch.quantiles(qs)

    def refine_quantiles(self, qs, source):
        """EXACT nearest-rank quantiles over the replayable ``source``
        (which must replay the very stream this tracker accumulated): ONE
        sketch-seeded multi-rank descent shares every streamed pass across
        all requested ranks, so m quantiles cost roughly the stream replays
        of one (streaming/chunked.py:streaming_kselect_many). ``source``
        may be the SpillStore a one-shot :meth:`update_stream` teed into —
        the descent then reads (and geometrically shrinks) the spilled
        generation instead of replaying the original stream."""
        from mpi_k_selection_tpu.streaming.chunked import streaming_kselect_many

        return streaming_kselect_many(
            source,
            quantile_ranks(qs, self.sketch.n),
            radix_bits=self.sketch.radix_bits,
            sketch=self.sketch,
            pipeline_depth=self.pipeline_depth,
            devices=self.devices,
            deferred=self.deferred,
            fused=self.fused,
            width_schedule=self.width_schedule,
            pack_spill=self.pack_spill,
            ingest_workers=self.ingest_workers,
            obs=self.obs,
        )


def batched_kselect(x, k):
    """Per-row exact k-th smallest along the last axis (1-indexed k).

    ``k`` may be a scalar or an array broadcastable to the batch shape
    (one rank per row). Batched full sort: ``lax.sort`` over rows is the
    efficient TPU shape (batch parallelism), and unlike the 1-D case the
    per-row histogram trick has no batch advantage to exploit.
    """
    from mpi_k_selection_tpu.utils.dtypes import _require_x64

    if hasattr(x, "dtype") and np.dtype(x.dtype).kind in "iu":
        # caller-typed host int64 would silently bit-truncate below;
        # weak-typed Python lists and float64 (value rounding, see
        # as_selection_array) keep the historical conversion
        _require_x64(x.dtype)
    x = jnp.asarray(x)
    if x.ndim < 2:
        raise ValueError("batched_kselect wants a (..., d) batch; use kselect for 1-D")
    d = x.shape[-1]
    check_concrete_k(k, d)
    k = jnp.asarray(k)
    s = jnp.sort(x, axis=-1)
    idx = jnp.clip(k.astype(jnp.int32) - 1, 0, d - 1)
    idx = jnp.broadcast_to(idx, x.shape[:-1])
    return jnp.take_along_axis(s, idx[..., None], axis=-1)[..., 0]


def batched_median(x):
    """Per-row lower median along the last axis."""
    d = np.shape(x)[-1] if np.shape(x) else 0  # no dtype-changing conversion
    return batched_kselect(x, max(1, d // 2))
