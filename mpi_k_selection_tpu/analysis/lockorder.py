"""Runtime lock-order sanitizer — the dynamic half of the KSL016
contract.

An opt-in test harness that wraps ``threading.Lock`` / ``threading.RLock``
construction with a recording proxy: every successful acquisition is
appended to a per-thread held-list, and each acquisition made while other
locks are held records *acquired-while-holding* edges — the same graph
the static pass (analysis/concurrency.py:build_lock_graph) derives from
the source, but observed from the real interleavings of the concurrency
suites (executor grid, serve burst, chaos grid, monitor). The gate test
(tests/test_concurrency.py) runs those workloads under one sanitizer,
asserts the observed graph is acyclic, checks it for direction conflicts
against the static graph, and writes the observed order as a JSON
artifact (/tmp/kselect_lockorder.json) next to the lint report.

Labeling and matching: a tracked lock is labeled by the first
package-owned stack frame at its construction — for the canonical
``self._lock = threading.Lock()`` pattern that is exactly the
definition line the static graph records as the node's ``site``, so the
two graphs join on ``relpath:lineno`` with no name mapping. Locks
constructed outside the package (jax, stdlib internals) are labeled
``ext:<file>:<line>`` and participate in edge recording but not in the
package acyclicity assertion (an external library's internal ordering
is not this repo's contract to enforce).

Scope and honesty bounds:

- Only locks CONSTRUCTED inside the ``with LockOrderSanitizer()`` window
  are tracked (the factory is patched, existing objects are not). The
  package's module-level locks (staging pool, live-staged accounting,
  the fault injector's active slot, the native loader) predate any test
  body, so :meth:`LockOrderSanitizer.patch_package_locks` swaps those
  known globals for tracked proxies — labeled with their static node
  keys — and restores them on exit.
- Two different lock OBJECTS sharing one creation-site label (two
  queues built on the same line, per-request ``PendingQuery`` locks)
  cannot be ordered by label: an edge between same-label objects is
  recorded into ``same_label_pairs`` — the classic two-instances-of-one-
  class ordering hazard, surfaced separately — rather than as a graph
  self-loop.
- ``threading.Condition``'s internal waiter locks come from
  ``_thread.allocate_lock`` directly, not the patched module attribute,
  so Condition/Event/Queue internals do not pollute the graph; their
  *mutex* (a ``threading.Lock()``) IS tracked, which is what makes a
  lock-held ``Queue.get`` visible as a real edge.
"""

from __future__ import annotations

import json
import sys
import threading

_PKG_MARKER = "mpi_k_selection_tpu"

#: The most recent sanitizer window's observed graph (``to_dict()``
#: form), published at window exit — the flight recorder's debug bundle
#: (obs/flight.py) embeds it as the ``lock_order`` section when a
#: sanitizer ran in this process. ``None`` until one has.
LAST_OBSERVED: dict | None = None


def _creation_label() -> str:
    """Label for a lock created right now: the first stack frame inside
    the package (``<relpath from package root>:<line>``), else the first
    frame outside this module/threading, as ``ext:<file>:<line>``."""
    f = sys._getframe(2)
    fallback = None
    while f is not None:
        fn = f.f_code.co_filename.replace("\\", "/")
        if _PKG_MARKER in fn:
            idx = fn.rindex(_PKG_MARKER)
            return f"{fn[idx:]}:{f.f_lineno}"
        if fallback is None and "lockorder" not in fn and not fn.endswith(
            ("threading.py", "queue.py", "dataclasses.py")
        ):
            fallback = f"ext:{fn.rsplit('/', 1)[-1]}:{f.f_lineno}"
        f = f.f_back
    return fallback or "ext:?"


class TrackedLock:
    """Proxy around a real lock primitive that reports successful
    acquisitions/releases to its sanitizer. Supports the full Lock/RLock
    protocol the stdlib relies on (``Condition`` works with a tracked
    mutex via the generic release/acquire fallback paths)."""

    def __init__(self, inner, sanitizer: "LockOrderSanitizer", label: str):
        # reentrancy needs no flag: _on_acquire's identity check handles
        # a re-acquire of the same object for Lock and RLock alike
        self._inner = inner
        self._san = sanitizer
        self.label = label

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san._on_acquire(self)
        return ok

    def release(self):
        self._san._on_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # RLock plumbing threading.Condition probes for -------------------------

    def _is_owned(self):
        inner = getattr(self._inner, "_is_owned", None)
        if inner is not None:
            return inner()
        # plain-lock fallback (mirrors threading.Condition's own)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        # full release regardless of depth: purge our bookkeeping first
        self._san._on_release_full(self)
        inner = getattr(self._inner, "_release_save", None)
        if inner is not None:
            return inner()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        inner = getattr(self._inner, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._inner.acquire()
        self._san._on_acquire(self)

    def __repr__(self):
        return f"<TrackedLock {self.label} wrapping {self._inner!r}>"


class LockOrderSanitizer:
    """Context manager arming the tracked-lock factories and collecting
    the runtime acquired-while-holding graph. Reentrant acquisition of
    one lock never records a self-edge; edges between distinct objects
    sharing a label go to :attr:`same_label_pairs`."""

    def __init__(self):
        # bookkeeping runs under a REAL lock (created before patching)
        self._state_lock = threading.Lock()
        self._local = threading.local()
        self.edges: dict = {}  # (src_label, dst_label) -> count
        self.same_label_pairs: dict = {}  # label -> count
        self.labels: set = set()
        self.threads_seen: set = set()
        self._saved = None
        self._module_patches: list = []

    # -- factory patching --------------------------------------------------

    def _make_lock(self):
        return TrackedLock(self._real_lock(), self, _creation_label())

    def _make_rlock(self):
        return TrackedLock(self._real_rlock(), self, _creation_label())

    def __enter__(self) -> "LockOrderSanitizer":
        if self._saved is not None:
            raise RuntimeError("LockOrderSanitizer is not reentrant")
        self._saved = (threading.Lock, threading.RLock)
        self._real_lock, self._real_rlock = self._saved
        threading.Lock = self._make_lock
        threading.RLock = self._make_rlock
        return self

    def __exit__(self, *exc):
        threading.Lock, threading.RLock = self._saved
        self._saved = None
        for obj, attr, original in self._module_patches:
            setattr(obj, attr, original)
        self._module_patches.clear()
        # publish the observed graph for postmortem consumers (the flight
        # recorder's debug bundle); single assignment, last window wins
        global LAST_OBSERVED
        LAST_OBSERVED = self.to_dict()
        return False

    def wrap_existing(self, obj, attr: str, label: str) -> None:
        """Swap an already-constructed lock living at ``obj.attr`` for a
        tracked proxy (restored on exit). Callers must name attributes
        that are looked up per use (module globals, instance attrs) —
        captured references keep the raw lock."""
        original = getattr(obj, attr)
        if isinstance(original, TrackedLock):  # already wrapped
            return
        setattr(obj, attr, TrackedLock(original, self, label))
        self._module_patches.append((obj, attr, original))

    def patch_package_locks(self) -> None:
        """Wrap the package's module-level locks (created at import time,
        before any sanitizer window) with labels equal to their static
        lock-graph node keys, so the consistency check joins them too."""
        # faults/__init__.py re-exports a FUNCTION named `inject`, which
        # shadows the submodule on attribute-style imports — resolve the
        # module objects through sys.modules
        import importlib

        _inj = importlib.import_module("mpi_k_selection_tpu.faults.inject")
        _ld = importlib.import_module("mpi_k_selection_tpu.native.loader")
        _pl = importlib.import_module("mpi_k_selection_tpu.streaming.pipeline")

        self.wrap_existing(
            _pl, "_LIVE_STAGED_LOCK",
            "mpi_k_selection_tpu/streaming/pipeline.py::_LIVE_STAGED_LOCK",
        )
        self.wrap_existing(
            _pl.STAGING_POOL, "_lock",
            "mpi_k_selection_tpu/streaming/pipeline.py::StagingPool._lock",
        )
        self.wrap_existing(
            _inj, "_ACTIVE_LOCK",
            "mpi_k_selection_tpu/faults/inject.py::_ACTIVE_LOCK",
        )
        self.wrap_existing(
            _ld, "_lock",
            "mpi_k_selection_tpu/native/loader.py::_lock",
        )

    # -- bookkeeping -------------------------------------------------------

    def _held(self) -> list:
        held = getattr(self._local, "held", None)
        if held is None:
            held = []
            self._local.held = held
        return held

    def _on_acquire(self, lock: TrackedLock) -> None:
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                entry[1] += 1  # reentrant re-acquire: no edge, no new hold
                return
        new_edges = []
        same_label = []
        for other, _depth in held:
            if other.label == lock.label:
                same_label.append(other.label)
            else:
                new_edges.append((other.label, lock.label))
        held.append([lock, 1])
        # identity via the C-level get_ident(): current_thread() would
        # CONSTRUCT a _DummyThread (Event -> another tracked lock ->
        # recursive _on_acquire) for not-yet-registered threads — a
        # self-deadlock on _state_lock
        ident = threading.get_ident()
        with self._state_lock:
            self.labels.add(lock.label)
            self.threads_seen.add(ident)
            for e in new_edges:
                self.edges[e] = self.edges.get(e, 0) + 1
            for lab in same_label:
                self.same_label_pairs[lab] = (
                    self.same_label_pairs.get(lab, 0) + 1
                )

    def _on_release(self, lock: TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return

    def _on_release_full(self, lock: TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    # -- analysis ----------------------------------------------------------

    @staticmethod
    def _is_package_label(label: str) -> bool:
        return label.startswith(_PKG_MARKER)

    def _snapshot(self) -> tuple:
        """One consistent copy of the mutable state (workload threads may
        still be recording while an observer reads — KSL015)."""
        with self._state_lock:
            return (
                dict(self.edges),
                dict(self.same_label_pairs),
                set(self.labels),
                set(self.threads_seen),
            )

    def package_edges(self) -> list:
        """Observed edges with BOTH endpoints package-owned — the
        subgraph the acyclicity and consistency contracts cover."""
        edges, _, _, _ = self._snapshot()
        return sorted(
            (a, b, n)
            for (a, b), n in edges.items()
            if self._is_package_label(a) and self._is_package_label(b)
        )

    def find_cycles(self, *, package_only: bool = True) -> list:
        from mpi_k_selection_tpu.analysis.concurrency import cycles_from_pairs

        pairs = (
            [(a, b) for a, b, _n in self.package_edges()]
            if package_only
            else list(self._snapshot()[0])
        )
        return cycles_from_pairs(pairs)

    def assert_acyclic(self) -> None:
        cycles = self.find_cycles(package_only=True)
        if cycles:
            raise AssertionError(
                "runtime lock-order cycle(s) observed: "
                + " ; ".join(" -> ".join(c + [c[0]]) for c in cycles)
            )

    def check_consistency(self, static_graph: dict) -> list:
        """Direction conflicts between the observed order and the static
        KSL016 graph (analysis/concurrency.py:build_concurrency_report's
        ``lock_graph``): a runtime edge A->B conflicts when the static
        graph orders the same two locks B->A. Locks are joined on the
        static node ``site`` (``relpath:lineno``) or the node key itself
        (module-global proxies are labeled with their keys directly);
        runtime labels with no static counterpart are skipped — the
        static pass is module-local and lexical, so the runtime graph is
        allowed to see MORE, never the reverse of what the static graph
        committed to."""
        site_to_key = {}
        for key, node in static_graph["nodes"].items():
            site_to_key[node["site"]] = key
            site_to_key[key] = key
        static_edges = {
            (e["src"], e["dst"]) for e in static_graph["edges"]
        }
        conflicts = []
        edges, _, _, _ = self._snapshot()
        for (a, b), n in sorted(edges.items()):
            ka, kb = site_to_key.get(a), site_to_key.get(b)
            if ka is None or kb is None:
                continue
            if (kb, ka) in static_edges and (ka, kb) not in static_edges:
                conflicts.append(
                    {
                        "runtime": [a, b],
                        "static": [kb, ka],
                        "count": n,
                    }
                )
        return conflicts

    def to_dict(self) -> dict:
        edges, same_label, labels, threads = self._snapshot()
        return {
            "labels": sorted(labels),
            "edges": [
                {"src": a, "dst": b, "count": n}
                for (a, b), n in sorted(edges.items())
            ],
            "package_edges": [
                {"src": a, "dst": b, "count": n}
                for a, b, n in self.package_edges()
            ],
            "same_label_pairs": dict(sorted(same_label.items())),
            "threads_seen": sorted(threads),
            "cycles": self.find_cycles(package_only=True),
        }

    def to_json(self, indent=2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)
