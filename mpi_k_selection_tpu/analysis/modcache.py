"""Session-scoped shared parsed-module cache for the whole-repo gates.

Four analysis passes now gate tier-1 over the whole repository — the
AST rules (test_analysis.py), concurrency (test_concurrency.py),
lifecycle (test_lifecycle.py) and placement (test_placement.py) — and
before this module each gate re-walked and re-parsed every package file.
``shared_modules`` parses once per session and hands every gate the SAME
:class:`~mpi_k_selection_tpu.analysis.core.SourceModule` list, which
also makes the per-module analyzer caches (concurrency's, lifecycle's,
placement's — all keyed by ``id(mod)``) hit across gates instead of
recomputing their dataflow per test file.

The cache key is (resolved scan paths, root); the value is guarded by a
per-file (path, mtime_ns, size) fingerprint, so an edited file
invalidates the whole set — correctness first, the cache only
accelerates the unchanged-tree case every test session actually is.

``ANALYSIS_GATE_WALL_BUDGET_S`` is the declared wall ceiling for the
four whole-repo scans COMBINED (contracts excluded — those trace jax
programs and budget themselves); tests/test_placement.py asserts the
budget holds, so a pass whose engine regresses to re-parsing (or whose
dataflow goes quadratic) fails tier-1 with a number attached.
"""

from __future__ import annotations

import os
import pathlib

from mpi_k_selection_tpu.analysis.core import iter_python_files, load_module

#: Declared combined wall ceiling (seconds) for the ast + concurrency +
#: lifecycle + placement whole-repo scans sharing one parsed-module set.
#: The four scans run in ~4-6 s on the CI container; 30 leaves honest
#: headroom for slow shared runners without masking a quadratic engine.
ANALYSIS_GATE_WALL_BUDGET_S = 30.0

_CACHE: dict[tuple, tuple] = {}


def _fingerprint(files) -> tuple:
    out = []
    for f in files:
        try:
            st = os.stat(f)
        except OSError:  # racing delete: treat as changed
            out.append((str(f), -1, -1))
            continue
        out.append((str(f), st.st_mtime_ns, st.st_size))
    return tuple(out)


def shared_modules(paths, *, root=None) -> list:
    """The parsed :class:`SourceModule` list for ``paths`` — cached
    across calls (and across the four gate test files) until any file's
    (mtime, size) changes. An unparseable file RAISES here rather than
    being silently dropped: a gate fed a shared set must never scan a
    quietly-smaller tree than the uncached path would (KSL000's
    scan-the-broken-file-anyway semantics stay with ``run_analysis``'s
    own parse loop, which fixture tests exercise without the cache)."""
    key = (
        tuple(sorted(str(pathlib.Path(p).resolve()) for p in paths)),
        str(pathlib.Path(root).resolve()) if root is not None else None,
    )
    files = iter_python_files(paths)
    fp = _fingerprint(files)
    hit = _CACHE.get(key)
    if hit is not None and hit[0] == fp:
        return hit[1]
    mods = [load_module(f, root=root) for f in files]
    _CACHE[key] = (fp, mods)
    return mods


def clear() -> None:
    """Drop the cache (tests that synthesize trees under one path)."""
    _CACHE.clear()
