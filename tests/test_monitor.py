"""Continuous monitoring (mpi_k_selection_tpu/monitor/): the windowed
ring's bit-identity and O(1)-advance structure, the decayed fold's
algebra (associativity/commutativity across split points, the
``decay=1.0`` degenerate identity, int64 headroom), the Monitor driver
over the real ingest pipeline (depth x devices bit-identity, drifting
streams, exact bounds), the windowed-histogram metrics bridge, the
serve ``latency_windows`` knob, and the CLI ``monitor`` subcommand.
"""

import json
import math
import urllib.request

import numpy as np
import pytest

from mpi_k_selection_tpu.monitor import (
    DECAY_SHIFT,
    DecayedSketch,
    DecayedWindowedSketch,
    Monitor,
    WindowedSketch,
    decay_weight,
    start_metrics_server,
)
from mpi_k_selection_tpu.streaming.sketch import RadixSketch


def _chunks(rng, sizes, dtype=np.int32, lo=-(2**31), hi=2**31 - 1):
    if np.issubdtype(np.dtype(dtype), np.integer):
        return [rng.integers(lo, hi, size=m, dtype=dtype) for m in sizes]
    return [rng.standard_normal(m).astype(dtype) for m in sizes]


def _scratch_merge(buckets, dtype, **kw):
    out = RadixSketch(dtype, **kw)
    for b in buckets:
        out.fold_scaled(b, 1)
    return out


# ---------------------------------------------------------------------------
# WindowedSketch — ring re-aggregation bit-identity


@pytest.mark.parametrize("window", [1, 2, 3, 8])
def test_windowed_query_bit_identical_to_scratch(window, rng):
    """Every (advance count, query window) over a 3x-wrap run: the
    two-stack aggregates must equal a from-scratch merge of the same
    live buckets, bit for bit."""
    ws = WindowedSketch(np.int32, window=window)
    raw = []
    for epoch in range(3 * window + 2):
        c = rng.integers(
            -(2**31), 2**31 - 1, size=int(rng.integers(1, 400)),
            dtype=np.int32,
        )
        ws.update(c)
        raw.append(c)
        for qw in [None] + list(range(1, window + 1)):
            w_eff = min(qw or window, len(raw), window)
            scratch = RadixSketch(np.int32)
            for b in raw[len(raw) - w_eff:]:
                scratch.update(b)
            assert ws.query(qw) == scratch, (window, epoch, qw)
        ws.advance()
    assert ws.epoch == 3 * window + 2
    assert ws.n_live == min(ws.epoch + 1, window)


def test_windowed_float32_and_heterogeneous_chunks(rng):
    ws = WindowedSketch(np.float32, window=3)
    raw = []
    for m in (7, 1, 300, 64, 2):
        c = rng.standard_normal(m).astype(np.float32)
        ws.update(c)
        raw.append([c])
        # several chunks per bucket
        c2 = rng.standard_normal(m + 3).astype(np.float32)
        ws.update(c2)
        raw[-1].append(c2)
        ws.advance()
    # the current (empty) bucket counts toward the window, so only the
    # newest window-1 = 2 closed buckets are live after the last advance
    live = [b for bucket in raw[-2:] for b in bucket]
    scratch = RadixSketch(np.float32)
    for c in live:
        scratch.update(c)
    assert ws.query() == scratch


def test_windowed_live_buckets_order(rng):
    ws = WindowedSketch(np.int32, window=3)
    cs = _chunks(rng, [5, 5, 5, 5])
    for c in cs:
        ws.update(c)
        ws.advance()
    live = ws.live_buckets()
    assert len(live) == 3  # 2 closed + current (empty)
    assert live[-1].n == 0
    assert [b.n for b in live[:-1]] == [5, 5]


def test_windowed_validation():
    with pytest.raises(ValueError, match="window must be >= 1"):
        WindowedSketch(np.int32, window=0)
    ws = WindowedSketch(np.int32, window=4)
    with pytest.raises(ValueError, match=r"query window must be in \[1, 4\]"):
        ws.query(5)
    with pytest.raises(ValueError, match=r"query window must be in \[1, 4\]"):
        ws.query(0)


def test_update_value_bit_identical_to_update(rng):
    for dtype, vals in (
        (np.int32, [-5, 0, 2**31 - 1, -(2**31)]),
        (np.float64, [0.0, -0.0, 1e-9, 3.5, -2.25, float("inf")]),
    ):
        a = RadixSketch(dtype)
        b = RadixSketch(dtype)
        for v in vals:
            a.update_value(v)
            b.update(np.asarray([v], dtype))
        assert a == b, dtype


def test_copy_is_independent(rng):
    a = RadixSketch(np.int32).update(_chunks(rng, [64])[0])
    b = a.copy()
    assert a == b
    b.update(_chunks(rng, [8])[0])
    assert a != b and a.n == 64


# ---------------------------------------------------------------------------
# count-scaled fold algebra (the decayed-merge satellite)


def test_fold_scaled_weight_one_matches_merge(rng):
    c1, c2 = _chunks(rng, [100, 37])
    a = RadixSketch(np.int32).update(c1)
    b = RadixSketch(np.int32).update(c2)
    merged = a.merge(b)
    folded = a.copy().fold_scaled(b, 1)
    assert folded == merged


def test_fold_scaled_validation(rng):
    a = RadixSketch(np.int32).update(_chunks(rng, [10])[0])
    b = RadixSketch(np.int32).update(_chunks(rng, [10])[0])
    with pytest.raises(ValueError, match="weight must be >= 0"):
        a.fold_scaled(b, -1)
    before = a.copy()
    a.fold_scaled(b, 0)  # zero weight: a no-op, not an error
    assert a == before
    with pytest.raises(ValueError, match="incompatible"):
        a.fold_scaled(RadixSketch(np.int32, radix_bits=2), 1)


def test_fold_scaled_associative_commutative_across_split_points(rng):
    """The decayed aggregate is sum_a bucket_a * w_a; any grouping and
    any order must produce a bitwise-identical accumulator."""
    buckets = [
        RadixSketch(np.int32).update(c)
        for c in _chunks(rng, [50, 200, 3, 77, 128])
    ]
    weights = [decay_weight(0.7, a) for a in range(5)]
    pairs = list(zip(buckets, weights))

    def fold(ordering, splits):
        acc = RadixSketch(np.int32)
        # fold a first segment into one sub-accumulator, the rest into
        # another, then combine — the "split point" shape
        lo = RadixSketch(np.int32)
        hi = RadixSketch(np.int32)
        for i, (b, w) in enumerate(ordering):
            (lo if i < splits else hi).fold_scaled(b, w)
        acc.fold_scaled(lo, 1)
        acc.fold_scaled(hi, 1)
        return acc

    want = fold(pairs, 0)
    for splits in (1, 2, 4, 5):
        assert fold(pairs, splits) == want, f"split at {splits}"
    assert fold(list(reversed(pairs)), 2) == want  # commutativity
    assert fold(pairs[2:] + pairs[:2], 3) == want  # rotation


def test_decay_one_degenerates_bit_identically(rng):
    """decay=1.0: every weight is exactly 2**DECAY_SHIFT, so the decayed
    pyramid is the undecayed one left-shifted — and every VALUE answer
    (quantiles, value_bounds, pin) is bit-identical."""
    dws = DecayedWindowedSketch(np.int32, window=4, decay=1.0)
    base = WindowedSketch(np.int32, window=4)
    for c in _chunks(rng, [100, 40, 7, 300, 100, 64]):
        dws.update(c)
        base.update(c)
        dws.advance()
        base.advance()
    md, mb = dws.query(), base.query()
    S = 1 << DECAY_SHIFT
    assert md.n == mb.n * S
    assert all(np.array_equal(a, b * S) for a, b in zip(md.hists, mb.hists))
    qs = [0.01, 0.5, 0.9, 0.99, 1.0]
    assert md.quantiles(qs) == mb.quantiles(qs)
    for q in qs:
        kd = max(1, math.ceil(q * md.n))
        kb = max(1, math.ceil(q * mb.n))
        assert md.value_bounds(kd) == mb.value_bounds(kb)


def test_decay_weight_contract():
    S = 1 << DECAY_SHIFT
    assert decay_weight(1.0, 0) == decay_weight(1.0, 99) == S
    assert decay_weight(0.5, 1) == S // 2
    assert decay_weight(0.5, DECAY_SHIFT + 1) == 0  # fully decayed out
    with pytest.raises(ValueError, match="decay must be in"):
        decay_weight(0.0, 1)
    with pytest.raises(ValueError, match="decay must be in"):
        decay_weight(1.5, 1)
    with pytest.raises(ValueError, match="age must be >= 0"):
        decay_weight(0.5, -1)


def test_decayed_bounds_match_weighted_oracle(rng):
    """The decayed sketch's value_bounds must bracket the TRUE weighted
    order statistic: expand every element by its bucket's integer
    weight and take the nearest-rank quantile of the expansion."""
    sizes = [60, 25, 90, 40]
    chunks = _chunks(rng, sizes, lo=-1000, hi=1000)
    # window=5: the empty current bucket (age 0) plus all 4 closed ones
    dws = DecayedWindowedSketch(np.int32, window=5, decay=0.5)
    for c in chunks:
        dws.update(c)
        dws.advance()
    # after 4 advances the current bucket is empty; ages of the closed
    # buckets are 1..4 (newest closed = age 1)
    m = dws.query()
    vals = np.concatenate(chunks)
    wts = np.concatenate(
        [
            np.full(c.size, decay_weight(0.5, age), np.int64)
            for age, c in zip(range(4, 0, -1), chunks)
        ]
    )
    order = np.argsort(vals, kind="stable")
    sv, sw = vals[order], np.cumsum(wts[order])
    assert m.n == int(sw[-1])
    for q in (0.1, 0.5, 0.9, 0.99):
        k = max(1, math.ceil(q * m.n))
        true = sv[int(np.searchsorted(sw, k, side="left"))]
        vlo, vhi = m.value_bounds(k)
        assert vlo <= true <= vhi, (q, vlo, true, vhi)
        lo, hi = m.rank_bounds(k)
        assert lo < k <= hi


def test_fold_scaled_headroom_refusal_at_max_scale():
    """int64 headroom: at the maximum weight (2**DECAY_SHIFT) a window
    whose unweighted count reaches 2**(63-DECAY_SHIFT) must refuse
    loudly, not wrap."""
    a = DecayedSketch(np.int32)
    big = RadixSketch(np.int32)
    big.n = 1 << (63 - DECAY_SHIFT)  # simulated giant bucket
    big.hists[-1][0] = big.n
    big._min_key = big._max_key = big.kdt.type(0)
    with pytest.raises(OverflowError, match="int64 accumulator"):
        a.fold_scaled(big, 1 << DECAY_SHIFT)
    # one below the edge folds fine
    big.n -= 1
    big.hists[-1][0] = big.n
    a.fold_scaled(big, 1 << DECAY_SHIFT)
    assert a.n == big.n * (1 << DECAY_SHIFT)


# ---------------------------------------------------------------------------
# Monitor — the driver over the real ingest pipeline


def _drifting_chunks(n_chunks, elems=2048, step=500, seed=7):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, 1000, size=elems) + i * step).astype(np.int32)
        for i in range(n_chunks)
    ]


def test_monitor_tracks_drift_with_exact_bounds():
    chunks = _drifting_chunks(12)
    mon = Monitor(window=4)
    samples = list(mon.run(iter(chunks), np.int32))  # one-shot source
    assert len(samples) == 12
    p50 = [s.values[0] for s in samples]
    assert p50[-1] > p50[0]  # the window follows the drift
    last = samples[-1]
    assert last.metric_name == "multirank_p50_p90_p99"
    live = np.concatenate(chunks[-4:])
    s_live = np.sort(live, kind="stable")
    for q, (vlo, vhi), (rlo, rhi) in zip(
        last.qs, last.value_bounds, last.rank_bounds
    ):
        k = max(1, math.ceil(q * live.size))
        assert vlo <= s_live[k - 1] <= vhi
        assert rlo < k <= rhi


@pytest.mark.parametrize("depth,devices", [(0, None), (2, None), (2, 2)])
def test_monitor_bit_identical_across_ingest_grid(depth, devices):
    """The pipeline/devices knobs change scheduling, never a sample bit
    — the update_stream contract inherited wholesale."""
    chunks = _drifting_chunks(9, elems=1500)
    want = [
        s.as_dict()
        for s in Monitor(window=3).run(list(chunks), np.int32)
    ]
    got = [
        s.as_dict()
        for s in Monitor(
            window=3, pipeline_depth=depth, devices=devices
        ).run(lambda: iter(chunks), np.int32)
    ]
    assert got == want


def test_monitor_emit_every_and_max_samples():
    chunks = _drifting_chunks(10, elems=256)
    mon = Monitor(window=4, emit_every=2)
    samples = list(mon.run(list(chunks), np.int32))
    assert len(samples) == 5  # 10 chunks / 2 per bucket
    assert samples[0].n == 512 and samples[-1].n == 4 * 512
    capped = list(
        Monitor(window=4, emit_every=2).run(
            list(chunks), np.int32, max_samples=2
        )
    )
    assert len(capped) == 2


def test_monitor_final_partial_bucket_sample():
    chunks = _drifting_chunks(5, elems=128)
    samples = list(
        Monitor(window=4, emit_every=2).run(list(chunks), np.int32)
    )
    # 2 full buckets + a trailing 1-chunk bucket
    assert len(samples) == 3
    assert samples[-1].chunks == 5 and samples[-1].n == 5 * 128


def test_monitor_decayed_samples():
    chunks = _drifting_chunks(8, elems=512)
    samples = list(
        Monitor(window=4, decay=0.5).run(list(chunks), np.int32)
    )
    assert all(s.scale == (1 << DECAY_SHIFT) for s in samples)
    # later samples weight recent (larger) data up: p50 tracks drift
    assert samples[-1].values[0] > samples[0].values[0]


def test_monitor_dtype_inference_and_validation():
    chunks = _drifting_chunks(3, elems=64)
    samples = list(Monitor(window=2).run(list(chunks)))  # inferred
    assert len(samples) == 3
    with pytest.raises(TypeError, match="pass dtype="):
        next(Monitor(window=2).run(iter(chunks)))
    with pytest.raises(ValueError, match="emit_every"):
        Monitor(emit_every=0)
    with pytest.raises(ValueError, match="at least one quantile"):
        Monitor(qs=())


def test_monitor_abandoned_generator_cleans_up():
    """Breaking out of the sample stream must tear the pipeline down
    (no leaked ksel- threads / staged buffers — conftest-enforced)."""
    chunks = _drifting_chunks(20, elems=256)
    for s in Monitor(window=4, pipeline_depth=2).run(list(chunks), np.int32):
        break  # abandon after the first sample


def test_monitor_obs_bit_identity_and_metrics():
    from mpi_k_selection_tpu import obs as obs_lib

    chunks = _drifting_chunks(6, elems=512)
    plain = [
        s.as_dict() for s in Monitor(window=3).run(list(chunks), np.int32)
    ]
    o = obs_lib.Observability.collecting()
    inst = [
        s.as_dict()
        for s in Monitor(window=3, obs=o).run(list(chunks), np.int32)
    ]
    assert inst == plain  # sinks on never change a sample bit
    reg = o.metrics
    assert reg.counter("monitor.samples").value == 6
    labs = {
        dict(m.labels)["q"]
        for m in reg.metrics()
        if m.name == "monitor.quantile"
    }
    assert labs == {"p50", "p90", "p99"}
    assert reg.gauge("monitor.window_n").value == inst[-1]["n"]
    # chunk events rode the monitor pass label
    kinds = [e.kind for e in o.events.events]
    assert kinds.count("stream.chunk") == 6


# ---------------------------------------------------------------------------
# windowed-histogram bridge + serve knob


def test_windowed_histogram_advances_on_observation_count(rng):
    from mpi_k_selection_tpu import obs as obs_lib

    reg = obs_lib.MetricsRegistry()
    reg.enable_windowed("serve.latency_seconds", window=2, advance_every=4)
    h = reg.histogram("serve.latency_seconds", labels={"tier": "exact"})
    for v in (1.0, 1.0, 1.0, 1.0):  # bucket 0
        h.observe(v)
    for v in (9.0, 9.0, 9.0, 9.0):  # bucket 1 — bucket 0 evicted (W=2)
        h.observe(v)
    assert h.window_sketch.epoch == 2
    snap = h.windowed_snapshot()
    # the live window holds only the second batch's observations
    assert snap["n"] == 4
    assert all(e["value"] == 9.0 for e in snap["quantiles"])
    # histogram side is untouched: full cumulative count
    assert h.count == 8
    d = h.as_dict()
    assert d["windowed"]["n"] == 4 and d["count"] == 8


def test_serve_latency_windows_bit_identity_and_exposition(rng):
    from mpi_k_selection_tpu import api, obs as obs_lib
    from mpi_k_selection_tpu.serve import KSelectServer

    from tests.test_prometheus import parse_exposition

    x = rng.integers(-(2**31), 2**31 - 1, size=1 << 15, dtype=np.int32)
    ks = [1, 7, 1 << 12, x.size]
    want = [int(np.asarray(api.kselect(x, k))) for k in ks]
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(
        obs=o, latency_windows=dict(window=4, advance_every=2)
    ) as srv:
        srv.add_dataset("d", x)
        got = [int(srv.kselect("d", k, tier="exact").value) for k in ks]
        text = srv.render_prometheus()
    assert got == want  # monitoring on, answers bit-identical
    types, _, samples = parse_exposition(text)
    assert types["ksel_serve_latency_seconds_windowed"] == "gauge"
    assert any(
        n == "ksel_serve_latency_seconds_windowed" and l.get("tier") == "exact"
        for n, l, _ in samples
    )


def test_serve_latency_windows_off_by_default(rng):
    from mpi_k_selection_tpu import obs as obs_lib
    from mpi_k_selection_tpu.serve import KSelectServer

    x = rng.integers(0, 100, size=1 << 10, dtype=np.int32)
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=o) as srv:
        srv.add_dataset("d", x)
        srv.kselect("d", 5, tier="exact")
        assert "_windowed" not in srv.render_prometheus()


def test_serve_latency_windows_knob_forms(rng):
    from mpi_k_selection_tpu import obs as obs_lib
    from mpi_k_selection_tpu.serve import KSelectServer

    # an int is a bucket count (the CLI's --latency-windows shape)
    o = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=o, latency_windows=6) as srv:
        h = o.metrics.histogram(
            "serve.latency_seconds", labels={"tier": "exact"}
        )
        assert h.window_sketch.window == 6
    # True takes the defaults
    o2 = obs_lib.Observability(metrics=obs_lib.MetricsRegistry())
    with KSelectServer(obs=o2, latency_windows=True):
        pass
    # requesting windows WITHOUT a metrics registry is a loud error,
    # not a silent no-op
    with pytest.raises(ValueError, match="metrics registry"):
        KSelectServer(latency_windows=8)


# ---------------------------------------------------------------------------
# Prometheus exporter + CLI


def test_start_metrics_server_serves_registry():
    from mpi_k_selection_tpu import obs as obs_lib

    reg = obs_lib.MetricsRegistry()
    reg.gauge("monitor.window_n").set(42)
    with start_metrics_server(reg) as srv:
        url = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(url + "/metrics", timeout=5) as r:
            body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
        assert "ksel_monitor_window_n 42" in body
        with urllib.request.urlopen(url + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["status"] == "ok"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(url + "/nope", timeout=5)


def test_cli_monitor_human_lines(capsys):
    from mpi_k_selection_tpu.cli import main

    rc = main(
        [
            "monitor", "--buckets", "3", "--window", "4",
            "--chunk-elems", "1024", "--drift", "50",
        ]
    )
    assert rc == 0
    lines = [
        l for l in capsys.readouterr().out.splitlines()
        if l.startswith("multirank_p50_p90_p99")
    ]
    assert len(lines) == 3
    assert "p99=" in lines[0] and "rank_err<=" in lines[0]


def test_cli_monitor_jsonl_decay_and_metrics(tmp_path, capsys):
    from mpi_k_selection_tpu.cli import main

    mpath = tmp_path / "mon.json"
    rc = main(
        [
            "monitor", "--buckets", "2", "--window", "3",
            "--chunk-elems", "512", "--decay", "0.5", "--emit-every", "2",
            "--quantiles", "0.5,0.95", "--json",
            "--metrics-json", str(mpath),
        ]
    )
    assert rc == 0
    recs = [
        json.loads(l) for l in capsys.readouterr().out.splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 2
    assert recs[0]["metric"] == "multirank_p50_p95"
    assert recs[0]["scale"] == 1 << DECAY_SHIFT
    assert recs[0]["chunks"] == 2  # --emit-every 2
    saved = json.loads(mpath.read_text())
    assert any(k.startswith("monitor.quantile") for k in saved)


def test_cli_monitor_validation():
    from mpi_k_selection_tpu.cli import main

    with pytest.raises(SystemExit, match="chunk-elems"):
        main(["monitor", "--chunk-elems", "0", "--buckets", "1"])
    with pytest.raises(SystemExit, match="quantiles"):
        main(["monitor", "--quantiles", "0.5,zap", "--buckets", "1"])
    with pytest.raises(SystemExit):
        main(["monitor", "--decay", "7.5", "--buckets", "1"])
