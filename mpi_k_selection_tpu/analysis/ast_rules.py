"""AST lint rules (KSL001-KSL014) — each encodes a bug class a human
reviewer caught in this repository at least once. docs/ANALYSIS.md holds
the catalog with the historical incident behind every rule.

The rules are module-local by design: reachability is computed from one
file's call graph (a function is "jit-reachable" when it, or a function
that references it by name in the same module, is jit/shard_map-wrapped).
Cross-module reachability would need whole-program import resolution for
marginal extra recall — the bug classes these rules gate have all been
single-module patterns.
"""

from __future__ import annotations

import ast
import pathlib
import re
import subprocess
import sys

from mpi_k_selection_tpu.analysis.core import Rule, SourceModule, register

# ---------------------------------------------------------------------------
# shared AST helpers


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _function_defs(tree: ast.AST) -> dict[str, list[ast.AST]]:
    """Every (possibly nested) function def in the module, by bare name."""
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


_JIT_WRAPPERS = {"jax.jit", "jit", "pjit", "jax.pjit"}
_SHARD_WRAPPERS = {
    "jax.shard_map",
    "shard_map",
    "_shard_map",
    "compat.shard_map",
    "jax.experimental.shard_map.shard_map",
}
_TRACE_WRAPPERS = _JIT_WRAPPERS | _SHARD_WRAPPERS


def _is_trace_wrapper_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name in _TRACE_WRAPPERS:
        return True
    # functools.partial(jax.jit, ...) — a jit decorator factory
    if name in ("functools.partial", "partial") and call.args:
        return dotted_name(call.args[0]) in _TRACE_WRAPPERS
    return False


def _jit_roots(tree: ast.AST, defs: dict[str, list[ast.AST]]) -> set[ast.AST]:
    """Function defs that are jit/shard_map-wrapped: decorated with a
    wrapper, or passed by name into a wrapper call anywhere in the
    module."""
    roots: set[ast.AST] = set()
    for nodes in defs.values():
        for node in nodes:
            for dec in node.decorator_list:
                if dotted_name(dec) in _TRACE_WRAPPERS:
                    roots.add(node)
                elif isinstance(dec, ast.Call) and _is_trace_wrapper_call(dec):
                    roots.add(node)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_trace_wrapper_call(node):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    roots.update(defs[arg.id])
    return roots


def _reachable_from(roots: set[ast.AST], defs: dict[str, list[ast.AST]]) -> set[ast.AST]:
    """Transitive closure over module-local name references (a reference is
    an edge — jitted code routinely passes local functions as closures)."""
    reached: set[ast.AST] = set()
    frontier = list(roots)
    while frontier:
        fn = frontier.pop()
        if fn in reached:
            continue
        reached.add(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in defs:
                for target in defs[node.id]:
                    if target not in reached:
                        frontier.append(target)
    return reached


_MODULE_ALIASES = {"np", "numpy", "jnp", "jax", "lax", "math", "functools", "pl", "pltpu"}


def _is_static_expr(node: ast.AST) -> bool:
    """True when the expression references no local/parameter names —
    constants like ``np.array(~np.uint64(0))`` trace fine inside jit; only
    expressions over runtime values force a host sync."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id not in _MODULE_ALIASES:
            return False
    return True


_SHAPE_TOKENS = re.compile(r"\.shape\b|\.ndim\b|\blen\(|\.itemsize\b|\.size\b")


def _path_endswith(mod: SourceModule, *suffixes: str) -> bool:
    """Exemption matching on the RESOLVED absolute path, so a scan
    invoked from inside the package (cwd-relative 'timing.py') still
    recognizes utils/timing.py — relpath depends on the caller's cwd."""
    p = pathlib.Path(mod.path).resolve().as_posix()
    return p.endswith(suffixes)


def _is_test_file(mod: SourceModule) -> bool:
    """Library-path rules (KSL001-KSL003, KSL007) skip test files: tests
    assert exact values and fail loudly where the library would silently
    truncate/sync, and they legitimately poke internals (building a
    `_Descent` directly, converting freshly-narrowed arrays, staging to a
    hand-picked device). Tests stay in scope for KSL004 (no raw clocks),
    KSL005 (tier-1 membership — a tests-only rule) and KSL006
    (version-sensitive jax attrs)."""
    p = pathlib.Path(mod.path).resolve()
    return p.name.startswith("test_") or "tests" in p.parts or p.name == "conftest.py"


# ---------------------------------------------------------------------------
# KSL001 — host syncs reachable from jit/shard_map


@register
class HostSyncInJit(Rule):
    id = "KSL001"
    title = "host sync reachable from jit/shard_map-wrapped code"
    rationale = (
        "`.item()`/`int()`/`np.asarray`/`jax.device_get` on a traced value "
        "either crashes (TracerArrayConversionError) or, on a concrete "
        "closure value, silently forces a device->host transfer inside the "
        "hot path. Every selection hot loop is jitted; host decode belongs "
        "in the eager shells (ops/radix.py:_f64_exact_shell is the "
        "pattern)."
    )

    _CAST_NAMES = {"int", "float", "bool"}
    _SYNC_ATTRS = {"item", "tolist"}
    _SYNC_CALLS = {"jax.device_get", "device_get", "np.asarray", "numpy.asarray"}

    def check_module(self, mod: SourceModule):
        if _is_test_file(mod):
            return
        defs = _function_defs(mod.tree)
        if not defs:
            return
        roots = _jit_roots(mod.tree, defs)
        if not roots:
            return
        seen: set[tuple[int, str]] = set()
        for fn in _reachable_from(roots, defs):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                name = dotted_name(node.func)
                if isinstance(node.func, ast.Attribute) and node.func.attr in self._SYNC_ATTRS:
                    msg = f".{node.func.attr}() forces a host sync under jit"
                elif name in self._SYNC_CALLS:
                    if not (node.args and _is_static_expr(node.args[0])):
                        msg = f"{name}() forces a host sync under jit"
                elif name in self._CAST_NAMES and node.args:
                    arg = node.args[0]
                    if not _is_static_expr(arg) and not _SHAPE_TOKENS.search(
                        mod.segment(arg)
                    ):
                        msg = (
                            f"{name}() on a runtime value forces a host sync "
                            "under jit (shape/ndim-derived values are exempt)"
                        )
                if msg is not None:
                    key = (node.lineno, msg)
                    if key not in seen:
                        seen.add(key)
                        yield node.lineno, (
                            f"{msg}; reachable from jit/shard_map via "
                            f"`{getattr(fn, 'name', '<fn>')}`"
                        )


# ---------------------------------------------------------------------------
# KSL002 — 64-bit host data entering jnp.asarray without an x64 guard


_X64_GUARDS = re.compile(
    r"_require_x64|require_x64|jax_enable_x64|maybe_x64|enable_x64"
)
_WIDE_TOKENS = re.compile(r"\bu?int64\b|\bfloat64\b|itemsize")


@register
class Unguarded64BitAsarray(Rule):
    id = "KSL002"
    title = "64-bit host data entering jnp.asarray/jnp.array without an x64 guard"
    rationale = (
        "With x64 off, `jnp.asarray` silently narrows int64/uint64/float64 "
        "host data to 32 bits — wrong answers, no error (the truncation "
        "class reviews r1-r5 kept catching). Any function that handles "
        "64-bit data and converts it to a device array must first consult "
        "an x64 guard (`utils.dtypes._require_x64`, a `jax_enable_x64` "
        "check, `utils.x64.maybe_x64`) or take a host fallback."
    )

    @staticmethod
    def _has_explicit_dtype(call: ast.Call) -> bool:
        """An explicit dtype (2nd positional or ``dtype=``) declares the
        width — the gated bug class is the *implicit* narrowing."""
        return len(call.args) >= 2 or any(
            kw.arg == "dtype" for kw in call.keywords
        )

    def check_module(self, mod: SourceModule):
        if _is_test_file(mod):
            return
        seen: set[tuple[int, int]] = set()  # a call in a nested def is
        # visited once per enclosing function — report it once
        for fn in (n for n in ast.walk(mod.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))):
            src = mod.segment(fn)
            if not _WIDE_TOKENS.search(src) or _X64_GUARDS.search(src):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and dotted_name(node.func) in ("jnp.asarray", "jnp.array")
                    and node.args
                    and not self._has_explicit_dtype(node)
                    and not _is_static_expr(node.args[0])
                    and (node.lineno, node.col_offset) not in seen
                ):
                    seen.add((node.lineno, node.col_offset))
                    yield node.lineno, (
                        f"`{dotted_name(node.func)}` in `{fn.name}`, which "
                        "handles 64-bit data but has no x64 guard or host "
                        "fallback — with x64 off this silently truncates to "
                        "32 bits"
                    )


# ---------------------------------------------------------------------------
# KSL003 — _Descent construction bypassing the f64-on-TPU warning


@register
class DescentWithoutF64Warning(Rule):
    id = "KSL003"
    title = "_Descent built outside the f64-on-TPU warning/exact-route shells"
    rationale = (
        "float64 selection on TPU through device keys is the documented "
        "~49-bit approximation (utils/dtypes.py:f64_raw_bits). Every path "
        "that builds a `_Descent` must either run under `_f64_exact_shell` "
        "(exact host keys when possible) or call `_warn_f64_tpu_approx` "
        "itself — ADVICE r5 #1: a silent approximation is the one thing a "
        "selection library must never do."
    )

    _SHELLS = ("_warn_f64_tpu_approx", "_f64_exact_shell")

    def check_module(self, mod: SourceModule):
        if _is_test_file(mod):
            return
        defs = _function_defs(mod.tree)
        # functions that call a shell themselves
        shelled: set[str] = set()
        for name, nodes in defs.items():
            for fn in nodes:
                if any(
                    isinstance(n, ast.Name) and n.id in self._SHELLS
                    for n in ast.walk(fn)
                ):
                    shelled.add(name)
        # functions referenced by name inside a shelled function are covered
        covered: set[str] = set(shelled)
        for name in shelled:
            for fn in defs[name]:
                for n in ast.walk(fn):
                    if isinstance(n, ast.Name) and n.id in defs:
                        covered.add(n.id)
        for name, nodes in defs.items():
            for fn in nodes:
                if name in covered:
                    continue
                for node in ast.walk(fn):
                    if (
                        isinstance(node, ast.Call)
                        and dotted_name(node.func).split(".")[-1] == "_Descent"
                    ):
                        yield node.lineno, (
                            f"`_Descent` built in `{name}`, which neither "
                            "calls `_warn_f64_tpu_approx` nor runs under "
                            "`_f64_exact_shell` — f64-on-TPU would approximate "
                            "silently"
                        )


# ---------------------------------------------------------------------------
# KSL004 — raw clocks outside the timing helpers


@register
class RawClockOutsideTiming(Rule):
    id = "KSL004"
    title = "raw time.time/perf_counter outside utils/timing + utils/profiling"
    rationale = (
        "Raw clock pairs around jax calls measure dispatch, not compute "
        "(async dispatch returns before the device finishes). "
        "utils/timing.time_fn blocks on the result tree; "
        "utils/profiling.PhaseTimer owns phase wall-clock. Bench code with "
        "a methodological reason to read clocks inline (the differential "
        "perturb-chain) carries a written noqa."
    )

    _CLOCKS = {
        "time.time",
        "time.perf_counter",
        "time.monotonic",
        "time.process_time",
        "timeit.default_timer",
    }
    _ALLOWED = ("utils/timing.py", "utils/profiling.py")

    def check_module(self, mod: SourceModule):
        if _path_endswith(mod, *self._ALLOWED):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and dotted_name(node.func) in self._CLOCKS:
                yield node.lineno, (
                    f"`{dotted_name(node.func)}()` — use utils/timing.time_fn "
                    "(device-sync semantics) or utils/profiling.PhaseTimer"
                )


# ---------------------------------------------------------------------------
# KSL005 — tier-1 membership audit (generalized tests/test_marker_audit.py)


@register
class Tier1Membership(Rule):
    id = "KSL005"
    title = "test file neither tier-1-collected nor explicitly slow-marked"
    rationale = (
        "The tier-1 gate runs `pytest -m 'not slow'`. A test file whose "
        "tests all carry an implicit skip (bad collection, module-level "
        "gating, a forgotten pytestmark) silently falls out of that gate. "
        "Every tests/test_*.py must contribute at least one collected test "
        "or contain an explicit pytest.mark.slow opt-out."
    )

    def collect_offenders(self, tests_dir: pathlib.Path) -> list[pathlib.Path]:
        """Offending test files under ``tests_dir`` — the single
        implementation behind both this rule and the historical
        tests/test_marker_audit.py (now a thin wrapper)."""
        out = subprocess.run(
            [
                sys.executable, "-m", "pytest", "--collect-only", "-q",
                "-m", "not slow", "--continue-on-collection-errors",
                "-p", "no:cacheprovider", str(tests_dir),
            ],
            capture_output=True,
            text=True,
            cwd=tests_dir.parent,
        )
        collected = {
            pathlib.Path(line.split("::")[0]).name
            for line in out.stdout.splitlines()
            if "::" in line
        }
        if not collected:
            raise RuntimeError(
                f"tier-1 collection produced nothing:\n{out.stdout}\n{out.stderr}"
            )
        return [
            f
            for f in sorted(tests_dir.glob("test_*.py"))
            if f.name not in collected
            and not re.search(r"pytest\.mark\.slow\b", f.read_text())
        ]

    def check_tree(self, mods):
        by_dir: dict[pathlib.Path, list[SourceModule]] = {}
        for mod in mods:
            p = pathlib.Path(mod.path)
            if p.name.startswith("test_") and p.parent.name == "tests":
                by_dir.setdefault(p.parent.resolve(), []).append(mod)
        for tests_dir, dir_mods in sorted(by_dir.items()):
            mod_by_name = {pathlib.Path(m.path).name: m for m in dir_mods}
            for offender in self.collect_offenders(tests_dir):
                mod = mod_by_name.get(offender.name)
                if mod is None:
                    continue  # offender outside the scanned set
                yield mod, 1, (
                    f"{offender.name} contributes no test to the tier-1 "
                    "selection (-m 'not slow') and has no pytest.mark.slow "
                    "opt-out — it silently fell out of the gate"
                )


# ---------------------------------------------------------------------------
# KSL006 — version-sensitive jax attributes outside utils/compat.py


@register
class DirectVersionSensitiveJaxAttr(Rule):
    id = "KSL006"
    title = "version-sensitive jax attribute accessed outside utils/compat.py"
    rationale = (
        "`jax.shard_map`, `jax.typeof`, `jax.enable_x64` and "
        "`jax.lax.pcast`/`pvary` moved (or did not exist) across the jax "
        "releases this package supports; direct access is an "
        "AttributeError on the 0.4.x line — the seed's entire 137-test "
        "failure set. utils/compat.py resolves every one of them exactly "
        "once; route through it."
    )

    _FORBIDDEN_ATTRS = {
        "jax.shard_map",
        "jax.experimental.shard_map",
        "jax.typeof",
        "jax.enable_x64",
        "jax.disable_x64",
        "jax.lax.pcast",
        "jax.lax.pvary",
    }
    _FORBIDDEN_IMPORTS = {
        ("jax.experimental.shard_map", None),  # any name from that module
        ("jax.experimental", "shard_map"),
        ("jax.experimental", "enable_x64"),
        ("jax.experimental", "disable_x64"),
    }

    def check_module(self, mod: SourceModule):
        if _path_endswith(mod, "utils/compat.py"):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name in self._FORBIDDEN_ATTRS:
                    yield node.lineno, (
                        f"direct `{name}` — moved across jax versions; use "
                        "the utils/compat.py shim"
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    if (node.module, alias.name) in self._FORBIDDEN_IMPORTS or (
                        node.module,
                        None,
                    ) in self._FORBIDDEN_IMPORTS:
                        yield node.lineno, (
                            f"direct `from {node.module} import {alias.name}` "
                            "— moved across jax versions; use the "
                            "utils/compat.py shim"
                        )


# ---------------------------------------------------------------------------
# KSL007 — device_put in streaming/ without an explicit device/sharding


@register
class StreamingDevicePutWithoutDevice(Rule):
    id = "KSL007"
    title = "jax.device_put in streaming/ without an explicit device/sharding"
    rationale = (
        "A bare `jax.device_put(x)` commits nothing: the buffer lands on "
        "the (thread-local) default device — device 0 for a fresh "
        "producer thread. The multi-device staged ingest round-robins "
        "chunks across `jax.devices()`; a staging call that drops the "
        "device argument silently lands EVERY staged buffer on one chip "
        "and the other p-1 idle through the pass with no error — the "
        "exact bug class the `devices` knob exists to prevent. Every "
        "`jax.device_put` under streaming/ must name its target (a "
        "device, a sharding, or an explicit None for the documented "
        "single-slot default path)."
    )

    # compatibility shim: the source model (what counts as an
    # untargeted put) now lives with the placement dataflow pass —
    # resource_protocols.TRANSFER_PUT_CALLS / PUT_TARGET_KWARGS — so one
    # placement vocabulary exists, not two. This rule keeps only its id,
    # fixtures and streaming/ scope.

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/streaming/" not in p or _is_test_file(mod):
            return
        from mpi_k_selection_tpu.analysis.placement import untargeted_puts

        for line, name in untargeted_puts(mod):
            yield line, (
                f"`{name}` without an explicit device/"
                "sharding argument — staged buffers silently pile onto "
                "one chip; pass the round-robin slot (or an explicit "
                "None for the single-slot default path)"
            )


# ---------------------------------------------------------------------------
# KSL008 — raw file writes in streaming/ outside the spill store API


@register
class StreamingRawFileWrite(Rule):
    id = "KSL008"
    title = "raw file write in streaming/ outside the spill store API"
    rationale = (
        "streaming/spill.py is the ONE sanctioned file-writing surface "
        "under streaming/: its records carry the (chunk_index, bucket, "
        "dtype, device) key, a CRC32, and a lifecycle (generations dropped "
        "eagerly, stores removed on every exit path — the leaked-dir test "
        "fixture). A raw `open(..., 'w')`/`np.save`/`.tofile` in the "
        "streaming layer dodges all three: no replay keying (the "
        "chunk->device determinism contract breaks silently), no checksum "
        "(a truncated write feeds the descent wrong survivors instead of "
        "raising SpillRecordError), and no cleanup discipline (temp files "
        "outlive the pass). Route every write through "
        "SpillStore/SpillWriter."
    )

    # call names that write files outright
    _WRITE_CALLS = {
        "np.save", "np.savez", "np.savez_compressed",
        "numpy.save", "numpy.savez", "numpy.savez_compressed",
        "np.memmap", "numpy.memmap",
        "pickle.dump", "shutil.copyfile", "shutil.copy", "shutil.copy2",
    }
    # method names that write files on their receiver (ndarray.tofile,
    # Path.write_bytes/write_text)
    _WRITE_METHODS = {"tofile", "write_bytes", "write_text"}
    _OPEN_NAMES = {"open", "io.open", "os.fdopen"}
    _WRITE_MODE = re.compile(r"[wax+]")

    def _open_writes(self, call: ast.Call, mode_pos: int) -> bool:
        """True when an ``open``-family call provably (or possibly) opens
        for writing: a constant mode containing w/a/x/+, or a NON-constant
        mode (can't prove read-only). A missing/constant read mode passes.
        ``mode_pos`` is the mode's positional index — 1 for the builtin
        ``open(path, mode)``, 0 for the receiver-qualified
        ``Path(...).open(mode)``."""
        mode = None
        if len(call.args) > mode_pos:
            mode = call.args[mode_pos]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return False  # bare open(path) = read
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return bool(self._WRITE_MODE.search(mode.value))
        return True  # dynamic mode: cannot prove it reads

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/streaming/" not in p or _is_test_file(mod):
            return
        if p.endswith("streaming/spill.py"):
            return  # the sanctioned writer
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in self._WRITE_CALLS:
                yield node.lineno, (
                    f"`{name}` writes a file outside the spill store API — "
                    "route it through SpillStore/SpillWriter "
                    "(streaming/spill.py) so it gets record keying, "
                    "checksums and cleanup"
                )
            elif (
                (name in self._OPEN_NAMES and self._open_writes(node, 1))
                or (
                    # receiver-qualified .open() — Path(p).open('wb') and
                    # friends; the mode is the FIRST argument there
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "open"
                    and name not in self._OPEN_NAMES
                    and self._open_writes(node, 0)
                )
            ):
                yield node.lineno, (
                    f"`{name or '.open'}` with a write mode outside the "
                    "spill store API — route it through "
                    "SpillStore/SpillWriter (streaming/spill.py) so it "
                    "gets record keying, checksums and cleanup"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in self._WRITE_METHODS
            ):
                yield node.lineno, (
                    f"`.{node.func.attr}(...)` writes a file outside the "
                    "spill store API — route it through "
                    "SpillStore/SpillWriter (streaming/spill.py)"
                )


# ---------------------------------------------------------------------------
# KSL009 — print/logging telemetry in library code


@register
class PrintLoggingTelemetry(Rule):
    id = "KSL009"
    title = "print/logging telemetry in library code — route through obs"
    rationale = (
        "Library telemetry that goes to stdout/stderr is invisible to "
        "every structured consumer — the bench records, the CLI's JSON "
        "mode (a stray print corrupts the `--json` stream callers parse), "
        "the metrics registry, and the event sinks — and unconditional "
        "`logging` calls pay string formatting on hot streaming paths "
        "whether anyone listens or not. Library code under "
        "mpi_k_selection_tpu/ reports through the obs subsystem "
        "(obs/events.py sinks, obs/metrics.py registry) or raises/warns; "
        "the CLI and the reporters (cli.py, __main__.py, "
        "analysis/reporters.py, utils/timing.py's reference-style result "
        "printer) are the sanctioned human-facing output surfaces."
    )

    # CLI and reporter surfaces: human-facing output is their JOB
    _EXEMPT = (
        "cli.py",
        "__main__.py",
        "analysis/reporters.py",
        "utils/timing.py",
    )
    _LOG_METHODS = {
        "debug", "info", "warning", "warn", "error", "critical",
        "exception", "log",
    }
    _LOG_RECEIVERS = {"logging", "logger", "log"}

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/mpi_k_selection_tpu/" not in p or _is_test_file(mod):
            return
        if _path_endswith(mod, *self._EXEMPT):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "print":
                yield node.lineno, (
                    "`print()` telemetry in library code — emit an obs "
                    "event or metric (mpi_k_selection_tpu/obs/) so "
                    "structured consumers see it, or raise/warn for "
                    "error conditions (CLI and reporters are exempt)"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in self._LOG_METHODS
                and name.split(".")[0] in self._LOG_RECEIVERS
            ):
                yield node.lineno, (
                    f"`{name}()` logging telemetry in library code — "
                    "route it through the obs registry/sinks "
                    "(mpi_k_selection_tpu/obs/) so bench records and "
                    "JSON consumers can read it (CLI and reporters are "
                    "exempt)"
                )
            elif name == "logging.getLogger":
                yield node.lineno, (
                    "`logging.getLogger()` in library code — the obs "
                    "subsystem (events/metrics/trace) is this package's "
                    "telemetry channel; loggers here end up emitting "
                    "unstructured text no consumer reads"
                )


# ---------------------------------------------------------------------------
# KSL010 — per-request compilation in serve/ handler paths


@register
class ServeHandlerCompile(Rule):
    id = "KSL010"
    title = "jit/compile-wrapping call in serve/ outside the registry's program cache"
    rationale = (
        "The query server answers many small requests over long-lived "
        "resident datasets; a `jax.jit`/`pjit`/`shard_map` wrap (or a "
        "`functools.partial(jax.jit, ...)` factory) sitting on a handler "
        "path builds a FRESH wrapped callable per request, so every "
        "request re-traces and the compile cache never hits — the classic "
        "accidental-recompile latency cliff, invisible in tests that "
        "issue one query. All compile-bearing callables under serve/ are "
        "built ONCE in serve/registry.py and reused through its keyed "
        "ProgramCache (hit/miss counters exported as "
        "`serve.program_cache.*`); handler code (server, batcher, lanes, "
        "tiers, http) dispatches through cached programs only — the "
        "per-device dispatch lanes (serve/lanes.py) route work, they "
        "never compile it, and registration-time warmup pre-builds "
        "through the same cache."
    )

    _SANCTIONED = ("serve/registry.py",)

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/serve/" not in p or _is_test_file(mod):
            return
        if _path_endswith(mod, *self._SANCTIONED):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_trace_wrapper_call(node):
                yield node.lineno, (
                    f"`{dotted_name(node.func) or '<wrapper>'}` builds a "
                    "compile-bearing callable on a serve/ handler path — "
                    "every request re-traces; build it once in "
                    "serve/registry.py and dispatch through the keyed "
                    "ProgramCache"
                )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # bare `@jax.jit` decorators (the Call branch above
                # already reports the `@partial(jax.jit, ...)` form)
                for dec in node.decorator_list:
                    if dotted_name(dec) in _TRACE_WRAPPERS:
                        yield node.lineno, (
                            f"`@{dotted_name(dec)}` on `{node.name}` in "
                            "serve/ — compiled programs belong in "
                            "serve/registry.py's ProgramCache, not on "
                            "handler paths"
                        )


# ---------------------------------------------------------------------------
# KSL011 — eager device gathers on streaming chunk-consume paths


@register
class StreamingEagerDeviceGather(Rule):
    id = "KSL011"
    title = "eager np.asarray of a masked/indexed device array in streaming/ outside executor.py"
    rationale = (
        "`np.asarray(kv[m])` (or `jax.device_get` of an indexed device "
        "value) at chunk-consume time blocks the consumer on a "
        "device->host sync PER CHUNK: the boolean gather's output shape "
        "is data-dependent, so jax must materialize it eagerly, and on a "
        "multi-device pass the p-wide in-flight window degrades toward "
        "serial on exactly the biggest reads — the r6 finding that "
        "serialized the spill tee and the survivor collect. The async "
        "executor (streaming/executor.py) is the ONE sanctioned home for "
        "that gather: it wraps the eager form as the deferred=off oracle "
        "and replaces it with a fixed-shape device-side compaction whose "
        "host materialization happens when the FIFO window pops. Any "
        "other asarray-of-a-subscript in the streaming layer reintroduces "
        "the serialization the executor retired."
    )

    _SYNC_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get", "device_get"}
    _SANCTIONED = ("streaming/executor.py",)

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/streaming/" not in p or _is_test_file(mod):
            return
        if _path_endswith(mod, *self._SANCTIONED):
            return  # the deferral surface owns the (oracle) eager gather
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and dotted_name(node.func) in self._SYNC_CALLS
                and node.args
                and isinstance(node.args[0], ast.Subscript)
            ):
                yield node.lineno, (
                    f"`{dotted_name(node.func)}` of an indexed/masked array "
                    "on a streaming chunk path — an eager per-chunk "
                    "device->host gather; route it through the async "
                    "executor's deferred compaction "
                    "(streaming/executor.py: dispatch_compaction / "
                    "materialize_compacted) so the transfer happens when "
                    "the FIFO window pops"
                )


# ---------------------------------------------------------------------------
# KSL012 — silent broad excepts in the resilience layers; raw time.sleep


@register
class SilentSwallowOrRawSleep(Rule):
    id = "KSL012"
    title = (
        "silent broad except in streaming//serve//faults/, or time.sleep "
        "outside the injectable sleeper"
    )
    rationale = (
        "The resilience vertical (faults/, docs/ROBUSTNESS.md) classifies "
        "failures: transients are retried, spill corruption takes the "
        "re-read/rebuild ladder, overload sheds — and every action emits a "
        "typed FaultEvent. A bare `except:`/`except Exception:` that "
        "neither re-raises nor even LOOKS at the exception swallows a "
        "failure none of that machinery ever sees: the descent keeps "
        "running on corrupt state, or a server thread dies silently — the "
        "MPI_Abort posture's evil twin. Separately, a raw `time.sleep` "
        "hard-codes real waiting into backoff/stall paths, making the "
        "seeded chaos grid minutes-slow and untestable; "
        "faults/sleeper.py's injectable Sleeper is the one sanctioned "
        "wait surface (the waiting twin of KSL004's clock discipline)."
    )

    _BROAD = {"Exception", "BaseException"}
    _SCOPED = ("/streaming/", "/serve/", "/faults/")
    _SLEEPER = ("faults/sleeper.py",)

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        types = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple)
            else [handler.type]
        )
        return any(
            dotted_name(t).split(".")[-1] in self._BROAD for t in types
        )

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if _is_test_file(mod):
            return
        if "/mpi_k_selection_tpu/" in p and not _path_endswith(
            mod, *self._SLEEPER
        ):
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and dotted_name(node.func) == "time.sleep"
                ):
                    yield node.lineno, (
                        "`time.sleep()` outside faults/sleeper.py — route "
                        "waiting through the injectable Sleeper "
                        "(RetryPolicy backoff, chaos stalls) so tests and "
                        "the seeded harness can virtualize it"
                    )
        if not any(seg in p for seg in self._SCOPED):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler) or not self._is_broad(node):
                continue
            # sanctioned handling: re-raising (incl. conditionally), or
            # binding the exception and actually using it (transporting
            # it to another thread, mapping it to a status/typed error,
            # emitting it) — "silent" means the exception VALUE is dropped
            if any(isinstance(x, ast.Raise) for x in ast.walk(node)):
                continue
            if node.name and any(
                isinstance(x, ast.Name) and x.id == node.name
                for stmt in node.body
                for x in ast.walk(stmt)
            ):
                continue
            yield node.lineno, (
                "broad except swallows the failure (no re-raise, and the "
                "exception value is never used): the resilience layers "
                "must retry, rebuild, shed, or surface a typed error — "
                "and emit a FaultEvent — never drop a failure on the "
                "floor (faults/, docs/ROBUSTNESS.md)"
            )


# ---------------------------------------------------------------------------
# KSL013 — unbounded metric label cardinality


@register
class UnboundedMetricLabels(Rule):
    id = "KSL013"
    title = (
        "metric labels= value derived from a loop variable "
        "(per-chunk/per-request cardinality)"
    )
    rationale = (
        "A metrics-registry label whose VALUE comes from a loop variable "
        "— a chunk index, a request id, a raw observation — mints one "
        "fresh (name, labels) series per iteration: the registry (and "
        "any Prometheus server scraping it) grows without bound, "
        "exposition cost grows with it, and per-series aggregates "
        "become meaningless (every series holds one point). Labels must "
        "partition over CLOSED sets (a device slot, a tier, a phase, a "
        "quantile); unbounded dimensions belong in the metric VALUE "
        "(a counter/histogram observation) or the event stream "
        "(obs/events.py), which is built for per-occurrence records. "
        "Bounded-in-practice loop sources (PhaseTimer phase names) "
        "carry a written noqa in the ledger."
    )

    _METRIC_METHODS = {"counter", "gauge", "histogram"}

    def _loop_targets(self, node) -> set[str]:
        """Names bound by a for-loop target or comprehension generator."""
        names: set[str] = set()
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                for sub in ast.walk(gen.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
        return names

    def _label_value_exprs(self, call: ast.Call):
        """The expressions that become label VALUES: the values of a
        ``labels={...}`` dict literal. Non-literal labels arguments (a
        name built elsewhere) are out of scope — tracing them needs
        dataflow this rule does not attempt."""
        for kw in call.keywords:
            if kw.arg == "labels" and isinstance(kw.value, ast.Dict):
                yield from kw.value.values

    def _walk(self, node, loop_names: set[str]):
        """Recursive walk tracking which names are loop-bound at each
        point. Function/lambda boundaries RESET the set (a parameter is
        the caller's choice, not an iteration — `phase=` style labels
        stay legal); for-loops and comprehension generators extend it
        for everything they enclose."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            loop_names = set()
        else:
            targets = self._loop_targets(node)
            if targets:
                loop_names = loop_names | targets
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in self._METRIC_METHODS
        ):
            for vexpr in self._label_value_exprs(node):
                hit = sorted(
                    {
                        n.id
                        for n in ast.walk(vexpr)
                        if isinstance(n, ast.Name) and n.id in loop_names
                    }
                )
                if hit:
                    yield node.lineno, (
                        f"metric label value derived from loop "
                        f"variable(s) {', '.join(hit)} — one fresh "
                        "series per iteration is unbounded label "
                        "cardinality; partition labels over a closed "
                        "set and put per-occurrence data in the "
                        "metric value or the obs event stream"
                    )
        for child in ast.iter_child_nodes(node):
            yield from self._walk(child, loop_names)

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/mpi_k_selection_tpu/" not in p or _is_test_file(mod):
            return
        seen: set[tuple[int, str]] = set()
        for lineno, msg in self._walk(mod.tree, set()):
            key = (lineno, msg)
            if key not in seen:
                seen.add(key)
                yield lineno, msg


# ---------------------------------------------------------------------------
# KSL014 — multiple device programs consuming one staged bucket per pass


@register
class MultiProgramStagedConsume(Rule):
    id = "KSL014"
    title = (
        "multiple ingest device programs dispatched against one staged "
        "bucket in streaming/ outside executor.py's sanctioned bundle"
    )
    rationale = (
        "The fused single-read ingest (ops/pallas/fused_ingest.py, "
        "ISSUE 11) exists because a staged chunk that is swept by "
        "SEVERAL device programs per pass — a histogram dispatch here, a "
        "compaction there — multiplies the per-pass HBM traffic of every "
        "staged key by the program count: each dispatch is its own read "
        "of the same pow2 bucket. streaming/executor.py owns the ONE "
        "sanctioned multi-program bundle (the fused=\"off\" oracle, plus "
        "the FusedIngestConsumer that collapses it to one program); a "
        "second ingest-program dispatch over the same staged buffer "
        "anywhere else in the streaming layer quietly reintroduces the "
        "read amplification the fusion retired. Route new per-chunk "
        "device work through the executor's consumer bundle (fused when "
        "possible) instead of dispatching beside it."
    )

    #: The ingest-program dispatch surface (matched on the last dotted
    #: segment): the histogram primitives, the executor's dispatch
    #: helpers, and the single-read programs themselves — both the XLA
    #: fusion (dispatch_fused_ingest / fused_ingest_core) and the sweep
    #: kernel (dispatch_sweep_ingest / sweep_ingest_core): each IS one
    #: read, so a second ingest program beside one re-introduces exactly
    #: the amplification it exists to retire. Two of these against one
    #: staged variable in one function is the read-amplification class;
    #: unrelated device calls (e.g. the sketch's extremes fold) are out
    #: of scope — they are not reads of the radix-ingest program family
    #: this rule gates.
    _DISPATCHERS = {
        "dispatch_chunk_histograms",
        "dispatch_compaction",
        "dispatch_fused_ingest",
        "dispatch_sweep_ingest",
        "fused_ingest_core",
        "sweep_ingest_core",
        "masked_radix_histogram",
        "multi_masked_radix_histogram",
    }
    _SANCTIONED = ("streaming/executor.py",)

    @staticmethod
    def _base_name(node: ast.AST):
        """Root Name of a Name/Attribute chain (``staged`` from
        ``staged.data``); None for anything without a stable base."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    @staticmethod
    def _own_nodes(fn: ast.AST):
        """The nodes belonging to ``fn``'s own scope — nested function
        defs are their own scopes and are skipped (each is visited as its
        own function by check_module)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/streaming/" not in p or _is_test_file(mod):
            return
        if _path_endswith(mod, *self._SANCTIONED):
            return  # the executor owns the sanctioned bundle
        for defs in _function_defs(mod.tree).values():
            for fn in defs:
                by_base: dict[str, list[tuple[int, str]]] = {}
                for node in self._own_nodes(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted_name(node.func).split(".")[-1]
                    if name not in self._DISPATCHERS:
                        continue
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        base = self._base_name(arg)
                        if base is not None:
                            by_base.setdefault(base, []).append(
                                (node.lineno, name)
                            )
                            break
                for base, calls in by_base.items():
                    for lineno, name in sorted(calls)[1:]:
                        yield lineno, (
                            f"`{name}` dispatches another ingest program "
                            f"against staged chunk `{base}` "
                            f"({len(calls)} programs in this function — "
                            "each one re-reads the whole staged bucket); "
                            "route the work through streaming/executor.py"
                            "'s consumer bundle (FusedIngestConsumer "
                            "fuses it into ONE program per bucket)"
                        )


# ---------------------------------------------------------------------------
# KSL018 — obs event types live in obs/events.py AND in the documented
# event catalog (docs/OBSERVABILITY.md), both directions


@register
class ObsEventCatalog(Rule):
    id = "KSL018"
    title = (
        "obs event type defined outside obs/events.py, or out of sync "
        "with the docs/OBSERVABILITY.md event-schema table"
    )
    rationale = (
        "The typed event stream is a consumer contract: sinks, "
        "check_stream_invariants, the flight recorder's debug bundle "
        "and every postmortem reader key on the documented `kind` "
        "catalog (docs/OBSERVABILITY.md). An event type declared beside "
        "its emitter dodges the one home consumers import "
        "(obs/events.py), and a type added there without its schema row "
        "— or a schema row whose type was renamed away — drifts the "
        "catalog exactly like the rule-id table PR 12's doc-drift gate "
        "covers. This rule is that gate extended to the event schema, "
        "both directions."
    )

    _EVENTS_FILE = "obs/events.py"

    @staticmethod
    def _event_classes(mod: SourceModule):
        """``(classdef, kind or None)`` for every event TYPE in ``mod``:
        a frozen dataclass with at least one base class carrying a
        ``kind`` class attribute (the ObsEvent idiom). The base-less
        ``ObsEvent`` root itself is not an emitted type and is skipped;
        ``kind`` is the string literal when one is assigned."""
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef) or not node.bases:
                continue
            frozen = False
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                if dotted_name(dec.func).split(".")[-1] != "dataclass":
                    continue
                for kw in dec.keywords:
                    if (
                        kw.arg == "frozen"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                    ):
                        frozen = True
            if not frozen:
                continue
            kind = None
            has_kind = False
            for stmt in node.body:
                target = None
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    target = stmt.target.id
                elif isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "kind"
                    for t in stmt.targets
                ):
                    target = "kind"
                if target != "kind":
                    continue
                has_kind = True
                value = getattr(stmt, "value", None)
                if isinstance(value, ast.Constant) and isinstance(
                    value.value, str
                ):
                    kind = value.value
            if has_kind:
                out.append((node, kind))
        return out

    @staticmethod
    def _documented_kinds(doc_text: str) -> set[str]:
        """First-column backticked kinds of the event-schema table: the
        rows between the '## Event schema' heading and the next '## '."""
        kinds: set[str] = set()
        in_section = False
        for line in doc_text.splitlines():
            if line.startswith("## "):
                in_section = line.lower().startswith("## event schema")
                continue
            if not in_section:
                continue
            m = re.match(r"\|\s*`([^`]+)`\s*\|", line)
            if m:
                kinds.add(m.group(1))
        return kinds

    def check_module(self, mod: SourceModule):
        p = pathlib.Path(mod.path).resolve().as_posix()
        if "/mpi_k_selection_tpu/" not in p or _is_test_file(mod):
            return
        if not _path_endswith(mod, self._EVENTS_FILE):
            for node, kind in self._event_classes(mod):
                yield node.lineno, (
                    f"obs event type `{node.name}` (kind "
                    f"{kind!r}) defined outside obs/events.py — event "
                    "types live in the ONE module consumers import, "
                    "next to their schema row (docs/OBSERVABILITY.md)"
                )
            return
        # the catalog half: events.py's kinds <-> the schema table rows.
        # The docs root sits two levels above obs/ (repo layout and the
        # fixture trees alike); a tree without the doc only exercises
        # the location half above.
        doc = pathlib.Path(mod.path).resolve().parents[2] / "docs" / "OBSERVABILITY.md"
        if not doc.is_file():
            return
        documented = self._documented_kinds(doc.read_text())
        defined: dict[str, tuple] = {}
        for node, kind in self._event_classes(mod):
            if kind is not None:
                defined[kind] = (node.lineno, node.name)
        for kind, (lineno, name) in sorted(defined.items()):
            if kind not in documented:
                yield lineno, (
                    f"event type `{name}` (kind {kind!r}) has no row in "
                    "docs/OBSERVABILITY.md's event-schema table — every "
                    "emitted kind is documented, both directions"
                )
        for kind in sorted(documented - set(defined)):
            yield 1, (
                f"docs/OBSERVABILITY.md documents event kind {kind!r} "
                "but obs/events.py defines no event type with it — "
                "stale schema row (renamed or removed type)"
            )
